// liboppack — native op-log packing for the TPU replay path.
//
// The host-side hot loop of bulk catch-up is turning op streams into the
// padded (D, T) int32 arrays the merge-tree kernel folds (see
// fluidframework_tpu/ops/mergetree_kernel.py::pack_mergetree_batch).  The
// ingestion side encodes string-channel ops once into a flat binary record
// stream (ops/native_pack.py::encode_string_ops); this library consumes
// that stream and fills the arrays in one pass — no Python objects, no
// per-op dict lookups.
//
// Record layout (little-endian, packed):
//   u8  kind        (1=insert, 2=remove, 3=annotate)
//   i32 seq
//   i32 ref_seq
//   i32 client_idx  (interned by the encoder)
//   i32 a           (pos | start)
//   i32 b           (end; 0 for insert)
//   i32 n_props     (annotate property pairs)
//   i32 text_len    (insert only, BYTES of utf-8; 0 otherwise)
//   { i32 key_idx, i32 val_idx } * n_props   (val -1 == PROP_ABSENT)
//   u8  text[text_len]
//
// Text offsets in the arrays are in CHARACTERS (the Python arena is a str);
// the packer counts code points while copying utf-8 bytes, so the caller
// can decode the byte arena once and every (tstart, tlen) span aligns.
//
// API (C ABI, ctypes-consumed):
//   oppack_count(...)  — sizing pre-pass
//   oppack_pack(...)   — fill one document's row of the batch arrays

#include <cstdint>
#include <cstring>

namespace {
constexpr int64_t kHeader = 1 + 4 * 7;  // kind byte + 7 i32 fields

inline int64_t count_codepoints(const uint8_t* p, int64_t n) {
    int64_t chars = 0;
    for (int64_t i = 0; i < n; ++i) {
        chars += (p[i] & 0xC0) != 0x80;
    }
    return chars;
}
}  // namespace

extern "C" {

// Sizing pre-pass.  Returns 0 on success; -1 on truncated/malformed input.
int oppack_count(const uint8_t* buf, int64_t len,
                 int32_t* n_ops, int64_t* text_bytes, int64_t* text_chars) {
    int64_t off = 0;
    int32_t ops = 0;
    int64_t bytes = 0, chars = 0;
    while (off < len) {
        if (off + kHeader > len) return -1;
        int32_t fields[7];
        std::memcpy(fields, buf + off + 1, 4 * 7);
        const int32_t n_props = fields[5];
        const int32_t text_len = fields[6];
        off += kHeader;
        if (n_props < 0 || text_len < 0) return -1;
        if (off + 8 * static_cast<int64_t>(n_props) + text_len > len)
            return -1;
        off += 8 * static_cast<int64_t>(n_props);
        chars += count_codepoints(buf + off, text_len);
        bytes += text_len;
        off += text_len;
        ops += 1;
    }
    *n_ops = ops;
    *text_bytes = bytes;
    *text_chars = chars;
    return 0;
}

// Packs one document's record stream into row-slices of the batch arrays.
// `pvals` is the (T, K) row in C order, pre-filled with PROP_NOT_TOUCHED.
// Returns ops packed, or -1 on malformed input / capacity overflow.
int32_t oppack_pack(const uint8_t* buf, int64_t len,
                    int32_t T, int32_t K, int64_t arena_base_chars,
                    int32_t* kind, int32_t* seq, int32_t* client,
                    int32_t* ref_seq, int32_t* a, int32_t* b,
                    int32_t* tstart, int32_t* tlen, int32_t* pvals,
                    uint8_t* arena_out, int64_t arena_capacity,
                    int64_t* arena_bytes, int64_t* arena_chars) {
    int64_t off = 0;
    int32_t t = 0;
    int64_t out_bytes = 0, out_chars = 0;
    while (off < len) {
        if (off + kHeader > len) return -1;
        if (t >= T) return -1;
        const uint8_t k = buf[off];
        int32_t fields[7];
        std::memcpy(fields, buf + off + 1, 4 * 7);
        off += kHeader;
        const int32_t n_props = fields[5];
        const int32_t text_len = fields[6];
        if (n_props < 0 || text_len < 0) return -1;
        if (off + 8 * static_cast<int64_t>(n_props) + text_len > len)
            return -1;
        kind[t] = static_cast<int32_t>(k);
        seq[t] = fields[0];
        ref_seq[t] = fields[1];
        client[t] = fields[2];
        a[t] = fields[3];
        b[t] = fields[4];
        for (int32_t i = 0; i < n_props; ++i) {
            int32_t pair[2];
            std::memcpy(pair, buf + off, 8);
            off += 8;
            if (pair[0] < 0 || pair[0] >= K) return -1;
            pvals[static_cast<int64_t>(t) * K + pair[0]] = pair[1];
        }
        if (text_len > 0) {
            if (out_bytes + text_len > arena_capacity) return -1;
            std::memcpy(arena_out + out_bytes, buf + off, text_len);
            const int64_t chars = count_codepoints(buf + off, text_len);
            tstart[t] = static_cast<int32_t>(arena_base_chars + out_chars);
            tlen[t] = static_cast<int32_t>(chars);
            out_bytes += text_len;
            out_chars += chars;
            off += text_len;
        } else {
            tstart[t] = 0;
            tlen[t] = 0;
        }
        t += 1;
    }
    *arena_bytes = out_bytes;
    *arena_chars = out_chars;
    return t;
}

}  // extern "C"
