// liboppack — native op-log packing for the TPU replay path.
//
// The host-side hot loop of bulk catch-up is turning op streams into the
// padded (D, T) int32 arrays the merge-tree kernel folds (see
// fluidframework_tpu/ops/mergetree_kernel.py::pack_mergetree_batch).  The
// ingestion side encodes string-channel ops once into a flat binary record
// stream (ops/native_pack.py::encode_string_ops); this library consumes
// that stream and fills the arrays in one pass — no Python objects, no
// per-op dict lookups.
//
// Record layout (little-endian, packed):
//   u8  kind        (1=insert, 2=remove, 3=annotate, 4=obliterate)
//   i32 seq
//   i32 ref_seq
//   i32 min_seq     (stamped MSN — zamboni-expiry parity on device)
//   i32 client_idx  (interned by the encoder)
//   i32 a           (pos | start)
//   i32 b           (end; 0 for insert)
//   i32 n_props     (annotate property pairs)
//   i32 text_len    (insert only, BYTES of utf-8; 0 otherwise)
//   { i32 key_idx, i32 val_idx } * n_props   (val -1 == PROP_ABSENT)
//   u8  text[text_len]
//
// Text offsets in the arrays are in CHARACTERS (the Python arena is a str);
// the packer counts code points while copying utf-8 bytes, so the caller
// can decode the byte arena once and every (tstart, tlen) span aligns.
//
// API (C ABI, ctypes-consumed):
//   oppack_count(...)    — sizing pre-pass
//   oppack_pack(...)     — fill one document's row of the batch arrays
//   oppack_extract(...)  — final device state → canonical summary-body JSON
//
// oppack_extract consumes the fused export buffer ([D, F, S] int32, see
// mergetree_kernel.EXPORT_SLOT_FIELDS) and emits, per document, the exact
// bytes of canonical_json(normalized_records): sorted keys, minimal
// separators, ensure_ascii=False (UTF-8 passthrough; only '"', '\\' and
// control chars escape, matching python json.dumps).  Slot rows carry two
// obliterate stamp pairs (rows 8..11); in-window stamps (> msn) emit as
// "ob":[[seq,"client"],...] and pin their tombstones past normal expiry.

#include <cstdint>
#include <cstring>

namespace {
constexpr int64_t kHeader = 1 + 4 * 8;  // kind byte + 8 i32 fields

inline int64_t count_codepoints(const uint8_t* p, int64_t n) {
    int64_t chars = 0;
    for (int64_t i = 0; i < n; ++i) {
        chars += (p[i] & 0xC0) != 0x80;
    }
    return chars;
}
}  // namespace

extern "C" {

// Sizing pre-pass.  Returns 0 on success; -1 on truncated/malformed input.
int oppack_count(const uint8_t* buf, int64_t len,
                 int32_t* n_ops, int64_t* text_bytes, int64_t* text_chars) {
    int64_t off = 0;
    int32_t ops = 0;
    int64_t bytes = 0, chars = 0;
    while (off < len) {
        if (off + kHeader > len) return -1;
        int32_t fields[8];
        std::memcpy(fields, buf + off + 1, 4 * 8);
        const int32_t n_props = fields[6];
        const int32_t text_len = fields[7];
        off += kHeader;
        if (n_props < 0 || text_len < 0) return -1;
        if (off + 8 * static_cast<int64_t>(n_props) + text_len > len)
            return -1;
        off += 8 * static_cast<int64_t>(n_props);
        chars += count_codepoints(buf + off, text_len);
        bytes += text_len;
        off += text_len;
        ops += 1;
    }
    *n_ops = ops;
    *text_bytes = bytes;
    *text_chars = chars;
    return 0;
}

// Packs one document's record stream into row-slices of the batch arrays.
// `pvals` is the (T, K) row in C order, pre-filled with PROP_NOT_TOUCHED.
// `key_map` / `val_map` translate the encoder's doc-local property key and
// value ids into the batch-global intern spaces (null = identity; negative
// value ids — PROP_ABSENT — pass through untranslated).
// Returns ops packed, or -1 on malformed input / capacity overflow.
int32_t oppack_pack(const uint8_t* buf, int64_t len,
                    int32_t T, int32_t K, int64_t arena_base_chars,
                    int32_t* kind, int32_t* seq, int32_t* client,
                    int32_t* ref_seq, int32_t* min_seq, int32_t* a,
                    int32_t* b, int32_t* tstart, int32_t* tlen,
                    int32_t* pvals,
                    uint8_t* arena_out, int64_t arena_capacity,
                    int64_t* arena_bytes, int64_t* arena_chars,
                    const int32_t* key_map, int32_t n_keys,
                    const int32_t* val_map, int32_t n_vals) {
    int64_t off = 0;
    int32_t t = 0;
    int64_t out_bytes = 0, out_chars = 0;
    while (off < len) {
        if (off + kHeader > len) return -1;
        if (t >= T) return -1;
        const uint8_t k = buf[off];
        int32_t fields[8];
        std::memcpy(fields, buf + off + 1, 4 * 8);
        off += kHeader;
        const int32_t n_props = fields[6];
        const int32_t text_len = fields[7];
        if (n_props < 0 || text_len < 0) return -1;
        if (off + 8 * static_cast<int64_t>(n_props) + text_len > len)
            return -1;
        kind[t] = static_cast<int32_t>(k);
        seq[t] = fields[0];
        ref_seq[t] = fields[1];
        min_seq[t] = fields[2];
        client[t] = fields[3];
        a[t] = fields[4];
        b[t] = fields[5];
        for (int32_t i = 0; i < n_props; ++i) {
            int32_t pair[2];
            std::memcpy(pair, buf + off, 8);
            off += 8;
            int32_t col = pair[0];
            int32_t val = pair[1];
            if (key_map != nullptr) {
                if (col < 0 || col >= n_keys) return -1;
                col = key_map[col];
            }
            if (val_map != nullptr && val >= 0) {
                if (val >= n_vals) return -1;
                val = val_map[val];
            }
            if (col < 0 || col >= K) return -1;
            pvals[static_cast<int64_t>(t) * K + col] = val;
        }
        if (text_len > 0) {
            if (out_bytes + text_len > arena_capacity) return -1;
            std::memcpy(arena_out + out_bytes, buf + off, text_len);
            const int64_t chars = count_codepoints(buf + off, text_len);
            tstart[t] = static_cast<int32_t>(arena_base_chars + out_chars);
            tlen[t] = static_cast<int32_t>(chars);
            out_bytes += text_len;
            out_chars += chars;
            off += text_len;
        } else {
            tstart[t] = 0;
            tlen[t] = 0;
        }
        t += 1;
    }
    *arena_bytes = out_bytes;
    *arena_chars = out_chars;
    return t;
}

// Final device state → canonical summary-body JSON for every document of a
// chunk, in one pass.  Layout contract with mergetree_kernel._export_state:
//   export_buf: [D, F, S] int32, C order, F = 12 + K + 1
//     rows 0..7: tstart, tlen, ins_seq, ins_client,
//                rem_seq, rem_client, rem2_seq, rem2_client
//     rows 8..11: ob1_seq, ob1_client, ob2_seq, ob2_client
//     rows 12..12+K-1: property value ids (PROP_ABSENT = -1)
//     row  12+K (misc): [n, overflow, live_len, 0...]
//   arena_utf8: the chunk text arena; tstart/tlen are CHAR offsets, so a
//     char→byte index is built once here.
//   client_json / key_json / val_json: pre-serialized JSON tokens
//     (canonical_json of each client name / property key / value),
//     flattened with offset tables.  clients are per-doc
//     (client_doc_start[d] .. client_doc_start[d+1] index the offs table);
//     keys arrive in SORTED key order with key_cols[k] = the export row of
//     the k-th sorted key.
//   msn / final over per doc: msn drives tombstone expiry + seq clamping.
// Output: out (capacity out_cap) receives the concatenated bodies;
//   out_offs[d]..out_offs[d+1] delimit doc d.  Docs flagged by `skip` get
//   empty bodies (oracle-fallback docs).  Returns 0, or the required
//   capacity as a negative number minus one (caller regrows), or -1 on
//   malformed input (since -1 also means "need 0 bytes", capacity requests
//   use -(need)-2).
int64_t oppack_extract(
    const int32_t* export_buf, int32_t D, int32_t F, int32_t S, int32_t K,
    const uint8_t* arena_utf8, int64_t arena_bytes_len, int64_t arena_chars,
    const uint8_t* client_json, const int64_t* client_offs,
    const int32_t* client_doc_start,
    const uint8_t* key_json, const int64_t* key_offs,
    const int32_t* key_cols,
    const uint8_t* val_json, const int64_t* val_offs, int32_t n_vals,
    const int32_t* msn, const uint8_t* skip,
    int32_t not_removed,
    uint8_t* out, int64_t out_cap, int64_t* out_offs) {
    if (F != 12 + K + 1) return -1;
    // char → byte index over the arena (one pass).
    int64_t* idx = new int64_t[arena_chars + 1];
    {
        int64_t c = 0;
        for (int64_t i = 0; i < arena_bytes_len; ++i) {
            if ((arena_utf8[i] & 0xC0) != 0x80) {
                if (c > arena_chars) { delete[] idx; return -1; }
                idx[c++] = i;
            }
        }
        if (c != arena_chars) { delete[] idx; return -1; }
        idx[arena_chars] = arena_bytes_len;
    }

    int64_t w = 0;  // write cursor; keeps counting past capacity
    bool fits = true;
    bool bad = false;
    auto put = [&](const uint8_t* p, int64_t n) {
        if (fits && w + n <= out_cap) std::memcpy(out + w, p, n);
        else fits = false;
        w += n;
    };
    auto put_lit = [&](const char* s) {
        put(reinterpret_cast<const uint8_t*>(s), std::strlen(s));
    };
    auto put_int = [&](int64_t v) {
        char tmp[24];
        int n = 0;
        if (v < 0) { tmp[n++] = '-'; v = -v; }
        char digits[20];
        int nd = 0;
        do { digits[nd++] = static_cast<char>('0' + v % 10); v /= 10; }
        while (v > 0);
        while (nd > 0) tmp[n++] = digits[--nd];
        put(reinterpret_cast<const uint8_t*>(tmp), n);
    };
    // Escaped UTF-8 emit (ensure_ascii=False): passthrough except
    // '"', '\\' and control chars — exactly python json.dumps.
    auto put_escaped = [&](const uint8_t* tp, int64_t tn) {
        int64_t run = 0;
        for (int64_t i = 0; i < tn; ++i) {
            const uint8_t ch = tp[i];
            if (!(ch == '"' || ch == '\\' || ch < 0x20)) { ++run; continue; }
            if (run) put(tp + i - run, run);
            run = 0;
            switch (ch) {
                case '"': put_lit("\\\""); break;
                case '\\': put_lit("\\\\"); break;
                case '\b': put_lit("\\b"); break;
                case '\t': put_lit("\\t"); break;
                case '\n': put_lit("\\n"); break;
                case '\f': put_lit("\\f"); break;
                case '\r': put_lit("\\r"); break;
                default: {
                    char u[6];
                    static const char* hex = "0123456789abcdef";
                    u[0] = '\\'; u[1] = 'u'; u[2] = '0'; u[3] = '0';
                    u[4] = hex[(ch >> 4) & 0xF];
                    u[5] = hex[ch & 0xF];
                    put(reinterpret_cast<const uint8_t*>(u), 6);
                }
            }
        }
        if (run) put(tp + tn - run, run);
    };
    auto put_client = [&](int32_t d, int32_t c) {
        const int32_t ci = client_doc_start[d] + c;
        if (ci >= client_doc_start[d + 1]) { bad = true; return; }
        put(client_json + client_offs[ci],
            client_offs[ci + 1] - client_offs[ci]);
    };

    const int64_t fs = static_cast<int64_t>(F) * S;
    for (int32_t d = 0; d < D && !bad; ++d) {
        out_offs[d] = w;
        if (skip != nullptr && skip[d]) continue;
        const int32_t* ex = export_buf + static_cast<int64_t>(d) * fs;
        const int32_t* p_tstart = ex + 0 * S;
        const int32_t* p_tlen = ex + 1 * S;
        const int32_t* p_ins_seq = ex + 2 * S;
        const int32_t* p_ins_client = ex + 3 * S;
        const int32_t* p_rem_seq = ex + 4 * S;
        const int32_t* p_rem_client = ex + 5 * S;
        const int32_t* p_rem2_client = ex + 7 * S;
        const int32_t* p_ob1_seq = ex + 8 * S;
        const int32_t* p_ob1_client = ex + 9 * S;
        const int32_t* p_ob2_seq = ex + 10 * S;
        const int32_t* p_ob2_client = ex + 11 * S;
        const int32_t n = ex[static_cast<int64_t>(12 + K) * S + 0];
        const int32_t doc_msn = msn[d];
        if (n < 0 || n > S) { bad = true; break; }

        // In-window obliterate stamps pin a tombstone past normal expiry
        // (tail inserts resolve their arrival verdict against it).
        auto live_stamps = [&](int32_t s) {
            int32_t count = 0;
            if (p_ob1_seq[s] != not_removed && p_ob1_seq[s] > doc_msn) ++count;
            if (p_ob2_seq[s] != not_removed && p_ob2_seq[s] > doc_msn) ++count;
            return count;
        };
        auto expired = [&](int32_t s) {
            const int32_t rs = p_rem_seq[s];
            return rs != not_removed && rs <= doc_msn &&
                   p_ins_seq[s] <= doc_msn && live_stamps(s) == 0;
        };
        // Merge-equality of two SURVIVING slots, mirroring
        // _extract_records: normalized (s, c), removal triple, overlap
        // remover, property row.  Expired tombstones between surviving
        // slots are invisible to the merge (python compares against the
        // last *emitted* record).
        auto meta_eq = [&](int32_t x, int32_t y) {
            const bool rx = p_rem_seq[x] != not_removed;
            const bool ry = p_rem_seq[y] != not_removed;
            const bool cx = p_ins_seq[x] <= doc_msn;
            const bool cy = p_ins_seq[y] <= doc_msn;
            if ((cx ? 0 : p_ins_seq[x]) != (cy ? 0 : p_ins_seq[y])) {
                return false;
            }
            if ((cx ? -1 : p_ins_client[x]) != (cy ? -1 : p_ins_client[y])) {
                return false;
            }
            if (rx != ry) return false;
            if (rx && (p_rem_seq[x] != p_rem_seq[y] ||
                       p_rem_client[x] != p_rem_client[y])) {
                return false;
            }
            if (p_rem2_client[x] != p_rem2_client[y]) return false;
            // in-window stamp lists must match
            const bool o1x = p_ob1_seq[x] != not_removed &&
                             p_ob1_seq[x] > doc_msn;
            const bool o1y = p_ob1_seq[y] != not_removed &&
                             p_ob1_seq[y] > doc_msn;
            const bool o2x = p_ob2_seq[x] != not_removed &&
                             p_ob2_seq[x] > doc_msn;
            const bool o2y = p_ob2_seq[y] != not_removed &&
                             p_ob2_seq[y] > doc_msn;
            if (o1x != o1y || o2x != o2y) return false;
            if (o1x && (p_ob1_seq[x] != p_ob1_seq[y] ||
                        p_ob1_client[x] != p_ob1_client[y])) return false;
            if (o2x && (p_ob2_seq[x] != p_ob2_seq[y] ||
                        p_ob2_client[x] != p_ob2_client[y])) return false;
            for (int32_t k = 0; k < K; ++k) {
                if (ex[(12 + static_cast<int64_t>(k)) * S + x] !=
                    ex[(12 + static_cast<int64_t>(k)) * S + y]) {
                    return false;
                }
            }
            return true;
        };

        put_lit("[");
        bool first_rec = true;
        int32_t s = 0;
        while (s < n && !bad) {
            if (expired(s)) { ++s; continue; }
            // Gather the merge group: surviving slots equal to s, skipping
            // expired tombstones in between.
            // Two passes, no buffer: find the group end (cur), then emit
            // text by re-walking [s, cur) and skipping expired slots.
            int32_t cur = s + 1;
            while (cur < n) {
                if (expired(cur)) { ++cur; continue; }
                if (!meta_eq(s, cur)) break;
                ++cur;
            }

            const bool removed = p_rem_seq[s] != not_removed;
            const bool clamp = p_ins_seq[s] <= doc_msn;
            const int32_t seq_out = clamp ? 0 : p_ins_seq[s];
            const int32_t c_out = clamp ? -1 : p_ins_client[s];

            if (!first_rec) put_lit(",");
            first_rec = false;
            put_lit("{\"c\":");
            if (c_out < 0) put_lit("null");
            else put_client(d, c_out);
            if (live_stamps(s) > 0) {
                put_lit(",\"ob\":[");
                bool first_ob = true;
                const int32_t ob_seqs[2] = {p_ob1_seq[s], p_ob2_seq[s]};
                const int32_t ob_clients[2] = {p_ob1_client[s],
                                               p_ob2_client[s]};
                for (int i = 0; i < 2; ++i) {
                    if (ob_seqs[i] == not_removed || ob_seqs[i] <= doc_msn)
                        continue;
                    if (!first_ob) put_lit(",");
                    first_ob = false;
                    put_lit("[");
                    put_int(ob_seqs[i]);
                    put_lit(",");
                    put_client(d, ob_clients[i]);
                    put_lit("]");
                }
                put_lit("]");
            }
            bool has_props = false;
            for (int32_t k = 0; k < K && !has_props; ++k) {
                has_props = ex[(12 + static_cast<int64_t>(k)) * S + s] >= 0;
            }
            if (has_props) {
                put_lit(",\"p\":{");
                bool first_p = true;
                for (int32_t k = 0; k < K; ++k) {  // sorted key order
                    const int32_t col = key_cols[k];
                    const int32_t vid =
                        ex[(12 + static_cast<int64_t>(col)) * S + s];
                    if (vid < 0) continue;
                    if (vid >= n_vals) { bad = true; break; }
                    if (!first_p) put_lit(",");
                    first_p = false;
                    put(key_json + key_offs[k],
                        key_offs[k + 1] - key_offs[k]);
                    put_lit(":");
                    put(val_json + val_offs[vid],
                        val_offs[vid + 1] - val_offs[vid]);
                }
                put_lit("}");
            }
            if (removed) {
                put_lit(",\"rc\":");
                if (p_rem_client[s] < 0) put_lit("null");
                else put_client(d, p_rem_client[s]);
            }
            if (p_rem2_client[s] >= 0) {
                put_lit(",\"ro\":[");
                put_client(d, p_rem2_client[s]);
                put_lit("]");
            }
            if (removed) {
                put_lit(",\"rs\":");
                put_int(p_rem_seq[s]);
            }
            put_lit(",\"s\":");
            put_int(seq_out);
            put_lit(",\"t\":\"");
            for (int32_t g = s; g < cur && !bad; ++g) {
                if (expired(g)) continue;
                const int64_t c0 = p_tstart[g];
                const int64_t cl = p_tlen[g];
                if (c0 < 0 || c0 + cl > arena_chars) { bad = true; break; }
                put_escaped(arena_utf8 + idx[c0], idx[c0 + cl] - idx[c0]);
            }
            put_lit("\"}");
            s = cur;
        }
        put_lit("]");
    }
    delete[] idx;
    if (bad) return -1;
    out_offs[D] = w;
    if (!fits) return -w - 2;
    return 0;
}

// oppack_widen — undo the export transfer encodings in one native pass:
// narrow (int16 / int8-pair) source buffer → the canonical [D, R_canon, S]
// int32 layout mergetree_kernel.widen_export produces (byte-identical;
// pinned by tests).  Replaces the numpy widen on the extraction hot path.
//
// desc: R_canon rows × 4 int32 = [mode, arg, fill, flags]
//   mode 0 = FILL      (constant `fill`)
//   mode 1 = ROW16     (arg = source row; int16 elements)
//   mode 2 = PAIR8     (arg = src_row * 2 + half; int16 lane holds two
//                       int8 values, half 0 = high byte, 1 = low byte)
//   mode 3 = MISC      (stitch misc[d, j] for j < misc_cols, else 0)
// flags bit0: remap sentinel_src → sentinel_dst
// flags bit1: re-add doc_base[d] on slots < n (live-slot tstart rebase);
//             n is read from the canonical misc row (always last, col 0).
int32_t oppack_widen(
    const int16_t* src, int32_t D, int32_t S,
    int32_t R_src, int32_t R_canon,
    const int16_t* misc, int32_t misc_cols,
    const int32_t* desc,
    const int32_t* doc_base,
    int32_t sentinel_src, int32_t sentinel_dst,
    int32_t* dst) {
    // Validate the desc table up front, like the per-doc `n` check below:
    // a source-row index past R_src (ROW16 directly, PAIR8 via arg/2) or a
    // MISC row without the misc output would read out of bounds.  -1, not
    // UB, on a malformed table.
    for (int32_t r = 0; r < R_canon; ++r) {
        const int32_t mode = desc[r * 4 + 0];
        const int32_t arg = desc[r * 4 + 1];
        if (mode == 1 && (arg < 0 || arg >= R_src)) return -1;
        if (mode == 2 && (arg < 0 || arg / 2 >= R_src)) return -1;
        if (mode == 3 && misc == nullptr) return -1;
        if (mode < 0 || mode > 3) return -1;
    }
    const int64_t src_doc = static_cast<int64_t>(R_src) * S;
    const int64_t dst_doc = static_cast<int64_t>(R_canon) * S;
    for (int32_t d = 0; d < D; ++d) {
        const int16_t* sp = src + static_cast<int64_t>(d) * src_doc;
        int32_t* dp = dst + static_cast<int64_t>(d) * dst_doc;
        // n for the live-slot rebase: misc col 0 (separate misc output in
        // the pair layout, last source row otherwise).
        const int32_t n = misc != nullptr
            ? misc[static_cast<int64_t>(d) * misc_cols + 0]
            : sp[static_cast<int64_t>(R_src - 1) * S + 0];
        if (n < 0 || n > S) return -1;
        for (int32_t r = 0; r < R_canon; ++r) {
            const int32_t mode = desc[r * 4 + 0];
            const int32_t arg = desc[r * 4 + 1];
            const int32_t fill = desc[r * 4 + 2];
            const int32_t flags = desc[r * 4 + 3];
            int32_t* row = dp + static_cast<int64_t>(r) * S;
            if (mode == 0) {
                for (int32_t s = 0; s < S; ++s) row[s] = fill;
                continue;
            }
            if (mode == 3) {
                for (int32_t s = 0; s < S; ++s)
                    row[s] = s < misc_cols
                        ? misc[static_cast<int64_t>(d) * misc_cols + s] : 0;
                continue;
            }
            if (mode == 1) {
                const int16_t* srow = sp + static_cast<int64_t>(arg) * S;
                for (int32_t s = 0; s < S; ++s) row[s] = srow[s];
            } else if (mode == 2) {
                const int16_t* srow =
                    sp + static_cast<int64_t>(arg / 2) * S;
                const bool hi = (arg % 2) == 0;
                for (int32_t s = 0; s < S; ++s) {
                    const uint16_t pair = static_cast<uint16_t>(srow[s]);
                    row[s] = static_cast<int8_t>(
                        hi ? (pair >> 8) : (pair & 0xFF));
                }
            } else {
                return -1;
            }
            if (flags & 1) {
                for (int32_t s = 0; s < S; ++s)
                    if (row[s] == sentinel_src) row[s] = sentinel_dst;
            }
            if ((flags & 2) && doc_base != nullptr) {
                const int32_t base = doc_base[d];
                for (int32_t s = 0; s < n; ++s) row[s] += base;
            }
        }
    }
    return 0;
}

}  // extern "C"
