"""North-star benchmark: bulk SharedString catch-up replay, device vs oracle.

Workload per BASELINE.json: many documents' sequenced op tails folded to
summaries.  The CPU baseline is the oracle replay harness (BASELINE.md: the
1× denominator); the device path is the merge-tree kernel vmapped over the
document axis on whatever backend jax selects (real TPU under the driver).

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": "ops/sec", "vs_baseline": ratio}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import random
import sys
import time

import jax
import numpy as np

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    _replay_batch,
    pack_mergetree_batch,
    replay_mergetree_batch,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

import os

N_DOCS = int(os.environ.get("BENCH_DOCS", "10240"))
OPS_PER_DOC = int(os.environ.get("BENCH_OPS", "96"))
CPU_SAMPLE_DOCS = int(os.environ.get("BENCH_CPU_SAMPLE", "24"))
ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def synth_doc(doc_idx: int, n_ops: int) -> MergeTreeDocInput:
    """A valid sequenced op stream: 3 clients round-robin, mixed edits."""
    rng = random.Random(doc_idx * 7919 + 13)
    ops, length = [], 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if r < 0.62 or length < 4:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 8))
            )
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.9:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {
                "kind": "annotate", "start": start, "end": end,
                "props": {"f": rng.randint(0, 3)},
            }
        ops.append(
            SequencedMessage(
                seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
                min_seq=0, type=MessageType.OP, contents=contents,
            )
        )
    return MergeTreeDocInput(
        doc_id=f"doc{doc_idx}", ops=ops, final_seq=n_ops, final_msn=0
    )


def main() -> None:
    t0 = time.time()
    docs = [synth_doc(d, OPS_PER_DOC) for d in range(N_DOCS)]
    total_ops = N_DOCS * OPS_PER_DOC
    print(
        f"generated {N_DOCS} docs x {OPS_PER_DOC} ops in {time.time()-t0:.1f}s "
        f"(backend={jax.default_backend()})",
        file=sys.stderr,
    )

    # --- CPU oracle baseline (the 1x denominator, BASELINE.md) ---
    t0 = time.time()
    for doc in docs[:CPU_SAMPLE_DOCS]:
        replica = SharedString(doc.doc_id)
        for msg in doc.ops:
            replica.process(msg, local=False)
    cpu_time = time.time() - t0
    cpu_ops_per_sec = CPU_SAMPLE_DOCS * OPS_PER_DOC / cpu_time
    print(
        f"cpu oracle: {CPU_SAMPLE_DOCS * OPS_PER_DOC} ops in {cpu_time:.2f}s "
        f"= {cpu_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- device path ---
    t0 = time.time()
    state, ops, meta = pack_mergetree_batch(docs)
    pack_time = time.time() - t0
    t0 = time.time()
    final = _replay_batch(state, ops)  # compile + first run
    jax.block_until_ready(final)
    warm_time = time.time() - t0
    t0 = time.time()
    final = _replay_batch(state, ops)
    jax.block_until_ready(final)
    device_time = time.time() - t0
    device_ops_per_sec = total_ops / device_time
    print(
        f"pack {pack_time:.1f}s | compile+first {warm_time:.1f}s | "
        f"steady replay {device_time:.3f}s = {device_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- sanity: device bytes == oracle bytes on a couple of docs ---
    check = replay_mergetree_batch(docs[:2])
    for doc, dev_summary in zip(docs[:2], check):
        replica = SharedString(doc.doc_id)
        for msg in doc.ops:
            replica.process(msg, local=False)
        assert dev_summary.digest() == replica.summarize().digest(), (
            f"bench sanity: {doc.doc_id} device summary != oracle"
        )
    print("sanity: device summaries byte-identical to oracle", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "sharedstring_catchup_replay_ops_per_sec",
                "value": round(device_ops_per_sec, 1),
                "unit": "ops/sec",
                "vs_baseline": round(device_ops_per_sec / cpu_ops_per_sec, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
