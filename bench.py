"""North-star benchmark: bulk SharedString catch-up replay, device vs oracle.

Workload per BASELINE.json: many documents' sequenced op tails folded to
summaries.  The CPU baseline is the oracle replay harness (BASELINE.md: the
1× denominator); the device path is the merge-tree kernel vmapped over the
document axis on whatever backend jax selects (real TPU under the driver).

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": "ops/sec", "vs_baseline": ratio}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import random
import sys
import time

import jax
import numpy as np

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.interning import Interner
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    _replay_batch_cold,
    pack_mergetree_batch,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.native_pack import (
    decode_string_ops,
    encode_string_ops,
    load_library,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

import os

N_DOCS = int(os.environ.get("BENCH_DOCS", "10240"))
OPS_PER_DOC = int(os.environ.get("BENCH_OPS", "96"))
CPU_SAMPLE_DOCS = int(os.environ.get("BENCH_CPU_SAMPLE", "24"))
# Documents fold in fixed-size chunks: one compiled shape reused across
# dispatches, bounded per-transfer sizes, and the dispatch/compute balance
# measured best at 1024 docs/chunk on v5e (larger single batches degrade
# per-op throughput and >4k-doc transfers can trip device faults).
CHUNK_DOCS = int(os.environ.get("BENCH_CHUNK", "1024"))
ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def synth_doc(doc_idx: int, n_ops: int) -> MergeTreeDocInput:
    """A valid sequenced op stream: 3 clients round-robin, mixed edits.
    70% of documents are pure insert/remove text traffic (ingested in the
    native binary record format); 30% carry annotate ops with props and
    take the Python pack path — a realistic mix that exercises both."""
    rng = random.Random(doc_idx * 7919 + 13)
    annotating_doc = doc_idx % 10 >= 7
    ops, length = [], 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if not annotating_doc:
            r = min(r, 0.89)  # no annotates in binary-ingested docs
        if r < 0.62 or length < 4:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 8))
            )
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.9:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {
                "kind": "annotate", "start": start, "end": end,
                "props": {"f": rng.randint(0, 3)},
            }
        ops.append(
            SequencedMessage(
                seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
                min_seq=0, type=MessageType.OP, contents=contents,
            )
        )
    # Ingestion-time binary encoding: the op stream is written once in the
    # liboppack record format; batch packing then runs in C++ (the
    # ops/native_pack fast path).  Annotates carry props, so those streams
    # keep the Python path — mirroring real mixed traffic.
    has_props = any(m.contents["kind"] == "annotate" for m in ops)
    if has_props:
        return MergeTreeDocInput(
            doc_id=f"doc{doc_idx}", ops=ops, final_seq=n_ops, final_msn=0
        )
    clients = Interner()
    blob = encode_string_ops(ops, clients)
    return MergeTreeDocInput(
        doc_id=f"doc{doc_idx}", ops=[], binary_ops=blob,
        binary_clients=list(clients.values), final_seq=n_ops, final_msn=0
    )


def main() -> None:
    t0 = time.time()
    docs = [synth_doc(d, OPS_PER_DOC) for d in range(N_DOCS)]
    total_ops = N_DOCS * OPS_PER_DOC
    print(
        f"generated {N_DOCS} docs x {OPS_PER_DOC} ops in {time.time()-t0:.1f}s "
        f"(backend={jax.default_backend()})",
        file=sys.stderr,
    )

    # --- CPU oracle baseline (the 1x denominator, BASELINE.md) ---
    def doc_ops(doc):
        if doc.binary_ops is not None:
            return decode_string_ops(doc.binary_ops,
                                     list(doc.binary_clients))
        return doc.ops

    t0 = time.time()
    for doc in docs[:CPU_SAMPLE_DOCS]:
        replica = SharedString(doc.doc_id)
        for msg in doc_ops(doc):
            replica.process(msg, local=False)
    cpu_time = time.time() - t0
    cpu_ops_per_sec = CPU_SAMPLE_DOCS * OPS_PER_DOC / cpu_time
    print(
        f"cpu oracle: {CPU_SAMPLE_DOCS * OPS_PER_DOC} ops in {cpu_time:.2f}s "
        f"= {cpu_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- device path: chunked fold, one compiled shape ---
    native = load_library() is not None
    t0 = time.time()
    packed = [
        pack_mergetree_batch(docs[i:i + CHUNK_DOCS])
        for i in range(0, len(docs), CHUNK_DOCS)
    ]
    pack_time = time.time() - t0
    print(f"pack path: {'C++ liboppack' if native else 'pure python'} | "
          f"{len(packed)} chunks x {CHUNK_DOCS} docs", file=sys.stderr)
    def fold(state, ops):
        # cold docs: initial state built in-graph, only op arrays upload
        return _replay_batch_cold(ops, state.tstart.shape[1])

    t0 = time.time()
    jax.block_until_ready(fold(packed[0][0], packed[0][1]))
    warm_time = time.time() - t0
    device_time = float("inf")
    for _rep in range(3):  # best-of-3: the device tunnel adds run noise
        t0 = time.time()
        finals = [fold(state, ops) for state, ops, _meta in packed]
        for final in finals:
            jax.block_until_ready(final)
        device_time = min(device_time, time.time() - t0)
    device_ops_per_sec = total_ops / device_time
    print(
        f"pack {pack_time:.1f}s | compile+first {warm_time:.1f}s | "
        f"steady replay {device_time:.3f}s = {device_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- sanity: device bytes == oracle bytes on a couple of docs ---
    check = replay_mergetree_batch(docs[:2])
    for doc, dev_summary in zip(docs[:2], check):
        replica = SharedString(doc.doc_id)
        for msg in doc_ops(doc):
            replica.process(msg, local=False)
        assert dev_summary.digest() == replica.summarize().digest(), (
            f"bench sanity: {doc.doc_id} device summary != oracle"
        )
    print("sanity: device summaries byte-identical to oracle", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "sharedstring_catchup_replay_ops_per_sec",
                "value": round(device_ops_per_sec, 1),
                "unit": "ops/sec",
                "vs_baseline": round(device_ops_per_sec / cpu_ops_per_sec, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
