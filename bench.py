"""North-star benchmark: bulk SharedString catch-up replay, device vs oracle.

Workload per BASELINE.json: many documents' sequenced op tails folded to
summaries.  The CPU baseline is the oracle replay harness (BASELINE.md: the
1× denominator, pinned there — workload generator, oracle definition, and
the committed round-2 number); the device path is the merge-tree kernel
vmapped over the document axis on whatever backend jax selects (real TPU
under the driver).

The end-to-end path is PIPELINED across three host stages that overlap with
device compute and the tunnel link (the measured bottleneck, VERDICT r2):

    packer thread:     pack chunk → dispatch fold (async)
    downloader thread: fetch fused int16 export (blocking link RPC)
    main thread:       C++ body extraction → canonical summaries

Numbers reported:
- ``value`` / ``vs_baseline``: the HONEST END-TO-END rate — wall-clock from
  raw op streams to canonical summaries materialized host-side for every
  document, all stages included.
- ``steady_fold_ops_per_sec``: the device fold alone with device-resident
  inputs (uploaded once, compiled, export not fetched) — the rate a
  saturated device approaches.
- ``link``: an in-run microbenchmark of the host↔device link (per-RPC
  latency + MB/s each way) so the fold-vs-e2e gap is attributable.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": "ops/sec", "vs_baseline": ratio,
     ...stage breakdown + link + fallback counts...}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import queue
import random
import sys
import threading
import time

import jax
import numpy as np

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.interning import Interner
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    export_to_numpy,
    pack_mergetree_batch,
    replay_export,
    replay_mergetree_batch,
    summaries_from_export,
)
from fluidframework_tpu.ops.native_pack import (
    decode_string_ops,
    encode_string_ops,
    load_library,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

N_DOCS = int(os.environ.get("BENCH_DOCS", "10240"))
OPS_PER_DOC = int(os.environ.get("BENCH_OPS", "96"))
CPU_SAMPLE_DOCS = int(os.environ.get("BENCH_CPU_SAMPLE", "256"))
# Documents fold in fixed-size chunks: one compiled shape reused across
# dispatches, bounded per-transfer sizes, and the dispatch/compute balance
# measured best at 1024 docs/chunk on v5e (larger single batches degrade
# per-op throughput and >4k-doc transfers can trip device faults).
CHUNK_DOCS = int(os.environ.get("BENCH_CHUNK", "1024"))
PACK_THREADS = int(os.environ.get("BENCH_PACK_THREADS", "4"))
# Extraction parallelism: the C++ extractor runs under ctypes (GIL
# released for the foreign call), so chunks extract concurrently.  At the
# 50x target the serial extract stage alone (~1.7s busy at round-2 scale)
# would cap the pipeline below budget.
EXTRACT_THREADS = int(os.environ.get("BENCH_EXTRACT_THREADS", "3"))
ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def synth_doc(doc_idx: int, n_ops: int) -> MergeTreeDocInput:
    """A valid sequenced op stream: 3 clients round-robin, mixed edits.
    70% of documents are pure insert/remove text traffic; 30% carry
    annotate ops with props.  ALL streams are ingested in the native binary
    record format (annotates ride encoder-local intern tables that packing
    translates to the batch-global spaces in C++).

    This generator is the PINNED workload of BASELINE.md config #1 — do not
    change its distribution without re-measuring the committed baseline."""
    rng = random.Random(doc_idx * 7919 + 13)
    annotating_doc = doc_idx % 10 >= 7
    ops, length = [], 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if not annotating_doc:
            r = min(r, 0.89)  # no annotates in pure-text docs
        if r < 0.62 or length < 4:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 8))
            )
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.9:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {
                "kind": "annotate", "start": start, "end": end,
                "props": {"f": rng.randint(0, 3)},
            }
        ops.append(
            SequencedMessage(
                seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
                min_seq=0, type=MessageType.OP, contents=contents,
            )
        )
    clients, keys, vals = Interner(), Interner(), Interner()
    blob = encode_string_ops(ops, clients, keys, vals)
    return MergeTreeDocInput(
        doc_id=f"doc{doc_idx}", ops=[], binary_ops=blob,
        binary_clients=list(clients.values),
        binary_prop_keys=list(keys.values) or None,
        binary_values=list(vals.values) or None,
        final_seq=n_ops, final_msn=0,
    )


def doc_ops(doc):
    return decode_string_ops(
        doc.binary_ops, list(doc.binary_clients),
        prop_keys=doc.binary_prop_keys, values=doc.binary_values,
    )


def oracle_replay(doc):
    replica = SharedString(doc.doc_id)
    for msg in doc_ops(doc):
        replica.process(msg, local=False)
    return replica


METRIC_NAME = "sharedstring_catchup_replay_ops_per_sec"
# Service-shaped corpus for the catch-up cache cold/warm metric: smaller
# than the raw-stream e2e by default (it adds two full service folds to
# the run), overridable like the rest of the workload knobs.
CATCHUP_DOCS = int(os.environ.get(
    "BENCH_CATCHUP_DOCS", str(min(N_DOCS, 2048))))


def build_catchup_corpus(service, n_docs: int, ops_per_doc: int):
    """Seed ``service`` with ``n_docs`` single-string documents: an empty
    seeded summary at seq 0 plus the PINNED synth_doc op tail appended
    straight to the op log (each op wrapped in the groupedBatch container
    envelope the runtime emits) — the service-shaped twin of the bench
    corpus, cheap enough to build at full scale.  Returns the doc ids."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "text")
    seed_tree = seeded.summarize()
    doc_ids = []
    for i in range(n_docs):
        doc_id = f"cdoc{i}"
        service.storage.upload(doc_id, seed_tree, 0)
        for m in doc_ops(synth_doc(i, ops_per_doc)):
            service.oplog.append(doc_id, SequencedMessage(
                seq=m.seq, client_id=m.client_id, client_seq=m.client_seq,
                ref_seq=m.ref_seq, min_seq=m.min_seq, type=MessageType.OP,
                contents={"type": "groupedBatch", "ops": [
                    {"ds": "ds", "channel": "text",
                     "clientSeq": m.client_seq,
                     "contents": m.contents}]},
            ))
        doc_ids.append(doc_id)
    return doc_ids


def catchup_oracle_digest(service, doc_id: str) -> str:
    """CPU container fold of one corpus doc — the byte-identity oracle
    for the cached catch-up section."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    runtime = ContainerRuntime()
    summary, ref_seq = service.storage.latest(doc_id)
    runtime.load(summary)
    for msg in service.oplog.get(doc_id, from_seq=ref_seq):
        runtime.process(msg)
    return runtime.summarize().digest()


def run_catchup_cache_bench(n_docs: int, ops_per_doc: int) -> dict:
    """Steady-state re-catch-up: fold a service corpus twice through
    CatchupService and report cold vs warm rates plus cache health.  The
    warm pass must be pure tier-1 hits (zero pack/fold/extract) — the
    repeated-read serving shape the two-tier cache exists for."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService
    from fluidframework_tpu.tools.bench_harness import benchmark_cold_warm

    service = LocalOrderingService()
    doc_ids = build_catchup_corpus(service, n_docs, ops_per_doc)
    svc = CatchupService(service)
    if svc.cache is None:
        # Operator disabled the gate (Catchup.Cache=off): the cold/warm
        # pair would measure nothing — keep the artifact schema stable
        # and say so instead of crashing the hardened bench.
        print("catchup cache disabled by config gate; skipping cold/warm",
              file=sys.stderr)
        return {
            "catchup_docs": n_docs,
            "catchup_cold_ops_per_sec": None,
            "catchup_warm_ops_per_sec": None,
            "catchup_warm_speedup": None,
            "cache_hit_rate": None,
            "catchup_cache": None,
            "pack_cache": None,
            "delta_cache": None,
            "device_cache": None,
            "catchup_stages_busy_sec": {},
            "catchup_d2h_bytes": None,
            "catchup_cold_d2h_bytes": None,
            "catchup_warm_d2h_bytes": None,
            "catchup_h2d_bytes": None,
            "catchup_cold_h2d_bytes": None,
            "catchup_warm_h2d_bytes": None,
        }
    total_ops = n_docs * ops_per_doc

    results = {}

    def fold():
        results["out"] = svc.catch_up(doc_ids, upload=False)

    before = svc.cache.counters.snapshot()
    pair = benchmark_cold_warm(fold, name="catchup", warm_runs=2,
                               stage=svc.pipeline_stage)
    after = svc.cache.counters.snapshot()
    warm_lookups = n_docs * pair.warm_runs
    hit_rate = (after["hits"] - before["hits"]) / max(1, warm_lookups)

    # Byte identity: the warm (cached) result equals the cold fold AND
    # the CPU container oracle on sampled docs.
    sample = [doc_ids[0], doc_ids[len(doc_ids) // 2], doc_ids[-1]]
    for doc_id in sample:
        handle, _seq = results["out"][doc_id]
        assert handle == catchup_oracle_digest(service, doc_id), (
            f"catchup cache: {doc_id} cached fold != container oracle"
        )
    out = {
        "catchup_docs": n_docs,
        "catchup_cold_ops_per_sec": round(total_ops / pair.cold_s, 1),
        "catchup_warm_ops_per_sec": round(total_ops / pair.warm_s, 1),
        "catchup_warm_speedup": round(pair.speedup, 1),
        "cache_hit_rate": round(hit_rate, 4),
        "catchup_cache": svc.cache.stats(),
        "pack_cache": (svc._pack_cache.stats()
                       if svc._pack_cache is not None else None),
        "delta_cache": (svc.delta_cache.stats()
                        if svc.delta_cache is not None else None),
        "device_cache": (svc.device_cache.stats()
                         if svc.device_cache is not None else None),
        "catchup_stages_busy_sec": {
            k: round(v, 3) for k, v in sorted(svc.pipeline_stage.items())
            if k not in ("d2h_bytes", "h2d_bytes")
        },
        "catchup_d2h_bytes": int(svc.pipeline_stage.get("d2h_bytes", 0)),
        # Warm tier-1 hits never reach the pipeline: warm bytes must be 0
        # each way.
        "catchup_cold_d2h_bytes": pair.cold_d2h_bytes,
        "catchup_warm_d2h_bytes": pair.warm_d2h_bytes,
        "catchup_h2d_bytes": int(svc.pipeline_stage.get("h2d_bytes", 0)),
        "catchup_cold_h2d_bytes": pair.cold_h2d_bytes,
        "catchup_warm_h2d_bytes": pair.warm_h2d_bytes,
    }
    print(f"catchup cache: {pair.report()} | hit rate {hit_rate:.3f}",
          file=sys.stderr)
    return out


# Delta-download (tier 0) workload knobs: a full-scale corpus whose tails
# grow on a fraction of documents between the cold fill and the warm
# re-fold — the steady maintenance shape where corpus size >> churn.
DELTA_DOCS = int(os.environ.get("BENCH_DELTA_DOCS", str(N_DOCS)))
DELTA_GROW_EVERY = int(os.environ.get("BENCH_DELTA_GROW_EVERY", "8"))


def run_delta_download_bench(n_docs: int, ops_per_doc: int) -> dict:
    """Warm grown-tail maintenance at full scale, BOTH link directions
    (ISSUE 6 d2h + ISSUE 13 h2d): fold a tokened message-list corpus
    cold (tiers 0/2/2.5 fill), grow every Nth document's tail, then
    re-fold warm twice — once with the cache stack ON (digest plane +
    changed rows only cross d2h; resident buffers + donated suffix
    splices keep the upload to the new rows) and once with it OFF (the
    full-transfer reference) — asserting the two runs are byte-identical
    and reporting the byte and busy-second drop each way."""
    from fluidframework_tpu.ops.device_cache import DevicePackCache
    from fluidframework_tpu.ops.pipeline import (
        PackCache,
        pipelined_mergetree_replay,
    )
    from fluidframework_tpu.service.catchup_cache import DeltaExportCache

    base_ops = max(2, (ops_per_doc * 5) // 6)
    streams = [doc_ops(synth_doc(i, ops_per_doc)) for i in range(n_docs)]

    def window(i, n_ops):
        msgs = streams[i][:n_ops]
        return MergeTreeDocInput(
            doc_id=f"ddoc{i}", ops=msgs, final_seq=msgs[-1].seq,
            final_msn=0, cache_token=("bench-epoch", f"ddoc{i}", 0, ""),
        )

    docs_base = [window(i, base_ops) for i in range(n_docs)]
    grown_idx = set(range(0, n_docs, max(1, DELTA_GROW_EVERY)))
    docs_grown = [
        window(i, ops_per_doc if i in grown_idx else base_ops)
        for i in range(n_docs)
    ]

    def one_pass(docs, delta_cache, pack_cache, device_cache=None):
        stage = {"d2h_bytes": 0, "h2d_bytes": 0}
        stats: dict = {}
        t0 = time.time()
        summaries = pipelined_mergetree_replay(
            docs, chunk_docs=CHUNK_DOCS, pack_threads=PACK_THREADS,
            extract_threads=EXTRACT_THREADS, stage=stage, stats=stats,
            delta_cache=delta_cache, pack_cache=pack_cache,
            device_cache=device_cache,
        )
        return summaries, stage, stats, time.time() - t0

    # BOTH warm runs ride an identically-warmed pack cache, so the fold
    # configuration (suffix-extended packs — whose arena-tail offsets
    # legitimately force the wide export layout at full scale) is the
    # same and ONLY the transfer policy differs; the reference would
    # otherwise fresh-pack narrow and the byte comparison would measure
    # the transfer encoding, not the cache tiers.
    delta, pack, dev = DeltaExportCache(), PackCache(), DevicePackCache()
    full_pack = PackCache()
    _cold, stage_cold, _st, cold_wall = one_pass(docs_base, delta, pack,
                                                 dev)
    one_pass(docs_base, None, full_pack)
    warm, stage_delta, stats_delta, delta_wall = one_pass(
        docs_grown, delta, pack, dev)
    full, stage_full, _st2, full_wall = one_pass(
        docs_grown, None, full_pack)
    assert [s.digest() for s in warm] == [s.digest() for s in full], (
        "delta-download summaries != full-download summaries"
    )
    reduction = stage_full["d2h_bytes"] / max(1, stage_delta["d2h_bytes"])
    h2d_reduction = stage_full["h2d_bytes"] / max(
        1, stage_delta["h2d_bytes"])
    out = {
        "delta_docs_total": n_docs,
        "delta_docs_grown": len(grown_idx),
        "delta_base_ops": base_ops,
        "delta_d2h_bytes_full": int(stage_full["d2h_bytes"]),
        "delta_d2h_bytes_delta": int(stage_delta["d2h_bytes"]),
        "delta_d2h_reduction": round(reduction, 2),
        # The upload mirror (tier 2.5): full re-upload vs resident
        # buffers + donated suffix splices on the same warm corpus.
        "resident_h2d_bytes_full": int(stage_full["h2d_bytes"]),
        "resident_h2d_bytes_delta": int(stage_delta["h2d_bytes"]),
        "resident_h2d_reduction": round(h2d_reduction, 2),
        "delta_docs_served": stats_delta.get("delta_docs", 0),
        "delta_warm_wall_sec": round(delta_wall, 3),
        "delta_full_wall_sec": round(full_wall, 3),
        "delta_cold_wall_sec": round(cold_wall, 3),
        "delta_stages_busy_sec": {
            k: round(v, 3) for k, v in sorted(stage_delta.items())
            if k not in ("d2h_bytes", "h2d_bytes")
        },
        "delta_full_stages_busy_sec": {
            k: round(v, 3) for k, v in sorted(stage_full.items())
            if k not in ("d2h_bytes", "h2d_bytes")
        },
        "delta_cache_stats": delta.stats(),
        "device_cache_stats": dev.stats(),
    }
    print(
        f"delta download: d2h {stage_full['d2h_bytes']/1e6:.1f} MB full "
        f"-> {stage_delta['d2h_bytes']/1e6:.2f} MB delta "
        f"({reduction:.1f}x less), {stats_delta.get('delta_docs', 0)}"
        f"/{n_docs} docs served without download | resident upload: h2d "
        f"{stage_full['h2d_bytes']/1e6:.1f} MB full -> "
        f"{stage_delta['h2d_bytes']/1e6:.2f} MB "
        f"({h2d_reduction:.1f}x less)",
        file=sys.stderr,
    )
    return out
# Coarse progress marker the run updates as it goes; the deadline watchdog
# embeds it in the skip JSON so a wedge DURING the byte-identity
# verification is distinguishable from a wedge during transfers (a skip
# that interrupted verification must not read as a clean environmental
# skip — ADVICE r4).
CURRENT_PHASE = {"phase": "init"}
# Global wall-clock ceiling for the whole bench: past this a watchdog emits
# the skip JSON and hard-exits, so a tunnel that wedges MID-run (observed:
# np.asarray hanging indefinitely on d2h) still yields a parseable artifact.
BENCH_DEADLINE_SEC = float(os.environ.get("BENCH_DEADLINE", "2700"))


def _emit_skip(reason: str, detail: dict | None = None,
               metric: str = METRIC_NAME,
               base: dict | None = None) -> None:
    """The one JSON line for a run that could not produce a number.

    Keeps the driver artifact parseable (VERDICT r3 item 2): rc=0, same
    metric name, explicit ``skipped`` marker plus whatever diagnostics were
    gathered before the failure."""
    line = {"metric": metric}
    line.update(base if base is not None
                else {"value": None, "unit": "ops/sec",
                      "vs_baseline": None,
                      # Schema-stable fields: consumers diffing artifacts
                      # across rounds always find them (null = the run
                      # never reached that phase).
                      "cache_hit_rate": None,
                      "d2h_bytes": None,
                      "h2d_bytes": None,
                      "delta_d2h_reduction": None,
                      "resident_h2d_reduction": None})
    line["skipped"] = reason
    line.update(detail or {})
    print(json.dumps(line), flush=True)


def run_hardened(metric: str, run_fn, deadline: float,
                 skip_base: dict | None = None) -> None:
    """Environment-hardened bench entry shared by bench.py and
    tools/bench_configs.py: exactly ONE JSON line reaches stdout, always.

    - dead backend → ``skipped: backend-unavailable``, rc 0;
    - wall-clock past ``deadline`` (mid-run tunnel wedge) → watchdog emits
      ``skipped: deadline-exceeded`` and hard-exits 0;
    - AssertionError (byte-identity broken) → ``correctness-failure``,
      rc 1 — a wrong-bytes run must never read as a sick environment;
    - other exceptions → ``runtime-error`` rc 0 when environmental
      (connection/jax/backend), else ``bench-bug`` rc 1.

    ``run_fn(probe) -> dict`` RETURNS the success line's payload instead
    of printing it: emission happens here under one lock shared with the
    watchdog, so a late-firing timer can never double-print or flip a
    nonzero exit into 0."""
    probe = _backend_probe()
    if not probe["ok"]:
        print(f"backend probe FAILED: {probe}", file=sys.stderr)
        _emit_skip(
            "backend-unavailable",
            {"probe": {k: v for k, v in probe.items() if k != "ok"}},
            metric=metric, base=skip_base,
        )
        return
    print(f"backend probe: {probe}", file=sys.stderr)
    if os.environ.get("FF_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["FF_BENCH_PLATFORM"])

    lock = threading.Lock()
    spoken = [False]

    def _say(fn) -> bool:
        """Run one emission exactly once across main thread + watchdog."""
        with lock:
            if spoken[0]:
                return False
            spoken[0] = True
            fn()
            return True

    def _deadline() -> None:
        if _say(lambda: _emit_skip(
                "deadline-exceeded",
                {"probe": probe, "deadline_sec": deadline,
                 "phase_at_deadline": CURRENT_PHASE["phase"]},
                metric=metric, base=skip_base)):
            print(f"BENCH DEADLINE ({deadline:.0f}s) exceeded", file=sys.stderr)
            sys.stderr.flush()
            os._exit(0)

    watchdog = threading.Timer(deadline, _deadline)
    watchdog.daemon = True
    watchdog.start()
    rc = 0
    try:
        result = run_fn(probe)
        _say(lambda: print(json.dumps(result), flush=True))
    except AssertionError:
        import traceback

        tb = traceback.format_exc()
        print(tb, file=sys.stderr)
        if _say(lambda: _emit_skip(
                "correctness-failure", {"probe": probe,
                                        "error_tail": tb[-800:]},
                metric=metric, base=skip_base)):
            rc = 1
    except Exception as exc:
        import traceback

        tb = traceback.format_exc()
        print(tb, file=sys.stderr)
        # Narrow on purpose: FileNotFoundError/PermissionError etc. are
        # OSError subclasses but indicate bench bugs, not a sick tunnel.
        # Classification is TYPE-based (ADVICE r4: a genuine bench bug
        # whose message merely mentions 'backend' must not read as a sick
        # environment); the one RuntimeError carve-out is jax's own
        # backend-init failure, matched on its known prefix.
        environmental = (
            isinstance(exc, (ConnectionError, TimeoutError,
                             jax.errors.JaxRuntimeError))
            or (isinstance(exc, RuntimeError)
                and str(exc).startswith("Unable to initialize backend"))
        )
        reason = "runtime-error" if environmental else "bench-bug"
        if _say(lambda: _emit_skip(reason, {"probe": probe,
                                            "error_tail": tb[-800:]},
                                   metric=metric, base=skip_base)):
            rc = 0 if environmental else 1
    finally:
        watchdog.cancel()
    if rc:
        sys.exit(rc)


def _backend_probe() -> dict:
    """Timeboxed SUBPROCESS probe of backend init before the parent touches
    jax: a wedged axon tunnel can hang ``jax.devices()`` indefinitely
    (observed in prior sessions — BASELINE.md), and a parent-side hang is
    unrecoverable.  The child inits the backend and runs one tiny jit; the
    parent gets (ok, diagnostics) either way.

    ``FF_BENCH_PLATFORM`` forces a platform via jax.config.update in BOTH
    child and parent (the axon sitecustomize force-sets JAX_PLATFORMS at
    interpreter startup, so the env var alone loses) — used by tests to
    simulate an unavailable backend and by operators to run the bench on
    cpu explicitly."""
    import subprocess

    code = (
        "import os, time, jax\n"
        "plat = os.environ.get('FF_BENCH_PLATFORM')\n"
        "if plat: jax.config.update('jax_platforms', plat)\n"
        "t0 = time.time()\n"
        "devs = jax.devices()\n"
        "t_init = time.time() - t0\n"
        "import jax.numpy as jnp\n"
        "t0 = time.time()\n"
        "jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones((8,))))\n"
        "t_exec = time.time() - t0\n"
        "kind = getattr(devs[0], 'device_kind', '?').replace(' ', '_')\n"
        "print('PROBE-OK %s %d %.2f %.2f %s' % "
        "(devs[0].platform, len(devs), t_init, t_exec, kind))\n"
    )
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
        )
        out = proc.stdout.strip().splitlines()
        ok = proc.returncode == 0 and any(
            ln.startswith("PROBE-OK") for ln in out
        )
        tail = (proc.stderr or proc.stdout)[-800:]
    except subprocess.TimeoutExpired as e:
        ok = False
        raw = e.stderr or e.stdout or b""
        if isinstance(raw, bytes):  # TimeoutExpired ignores text=True
            raw = raw.decode("utf-8", errors="replace")
        tail = f"probe timed out after {timeout:.0f}s: {raw[-400:]}"
    info = {"ok": ok, "probe_sec": round(time.time() - t0, 1)}
    if ok:
        fields = next(
            ln for ln in out if ln.startswith("PROBE-OK")
        ).split()
        info.update(
            platform=fields[1], n_devices=int(fields[2]),
            init_sec=float(fields[3]), first_exec_sec=float(fields[4]),
            device_kind=fields[5] if len(fields) > 5 else "?",
        )
    else:
        info["error_tail"] = tail
    return info


def _forced_layout_canary() -> None:
    """Compile-and-fetch a TINY forced-layout program in a SUBPROCESS with
    a timeout before the warmup compiles the real one.  If the canary
    hangs or fails (an unhealthy tunnel can wedge on layout-constrained
    compilation), flip the kill switch so the run completes without the
    forced-layout fetch optimization instead of hanging the whole bench."""
    import subprocess

    if os.environ.get("FF_NO_FORCED_LAYOUT"):
        return
    # Run BEFORE the parent touches the backend: on exclusive-ownership
    # TPU runtimes the subprocess must be able to acquire the device.
    code = (
        "import os, jax, jax.numpy as jnp\n"
        "import sys\n"
        "plat = os.environ.get('FF_BENCH_PLATFORM')\n"
        "if plat: jax.config.update('jax_platforms', plat)\n"
        "sys.exit(0) if jax.default_backend() == 'cpu' else None\n"
        "from jax.experimental.layout import Format, Layout\n"
        "from jax.sharding import SingleDeviceSharding\n"
        "import numpy as np\n"
        "fmt = Format(Layout(major_to_minor=(0, 1, 2)),"
        " SingleDeviceSharding(jax.devices()[0]))\n"
        "f = jax.jit(lambda x: x * 2, out_shardings=fmt)\n"
        "out = np.asarray(f(jnp.ones((4, 4, 8), jnp.int16)))\n"
        "assert out[0, 0, 0] == 2\n"
        "print('canary-ok')\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=180,
        )
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        os.environ["FF_NO_FORCED_LAYOUT"] = "1"
        print("forced-layout canary FAILED; running without the "
              "layout-forced fetch", file=sys.stderr)


def _pallas_canary() -> dict | None:
    """First-Mosaic-compile measurement of the Pallas VMEM-resident fold
    (VERDICT r4 item 2), in a SUBPROCESS before the parent touches the
    backend (exclusive-ownership TPU runtimes) so a Mosaic crash or hang
    can never take the main bench down with it.  Returns a dict for the
    JSON line: compile outcome, fold rates (pallas vs scan, same chunk),
    and array parity — or the captured error."""
    import subprocess

    if os.environ.get("FF_NO_PALLAS_CANARY"):
        return None
    code = r"""
import json, os, sys, time
import jax
plat = os.environ.get('FF_BENCH_PLATFORM')
if plat: jax.config.update('jax_platforms', plat)
if jax.default_backend() == 'cpu':
    print(json.dumps({'skipped': 'cpu-backend'})); sys.exit(0)
import numpy as np
import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    pack_mergetree_batch, replay_vmapped)
from fluidframework_tpu.ops.pallas_fold import replay_vmapped_pallas
D, OPS = 1024, 96
docs = [bench.synth_doc(i, OPS) for i in range(D)]
state, ops, meta = pack_mergetree_batch(docs)
out = {'docs': D, 'ops_per_doc': OPS, 'S': int(state.tstart.shape[1])}
scan = jax.jit(replay_vmapped)
t0 = time.time()
final_scan = scan(state, ops)
jax.block_until_ready(final_scan)
out['scan_compile_sec'] = round(time.time() - t0, 1)
best = float('inf')
for _ in range(3):
    t0 = time.time()
    jax.block_until_ready(scan(state, ops))
    best = min(best, time.time() - t0)
out['scan_fold_ops_per_sec'] = round(D * OPS / best, 1)
try:
    t0 = time.time()
    final_p = replay_vmapped_pallas(state, ops, interpret=False)
    jax.block_until_ready(final_p)
    out['mosaic_compile_ok'] = True
    out['pallas_compile_sec'] = round(time.time() - t0, 1)
except Exception:
    import traceback
    out['mosaic_compile_ok'] = False
    out['error_tail'] = traceback.format_exc()[-800:]
    print(json.dumps(out)); sys.exit(0)
best = float('inf')
for _ in range(3):
    t0 = time.time()
    jax.block_until_ready(replay_vmapped_pallas(state, ops, interpret=False))
    best = min(best, time.time() - t0)
out['pallas_fold_ops_per_sec'] = round(D * OPS / best, 1)
n = np.asarray(final_scan.n)
slot = np.arange(final_scan.tstart.shape[1])[None, :]
mask = slot < n[:, None]
parity = bool(np.array_equal(n, np.asarray(final_p.n)))
for field in final_scan._fields:
    if field in ('n', 'overflow'):
        continue
    av = np.asarray(getattr(final_scan, field))
    bv = np.asarray(getattr(final_p, field))
    m = mask[:, :, None] if av.ndim == 3 else mask
    if not np.array_equal(np.where(m, av, 0), np.where(m, bv, 0)):
        parity = False
        out.setdefault('parity_mismatch_fields', []).append(field)
out['parity_ok'] = parity
print(json.dumps(out))
"""
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=float(os.environ.get("FF_PALLAS_CANARY_TIMEOUT", "420")),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        result = json.loads(lines[-1]) if lines else {
            "error": f"no output rc={proc.returncode}",
            "error_tail": (proc.stderr or "")[-800:],
        }
    except subprocess.TimeoutExpired:
        result = {"error": "timeout (Mosaic compile or run wedged)"}
    except (json.JSONDecodeError, ValueError) as exc:
        result = {"error": f"unparseable canary output: {exc}"}
    result["canary_sec"] = round(time.time() - t0, 1)
    print(f"pallas canary: {result}", file=sys.stderr)
    return result


# Peak single-chip HBM bandwidth by device kind (GB/s), for the roofline.
# Source: public TPU spec sheets; unknown kinds fall back to v5e.
HBM_GBPS = {
    "TPU_v4": 1228.0,
    "TPU_v5_lite": 819.0,
    "TPU_v5e": 819.0,
    "TPU_v5p": 2765.0,
    "TPU_v5": 2765.0,
    "TPU_v6_lite": 1640.0,
    "TPU_v6e": 1640.0,
}


def roofline(S: int, K: int, device_kind: str) -> dict:
    """HBM roofline for the merge-tree fold (VERDICT r3 item 5).

    The scan's carried state per document is 12 int32 [S] columns plus an
    [S, K] int32 props plane; each scan step (one applied op per doc under
    vmap) must stream that state out of HBM and write it back at least
    once — the op row itself is negligible.  So the OPTIMISTIC (perfect
    XLA fusion into one read + one write pass per step) bytes-per-op is

        bytes_per_op = 2 * S * (12 + K) * 4

    and the bandwidth-bound rate is HBM_GBps / bytes_per_op.  The real
    kernel makes several masked passes per step (two boundary splits each
    shuffling every column, the visible-length prefix sums, the stamp
    selects), so measured/bound below ~30% can still mean "fused about as
    well as the pass structure allows"; the number's job is to separate a
    kernel-shaped problem (low pct AND healthy link) from a link-shaped
    one (VERDICT r3: 'fast or just correct' must be answerable)."""
    gbps = HBM_GBPS.get(device_kind, 819.0)
    bytes_per_op = 2 * S * (12 + K) * 4
    return {
        "S": S,
        "props_plane_K": K,
        "bytes_per_op_optimistic": bytes_per_op,
        "hbm_GBps": gbps,
        "device_kind": device_kind,
        "bound_ops_per_sec": round(gbps * 1e9 / bytes_per_op, 1),
    }


def exec_latency_probe() -> float:
    """Best-of-3 trivial-program round trip — re-run AFTER the e2e to
    detect the axon client's persistent degraded mode (BASELINE.md
    round 5: post-e2e exec latency jumped 0.1 ms → 70-90 ms under the
    legacy pipeline's concurrent fetch+dispatch; the A/B between
    pipelines is decided by this number)."""
    tiny = jax.jit(lambda x: x * 2)
    h = jax.device_put(np.zeros((1,), np.int32))
    jax.block_until_ready(tiny(h))  # compile/warm outside the timing
    lat = float("inf")
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(tiny(h))
        lat = min(lat, time.time() - t0)
    return lat


def link_microbench() -> dict:
    """Measure the host↔device link in-run: per-RPC latency (best of 3
    one-element round trips) and MB/s each way on a 16MB default-layout
    buffer.  Bandwidth subtracts the latency floor but never more than 80%
    of the measured transfer time, so a jittery latency sample cannot
    inflate MB/s to absurdity."""
    small = np.zeros((1,), np.int32)
    big = np.zeros((4 << 20,), np.int32)  # 16 MiB
    np.asarray(jax.device_put(small))  # warm the path
    lat_up = lat_down = float("inf")
    for _ in range(3):
        t0 = time.time()
        h = jax.device_put(small)
        jax.block_until_ready(h)
        lat_up = min(lat_up, time.time() - t0)
        t0 = time.time()
        np.asarray(h)
        lat_down = min(lat_down, time.time() - t0)
    # Trivial-program execution latency: separates a sick COMPUTE path
    # (dispatch/executor degradation) from a sick TRANSFER path when the
    # fold rate collapses — without this the two are indistinguishable in
    # the stage breakdown.
    lat_exec = exec_latency_probe()
    t0 = time.time()
    hb = jax.device_put(big)
    jax.block_until_ready(hb)
    up = time.time() - t0
    t0 = time.time()
    np.asarray(hb)
    down = time.time() - t0
    mb = big.nbytes / 1e6
    return {
        "rpc_latency_up_s": round(lat_up, 4),
        "rpc_latency_down_s": round(lat_down, 4),
        "exec_latency_s": round(lat_exec, 6),
        "h2d_MBps": round(mb / max(up - lat_up, up * 0.2, 1e-9), 1),
        "d2h_MBps": round(mb / max(down - lat_down, down * 0.2, 1e-9), 1),
    }


def run_e2e(docs):
    """Pipelined end-to-end: returns
    (summaries, stats, stage_times, wall, packed_chunks).

    Stage times are per-stage BUSY seconds (they overlap); ``wall`` is the
    honest end-to-end wall-clock the throughput number uses.
    ``packed_chunks`` [(state_or_None, ops, meta, S)] lets the
    steady-fold section reuse the pack work (warm chunks keep their base
    state so the re-timed fold runs the e2e's own executable).

    Two pipeline shapes, selected by ``BENCH_E2E_PIPELINE``:

    - ``single-device-thread`` (default): ALL device interaction —
      dispatch, async copy-to-host, blocking fetch — happens on the
      calling thread; worker pools only pack (C++, GIL-released) and
      extract (ditto).  Round 5's TPU window showed the legacy shape's
      concurrent dispatch (packer thread) + blocking ``np.asarray``
      (downloader thread) flips the axon client into a persistent
      degraded mode (~3.66 s/chunk on a fold a clean process runs in
      ~0.2 ms; exec latency 70–90 ms after the flip — BASELINE.md
      round-5 status).  Overlap is preserved without a second device
      thread: dispatch is async, ``copy_to_host_async`` starts the d2h
      transfer at dispatch time, and the blocking fetch trails
      ``BENCH_FETCH_DEPTH`` chunks behind the dispatch front.
    - ``legacy``: the round-2..4 three-thread shape (packer dispatches,
      downloader fetches concurrently), kept for hardware A/B."""
    if os.environ.get("BENCH_E2E_PIPELINE", "").lower() == "legacy":
        return _run_e2e_legacy(docs)
    return _run_e2e_single_device_thread(docs)


def _run_e2e_single_device_thread(docs):
    """The PRODUCT pipeline (fluidframework_tpu.ops.pipeline) with the
    bench's instrumentation hooks attached — the harness measures the
    same code the catch-up service runs, not a private copy of it."""
    from fluidframework_tpu.ops.pipeline import pipelined_mergetree_replay

    stage = {"pack": 0.0, "dispatch": 0.0, "upload": 0.0,
             "device_wait": 0.0, "download": 0.0, "extract": 0.0,
             "d2h_bytes": 0, "h2d_bytes": 0}
    packed_chunks: list = []
    stats: dict = {}
    wall0 = time.time()
    summaries = pipelined_mergetree_replay(
        docs,
        chunk_docs=CHUNK_DOCS,
        pack_threads=PACK_THREADS,
        extract_threads=EXTRACT_THREADS,
        fetch_depth=int(os.environ.get("BENCH_FETCH_DEPTH", "2")),
        schedule=True,
        stats=stats,
        stage=stage,
        packed_out=packed_chunks,
    )
    return summaries, stats, stage, time.time() - wall0, packed_chunks


def _run_e2e_legacy(docs):
    """The round-2..4 three-thread pipeline (packer thread dispatches,
    downloader thread fetches concurrently) — kept for hardware A/B
    against the single-device-thread default.  A failure in any stage
    sets ``abort`` so the other stages unblock from their bounded queues
    and the first error re-raises in the caller instead of
    deadlocking."""
    stage = {"pack": 0.0, "dispatch": 0.0, "upload": 0.0,
             "device_wait": 0.0, "download": 0.0, "extract": 0.0,
             "d2h_bytes": 0, "h2d_bytes": 0}
    folded: queue.Queue = queue.Queue(maxsize=3)
    downloaded: queue.Queue = queue.Queue(maxsize=3)
    errors = []
    abort = threading.Event()
    packed_chunks = []

    def put(q, item) -> bool:
        while not abort.is_set():
            try:
                q.put(item, timeout=0.25)
                return True
            except queue.Full:
                continue
        return False

    def get(q):
        while True:
            try:
                return q.get(timeout=0.25)
            except queue.Empty:
                if abort.is_set():
                    return None

    def pack_one(lo):
        t0 = time.time()
        state, ops, meta = pack_mergetree_batch(docs[lo:lo + CHUNK_DOCS])
        # Narrow on the pack thread (the product pipeline's split) so the
        # dispatch leg can count the h2d bytes that really cross.
        from fluidframework_tpu.ops.mergetree_kernel import (
            narrow_ops_for_upload,
        )

        ops = narrow_ops_for_upload(ops, meta)
        return state, ops, meta, time.time() - t0

    def packer():
        # Packing is parallel across chunks (the C++ row-filling releases
        # the GIL), dispatch stays in submission order.  At 50× the whole
        # pipeline budget is under a second — a single-threaded pack stage
        # alone would exceed it.  Submission rides a bounded sliding
        # window so in-flight packed chunks stay capped (backpressure from
        # the downstream queues) and an abort only waits out the ≤
        # PACK_THREADS packs already running, cancelling the rest.
        import collections
        from concurrent.futures import ThreadPoolExecutor

        starts = list(range(0, len(docs), CHUNK_DOCS))
        window = PACK_THREADS + 1
        futs: collections.deque = collections.deque()
        try:
            with ThreadPoolExecutor(max_workers=PACK_THREADS) as pool:
                try:
                    next_i = 0
                    while next_i < len(starts) and len(futs) < window:
                        futs.append(pool.submit(pack_one, starts[next_i]))
                        next_i += 1
                    while futs:
                        fut = futs.popleft()
                        state, ops, meta, dt = fut.result()
                        if next_i < len(starts):
                            futs.append(
                                pool.submit(pack_one, starts[next_i])
                            )
                            next_i += 1
                        stage["pack"] += dt  # busy (overlapped) seconds
                        t0 = time.time()
                        S = state.tstart.shape[1]
                        stage["h2d_bytes"] += int(sum(
                            np.asarray(x).nbytes for x in ops))
                        ex = replay_export(None, ops, meta, S=S)
                        stage["dispatch"] += time.time() - t0
                        packed_chunks.append((None, ops, meta, S))
                        if not put(folded, (meta, ex)):
                            return
                finally:
                    # Cancel BEFORE the pool context exits — shutdown
                    # waits for queued futures, so cancelling after it
                    # would be dead code and delay error surfacing.
                    for f in futs:
                        f.cancel()
        except BaseException as e:  # surface in main thread
            errors.append(e)
            abort.set()
        finally:
            put(folded, None)

    def downloader():
        try:
            while True:
                item = get(folded)
                if item is None:
                    break
                meta, ex = item
                # Honest split (mirrors the product pipeline): wait for
                # device completion first, so "download" times the copy.
                t0 = time.time()
                jax.block_until_ready(ex)
                stage["device_wait"] += time.time() - t0
                t0 = time.time()
                arr = export_to_numpy(ex)  # the D2H link RPC(s)
                stage["download"] += time.time() - t0
                stage["d2h_bytes"] += int(sum(
                    a.nbytes for a in
                    (arr if isinstance(arr, tuple) else (arr,))))
                if not put(downloaded, (meta, arr)):
                    break
        except BaseException as e:
            errors.append(e)
            abort.set()
        finally:
            put(downloaded, None)

    tp = threading.Thread(target=packer, daemon=True)
    td = threading.Thread(target=downloader, daemon=True)
    wall0 = time.time()
    tp.start()
    td.start()
    summaries, stats = [], {}

    def extract_one(meta, arr):
        t0 = time.time()
        st: dict = {}
        res = summaries_from_export(meta, arr, stats=st)
        return res, st, time.time() - t0

    import collections
    from concurrent.futures import ThreadPoolExecutor

    futures: collections.deque = collections.deque()

    def collect(fut) -> None:
        res, st, dt = fut.result()
        summaries.extend(res)
        stage["extract"] += dt  # busy (overlapped) seconds
        for k, v in st.items():
            stats[k] = stats.get(k, 0) + v

    try:
        # Extraction fans out across chunks (the C++ extractor releases
        # the GIL) through a BOUNDED sliding window (same shape as the
        # packer's): in-flight chunk buffers stay capped — preserving the
        # queue's backpressure — and an extraction error aborts within a
        # window, not after the whole stream.  Collection order = submit
        # order, so the summary list stays chunk-ordered.
        with ThreadPoolExecutor(max_workers=EXTRACT_THREADS) as pool:
            window = EXTRACT_THREADS + 1
            while True:
                item = get(downloaded)
                if item is None:
                    break
                meta, arr = item
                futures.append(pool.submit(extract_one, meta, arr))
                if len(futures) >= window:
                    collect(futures.popleft())
            while futures:
                collect(futures.popleft())
    except BaseException as e:
        errors.append(e)
        abort.set()
        # An extraction error must not wait out the queued window on pool
        # shutdown (mirrors the packer's cancel-before-exit discipline).
        for f in futures:
            f.cancel()
        raise
    finally:
        if errors:
            abort.set()
        tp.join()
        td.join()
    if errors:
        raise errors[0]
    return summaries, stats, stage, time.time() - wall0, packed_chunks


def main() -> None:
    run_hardened(METRIC_NAME, _run_bench, BENCH_DEADLINE_SEC)


def _run_bench(probe: dict) -> dict:
    # Both canaries run as subprocesses BEFORE any parent-side backend
    # init (exclusive-ownership TPU runtimes).
    CURRENT_PHASE["phase"] = "pallas-canary"
    pallas = (
        _pallas_canary()
        if probe.get("platform") in ("tpu", "axon") else None
    )
    CURRENT_PHASE["phase"] = "generate"
    _forced_layout_canary()
    t0 = time.time()
    docs = [synth_doc(d, OPS_PER_DOC) for d in range(N_DOCS)]
    total_ops = N_DOCS * OPS_PER_DOC
    print(
        f"generated {N_DOCS} docs x {OPS_PER_DOC} ops in {time.time()-t0:.1f}s "
        f"(backend={jax.default_backend()}, "
        f"native={'yes' if load_library() is not None else 'NO'})",
        file=sys.stderr,
    )

    # --- CPU oracle baseline (the 1x denominator; definition pinned in
    # BASELINE.md: per-op SharedString.process over the same streams) ---
    CURRENT_PHASE["phase"] = "oracle"
    t0 = time.time()
    for doc in docs[:CPU_SAMPLE_DOCS]:
        oracle_replay(doc)
    cpu_time = time.time() - t0
    cpu_ops_per_sec = CPU_SAMPLE_DOCS * OPS_PER_DOC / cpu_time
    print(
        f"cpu oracle: {CPU_SAMPLE_DOCS * OPS_PER_DOC} ops in {cpu_time:.2f}s "
        f"= {cpu_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- link microbenchmark (attributes the fold-vs-e2e gap) ---
    CURRENT_PHASE["phase"] = "link-microbench"
    link = link_microbench()
    print(f"link: {link}", file=sys.stderr)

    # --- fact-homogeneous chunk schedule: group annotate-free docs
    # together so their chunks fold with the props plane traced away
    # (has_props chunk fact, ~20% fold speedup on the 70% pure-text
    # volume).  A service-side BATCHING choice, not a workload change —
    # the oracle denominator above sampled the original pinned order.
    docs_sched = sorted(docs, key=lambda d: d.binary_prop_keys is not None)

    # --- warm the compile cache outside the timed run (a fresh process
    # pays XLA compilation once; steady service operation does not).
    # Warm slices are ALIGNED TO THE E2E CHUNK GRID and cover every fact
    # signature the schedule can produce: the first chunk (props-free
    # majority), the group-boundary chunk (mixed when the pure count is
    # not a chunk multiple — without warming it, its executable would
    # compile INSIDE the timed e2e), and the last chunk (props group).
    CURRENT_PHASE["phase"] = "warm-compile"
    starts = list(range(0, len(docs_sched), CHUNK_DOCS))
    n_pure = sum(1 for d in docs_sched if d.binary_prop_keys is None)
    boundary = min((n_pure // CHUNK_DOCS) * CHUNK_DOCS, starts[-1])
    S = None
    roof_k_eff = roof_group = None
    for lo in sorted({0, boundary, starts[-1]}):
        warm_docs = docs_sched[lo:lo + CHUNK_DOCS]
        warm_state, warm_ops, warm_meta = pack_mergetree_batch(warm_docs)
        s_warm = warm_state.tstart.shape[1]
        if S is None:
            # Roofline pins the FIRST chunk's shape — the majority group
            # (props-free chunks stream no props plane: effective K = 0).
            S = s_warm
            carried = bool(warm_meta.get("has_props", True))
            roof_k_eff = int(warm_state.props.shape[-1]) if carried else 0
            roof_group = "props-carried" if carried else "props-free"
        t0 = time.time()
        jax.block_until_ready(
            replay_export(None, warm_ops, warm_meta, S=s_warm)
        )
        warm_time = time.time() - t0
        print(
            f"compile+first fold {warm_time:.1f}s "
            f"(chunk@{lo}, S={s_warm}, "
            f"i16={'yes' if warm_meta['i16_ok'] else 'no'}, "
            f"i8={'yes' if warm_meta.get('i8_ok') else 'no'}, "
            f"ob_rows={'yes' if warm_meta.get('ob_rows', True) else 'ELIDED'}, "
            f"ov_rows={'yes' if warm_meta.get('ov_rows', True) else 'ELIDED'}, "
            f"props={'carried' if warm_meta.get('has_props', True) else 'ELIDED'})",
            file=sys.stderr,
        )

    # --- HONEST END-TO-END: raw streams → host-side canonical summaries,
    # stages pipelined (see run_e2e) ---
    CURRENT_PHASE["phase"] = "e2e"
    summaries, stats, stage, e2e_time, packed_chunks = run_e2e(docs_sched)
    # Did the e2e flip the client into the degraded mode?  (The sdt
    # pipeline exists to prevent this; the legacy A/B run shows it.)
    link["exec_latency_after_e2e_s"] = round(exec_latency_probe(), 6)
    assert len(summaries) == N_DOCS
    e2e_ops_per_sec = total_ops / e2e_time
    fallbacks = stats.get("fallback_docs", 0)
    print(
        f"end-to-end {e2e_time:.2f}s = {e2e_ops_per_sec:,.0f} ops/s "
        f"(busy: pack {stage['pack']:.2f} | dispatch {stage['dispatch']:.2f}"
        f" | upload {stage.get('upload', 0.0):.2f}"
        f" | device_wait {stage['device_wait']:.2f}"
        f" | download {stage['download']:.2f} | extract+summarize "
        f"{stage['extract']:.2f} | h2d {stage['h2d_bytes']/1e6:.1f} MB"
        f" | d2h {stage['d2h_bytes']/1e6:.1f} MB)"
        f" | oracle fallbacks {fallbacks}/{N_DOCS}",
        file=sys.stderr,
    )

    # --- steady-state device fold: inputs uploaded once (device-resident,
    # reusing the e2e run's pack work), export computed but not fetched —
    # the saturated-device rate ---
    CURRENT_PHASE["phase"] = "steady-fold"
    from fluidframework_tpu.ops.mergetree_kernel import narrow_ops_for_upload

    resident = []
    upload_bytes = 0
    for chunk_state, ops, meta, s in packed_chunks:
        ops_n = narrow_ops_for_upload(ops, meta)  # same stream e2e uploads
        upload_bytes += sum(np.asarray(x).nbytes for x in ops_n)
        ops_dev = jax.device_put(ops_n)
        jax.block_until_ready(ops_dev)
        # Warm chunks re-time with their base state resident too — the
        # SAME executable the e2e dispatched, not a cold rebuild.
        state_dev = None
        if chunk_state is not None:
            state_dev = jax.device_put(chunk_state)
            jax.block_until_ready(state_dev)
            upload_bytes += sum(
                np.asarray(x).nbytes for x in chunk_state)
        resident.append((state_dev, ops_dev, meta, s))
    print(
        f"op-stream upload (narrowed where i16_ok): "
        f"{upload_bytes / 1e6:.1f} MB",
        file=sys.stderr,
    )
    fold_time = float("inf")
    for _rep in range(3):
        t0 = time.time()
        finals = [
            replay_export(state_dev, ops_dev, meta, S=s)
            for state_dev, ops_dev, meta, s in resident
        ]
        for final in finals:
            jax.block_until_ready(final)
        fold_time = min(fold_time, time.time() - t0)
    fold_ops_per_sec = total_ops / fold_time
    print(
        f"steady fold {fold_time:.3f}s = {fold_ops_per_sec:,.0f} ops/s "
        f"(device-resident inputs, export not fetched)",
        file=sys.stderr,
    )

    # --- HBM roofline: is the fold fast, or just correct? (only
    # meaningful on a real TPU; the cpu backend has no pinned HBM figure)
    roof = None
    if probe.get("platform") in ("tpu", "axon"):
        # (S, K) pinned together from the FIRST warm chunk — the majority
        # fact-group — so the bound describes a configuration that really
        # executes (K is the PADDED carried width; 0 when the props plane
        # is traced away on props-free chunks).
        roof = roofline(S, roof_k_eff, probe.get("device_kind", "?"))
        roof["group"] = roof_group
        roof["steady_fold_pct_of_bound"] = round(
            100.0 * fold_ops_per_sec / roof["bound_ops_per_sec"], 2
        )
        print(f"roofline: {roof}", file=sys.stderr)

    # --- sanity: device bytes == oracle bytes on sampled docs ---
    CURRENT_PHASE["phase"] = "verify-bytes"
    sample = [docs[0], docs[7], docs[N_DOCS // 2]]
    for doc, dev_summary in zip(sample, replay_mergetree_batch(sample)):
        assert dev_summary.digest() == oracle_replay(doc).summarize().digest(), (
            f"bench sanity: {doc.doc_id} device summary != oracle"
        )
    # and against the end-to-end pipeline output (chunk-scheduled order)
    assert summaries[0].digest() == \
        oracle_replay(docs_sched[0]).summarize().digest()
    assert summaries[-1].digest() == \
        oracle_replay(docs_sched[-1]).summarize().digest()
    print("sanity: device summaries byte-identical to oracle", file=sys.stderr)

    # --- steady-state re-catch-up (the serving shape): the same corpus
    # folded twice through the SERVICE path — cold pays pack+fold+extract,
    # warm must serve from the seq-anchored cache with zero device work.
    CURRENT_PHASE["phase"] = "catchup-cache"
    catchup = run_catchup_cache_bench(CATCHUP_DOCS, OPS_PER_DOC)

    # --- digest-gated delta download (tier 0): the warm grown-tail
    # maintenance shape — corpus size >> churn, so d2h must scale with
    # what CHANGED, not with the corpus.
    CURRENT_PHASE["phase"] = "delta-download"
    delta = run_delta_download_bench(DELTA_DOCS, OPS_PER_DOC)
    CURRENT_PHASE["phase"] = "done"

    # Returned (not printed): run_hardened emits exactly one line under
    # its watchdog lock.
    return {
        "metric": METRIC_NAME,
        "backend": probe.get("platform", "unknown"),
        "forced_layout_disabled": bool(
            os.environ.get("FF_NO_FORCED_LAYOUT")
        ),
        "value": round(e2e_ops_per_sec, 1),
        "unit": "ops/sec",
        "vs_baseline": round(e2e_ops_per_sec / cpu_ops_per_sec, 2),
        "steady_fold_ops_per_sec": round(fold_ops_per_sec, 1),
        "steady_fold_vs_baseline": round(
            fold_ops_per_sec / cpu_ops_per_sec, 2
        ),
        "cpu_baseline_ops_per_sec": round(cpu_ops_per_sec, 1),
        "roofline": roof,
        "pallas": pallas,
        "link": link,
        "stages_busy_sec": {
            "pack": round(stage["pack"], 3),
            "fold_dispatch": round(stage["dispatch"], 3),
            # Explicit resident-tier transfers only; without the tier
            # the upload rides the dispatch jit (and h2d_bytes still
            # counts the host arrays it pushes).
            "upload": round(stage.get("upload", 0.0), 3),
            # "download" used to absorb the async fold wait (CPU d2h is
            # hundreds of GB/s yet "download" read as 12 s in r05c);
            # device_wait now carries the wait, download the copy alone.
            "device_wait": round(stage["device_wait"], 3),
            "download": round(stage["download"], 3),
            "extract_summarize": round(stage["extract"], 3),
        },
        "d2h_bytes": int(stage["d2h_bytes"]),
        "h2d_bytes": int(stage["h2d_bytes"]),
        "end_to_end_sec": round(e2e_time, 3),
        "oracle_fallback_docs": fallbacks,
        **catchup,
        **delta,
        "op_upload_MB": round(upload_bytes / 1e6, 1),
        # The resolved choice — the same predicate run_e2e dispatches on.
        "e2e_pipeline": (
            "legacy"
            if os.environ.get("BENCH_E2E_PIPELINE", "").lower() == "legacy"
            else "single-device-thread"
        ),
        "n_docs": N_DOCS,
        "ops_per_doc": OPS_PER_DOC,
    }


if __name__ == "__main__":
    main()
