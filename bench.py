"""North-star benchmark: bulk SharedString catch-up replay, device vs oracle.

Workload per BASELINE.json: many documents' sequenced op tails folded to
summaries.  The CPU baseline is the oracle replay harness (BASELINE.md: the
1× denominator); the device path is the merge-tree kernel vmapped over the
document axis on whatever backend jax selects (real TPU under the driver).

Two numbers are measured and reported:
- ``value`` / ``vs_baseline``: the HONEST END-TO-END rate — wall-clock from
  raw op streams to canonical summaries materialized host-side for every
  document (pack → upload → fold → fused-export download → C++ body
  extraction), including every stage.
- ``steady_fold_ops_per_sec``: the device fold alone (compiled, resident),
  the rate a saturated pipeline approaches when host stages overlap
  back-to-back batches.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": "ops/sec", "vs_baseline": ratio,
     ...stage breakdown + fallback counts...}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

import jax
import numpy as np

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.interning import Interner
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    _replay_export_cold,
    pack_mergetree_batch,
    replay_mergetree_batch,
    summaries_from_export,
)
from fluidframework_tpu.ops.native_pack import (
    decode_string_ops,
    encode_string_ops,
    load_library,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

N_DOCS = int(os.environ.get("BENCH_DOCS", "10240"))
OPS_PER_DOC = int(os.environ.get("BENCH_OPS", "96"))
CPU_SAMPLE_DOCS = int(os.environ.get("BENCH_CPU_SAMPLE", "24"))
# Documents fold in fixed-size chunks: one compiled shape reused across
# dispatches, bounded per-transfer sizes, and the dispatch/compute balance
# measured best at 1024 docs/chunk on v5e (larger single batches degrade
# per-op throughput and >4k-doc transfers can trip device faults).
CHUNK_DOCS = int(os.environ.get("BENCH_CHUNK", "1024"))
ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def synth_doc(doc_idx: int, n_ops: int) -> MergeTreeDocInput:
    """A valid sequenced op stream: 3 clients round-robin, mixed edits.
    70% of documents are pure insert/remove text traffic; 30% carry
    annotate ops with props.  ALL streams are ingested in the native binary
    record format (annotates ride encoder-local intern tables that packing
    translates to the batch-global spaces in C++)."""
    rng = random.Random(doc_idx * 7919 + 13)
    annotating_doc = doc_idx % 10 >= 7
    ops, length = [], 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if not annotating_doc:
            r = min(r, 0.89)  # no annotates in pure-text docs
        if r < 0.62 or length < 4:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 8))
            )
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.9:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {
                "kind": "annotate", "start": start, "end": end,
                "props": {"f": rng.randint(0, 3)},
            }
        ops.append(
            SequencedMessage(
                seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
                min_seq=0, type=MessageType.OP, contents=contents,
            )
        )
    clients, keys, vals = Interner(), Interner(), Interner()
    blob = encode_string_ops(ops, clients, keys, vals)
    return MergeTreeDocInput(
        doc_id=f"doc{doc_idx}", ops=[], binary_ops=blob,
        binary_clients=list(clients.values),
        binary_prop_keys=list(keys.values) or None,
        binary_values=list(vals.values) or None,
        final_seq=n_ops, final_msn=0,
    )


def doc_ops(doc):
    return decode_string_ops(
        doc.binary_ops, list(doc.binary_clients),
        prop_keys=doc.binary_prop_keys, values=doc.binary_values,
    )


def oracle_replay(doc):
    replica = SharedString(doc.doc_id)
    for msg in doc_ops(doc):
        replica.process(msg, local=False)
    return replica


def main() -> None:
    t0 = time.time()
    docs = [synth_doc(d, OPS_PER_DOC) for d in range(N_DOCS)]
    total_ops = N_DOCS * OPS_PER_DOC
    print(
        f"generated {N_DOCS} docs x {OPS_PER_DOC} ops in {time.time()-t0:.1f}s "
        f"(backend={jax.default_backend()}, "
        f"native={'yes' if load_library() is not None else 'NO'})",
        file=sys.stderr,
    )

    # --- CPU oracle baseline (the 1x denominator, BASELINE.md) ---
    t0 = time.time()
    for doc in docs[:CPU_SAMPLE_DOCS]:
        oracle_replay(doc)
    cpu_time = time.time() - t0
    cpu_ops_per_sec = CPU_SAMPLE_DOCS * OPS_PER_DOC / cpu_time
    print(
        f"cpu oracle: {CPU_SAMPLE_DOCS * OPS_PER_DOC} ops in {cpu_time:.2f}s "
        f"= {cpu_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- warm the compile cache outside the timed run (a fresh process
    # pays XLA compilation once; steady service operation does not) ---
    warm_state, warm_ops, _ = pack_mergetree_batch(docs[:CHUNK_DOCS])
    S = warm_state.tstart.shape[1]
    t0 = time.time()
    jax.block_until_ready(_replay_export_cold(warm_ops, S))
    warm_time = time.time() - t0
    print(f"compile+first fold {warm_time:.1f}s (S={S})", file=sys.stderr)

    # --- HONEST END-TO-END: raw streams → host-side canonical summaries.
    # Stages pipeline: all folds dispatch asynchronously (device runs while
    # the host packs the next chunk); downloads then drain in order while
    # extraction of earlier chunks proceeds.
    e2e_t0 = time.time()
    pack_time = fold_dispatch_time = 0.0
    metas, exports, packed = [], [], []
    for i in range(0, len(docs), CHUNK_DOCS):
        t0 = time.time()
        state, ops, meta = pack_mergetree_batch(docs[i:i + CHUNK_DOCS])
        pack_time += time.time() - t0
        t0 = time.time()
        exports.append(_replay_export_cold(ops, state.tstart.shape[1]))
        fold_dispatch_time += time.time() - t0
        metas.append(meta)
        packed.append((state, ops))
    t0 = time.time()
    exports_np = [np.asarray(e) for e in exports]  # D2H (fused, 1/chunk)
    download_time = time.time() - t0
    t0 = time.time()
    summaries = []
    stats: dict = {}
    for meta, ex in zip(metas, exports_np):
        summaries.extend(summaries_from_export(meta, ex, stats=stats))
    extract_time = time.time() - t0
    e2e_time = time.time() - e2e_t0
    assert len(summaries) == N_DOCS
    e2e_ops_per_sec = total_ops / e2e_time
    fallbacks = stats.get("fallback_docs", 0)
    print(
        f"end-to-end {e2e_time:.2f}s = {e2e_ops_per_sec:,.0f} ops/s "
        f"(pack {pack_time:.2f} | dispatch {fold_dispatch_time:.2f} | "
        f"download {download_time:.2f} | extract+summarize "
        f"{extract_time:.2f}) | oracle fallbacks {fallbacks}/{N_DOCS}",
        file=sys.stderr,
    )

    # --- steady-state device fold (resident data, compiled; reuses the
    # packed chunks from the e2e run) ---
    fold_time = float("inf")
    for _rep in range(3):  # best-of-3: the device tunnel adds run noise
        t0 = time.time()
        finals = [
            _replay_export_cold(ops, state.tstart.shape[1])
            for state, ops in packed
        ]
        for final in finals:
            jax.block_until_ready(final)
        fold_time = min(fold_time, time.time() - t0)
    fold_ops_per_sec = total_ops / fold_time
    print(
        f"steady fold {fold_time:.3f}s = {fold_ops_per_sec:,.0f} ops/s",
        file=sys.stderr,
    )

    # --- sanity: device bytes == oracle bytes on sampled docs ---
    sample = [docs[0], docs[7], docs[N_DOCS // 2]]
    for doc, dev_summary in zip(sample, replay_mergetree_batch(sample)):
        assert dev_summary.digest() == oracle_replay(doc).summarize().digest(), (
            f"bench sanity: {doc.doc_id} device summary != oracle"
        )
    # and against the end-to-end pipeline output
    assert summaries[0].digest() == oracle_replay(docs[0]).summarize().digest()
    print("sanity: device summaries byte-identical to oracle", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "sharedstring_catchup_replay_ops_per_sec",
                "value": round(e2e_ops_per_sec, 1),
                "unit": "ops/sec",
                "vs_baseline": round(e2e_ops_per_sec / cpu_ops_per_sec, 2),
                "steady_fold_ops_per_sec": round(fold_ops_per_sec, 1),
                "steady_fold_vs_baseline": round(
                    fold_ops_per_sec / cpu_ops_per_sec, 2
                ),
                "cpu_baseline_ops_per_sec": round(cpu_ops_per_sec, 1),
                "stages_sec": {
                    "pack": round(pack_time, 3),
                    "fold_dispatch": round(fold_dispatch_time, 3),
                    "download": round(download_time, 3),
                    "extract_summarize": round(extract_time, 3),
                    "end_to_end": round(e2e_time, 3),
                },
                "oracle_fallback_docs": fallbacks,
                "n_docs": N_DOCS,
                "ops_per_doc": OPS_PER_DOC,
            }
        )
    )


if __name__ == "__main__":
    main()
