"""Interval-op folding over final device merge-tree state (host side).

Interval ops (add/change/delete) are rare relative to text ops, so the device
folds only the text ops; this module folds the interval ops afterwards *over
the final device state*.  That is possible because the device keeps every
tombstone: any historical view is reconstructible from the final arrays —

- bounded visibility at fold position ``s`` for client ``c``:
  insert counts iff ``ins_seq <= ref`` or (own and ``ins_seq < s``); removal
  counts iff ``rem_seq <= ref`` or the client is a remover whose removal
  sequenced before ``s`` (the second-remover fields carry exact overlap
  timing — the reason the kernel tracks (seq, client) pairs, not a bitmask);
- reference slides replay lazily as a cascade: a ref attached at ``s`` on a
  segment removed at ``t >= s`` slides at ``t`` to the nearest segment that
  was sequenced-alive *at t* (``ins_seq < t`` and not removed before ``t``),
  repeating while the landing segment is itself removed later.  This
  reproduces the oracle's eager slide-on-remove event order exactly.

The output is the same canonical intervals blob ``SharedString.summarize()``
emits; byte-identity vs the oracle is asserted by tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..protocol.messages import SequencedMessage

NO_CLIENT_IDX = -2  # matches no per-doc client index


class FinalStateView:
    """Historical-view resolution over one document's final segment arrays."""

    def __init__(self, state_np: dict, d: int, not_removed: int) -> None:
        n = int(state_np["n"][d])
        self.n = n
        self.tlen = np.asarray(state_np["tlen"][d, :n])
        self.ins_seq = np.asarray(state_np["ins_seq"][d, :n])
        self.ins_client = np.asarray(state_np["ins_client"][d, :n])
        self.rem_seq = np.asarray(state_np["rem_seq"][d, :n])
        self.rem_client = np.asarray(state_np["rem_client"][d, :n])
        self.rem2_seq = np.asarray(state_np["rem2_seq"][d, :n])
        self.rem2_client = np.asarray(state_np["rem2_client"][d, :n])
        self.ob1_seq = np.asarray(state_np["ob1_seq"][d, :n])
        self.ob1_client = np.asarray(state_np["ob1_client"][d, :n])
        self.ob2_seq = np.asarray(state_np["ob2_seq"][d, :n])
        self.ob2_client = np.asarray(state_np["ob2_client"][d, :n])
        self.not_removed = not_removed
        self._vis_cache: Dict[tuple, np.ndarray] = {}

    # -- bounded historical views ---------------------------------------------

    def _vis_cumsum(self, ref: int, client: int, up_to: int) -> np.ndarray:
        """Inclusive cumsum of per-slot visible lengths for one bounded
        view.  A slot is visible iff its insert sequenced at or below
        ``ref`` (or is the client's own, earlier in the fold) AND no
        removal counts against the view: a removal sequenced at or below
        ``ref``, or the client's own first/second removal earlier in the
        fold (NOT_REMOVED is int32-max, so the < / <= comparisons short
        out identically to the scalar rules).  Tiny FIFO cache (2
        entries): every realizable hit is either the base view resolved
        repeatedly up front or one op's start/end pair back-to-back —
        each interval op's (ref, client, seq) key is unique, so an
        unbounded cache would retain one O(n) array per op for the
        lifetime of the extraction."""
        key = (ref, client, up_to)
        hit = self._vis_cache.get(key)
        if hit is not None:
            return hit
        if len(self._vis_cache) >= 2:
            self._vis_cache.pop(next(iter(self._vis_cache)))
        ins_vis = (self.ins_seq <= ref) | (
            (self.ins_client == client) & (self.ins_seq < up_to)
        )
        is_removed = self.rem_seq != self.not_removed
        removed = (
            (is_removed & (self.rem_seq <= ref))
            | ((self.rem_client == client) & (self.rem_seq < up_to))
            | ((self.rem2_client == client) & (self.rem2_seq < up_to))
            # Ob-stamp authors are involved in the removal (the oracle's
            # rule; kernel-side gap found at fuzz seed 1500041) — the
            # stamp itself must be sequenced before the view's fold
            # position, as must the removal.
            | (is_removed & (self.rem_seq < up_to)
               & (((self.ob1_client == client) & (self.ob1_seq < up_to))
                  | ((self.ob2_client == client) & (self.ob2_seq < up_to))))
        )
        cum = np.cumsum(np.where(ins_vis & ~removed, self.tlen, 0))
        self._vis_cache[key] = cum
        return cum

    def resolve(self, pos: int, ref: int, client: int, up_to: int):
        """View-position → (slot, offset) anchor, or None (empty view).
        Mirrors MergeTreeOracle.create_reference.  Vectorized: one
        visibility cumsum + searchsorted instead of a per-slot Python
        walk (the interval fold's hot loop — config #3)."""
        if self.n == 0:
            return None
        cum = self._vis_cumsum(ref, client, up_to)
        total = int(cum[-1])
        if pos < total:
            s = int(np.searchsorted(cum, pos, side="right"))
            return s, pos - int(cum[s - 1] if s else 0)
        if total == 0:
            return None  # empty view — nothing to anchor to
        # Past the end: anchor at the END of the LAST visible slot — the
        # first index where cum reaches total (contributions are
        # positive, so that index is the last contributor).
        s = int(np.searchsorted(cum, total - 1, side="right"))
        return s, int(self.tlen[s])

    # -- slide cascade ---------------------------------------------------------

    def _valid_at(self, s: int, t: int) -> bool:
        if self.ins_seq[s] >= t:
            return False  # not sequenced-inserted yet at t
        return self.rem_seq[s] == self.not_removed or self.rem_seq[s] > t

    def anchor_final(self, slot: int, offset: int, attach_seq: int):
        """Replay the slide cascade for a ref attached at fold position
        ``attach_seq``; returns the final (slot, offset) or None (detached)."""
        s = attach_seq
        while slot is not None and self.rem_seq[slot] != self.not_removed:
            t = max(s, int(self.rem_seq[slot]))
            target = None
            for j in range(slot + 1, self.n):
                if self._valid_at(j, t):
                    target, offset = j, 0
                    break
            if target is None:
                for j in range(slot - 1, -1, -1):
                    if self._valid_at(j, t):
                        target, offset = j, int(self.tlen[j])
                        break
            if target is None:
                return None
            slot, s = target, t
        return slot, offset

    def position(self, anchor) -> int:
        """Final sequenced-view position of an anchor (None → 0)."""
        if anchor is None:
            return 0
        slot, offset = anchor
        pos = int(
            np.sum(
                np.where(self.rem_seq[:slot] == self.not_removed,
                         self.tlen[:slot], 0)
            )
        )
        if self.rem_seq[slot] == self.not_removed:
            pos += min(offset, int(self.tlen[slot]))
        return pos


def replay_intervals(
    view: FinalStateView,
    interval_ops: Sequence[SequencedMessage],
    client_index,  # callable client_id -> per-doc idx
    base_intervals: Optional[Dict[str, dict]] = None,
    base_seq: int = 0,
) -> Dict[str, dict]:
    """Fold interval ops over the final state; returns {label: summary_obj}
    byte-compatible with IntervalCollection.summary_obj()."""
    # label -> id -> (start_ref, end_ref, props) with ref = (slot, off, seq)
    collections: Dict[str, Dict[str, list]] = {}
    for label, obj in (base_intervals or {}).items():
        coll = collections.setdefault(label, {})
        for interval_id, rec in obj.items():
            start = view.resolve(rec["start"], base_seq, NO_CLIENT_IDX, base_seq + 1)
            end = view.resolve(rec["end"], base_seq, NO_CLIENT_IDX, base_seq + 1)
            coll[interval_id] = [
                (*start, base_seq) if start else None,
                (*end, base_seq) if end else None,
                dict(rec.get("props") or {}),
            ]
    for msg in interval_ops:
        op = msg.contents
        label = op.get("label", "default")
        coll = collections.setdefault(label, {})
        interval_id = op["id"]
        kind = op["kind"]
        client = client_index(msg.client_id)

        def res(pos):
            a = view.resolve(pos, msg.ref_seq, client, msg.seq)
            return (*a, msg.seq) if a is not None else None

        if kind == "intervalAdd":
            props = {
                k: v for k, v in (op.get("props") or {}).items()
                if v is not None
            }
            coll[interval_id] = [res(op["start"]), res(op["end"]), props]
        elif kind == "intervalChange":
            iv = coll.get(interval_id)
            if iv is None:
                continue
            if op.get("start") is not None:
                iv[0] = res(op["start"])
            if op.get("end") is not None:
                iv[1] = res(op["end"])
            for key, value in (op.get("props") or {}).items():
                if value is None:
                    iv[2].pop(key, None)
                else:
                    iv[2][key] = value
        elif kind == "intervalDelete":
            coll.pop(interval_id, None)
        else:
            raise ValueError(f"unknown interval op kind {kind!r}")

    out: Dict[str, dict] = {}
    for label in sorted(collections):
        if not collections[label]:
            continue
        obj = {}
        for interval_id in sorted(collections[label]):
            start_ref, end_ref, props = collections[label][interval_id]
            rec: Dict[str, Any] = {
                "start": view.position(
                    view.anchor_final(*start_ref) if start_ref else None
                ),
                "end": view.position(
                    view.anchor_final(*end_ref) if end_ref else None
                ),
            }
            if props:
                rec["props"] = dict(sorted(props.items()))
            obj[interval_id] = rec
        if obj:
            out[label] = obj
    return out
