"""SharedTree catch-up replay on device.

Re-expresses the oracle's sequenced-forest fold (dds/tree.py
``apply_changeset``, semantics pinned by SEMANTICS.md §tree) as array
state + an edit-fold.  The id-addressed design pays off here: because edits
name node ids instead of positions, every scan step is O(1) scatter work —
no position resolution, no visible-length prefix sums:

- forest structure is a **doubly-linked sibling list per container** (a
  container = one (parent node, field) pair, interned at pack time):
  ``head[C]``, ``next[N]``, ``prev[N]``, ``node_container[N]``;
- **insert** splices a pre-materialized chain after its anchor (content
  blocks, nested children, their container heads, values, and insert seqs
  are all known at pack time — the fold only links them in);
- **remove** is a first-wins scatter into ``removed_seq``;
- **set** is an LWW scatter into ``value``/``value_seq``;
- **move** is detach + splice + seq restamp, with the cycle test (is the
  destination inside the moved subtree?) as a bounded ancestor walk.

Like the merge-tree kernel, zamboni never runs on device: tombstones keep
their slots (purge only drops state no reachable view distinguishes) and
the host-side extractor applies the same normalization the oracle's
summarizer does.  Rare shapes take the oracle path instead of being
approximated: **revive** edits (undo-of-remove — their purge-timing
interaction needs the full forest), **multi-id moves** (block-cycle
semantics), and ancestor walks deeper than ``MAX_DEPTH`` (flagged by the
device as overflow).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.messages import SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .interning import Interner, next_bucket

NOT_REMOVED = np.int32(np.iinfo(np.int32).max)
NO_VALUE = -1          # value column sentinel (interned ids are >= 0)
NIL = -1               # null node / container index

K_NOOP, K_INSERT, K_REMOVE, K_SET, K_MOVE = 0, 1, 2, 3, 4

#: Ancestor-walk budget for the move cycle test; deeper forests overflow
#: to the oracle path (never silently wrong).
MAX_DEPTH = 64


class TreeState(NamedTuple):
    """Per-document forest arrays.  ``container_parent`` is static (a
    container's owning node never changes; *nodes* move between
    containers)."""

    head: jnp.ndarray              # [C] first node idx of container / NIL
    next: jnp.ndarray              # [N]
    prev: jnp.ndarray              # [N]
    node_container: jnp.ndarray    # [N] current container / NIL (unlinked)
    container_parent: jnp.ndarray  # [C] owning node idx (0 = root) — static
    value: jnp.ndarray             # [N] interned value id / NO_VALUE
    value_seq: jnp.ndarray         # [N]
    insert_seq: jnp.ndarray        # [N] (restamped by moves)
    removed_seq: jnp.ndarray       # [N] NOT_REMOVED if alive
    overflow: jnp.ndarray          # [] bool: ancestor walk exceeded budget


class TreeEdits(NamedTuple):
    """Packed edit stream (scan xs), one row per flattened edit."""

    kind: jnp.ndarray       # [T]
    seq: jnp.ndarray        # [T]
    container: jnp.ndarray  # [T] destination container (insert/move)
    anchor: jnp.ndarray     # [T] anchor node idx / NIL = field start
    first: jnp.ndarray      # [T] block chain head (insert) / target (others)
    tail: jnp.ndarray       # [T] block chain tail (insert; == first for move)
    value: jnp.ndarray      # [T] interned value id (set)
    purge_msn: jnp.ndarray  # [T] purge boundary when this edit applies: the
    #                         max min_seq over all PRIOR messages (+ base
    #                         minSeq) — the oracle pops expired tombstones
    #                         exactly up to here before applying this edit


def _splice_after(state: TreeState, c, anchor, first, tail) -> TreeState:
    """Link chain [first..tail] into container ``c`` after ``anchor`` (or at
    head when the anchor is NIL / not currently in ``c`` — the oracle's
    deterministic fallback)."""
    use_anchor = (anchor != NIL) & (state.node_container[anchor] == c)
    old = jnp.where(use_anchor, state.next[anchor], state.head[c])
    nxt = state.next.at[tail].set(old)
    prv = state.prev
    prv = jnp.where(old != NIL, prv.at[old].set(tail), prv)
    nxt = jnp.where(use_anchor, nxt.at[anchor].set(first), nxt)
    prv = prv.at[first].set(jnp.where(use_anchor, anchor, NIL))
    head = jnp.where(
        use_anchor, state.head, state.head.at[c].set(first)
    )
    return state._replace(head=head, next=nxt, prev=prv)


def _detach(state: TreeState, target) -> TreeState:
    p, nx = state.prev[target], state.next[target]
    c = state.node_container[target]
    head = jnp.where(
        p == NIL, state.head.at[c].set(nx), state.head
    )
    nxt = jnp.where(p != NIL, state.next.at[p].set(nx), state.next)
    prv = jnp.where(nx != NIL, state.prev.at[nx].set(p), state.prev)
    return state._replace(head=head, next=nxt, prev=prv)


def _in_subtree(state: TreeState, dest_container, target):
    """Does ``dest_container`` live inside ``target``'s subtree?  Walk the
    ancestor chain container→owner-node→its-container…; root's container is
    NIL.  Returns (hit, overflowed)."""

    def step(carry, _):
        cur_node, hit, alive = carry
        hit = hit | (alive & (cur_node == target))
        c = jnp.where(alive & (cur_node != NIL),
                      state.node_container[cur_node], NIL)
        nxt_node = jnp.where(c != NIL, state.container_parent[c], NIL)
        alive = alive & (c != NIL)
        return (nxt_node, hit, alive), None

    start = state.container_parent[dest_container]
    (last, hit, alive), _ = jax.lax.scan(
        step, (start, jnp.bool_(False), jnp.bool_(True)), None,
        length=MAX_DEPTH,
    )
    return hit, alive  # still alive after MAX_DEPTH = didn't reach root


def _apply_edit(state: TreeState, e) -> TreeState:
    """One flattened edit — the scan step."""
    is_ins = e.kind == K_INSERT
    is_rem = e.kind == K_REMOVE
    is_set = e.kind == K_SET
    is_mov = e.kind == K_MOVE
    target = e.first

    def _expired(idx):
        rs = state.removed_seq[idx]
        return (rs != NOT_REMOVED) & (rs <= e.purge_msn)

    # --- insert: splice the pre-materialized chain.  A popped (expired-
    # purged) anchor falls back to field start, as the oracle's
    # contains(anchor) check does.  (Inserts into popped PARENTS are a
    # pack-time oracle fallback — their skipped content would need an
    # existence simulation here.)
    ins_anchor = jnp.where(
        (e.anchor != NIL) & _expired(e.anchor), NIL, e.anchor
    )
    ins = _splice_after(state, e.container, ins_anchor, e.first, e.tail)
    state = jax.tree.map(
        lambda new, old: jnp.where(is_ins, new, old), ins, state
    )

    # --- remove: first remover wins the tombstone.
    state = state._replace(
        removed_seq=state.removed_seq.at[target].set(
            jnp.where(
                is_rem & (state.removed_seq[target] == NOT_REMOVED),
                e.seq, state.removed_seq[target],
            )
        )
    )

    # --- set: LWW by fold order.
    state = state._replace(
        value=state.value.at[target].set(
            jnp.where(is_set, e.value, state.value[target])
        ),
        value_seq=state.value_seq.at[target].set(
            jnp.where(is_set, e.seq, state.value_seq[target])
        ),
    )

    # --- move: purge gates + cycle test, detach, splice, restamp.
    # The oracle pops expired tombstones before applying this edit; a move
    # whose TARGET was popped, or whose destination PARENT was popped, is a
    # no-op there and must be here (ids referencing live limbo nodes still
    # move — that's the rescue path).
    hit, deep = _in_subtree(state, e.container, target)
    dest_owner = state.container_parent[e.container]
    do_move = is_mov & ~hit & ~_expired(target) & ~_expired(dest_owner)
    anchor = jnp.where(e.anchor == target, NIL, e.anchor)
    # A popped anchor falls back to field start (the oracle's
    # contains(anchor) check); a live limbo anchor keeps the same fallback
    # via the not-in-this-container test inside _splice_after.
    anchor = jnp.where(
        (anchor != NIL) & _expired(anchor), NIL, anchor
    )
    moved = _detach(state, target)
    moved = _splice_after(moved, e.container, anchor, target, target)
    moved = moved._replace(
        node_container=moved.node_container.at[target].set(e.container),
        insert_seq=moved.insert_seq.at[target].set(e.seq),
    )
    state = jax.tree.map(
        lambda new, old: jnp.where(do_move, new, old), moved, state
    )
    # node_container for inserts: pre-set at pack time (rows are inert until
    # linked, and nothing references a node before its insert sequences).
    return state._replace(overflow=state.overflow | (is_mov & deep))


def replay_scan(state: TreeState, edits: TreeEdits) -> TreeState:
    """Pure single-document edit-fold (no jit)."""

    def step(carry, e):
        return _apply_edit(carry, e), None

    final, _ = jax.lax.scan(step, state, edits)
    return final


#: vmapped over the document axis — the unit the parallel/ package shards.
replay_vmapped = jax.vmap(replay_scan)

_replay_batch = jax.jit(replay_vmapped)


# ---------------------------------------------------------------------------
# Host side: packing and canonical summary extraction
# ---------------------------------------------------------------------------


@dataclass
class TreeDocInput:
    """One document's catch-up work item: optional base summary + op tail."""

    doc_id: str
    ops: Sequence[SequencedMessage]   # tree changeset messages, ascending seq
    base_summary: Optional[SummaryTree] = None
    final_seq: int = 0
    final_msn: int = 0
    #: attribution-enabled document (SURVEY §1 layer 8): the summary gains
    #: an "attribution" blob of pre-clamp (insert, value) seqs per emitted
    #: node.  The device state carries raw seqs — clamping is host-side —
    #: and the pack restores a warm base's keys, so this is extraction
    #: work only.
    attribution: bool = False
    #: catch-up cache identity (tiers 0/2/2.5, same contract as
    #: ``MergeTreeDocInput.cache_token``): ``(storage epoch, channel id,
    #: base ref_seq, base summary digest)`` — within one storage
    #: generation the edit stream extends append-only under this anchor.
    #: None bypasses every cache tier.
    cache_token: Optional[tuple] = None


class _DocPack:
    """Per-document host bookkeeping: node/container interning plus the
    static attributes the device never needs (ids, types), and the purge
    bookkeeping (``removal_time``/``boundary``) the suffix extension
    resumes from."""

    def __init__(self) -> None:
        self.node_ids = Interner()     # node id str -> node idx
        self.node_types: List[str] = []
        self.containers = Interner()   # (node idx, field) -> container idx
        self.fallback_reason: Optional[str] = None
        self.header_seq = 0            # channel fold position for the header
        self.base_min_seq = 0
        #: host-exact removal times (first remover wins; base tombstones
        #: count) — they decide, per edit, whether the oracle had already
        #: popped a referenced node when the edit applied.
        self.removal_time: Dict[str, int] = {}
        #: purge boundary while applying the NEXT message = max min_seq
        #: over all prior messages (+ the base minSeq).
        self.boundary = 0
        self.node_ids.intern("")       # root is node 0
        self.node_types.append("")

    @property
    def needs_fallback(self) -> bool:
        return self.fallback_reason is not None

    def mark_fallback(self, reason: str) -> None:
        """First reason wins (it names the edit that disqualified the
        doc); later shapes would have routed through the oracle anyway."""
        if self.fallback_reason is None:
            self.fallback_reason = reason

    def node(self, node_id: str) -> int:
        idx = self.node_ids.intern(node_id)
        while len(self.node_types) <= idx:
            self.node_types.append("")
        return idx

    def container(self, parent_idx: int, field_name: str) -> int:
        return self.containers.intern((parent_idx, field_name))


def _count_nodes_and_edits(doc: TreeDocInput) -> Tuple[int, int]:
    from ..dds.tree import content_ids

    nodes, edits = 1, 0  # root
    if doc.base_summary is not None:
        import json

        obj = json.loads(doc.base_summary.blob_bytes("header"))

        def count(o):
            return 1 + sum(
                count(ch)
                for chs in o.get("fields", {}).values() for ch in chs
            )

        nodes += sum(
            count(ch)
            for chs in obj.get("fields", {}).values() for ch in chs
        )
    for msg in doc.ops:
        for edit in msg.contents["edits"]:
            kind = edit["kind"]
            if kind == "insert":
                nodes += sum(len(content_ids(s)) for s in edit["content"])
                edits += 1
            elif kind in ("remove", "move"):
                edits += len(edit["ids"])
            elif kind == "revive":
                nodes += sum(len(content_ids(s)) for s in edit["content"])
                edits += len(edit["ids"])
            else:
                edits += 1
    return nodes, edits


def _materialize_spec(pack: _DocPack, values: Interner, node_rows: Dict,
                      chains: Dict, spec: dict, container: int) -> int:
    """Intern one NodeSpec subtree into host rows: the node row (value /
    seqs / tombstone), its nested containers, and their ordered chains.
    THE one materialization shared by the fresh pack and the tier-2
    suffix extension."""
    idx = pack.node(spec["id"])
    pack.node_types[idx] = spec["type"]
    node_rows[idx] = {
        "container": container,
        "value": (
            values.intern(spec["value"])
            if "value" in spec and spec["value"] is not None
            else NO_VALUE
        ),
        "value_seq": 0,
        "insert_seq": 0,
        "removed_seq": (
            spec["removedSeq"] if "removedSeq" in spec
            else int(NOT_REMOVED)
        ),
    }
    for f, children in spec.get("fields", {}).items():
        c = pack.container(idx, f)
        for ch in children:
            chains.setdefault(c, []).append(
                _materialize_spec(pack, values, node_rows, chains, ch, c))
    return idx


def _note_removals(removal_time: Dict[str, int], spec: dict) -> None:
    if spec.get("removedSeq") is not None:
        removal_time[spec["id"]] = spec["removedSeq"]
    for chs in spec.get("fields", {}).values():
        for ch in chs:
            _note_removals(removal_time, ch)


def fill_tree_doc_messages(pack: _DocPack, values: Interner,
                           node_rows: Dict, chains: Dict,
                           edit_rows: List[dict],
                           msgs: Sequence[SequencedMessage]) -> None:
    """THE per-message edit-row fill shared by ``pack_tree_batch`` and
    the pack cache's suffix extension (ops/tree_pipeline.py) — byte
    drift between fresh and suffix-extended packs is impossible by
    construction.  Resumes from (and advances) ``pack.removal_time`` /
    ``pack.boundary`` / ``pack.header_seq`` / ``pack.base_min_seq``, so
    filling a suffix continues exactly where the cached window stopped."""
    for msg in msgs:
        for edit in msg.contents["edits"]:
            if edit["kind"] == "remove":
                for nid in edit["ids"]:
                    # First remover wins; a FUTURE removal can never
                    # satisfy ``rt <= boundary`` below (its seq exceeds
                    # every prior min_seq), so pre-noting the whole span
                    # is equivalent to noting incrementally.
                    pack.removal_time.setdefault(nid, msg.seq)

    def popped(node_id: str) -> bool:
        rt = pack.removal_time.get(node_id)
        return rt is not None and rt <= pack.boundary

    for msg in msgs:
        pack.header_seq = max(pack.header_seq, msg.seq)
        pack.base_min_seq = max(pack.base_min_seq, msg.min_seq)
        rows_before = len(edit_rows)
        for edit in msg.contents["edits"]:
            kind = edit["kind"]
            if kind == "insert":
                if popped(edit["parent"]):
                    # The oracle skips this insert entirely (parent
                    # popped); follow-on references to its content
                    # would need an existence simulation — fallback.
                    pack.mark_fallback("purged_parent_insert")
                parent_idx = pack.node(edit["parent"])
                c = pack.container(parent_idx, edit["field"])
                block: List[int] = []
                for spec in edit["content"]:
                    idx = _materialize_spec(pack, values, node_rows,
                                            chains, spec, c)
                    node_rows[idx]["insert_seq"] = msg.seq
                    node_rows[idx]["value_seq"] = max(msg.seq, 0)
                    block.append(idx)
                # Nested nodes' seqs:
                def stamp(spec):
                    i = pack.node(spec["id"])
                    node_rows[i]["insert_seq"] = msg.seq
                    if node_rows[i]["value"] != NO_VALUE:
                        node_rows[i]["value_seq"] = msg.seq
                    for chs in spec.get("fields", {}).values():
                        for ch in chs:
                            stamp(ch)
                for spec in edit["content"]:
                    stamp(spec)
                anchor = edit["anchor"]
                edit_rows.append({
                    "kind": K_INSERT, "seq": msg.seq, "container": c,
                    "anchor": (
                        pack.node(anchor) if anchor is not None else NIL
                    ),
                    "first": block[0], "tail": block[-1],
                    "block": block,
                })
            elif kind == "remove":
                for nid in edit["ids"]:
                    edit_rows.append({
                        "kind": K_REMOVE, "seq": msg.seq,
                        "first": pack.node(nid),
                    })
            elif kind == "set":
                edit_rows.append({
                    "kind": K_SET, "seq": msg.seq,
                    "first": pack.node(edit["id"]),
                    "value": (
                        values.intern(edit["value"])
                        if edit["value"] is not None else NO_VALUE
                    ),
                })
            elif kind == "move":
                if len(edit["ids"]) != 1:
                    pack.mark_fallback("multi_id_move")  # block-cycle rules
                    continue
                parent_idx = pack.node(edit["parent"])
                c = pack.container(parent_idx, edit["field"])
                anchor = edit["anchor"]
                tgt = pack.node(edit["ids"][0])
                edit_rows.append({
                    "kind": K_MOVE, "seq": msg.seq, "container": c,
                    "anchor": (
                        pack.node(anchor) if anchor is not None else NIL
                    ),
                    "first": tgt, "tail": tgt,
                })
            elif kind == "revive":
                pack.mark_fallback("revive")  # purge-timing interaction
            else:
                raise ValueError(f"unknown edit kind {kind!r}")
        for row in edit_rows[rows_before:]:
            row["purge_msn"] = pack.boundary
        pack.boundary = max(pack.boundary, msg.min_seq)


def load_tree_base(pack: _DocPack, values: Interner, node_rows: Dict,
                   chains: Dict, doc: TreeDocInput) -> None:
    """Materialize a warm base summary into host rows (header seqs,
    tombstone times, attribution-key restore) — the pre-message half of
    the per-doc pack."""
    import json

    if doc.base_summary is None:
        return
    base_obj = obj = json.loads(doc.base_summary.blob_bytes("header"))
    pack.header_seq = obj.get("seq", 0)
    pack.base_min_seq = obj.get("minSeq", 0)
    pack.boundary = pack.base_min_seq
    if obj.get("limbo"):
        # Detached-but-rescuable subtrees in the base need a
        # container-less representation — oracle fallback.
        pack.mark_fallback("base_limbo")
    for f, children in obj.get("fields", {}).items():
        c = pack.container(0, f)
        for ch in children:
            idx = _materialize_spec(pack, values, node_rows, chains, ch, c)
            chains.setdefault(c, []).append(idx)
            node_rows[idx]["insert_seq"] = ch["insertSeq"]
    # insert/value seqs for nested nodes come from the summary obj.
    def fix_seqs(o):
        idx = pack.node(o["id"])
        node_rows[idx]["insert_seq"] = o["insertSeq"]
        node_rows[idx]["value_seq"] = o.get("valueSeq", 0)
        for chs in o.get("fields", {}).values():
            for ch in chs:
                fix_seqs(ch)
    for chs in obj.get("fields", {}).values():
        for ch in chs:
            fix_seqs(ch)
    if "attribution" in doc.base_summary.children:
        # Warm base carrying pre-clamp keys: restore them via the
        # ONE shared helper (SharedTree.load uses it too), so
        # re-summarizing regenerates identical keys.
        from ..dds.tree import restore_attribution_seqs

        def get_seqs(nid):
            if nid not in pack.node_ids:
                return None
            row = node_rows.get(pack.node(nid))
            return None if row is None else (
                row["insert_seq"], row["value_seq"])

        def put_seqs(nid, ins, val):
            row = node_rows[pack.node(nid)]
            row["insert_seq"], row["value_seq"] = ins, val

        restore_attribution_seqs(
            json.loads(
                doc.base_summary.blob_bytes("attribution")),
            get_seqs, put_seqs,
        )
    for chs in base_obj.get("fields", {}).values():
        for ch in chs:
            _note_removals(pack.removal_time, ch)


def scatter_tree_doc_rows(st: dict, ed: dict, d: int, node_rows: Dict,
                          chains: Dict, edit_rows: List[dict],
                          containers: List[tuple], t_base: int = 0,
                          cont_start: int = 0) -> None:
    """Write one document's host rows into the batch arrays (dicts of
    numpy planes).  THE one scatter shared by the fresh pack (``t_base``
    / ``cont_start`` 0) and the suffix extension (which scatters ONLY
    the new rows into copied planes: edit rows land at ``t_base``+,
    container rows from ``cont_start``)."""
    for c in range(cont_start, len(containers)):
        st["container_parent"][d, c] = containers[c][0]
    for idx, row in node_rows.items():
        st["node_container"][d, idx] = row["container"]
        st["value"][d, idx] = row["value"]
        st["value_seq"][d, idx] = row["value_seq"]
        st["insert_seq"][d, idx] = row["insert_seq"]
        st["removed_seq"][d, idx] = row["removed_seq"]
    # Pre-link chains: base-summary sibling lists fully; insert-block
    # interiors (head/prev of the block come alive at splice time).
    for e in edit_rows:
        if e["kind"] == K_INSERT:
            block = e["block"]
            for a, b in zip(block, block[1:]):
                st["next"][d, a] = b
                st["prev"][d, b] = a
    for c, members in chains.items():
        # Base lists (live at t=0) need head set; nested insert-block
        # chains were added under their materialized parent and are
        # reachable only through it, so setting head is safe for both —
        # an unreachable container's head is never read before its
        # parent links in.
        st["head"][d, c] = members[0]
        for a, b in zip(members, members[1:]):
            st["next"][d, a] = b
            st["prev"][d, b] = a
    for t, e in enumerate(edit_rows):
        ed["kind"][d, t_base + t] = e["kind"]
        ed["seq"][d, t_base + t] = e["seq"]
        ed["container"][d, t_base + t] = e.get("container", 0)
        ed["anchor"][d, t_base + t] = e.get("anchor", NIL)
        ed["first"][d, t_base + t] = e["first"]
        ed["tail"][d, t_base + t] = e.get("tail", e["first"])
        ed["value"][d, t_base + t] = e.get("value", NO_VALUE)
        ed["purge_msn"][d, t_base + t] = e.get("purge_msn", -1)


def empty_tree_arrays(D: int, N: int, C: int, T: int):
    """Fresh default-filled batch planes — also what the suffix
    extension's unwritten new rows must equal (inert interned rows keep
    these defaults)."""
    st = {
        "head": np.full((D, C), NIL, np.int32),
        "next": np.full((D, N), NIL, np.int32),
        "prev": np.full((D, N), NIL, np.int32),
        "node_container": np.full((D, N), NIL, np.int32),
        "container_parent": np.full((D, C), NIL, np.int32),
        "value": np.full((D, N), NO_VALUE, np.int32),
        "value_seq": np.zeros((D, N), np.int32),
        "insert_seq": np.zeros((D, N), np.int32),
        "removed_seq": np.full((D, N), NOT_REMOVED, np.int32),
        "overflow": np.zeros((D,), np.bool_),
    }
    ed = {
        "kind": np.zeros((D, T), np.int32),
        "seq": np.zeros((D, T), np.int32),
        "container": np.zeros((D, T), np.int32),
        "anchor": np.full((D, T), NIL, np.int32),
        "first": np.zeros((D, T), np.int32),
        "tail": np.zeros((D, T), np.int32),
        "value": np.full((D, T), NO_VALUE, np.int32),
        "purge_msn": np.full((D, T), -1, np.int32),
    }
    return st, ed


def tree_buckets(docs: Sequence[TreeDocInput]):
    """(N, T) sizing buckets from the estimate predicate.  +2·edits
    slack on N: anchors/parents naming already-purged ids intern fresh
    (inert) rows — the oracle's "missing → field start / drop" fallback
    falls out of their NIL containers.  ONE derivation point: the
    suffix extension re-evaluates this same predicate over the combined
    windows to decide whether the cached buckets still hold."""
    sizes = [_count_nodes_and_edits(d) for d in docs]
    N = next_bucket(
        max((n + 2 * e for n, e in sizes), default=1), floor=16
    )
    T = next_bucket(max((e for _, e in sizes), default=1), floor=16)
    return N, T


def pack_tree_batch(docs: Sequence[TreeDocInput]):
    """Pack documents into uniform-shape arrays + host metadata."""
    values = Interner()
    doc_packs = [_DocPack() for _ in docs]
    N, T = tree_buckets(docs)
    D = len(docs)
    # Containers ≤ nodes·fields; sized after a packing dry run is overkill —
    # intern first, then allocate.  Two passes keep the arrays exact.

    packed_docs = []
    for d, doc in enumerate(docs):
        pack = doc_packs[d]
        node_rows: Dict[int, dict] = {}
        chains: Dict[int, List[int]] = {}  # container -> ordered node idxs
        edit_rows: List[dict] = []
        load_tree_base(pack, values, node_rows, chains, doc)
        fill_tree_doc_messages(pack, values, node_rows, chains, edit_rows,
                               doc.ops)
        packed_docs.append((node_rows, chains, edit_rows))

    C = next_bucket(
        max((len(p.containers) for p in doc_packs), default=1), floor=8
    )
    st, ed = empty_tree_arrays(D, N, C, T)
    for d, (node_rows, chains, edit_rows) in enumerate(packed_docs):
        scatter_tree_doc_rows(st, ed, d, node_rows, chains, edit_rows,
                              doc_packs[d].containers.values)

    meta = {
        "doc_packs": doc_packs, "values": values, "docs": docs,
        # Per-doc used-row counts: the digest mask (only written rows may
        # hash) and the suffix extension/splice windows read these.
        "n_nodes": np.asarray([len(p.node_ids) for p in doc_packs],
                              np.int32),
        "n_cont": np.asarray([len(p.containers) for p in doc_packs],
                             np.int32),
        "t_rows": np.asarray([len(rows) for _n, _c, rows in packed_docs],
                             np.int32),
    }
    return TreeState(**st), TreeEdits(**ed), meta


class _ChainCycleError(Exception):
    """A sibling chain longer than the doc's interned rows: a cycle in
    the final linked list, reachable only through out-of-contract input
    (duplicate node ids) — extraction bails to the oracle."""


def oracle_fallback_summary(doc: TreeDocInput) -> SummaryTree:
    """Full oracle replay of one document — the exactness escape hatch."""
    from ..dds.tree import SharedTree

    replica = SharedTree(doc.doc_id)
    if doc.attribution:
        # Attribution-enabled docs must emit their keys blob on fallback
        # too (summarize keys on the flag alone).
        from ..runtime.attributor import Attributor

        replica._attributor = Attributor()
    if doc.base_summary is not None:
        replica.load(doc.base_summary)
    for msg in doc.ops:
        replica.process(msg, local=False)
    replica.advance(doc.final_seq, doc.final_msn)
    return replica.summarize()


#: distinct-from-None sentinel: the memoized verdict itself can be None
_VERDICT_UNSET = object()


def known_tree_fallback(doc: TreeDocInput):
    # Memoized per doc object (same discipline as known_oracle_fallback):
    # benches and warm catch-up passes re-route the same doc objects, and
    # the base-header JSON parse + full op scan must not repeat per pass.
    cached = getattr(doc, "_fallback_verdict", _VERDICT_UNSET)
    if cached is not _VERDICT_UNSET:
        return cached
    verdict = _known_tree_fallback_uncached(doc)
    doc._fallback_verdict = verdict
    return verdict


def _known_tree_fallback_uncached(doc: TreeDocInput):
    """Pre-pack oracle routing: the reason string when the document's
    SHAPE disqualifies the device fold before packing — revive edits,
    multi-id moves, a base summary carrying limbo roots — else None.
    Mirrors the pack-time ``mark_fallback`` calls (MAX_DEPTH overflow
    and purged-parent inserts need the fold/purge simulation and stay
    post-pack); routing these out FIRST keeps them from inflating the
    shared N/T buckets, exactly like ``known_oracle_fallback`` does for
    merge-tree docs."""
    if doc.base_summary is not None:
        import json

        if json.loads(doc.base_summary.blob_bytes("header")).get("limbo"):
            return "base_limbo"
    for msg in doc.ops:
        for edit in msg.contents["edits"]:
            kind = edit["kind"]
            if kind == "revive":
                return "revive"
            if kind == "move" and len(edit["ids"]) != 1:
                return "multi_id_move"
    return None


def summary_from_state(meta, state_np: dict, d: int,
                       stats: Optional[dict] = None) -> SummaryTree:
    """Final device state → the oracle's canonical summary bytes.
    ``stats`` counts this doc as device/fallback WHERE the routing
    decision is made — per REASON (revive / multi-id move / MAX_DEPTH
    overflow / …) through the shared ``count_fallback`` — so the
    counters can never drift from the actual serving path."""
    from .batching import count_fallback

    doc: TreeDocInput = meta["docs"][d]
    pack: _DocPack = meta["doc_packs"][d]
    if pack.needs_fallback or bool(state_np["overflow"][d]):
        count_fallback(stats, pack.fallback_reason or "max_depth")
        return oracle_fallback_summary(doc)
    values: Interner = meta["values"]
    msn = max(doc.final_msn, pack.base_min_seq)

    # containers by owning node, in interning order (which preserves field
    # name order only per first appearance — re-sort by field name to match
    # the oracle's sorted(fields) serialization).
    by_node: Dict[int, List[Tuple[str, int]]] = {}
    for (pidx, fname), c in zip(pack.containers.values,
                                range(len(pack.containers))):
        by_node.setdefault(pidx, []).append((fname, c))

    head = state_np["head"][d]
    nxt = state_np["next"][d]
    removed = state_np["removed_seq"][d]
    ins_seq = state_np["insert_seq"][d]
    val = state_np["value"][d]
    val_seq = state_np["value_seq"][d]
    node_container = state_np["node_container"][d]

    def keep(idx: int) -> bool:
        rs = int(removed[idx])
        return not (rs != int(NOT_REMOVED) and rs <= msn)

    n_used = len(pack.node_ids)

    def chain(c: int) -> List[int]:
        out = []
        cur = int(head[c])
        while cur != NIL:
            # Only nodes currently linked in this container (a node moved
            # away leaves no stale link — splice repairs both sides).
            if len(out) >= n_used:
                # More links than interned rows proves a CYCLE — possible
                # only on out-of-contract streams (e.g. duplicate node
                # ids).  The walk must terminate regardless; the doc
                # routes to the oracle below.
                raise _ChainCycleError()
            out.append(cur)
            cur = int(nxt[cur])
        return out

    def node_obj(idx: int) -> dict:
        obj: Dict[str, Any] = {
            "id": pack.node_ids.values[idx],
            "type": pack.node_types[idx],
            "insertSeq": 0 if int(ins_seq[idx]) <= msn else int(ins_seq[idx]),
        }
        v = int(val[idx])
        if v != NO_VALUE:
            obj["value"] = values.lookup(v)
            vs = int(val_seq[idx])
            obj["valueSeq"] = 0 if vs <= msn else vs
        rs = int(removed[idx])
        if rs != int(NOT_REMOVED):
            obj["removedSeq"] = rs
        fields = fields_obj(idx)
        if fields:
            obj["fields"] = fields
        return obj

    def fields_obj(idx: int) -> dict:
        out = {}
        for fname, c in sorted(by_node.get(idx, [])):
            kids = [node_obj(i) for i in chain(c) if keep(i)]
            if kids:
                out[fname] = kids
        return out

    try:
        root_obj = {
            "fields": fields_obj(0),
            "minSeq": msn,
            "seq": pack.header_seq,
        }
        # Limbo: kept nodes still linked in a chain whose owning node is
        # NOT kept (their enclosing tombstone expired).  The oracle
        # detaches them at purge time; here they surface at extraction —
        # same set, because rescued nodes were re-linked under kept
        # owners by their moves.  Unlinked rows (e.g. content of
        # oracle-skipped inserts, which are a pack-time fallback anyway)
        # are reachable from no chain.
        limbo_idxs = []
        for c in range(len(pack.containers)):
            owner = int(state_np["container_parent"][d][c])
            if owner == NIL or keep(owner):
                continue
            limbo_idxs.extend(i for i in chain(c) if keep(i))
        if limbo_idxs:
            limbo_idxs.sort(key=lambda i: pack.node_ids.values[i])
            root_obj["limbo"] = [node_obj(i) for i in limbo_idxs]
    except (_ChainCycleError, RecursionError):
        # A next-link or container-nesting cycle (out-of-contract input
        # such as duplicate node ids): extraction must never hang or
        # blow the stack — lose the device win, serve the oracle bytes.
        count_fallback(stats, "chain_cycle")
        return oracle_fallback_summary(doc)
    if stats is not None:
        stats["device_docs"] = stats.get("device_docs", 0) + 1
    tree = SummaryTree()
    tree.add_blob("header", canonical_json(root_obj))
    if doc.attribution:
        # Mirror SharedTree.summarize's key emission: pre-clamp (insert,
        # value) seqs for every EMITTED node whose seq the header clamped
        # (the state rows are pre-clamp; node_obj clamps at emission).
        emitted: List[int] = []

        def collect(node_o: dict) -> None:
            emitted.append(pack.node(node_o["id"]))
            for children in node_o.get("fields", {}).values():
                for child in children:
                    collect(child)

        for children in root_obj.get("fields", {}).values():
            for child in children:
                collect(child)
        for spec in root_obj.get("limbo", []):
            collect(spec)
        keys = {
            pack.node_ids.values[i]: [int(ins_seq[i]), int(val_seq[i])]
            for i in emitted
            if 0 < int(ins_seq[i]) <= msn or 0 < int(val_seq[i]) <= msn
        }
        if keys:
            tree.add_blob("attribution", canonical_json(keys))
    return tree


def replay_tree_batch(docs: Sequence[TreeDocInput],
                      stats: Optional[dict] = None) -> List[SummaryTree]:
    """Full pipeline: pack → vmapped device edit-fold → canonical summaries.

    Byte-identical to ``SharedTree.summarize()`` after the oracle replays
    the same log (asserted by tests/test_tree_kernel.py).  ``stats``
    accumulates ``device_docs`` / ``fallback_docs`` (pack-time revive /
    multi-id-move detection + fold overflow).
    """
    if not docs:
        return []
    out: List[Optional[SummaryTree]] = [None] * len(docs)
    state, edits, meta = pack_tree_batch(docs)
    final = _replay_batch(state, edits)
    state_np = {k: np.asarray(v) for k, v in final._asdict().items()}
    for d in range(len(docs)):
        out[d] = summary_from_state(meta, state_np, d, stats=stats)
    return out
