"""Kernel-family descriptor: the contract a vmap-able replay kernel
implements to ride the SHARED catch-up pipeline (ops/pipeline.py) and its
cache tiers instead of a bare ``replay_*_batch`` loop.

PAPER.md §0 names TWO kernels that trace and ``vmap`` — the merge-tree
op-apply loop and the SharedTree rebaser — but through round 13 every
cache tier, stage counter, and bench measured only the merge-tree
instance, and the pipeline was hard-wired to its types.  This descriptor
is the round-14 refactor: everything the pipeline does per chunk — pack,
tier-2 window reuse, upload (tier 2.5), dispatch, the tier-0 digest
handshake, download, extraction, fallback routing — goes through these
hooks, and ``pipelined_mergetree_replay`` becomes one instance of the
generic fold next to the SharedTree instance (ops/tree_pipeline.py).
The tier-1 result cache (service/catchup_cache.py) is already
family-agnostic (it keys folded summary trees, not kernel arrays).

A family's ``(state, ops)`` are namedtuples of ``[D, ...]`` planes with
the document axis leading — the invariant every generic helper
(``match_windows``, ``gather_export_rows``, the mesh doc-sharding)
relies on.  Hooks that a family does not support are None and the
corresponding tier degrades gracefully (e.g. ``extend=None`` turns every
grown-tail window into a full repack — a lost win, never corruption).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KernelFamily:
    """One replay kernel's bindings into the family-generic pipeline.

    Grouped by pipeline leg (see ops/pipeline.py ``_pipelined_fold`` for
    the call sites; parallel/shard.py ``replay_family_sharded`` consumes
    the same hooks plus ``dispatch_sharded``/``make_pad``/``pad_token``):

    routing
      - ``known_fallback(doc)`` → falsy | True | reason str: pre-pack
        oracle routing (reasons feed the per-reason fallback counters);
      - ``fallback_summary(doc)`` → SummaryTree: the exactness escape
        hatch (also the post-fold fallback the extractor takes).

    pack / tier 2
      - ``pack(chunk)`` → ``(state, ops, meta)``;
      - ``bypass(doc)`` → bool: cache-ineligible beyond a missing token
        (e.g. merge-tree binary streams);
      - ``entry_rows(chunk, meta)`` → per-doc used op-row counts (the
        suffix fill offsets the cache entry tracks);
      - ``entry_nbytes(state, ops, meta)`` → retained bytes for the LRU
        budget;
      - ``extend(entry, chunk)`` → ``(state, ops, meta)`` | None: pack
        only the suffix on top of a cached window (None = repack).

    upload / dispatch
      - ``order(batch, schedule)`` → schedule-order index list;
      - ``narrow(chunk, state, ops, meta)`` → ``(state_u | None,
        ops_u)``: the h2d transfer encodings;
      - ``aux(meta, digest)`` → host array tree the dispatch needs next
        to state/ops (merge-tree: the per-doc arena base; tree: used
        node/container counts for the digest mask);
      - ``dispatch(state_u, ops_u, meta, digest, aux_dev)`` → export
        handle(s); ``aux_dev`` is the device-resident aux from tier 2.5
        or None (derive from ``aux``);
      - ``split_digest(export, want)`` → ``(core, digest | None)``;
      - ``chunk_tag(meta)`` → value stored in ``packed_out`` tuples.

    download / extract / tier 0
      - ``fetch(core)`` → host arrays (the full d2h transfer);
      - ``gather_rows(core, idx)`` → ``(rows, moved_bytes)``: only the
        changed documents' rows;
      - ``extract(meta, arr, stats)`` → summaries (counting post-fold
        fallbacks per reason into ``stats``);
      - ``per_doc_meta``: names of per-doc ndarray meta entries the
        changed-rows sub-meta must slice alongside docs/doc_packs.

    mesh (parallel/shard.py)
      - ``make_pad()`` → an empty pad document;
      - ``pad_token(k)`` → deterministic cache token for pad docs;
      - ``dispatch_sharded(mesh, state_u, ops_u, meta, digest,
        aux_dev)`` → export placed doc-sharded over the mesh.
    """

    name: str
    # routing
    known_fallback: Callable[[Any], Any]
    fallback_summary: Callable[[Any], Any]
    # pack / tier 2
    pack: Callable[[Any], Tuple[Any, Any, dict]]
    bypass: Callable[[Any], bool]
    entry_rows: Callable[[Any, dict], Any]
    entry_nbytes: Callable[[Any, Any, dict], int]
    extend: Optional[Callable[[Any, Any], Any]]
    # upload / dispatch
    order: Callable[[Any, bool], Any]
    narrow: Callable[[Any, Any, Any, dict], Tuple[Any, Any]]
    aux: Callable[[dict, bool], Any]
    dispatch: Callable[[Any, Any, dict, bool, Any], Any]
    split_digest: Callable[[Any, bool], Tuple[Any, Any]]
    chunk_tag: Callable[[dict], Any]
    # download / extract / tier 0
    fetch: Callable[[Any], Any]
    gather_rows: Callable[[Any, Any], Tuple[Any, int]]
    extract: Callable[[dict, Any, dict], Any]
    per_doc_meta: Tuple[str, ...] = ()
    # mesh
    make_pad: Optional[Callable[[], Any]] = None
    pad_token: Optional[Callable[[int], tuple]] = None
    dispatch_sharded: Optional[Callable[..., Any]] = None
