"""SharedTree as the SECOND kernel family of the generic catch-up
pipeline (ISSUE 14 tentpole; PAPER §0 names exactly two vmap-able
kernels — the merge-tree op-apply loop and the SharedTree rebaser — and
through round 13 only the first rode the cache/pipeline stack).

This module binds ``ops/tree_kernel.py`` into the four-tier pipeline of
``ops/pipeline.py``:

- **tier 2** (:class:`~fluidframework_tpu.ops.pipeline.PackCache` over
  :data:`TREE_FAMILY`): packed forest windows reuse across catch-ups —
  an exact edit-window hit costs a dict lookup, a grown tail packs ONLY
  its suffix messages onto copies of the cached planes through the SAME
  per-message fill the fresh pack uses
  (``tree_kernel.fill_tree_doc_messages`` — byte drift is impossible by
  construction).  Tree suffixes, unlike merge-tree ones, also
  materialize NEW node/container state rows; those land strictly in the
  per-doc row suffixes of the state planes (interning is append-only and
  edits never rewrite a base row at pack time), which is what makes the
  tier-2.5 splice below sound;
- **tier 2.5** (:class:`~fluidframework_tpu.ops.device_cache.
  DevicePackCache` with :class:`TreeDeviceOps`): forest + edit planes
  stay device-resident; an exact window dispatches with ZERO h2d pack
  bytes, a lineage-proven grown tail uploads only its new edit rows AND
  its newly-materialized node/container rows, spliced in place over
  three donated row axes;
- **tier 0** (the family-agnostic ``DeltaExportCache``): the fold
  exports a per-doc ``[D, 2]`` digest of the FINAL forest arrays
  (:func:`tree_doc_digests`, masked to each doc's used node/container
  rows so bucket padding and neighbours' growth never perturb it);
  unchanged documents serve their cached summaries with no download,
  changed documents gather only their rows;
- tier 1 (the folded-result cache) needs nothing: it was always
  family-agnostic.

``pipelined_tree_replay`` is the drop-in bulk entry point with the full
``pack/upload/dispatch/device_wait/download/extract`` +
``h2d_bytes``/``d2h_bytes`` stage schema; the mesh twin rides
``parallel/shard.replay_tree_sharded`` through the same family hooks.
Fallback routing (revive / multi-id move / MAX_DEPTH / purged-parent
inserts / limbo bases) is byte-exact as ever — and now counted PER
REASON through ``ops/batching.count_fallback``.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .device_cache import (
    DevicePackCache,
    gather_suffix_rows,
    splice_row_planes,
    tuple_sig,
)
from .family import KernelFamily
from .interning import Interner
from .mergetree_kernel import _mix_u32, export_to_numpy, gather_export_rows
from .pipeline import (
    PackCache,
    _copy_interner,
    _mt_pad_token,
    pipelined_family_replay,
)
from .tree_kernel import (
    TreeDocInput,
    TreeEdits,
    TreeState,
    fill_tree_doc_messages,
    known_tree_fallback,
    oracle_fallback_summary,
    pack_tree_batch,
    replay_vmapped,
    scatter_tree_doc_rows,
    summary_from_state,
    tree_buckets,
)

__all__ = [
    "TREE_FAMILY",
    "TreeDeviceOps",
    "pipelined_tree_replay",
    "summaries_from_tree_export",
    "tree_device_cache",
    "tree_doc_digests",
    "tree_pack_cache",
]


# ---------------------------------------------------------------------------
# Device digest over the final forest arrays (the tier-0 gate)
# ---------------------------------------------------------------------------

#: node-axis planes entering the digest (order = salt index); container
#: planes follow at _CONT_SALT_BASE.
_DIGEST_NODE_PLANES = ("next", "prev", "node_container", "value",
                       "value_seq", "insert_seq", "removed_seq")
_DIGEST_CONT_PLANES = ("head", "container_parent")
_CONT_SALT_BASE = 8
#: active-row value mask: XORed into live values so a stored 0 at an
#: active position never aliases a masked (padding) position's zero
#: contribution.
_ACTIVE_XOR = 0xA5A5A5A5


def tree_doc_digests(final: TreeState, n_nodes: jnp.ndarray,
                     n_cont: jnp.ndarray) -> jnp.ndarray:
    """``[D, 2]`` int32 digest of each document's final forest — the
    device-computed identity the tier-0 delta path compares before
    deciding which documents' state rows must cross the d2h link.

    Properties the delta path relies on (pinned by tests):

    - **masked**: only rows the document actually interned
      (``idx < n_nodes[d]`` / ``idx < n_cont[d]``) contribute — bucket
      padding (which legitimately grows when a NEIGHBOUR document in
      the chunk grows) never reaches the hash, and the fold provably
      never writes past the interned rows (every edit targets an
      interned index);
    - **position-salted**: weights are per (plane, row-index), so two
      different forests cannot cancel by swapping rows; live values XOR
      a constant so value 0 at a live row stays distinct from absence;
    - 64 bits across two independently-salted lanes, ``overflow`` mixed
      in (an overflowed doc routes to the oracle — its digest must not
      alias the non-overflowed fold of other inputs); every structural
      failure (missing entry, anchor drift, digest mismatch) falls back
      to the full download, so a collision is the only wrong-serve path
      and the host anchor already pins the op-list identity.
    """
    D, N = final.next.shape
    C = final.head.shape[1]
    node_idx = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    cont_idx = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
    act_n = node_idx < n_nodes[:, None]
    act_c = cont_idx < n_cont[:, None]
    node_u = node_idx.astype(jnp.uint32)
    cont_u = cont_idx.astype(jnp.uint32)
    accs = []
    for lane_salt in (jnp.uint32(0x9E3779B9), jnp.uint32(0x85EBCA6B)):
        acc = jnp.zeros((D,), jnp.uint32)
        for i, f in enumerate(_DIGEST_NODE_PLANES):
            v = jnp.where(
                act_n,
                getattr(final, f).astype(jnp.uint32)
                ^ jnp.uint32(_ACTIVE_XOR),
                jnp.uint32(0))
            w = _mix_u32(node_u * jnp.uint32(0x01000193)
                         + jnp.uint32(i) + lane_salt)
            acc = acc + (v * w).sum(axis=1, dtype=jnp.uint32)
        for i, f in enumerate(_DIGEST_CONT_PLANES):
            v = jnp.where(
                act_c,
                getattr(final, f).astype(jnp.uint32)
                ^ jnp.uint32(_ACTIVE_XOR),
                jnp.uint32(0))
            w = _mix_u32(cont_u * jnp.uint32(0x01000193)
                         + jnp.uint32(_CONT_SALT_BASE + i) + lane_salt)
            acc = acc + (v * w).sum(axis=1, dtype=jnp.uint32)
        acc = acc ^ _mix_u32(n_nodes.astype(jnp.uint32) + lane_salt)
        acc = acc ^ _mix_u32(n_cont.astype(jnp.uint32) * jnp.uint32(3)
                             + lane_salt)
        acc = acc ^ jnp.where(final.overflow, jnp.uint32(0x5BD1E995),
                              jnp.uint32(0))
        accs.append(_mix_u32(acc))
    return jax.lax.bitcast_convert_type(
        jnp.stack(accs, axis=-1), jnp.int32)


# ---------------------------------------------------------------------------
# Dispatch / extraction (the family's export legs)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=4)
def _tree_export_fn(digest: bool):
    """Jitted fold+export: the vmapped edit-fold with the final forest
    planes as a flat tuple (``TreeState`` field order; ``overflow``
    rides as a plane — the host routes MAX_DEPTH fallbacks off it) and,
    under ``digest``, the ``[D, 2]`` digest plane appended LAST — the
    same split contract as the merge-tree export."""

    def run(state: TreeState, edits: TreeEdits, n_nodes, n_cont):
        final = replay_vmapped(state, edits)
        out = tuple(final)
        if digest:
            out = out + (tree_doc_digests(final, n_nodes, n_cont),)
        return out

    return jax.jit(run)


def _tree_aux(meta: dict, digest: bool):
    """Per-doc used-row counts — the digest's mask inputs (tiny [D]
    planes; uploaded, or served device-resident by tier 2.5)."""
    return (np.asarray(meta["n_nodes"], np.int32),
            np.asarray(meta["n_cont"], np.int32))


def _tree_dispatch(state: TreeState, edits: TreeEdits, meta: dict,
                   digest: bool, aux_dev):
    if aux_dev is None:
        aux_dev = _tree_aux(meta, digest)
    n_nodes, n_cont = aux_dev
    return _tree_export_fn(digest)(state, edits, n_nodes, n_cont)


def _tree_dispatch_sharded(mesh, state: TreeState, edits: TreeEdits,
                           meta: dict, digest: bool, aux_dev):
    from ..parallel.shard import tree_sharded_export_step

    if aux_dev is None:
        aux_dev = _tree_aux(meta, digest)
    n_nodes, n_cont = aux_dev
    return tree_sharded_export_step(mesh, digest)(state, edits,
                                                  n_nodes, n_cont)


def _split_tree_digest(export, digested: bool):
    """``(core, digest_or_None)``: the digest plane rides LAST."""
    if not digested:
        return export, None
    return export[:-1], export[-1]


def summaries_from_tree_export(meta, arr, stats: Optional[dict] = None
                               ) -> List:
    """Downloaded final-forest planes → canonical summaries, routing
    pack-time and overflow fallbacks to the oracle (counted per reason).
    ``arr`` is the fetched core tuple in ``TreeState`` field order —
    either a whole chunk's rows or the tier-0 changed-rows gather (the
    meta is then the sliced sub-meta)."""
    state_np = dict(zip(TreeState._fields, arr))
    return [summary_from_state(meta, state_np, d, stats=stats)
            for d in range(len(meta["docs"]))]


def _tree_narrow(chunk, state, edits, meta):
    """No transfer-narrowing for the forest planes (all int32; the
    linked-list indices and seqs genuinely span the int32 range at
    bucket scale) — state uploads cold AND warm (a cold doc's base
    rows are the materialized insert blocks, not derivable in-graph)."""
    return state, edits


# ---------------------------------------------------------------------------
# Tier 2: the suffix extension (family ``extend`` hook)
# ---------------------------------------------------------------------------


def _copy_tree_pack(pack):
    from .tree_kernel import _DocPack

    out = _DocPack.__new__(_DocPack)
    out.node_ids = _copy_interner(pack.node_ids)
    out.node_types = list(pack.node_types)
    out.containers = _copy_interner(pack.containers)
    out.fallback_reason = pack.fallback_reason
    out.header_seq = pack.header_seq
    out.base_min_seq = pack.base_min_seq
    out.removal_time = dict(pack.removal_time)
    out.boundary = pack.boundary
    return out


def _extend_tree(entry, chunk: Sequence[TreeDocInput]):
    """Pack only each doc's suffix messages on top of the cached forest
    planes; None = shape buckets do not hold (caller full-packs).

    Soundness: interning is append-only (old node/container indices are
    stable), per-message fills only MATERIALIZE new rows — a suffix edit
    referencing an existing node adds an edit row, never rewrites a
    packed state row — so the combined arrays are the cached arrays plus
    per-doc row suffixes, written through the SAME fill/scatter helpers
    as a fresh pack."""
    meta = entry.meta
    T = entry.ops.kind.shape[1]
    N = entry.state.next.shape[1]
    C = entry.state.head.shape[1]
    # The shared sizing predicate over the COMBINED windows must land in
    # the SAME buckets (estimates are upper bounds of used rows, so an
    # equal bucket proves the cached arrays are large enough for N/T) —
    # tree_buckets is the ONE derivation point, shared with the fresh
    # pack.
    if tree_buckets(chunk) != (N, T):
        return None

    values: Interner = meta["values"]  # shared, append-only
    doc_packs = [_copy_tree_pack(p) for p in meta["doc_packs"]]
    filled = []
    try:
        for d, doc in enumerate(chunk):
            pack = doc_packs[d]
            suffix = doc.ops[entry.n_ops[d]:]
            node_rows, chains, edit_rows = {}, {}, []
            fill_tree_doc_messages(pack, values, node_rows, chains,
                                   edit_rows, suffix)
            filled.append((node_rows, chains, edit_rows))
    except ValueError:
        # An edit shape this fill doesn't know must degrade to a full
        # pack — which raises the same error if genuinely malformed —
        # never crash only-when-warm.  Interner appends already made are
        # unreferenced and harmless.
        return None
    old_t = entry.t_rows
    if any(len(p.containers) > C for p in doc_packs) \
            or any(len(p.node_ids) > N for p in doc_packs) \
            or any(old_t[d] + len(rows) > T
                   for d, (_n, _c, rows) in enumerate(filled)):
        return None  # container bucket (unsized by the estimate) grew
    old_n = np.asarray(meta["n_nodes"])
    for d, (node_rows, _chains, _rows) in enumerate(filled):
        if node_rows and min(node_rows) < int(old_n[d]):
            # A suffix spec re-interned an EXISTING node id (a
            # duplicate-id stream — nothing validates client-minted
            # ids): the rewrite lands BELOW the cached row watermark,
            # which the device-resident splice (strictly rows >=
            # watermark) could never mirror.  Full repack keeps every
            # tier byte-exact — lose the win, never corrupt.
            return None

    # Commit: copy the cached planes (the entry must stay intact) and
    # scatter ONLY the new rows through the shared scatter.
    st = {f: np.copy(getattr(entry.state, f)) for f in TreeState._fields}
    ed = {f: np.copy(getattr(entry.ops, f)) for f in TreeEdits._fields}
    old_cont = np.asarray(meta["n_cont"])
    for d, (node_rows, chains, edit_rows) in enumerate(filled):
        scatter_tree_doc_rows(st, ed, d, node_rows, chains, edit_rows,
                              doc_packs[d].containers.values,
                              t_base=int(old_t[d]),
                              cont_start=int(old_cont[d]))
    new_meta = dict(
        meta,
        docs=list(chunk),
        doc_packs=doc_packs,
        n_nodes=np.asarray([len(p.node_ids) for p in doc_packs],
                           np.int32),
        n_cont=np.asarray([len(p.containers) for p in doc_packs],
                          np.int32),
        t_rows=np.asarray(
            [int(old_t[d]) + len(rows)
             for d, (_n, _c, rows) in enumerate(filled)], np.int32),
    )
    return TreeState(**st), TreeEdits(**ed), new_meta


def _tree_entry_rows(chunk, meta):
    return [int(x) for x in np.asarray(meta["t_rows"])]


def _tree_entry_nbytes(state, edits, meta) -> int:
    # The retained HOST meta rides the entry too: the shared value
    # interner plus each doc's id/container interners and purge
    # bookkeeping (flat deterministic per-item estimates — the LRU
    # budget must track real memory, not just the int32 planes; the
    # merge-tree twin counts its arena the same way).
    host = len(meta["values"]) * 8
    for p in meta["doc_packs"]:
        host += (len(p.node_ids) + len(p.containers)) * 64
        host += (len(p.removal_time) + len(p.node_types)) * 32
    return int(sum(np.asarray(x).nbytes for x in edits)
               + sum(np.asarray(x).nbytes for x in state) + host)


# ---------------------------------------------------------------------------
# Tier 2.5: the tree device-ops (three donated splice axes)
# ---------------------------------------------------------------------------


class _TreeNodePlanes(NamedTuple):
    """The ``[D, N]`` node-axis planes of :class:`TreeState` — the
    second splice group (suffix inserts materialize new node rows)."""

    next: jnp.ndarray
    prev: jnp.ndarray
    node_container: jnp.ndarray
    value: jnp.ndarray
    value_seq: jnp.ndarray
    insert_seq: jnp.ndarray
    removed_seq: jnp.ndarray


class _TreeContPlanes(NamedTuple):
    """The ``[D, C]`` container-axis planes — the third splice group."""

    head: jnp.ndarray
    container_parent: jnp.ndarray


def _group(tuple_type, tree):
    return tuple_type(*(getattr(tree, f) for f in tuple_type._fields))


class TreeDeviceOps:
    """The tree family's tier-2.5 hooks.  All planes are int32 (no
    narrow encodings → ``migrate`` is a no-op and any signature move is
    a genuine bucket change), the aux planes are the per-doc used-row
    counts the digest masks by, and a suffix splice writes THREE donated
    row axes: edit rows (like the merge-tree op splice) plus the node
    and container state rows the suffix's inserts materialized."""

    @staticmethod
    def bypass(docs) -> bool:
        return False  # tree docs carry no binary-stream form

    @staticmethod
    def sig(state, edits) -> tuple:
        return tuple_sig(state, edits)

    @staticmethod
    def aux(meta):
        return _tree_aux(meta, True)

    @staticmethod
    def t_rows(host_edits) -> np.ndarray:
        return np.count_nonzero(
            np.asarray(host_edits.kind), axis=1).astype(np.int32)

    @staticmethod
    def entry_aux(meta):
        """Host row-count snapshot the NEXT splice diffs against."""
        return (np.asarray(meta["n_nodes"], np.int32),
                np.asarray(meta["n_cont"], np.int32))

    def migrate(self, cache, tokens, entry, sig, docs) -> None:
        return  # int32-only planes: no encoding flip exists

    def splice(self, cache: DevicePackCache, entry, docs,
               state: TreeState, edits: TreeEdits, meta: dict,
               sharding) -> Optional[int]:
        t_new = self.t_rows(edits)
        t_old = np.asarray(entry.t_rows, np.int32)
        n_new, c_new = self.aux(meta)
        n_old, c_old = entry.aux
        if np.any(t_new < t_old) or np.any(n_new < n_old) \
                or np.any(c_new < c_old):
            return None
        # Pre-flight EVERY host gather before the first donation: a
        # bail after donating would leave the entry half-spliced.
        ed_rows, _ = gather_suffix_rows(TreeEdits, edits, t_old, t_new)
        if ed_rows is None:
            return None  # suffix ~ whole buffer: full upload is cheaper
        node_rows = cont_rows = None
        if np.any(n_new > n_old):
            node_rows, _ = gather_suffix_rows(
                _TreeNodePlanes, _group(_TreeNodePlanes, state),
                n_old, n_new)
            if node_rows is None:
                return None
        if np.any(c_new > c_old):
            cont_rows, _ = gather_suffix_rows(
                _TreeContPlanes, _group(_TreeContPlanes, state),
                c_old, c_new)
            if cont_rows is None:
                return None
        uploaded = sum(v.nbytes for v in ed_rows.values()) \
            + 2 * t_new.nbytes
        new_edits = splice_row_planes(
            TreeEdits, entry.ops,
            TreeEdits(**{f: cache.put(v, sharding)
                         for f, v in ed_rows.items()}),
            cache.put(t_old, sharding),
            cache.put(t_new - t_old, sharding))
        entry.ops = new_edits
        node_group = _group(_TreeNodePlanes, entry.state)
        if node_rows is not None:
            uploaded += sum(v.nbytes for v in node_rows.values()) \
                + 2 * n_new.nbytes
            node_group = splice_row_planes(
                _TreeNodePlanes, node_group,
                _TreeNodePlanes(**{f: cache.put(v, sharding)
                                   for f, v in node_rows.items()}),
                cache.put(n_old, sharding),
                cache.put(n_new - n_old, sharding))
        cont_group = _group(_TreeContPlanes, entry.state)
        if cont_rows is not None:
            uploaded += sum(v.nbytes for v in cont_rows.values()) \
                + 2 * c_new.nbytes
            cont_group = splice_row_planes(
                _TreeContPlanes, cont_group,
                _TreeContPlanes(**{f: cache.put(v, sharding)
                                   for f, v in cont_rows.items()}),
                cache.put(c_old, sharding),
                cache.put(c_new - c_old, sharding))
        # Reassemble the resident state from the (possibly spliced)
        # groups; ``overflow`` is an input plane that suffix packs never
        # touch (always the initial zeros), so it carries over.
        entry.state = TreeState(
            head=cont_group.head,
            container_parent=cont_group.container_parent,
            overflow=entry.state.overflow,
            **{f: getattr(node_group, f)
               for f in _TreeNodePlanes._fields})
        # The digest masks by the NEW counts: refresh the resident aux
        # planes (tiny upload, counted).
        entry.base = (cache.put(n_new, sharding),
                      cache.put(c_new, sharding))
        uploaded += 2 * n_new.nbytes
        # Advance the splice watermark (the merge-tree twin does the
        # same): the NEXT splice must gather only rows past THIS one,
        # not re-upload everything since the last full store.
        entry.t_rows = t_new
        return int(uploaded)


# ---------------------------------------------------------------------------
# The family instance + public entry points
# ---------------------------------------------------------------------------


TREE_FAMILY = KernelFamily(
    name="tree",
    known_fallback=known_tree_fallback,
    fallback_summary=oracle_fallback_summary,
    pack=pack_tree_batch,
    bypass=lambda d: False,
    entry_rows=_tree_entry_rows,
    entry_nbytes=_tree_entry_nbytes,
    extend=_extend_tree,
    order=lambda batch, schedule: list(range(len(batch))),
    narrow=_tree_narrow,
    aux=_tree_aux,
    dispatch=_tree_dispatch,
    split_digest=_split_tree_digest,
    chunk_tag=lambda meta: None,
    fetch=export_to_numpy,
    gather_rows=gather_export_rows,
    extract=lambda meta, arr, st: summaries_from_tree_export(
        meta, arr, stats=st),
    per_doc_meta=("n_nodes", "n_cont", "t_rows"),
    make_pad=lambda: TreeDocInput(doc_id="\x00pad", ops=[]),
    pad_token=_mt_pad_token,
    dispatch_sharded=_tree_dispatch_sharded,
)


def tree_pack_cache(max_bytes: int = 192 << 20) -> PackCache:
    """A tier-2 pack cache bound to the tree family."""
    return PackCache(max_bytes, family=TREE_FAMILY)


def tree_device_cache(max_bytes: int = 192 << 20,
                      sharding=None) -> DevicePackCache:
    """A tier-2.5 device-resident cache bound to the tree family."""
    return DevicePackCache(max_bytes, sharding=sharding,
                           device_ops=TreeDeviceOps())


def pipelined_tree_replay(docs: Sequence[TreeDocInput], **kwargs):
    """Bulk SharedTree catch-up through the generic four-tier pipeline —
    the second instance of ``pipelined_family_replay`` (the merge-tree
    entry point is ``pipelined_mergetree_replay``).  Byte-identical to
    ``replay_tree_batch`` and the ``dds/tree.py`` oracle with every
    cache on, off, or freshly invalidated (pinned by
    tests/test_tree_pipeline.py)."""
    return pipelined_family_replay(TREE_FAMILY, docs, **kwargs)
