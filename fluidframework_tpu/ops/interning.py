"""Host-side interning: strings/JSON values ↔ dense int32 ids.

The device kernels operate on int32 tensors only; everything symbolic (client
ids, map keys, property keys, JSON values, text payloads) is interned on the
host during packing and restored during summary extraction.  Interning order
is deterministic (first-appearance in op order) so packing itself is
reproducible.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..protocol.summary import canonical_json


def next_bucket(n: int, floor: int = 64) -> int:
    """Round up to a power-of-two bucket so jitted kernels see a small, stable
    set of shapes instead of recompiling per batch."""
    size = floor
    while size < n:
        size *= 2
    return size


def next_bucket_fine(n: int, floor: int = 64) -> int:
    """Round up to the {f, 1.5f, 2f, 3f, 4f, 6f, ...} ladder — powers of two
    plus their 1.5× midpoints.  Twice the shape variants of
    :func:`next_bucket`, but up to 25% less padding: use it for dimensions
    whose cost is per-element on the hot path (device→host transfer bytes,
    scan length), not for axes that must divide a mesh."""
    size = floor
    while True:
        if n <= size:
            return size
        if n <= size * 3 // 2:
            return size * 3 // 2
        size *= 2


class Interner:
    """Dense id assignment by first appearance."""

    def __init__(self) -> None:
        self._by_key: Dict[Any, int] = {}
        self.values: List[Any] = []

    def intern(self, value: Any) -> int:
        key = self._hashable(value)
        idx = self._by_key.get(key)
        if idx is None:
            idx = len(self.values)
            self._by_key[key] = idx
            self.values.append(value)
        return idx

    def lookup(self, idx: int) -> Any:
        return self.values[idx]

    def __contains__(self, value: Any) -> bool:
        return self._hashable(value) in self._by_key

    def __len__(self) -> int:
        return len(self.values)

    @staticmethod
    def _hashable(value: Any):
        if isinstance(value, (dict, list)):
            return canonical_json(value)
        return value


class TextArena:
    """Append-only byte arena for text payloads; device state references
    (start, len) spans.  Kept host-side: the device tracks structure, not
    bytes (SURVEY.md §7 design stance)."""

    def __init__(self) -> None:
        self._chunks: List[str] = []
        self._length = 0

    def append(self, text: str) -> int:
        """Returns the start offset of the appended text (in characters)."""
        start = self._length
        self._chunks.append(text)
        self._length += len(text)
        return start

    def finalize(self) -> str:
        # Append-safe compaction: the catch-up pack cache shares one
        # arena between a cached chunk being extracted (finalize) and a
        # suffix extension appending new text.  Join a snapshot prefix
        # and splice it back over exactly those elements — a chunk
        # appended mid-join lands at index >= n and survives the slice
        # assignment (each list op is atomic under the GIL), where the
        # old wholesale `self._chunks = [joined]` would silently drop it.
        n = len(self._chunks)
        if n == 0:
            return ""
        if n > 1:
            joined = "".join(self._chunks[:n])
            self._chunks[:n] = [joined]
            return joined
        return self._chunks[0]

    def slice(self, start: int, length: int) -> str:
        return self.finalize()[start : start + length]

    def __len__(self) -> int:
        return self._length
