"""Device-resident pack buffers — tier 2.5 of the catch-up cache.

Tier 2 (:class:`~fluidframework_tpu.ops.pipeline.PackCache`) killed the
host *pack* work on warm catch-ups, and tier 0 made downloads delta-only
— but the **upload** leg stayed untouched: even on an exact tier-2 hit,
the pipeline re-uploads the full packed planes to the device on every
fold call.  On the recorded tunnel link
(``BENCH_tpu_measured_r05.json``: h2d 15 MB/s) that re-upload IS the
warm hot path.  This module keeps the packed chunk arrays resident in
device memory across fold calls, keyed by the chunk's ordered
``cache_token`` tuple — the same identity tier 2 already proves sound:

- **exact** hit (every doc's op window unchanged): the dispatch leg
  consumes the resident buffers directly — ZERO h2d bytes for ops,
  state and the per-doc aux planes;
- **suffix** hit (windows grew under the same pack-cache lineage): only
  the new suffix rows cross the link as fine-bucketed ``[D, L]`` row
  planes, and a jitted splice with ``donate_argnums`` writes them into
  the resident buffers IN PLACE — no 2× HBM spike, and the jit cache
  stays bounded because ``L`` rides the fine bucket ladder;
- anything else — bucket overflow (shape signature moved), a
  narrow↔wide transfer-encoding flip (dtype signature moved), unknown
  pack lineage, window mismatch — falls back to the full upload and
  re-stores.  The resident tier can lose a win, never corrupt.

The class is FAMILY-GENERIC since round 14: window matching, the LRU,
epoch invalidation, and the store/serve handshake are shared, while the
family-shaped pieces — the transfer-encoding signature, the donated
splice (merge-tree splices one op-row axis; the tree family splices edit
rows AND the node/container state rows its suffix inserts materialized
— see ops/tree_pipeline.py), and encoding migration — live on a small
*device-ops* object (:class:`MergeTreeDeviceOps` is the default).

Soundness of the suffix splice is *structural*, belt and braces:

- the token contract (append-only op stream over a pinned base within
  one storage generation) pins the shared prefix bytes;
- the **pack lineage** (``meta["_pack_lineage"]``, stamped by tier 2)
  additionally proves the host arrays in hand are the literal
  suffix-extension of the arrays the resident buffers were built from —
  a fresh repack (whose arena layout may legitimately differ) can never
  masquerade as an extension;
- the **encoding signature** (per-field dtype + shape of the narrowed
  upload arrays) pins the transfer encoding: an ``i16``→wide flip or a
  bucket change is a signature mismatch, not a corrupted splice.

Donation discipline: after the splice the PREVIOUS resident buffers are
dead (XLA reused their memory) — the entry swaps in the splice outputs
and the old references are never read again (the FL-TRACE-DONATE lint
rule pins this discipline package-wide).  All device interaction
(``device_put``, the splice dispatch) must happen on the caller's single
device thread — the same contract the pipeline already holds for
dispatch/fetch; the lock here guards only the entry map and counters.

Byte-bounded LRU over insertion order (no wall-clock — replay-safe),
epoch invalidation riding the existing fence/epoch sweeps (tokens carry
the storage epoch as component 0).  Counters: ``served`` (exact hits —
zero-upload dispatches), ``spliced`` (suffix splices), ``misses``,
``bypass``, ``inserts``, ``evictions``, ``invalidations``, and
``bytes_saved`` (h2d bytes the resident tier kept off the link).
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.telemetry import CounterSet
from .interning import next_bucket_fine
from .mergetree_kernel import MTOps, MTState, _widen_ops, _widen_state
# The shared tier-2/2.5 contracts live in the pipeline module (no
# cycle: pipeline never imports this module): _np_nbytes is THE "what
# the dispatch jit pushes over h2d" byte rule the reductions compare
# against, _doc_window/match_windows THE window-identity rules.
from .pipeline import _doc_window, _np_nbytes, match_windows


def _dev_nbytes(*trees) -> int:
    total = 0
    for tree in trees:
        if tree is None:
            continue
        total += int(sum(leaf.nbytes for leaf in jax.tree.leaves(tree)))
    return total


def tuple_sig(state, ops) -> tuple:
    """The transfer-encoding signature over namedtuple plane trees:
    per-field dtype + shape of the (already narrowed) upload arrays.
    Any bucket growth, narrow↔wide encoding flip, or cold↔warm change
    moves it — and a moved signature means the resident buffers cannot
    be extended, only replaced (unless the family's ``migrate`` can
    convert them in-graph)."""
    sig = tuple((f, str(getattr(ops, f).dtype), getattr(ops, f).shape)
                for f in type(ops)._fields)
    if state is not None:
        sig += tuple((f, str(getattr(state, f).dtype),
                      getattr(state, f).shape)
                     for f in type(state)._fields)
    return sig


def splice_row_planes(tuple_type, resident, rows, start, count):
    """Donated in-place row splice over a namedtuple of ``[D, L, ...]``
    planes: ``out[d, start[d] + j] = rows[d, j]`` for ``j < count[d]``
    — THE shared splice primitive (merge-tree op rows, tree edit rows,
    tree node/container state rows all ride it).  ``resident`` is
    DONATED; expressed as a clipped take-along-axis + masked select (no
    scatter), elementwise along the doc axis, so the same executable
    serves the sharded mesh placement with zero collectives."""
    return _splice_jit(tuple_type)(resident, rows, start, count)


def _splice_ops(ops: MTOps, rows: MTOps, start, count) -> MTOps:
    """The merge-tree instance of :func:`splice_row_planes` (the name
    the splice-parity tests pin).  ``ops`` is DONATED."""
    return splice_row_planes(MTOps, ops, rows, start, count)


@functools.lru_cache(maxsize=16)
def _splice_jit(tuple_type):
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _splice(resident, rows, start, count):
        lead = getattr(resident, tuple_type._fields[0])
        T = lead.shape[1]
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)  # [1, T]
        rel = t_idx - start[:, None]                            # [D, T]
        L = getattr(rows, tuple_type._fields[0]).shape[1]
        take = jnp.clip(rel, 0, L - 1)
        mask = (rel >= 0) & (rel < count[:, None])

        def one(field, r):
            if field.ndim == 2:
                return jnp.where(
                    mask, jnp.take_along_axis(r, take, axis=1), field)
            return jnp.where(
                mask[:, :, None],
                jnp.take_along_axis(r, take[:, :, None], axis=1),
                field)

        return tuple_type(*(one(getattr(resident, f), getattr(rows, f))
                            for f in tuple_type._fields))

    return _splice


def gather_suffix_rows(tuple_type, host_tree, t_old: np.ndarray,
                       t_new: np.ndarray, floor: int = 8):
    """Host-side suffix-row gather for the splice upload: each doc's
    rows ``[t_old[d], t_new[d])`` taken from the combined host planes
    into fine-bucketed ``[D, L, ...]`` arrays (pad rows clone the last
    valid index — masked out by the splice).  Returns ``(rows_np, L)``
    or ``(None, L)`` when ``L`` reaches the full plane width (the full
    upload is then cheaper than a splice)."""
    lead = np.asarray(getattr(host_tree, tuple_type._fields[0]))
    T = lead.shape[1]
    grow = int((t_new - t_old).max(initial=0))
    L = min(next_bucket_fine(max(grow, 1), floor=floor), T)
    if L >= T:
        return None, L
    idx = np.minimum(
        t_old[:, None] + np.arange(L, dtype=np.int32)[None, :], T - 1)
    rows_np = {}
    for f in tuple_type._fields:
        v = np.asarray(getattr(host_tree, f))
        take = idx if v.ndim == 2 else idx[:, :, None]
        rows_np[f] = np.take_along_axis(v, take, axis=1)
    return rows_np, L


class _ResidentEntry:
    """One chunk's device-resident upload state + the host bookkeeping
    needed to match and extend it."""

    __slots__ = ("tokens", "n_ops", "first_seq", "last_seq", "t_rows",
                 "sig", "gen", "state", "ops", "base", "aux", "nbytes",
                 "pinned", "spilled")

    def __init__(self, tokens, n_ops, first_seq, last_seq, t_rows, sig,
                 gen, state, ops, base, aux=None):
        self.tokens = tokens
        self.n_ops = n_ops
        self.first_seq = first_seq
        self.last_seq = last_seq
        self.t_rows = t_rows            # np [D]: used op rows per doc
        self.sig = sig
        self.gen = gen                  # tier-2 pack generation (or None)
        self.state = state              # device state tree or None (cold)
        self.ops = ops                  # device ops tree
        self.base = base                # device per-doc aux tree
        self.aux = aux                  # family host bookkeeping (counts)
        self.nbytes = _dev_nbytes(state, ops, base)
        #: resident-state tier (round 16): a pinned entry is exempt from
        #: the LRU sweep — the streaming fold keeps its working set live
        #: across fold calls.  Over the pin budget the oldest pinned
        #: entry SPILLS to host numpy copies (spilled=True): device HBM
        #: freed, the next acquire re-uploads from the host copy instead
        #: of repacking — a lost win, never corruption.
        self.pinned = False
        self.spilled = False


def _lineage_gen(meta: dict) -> Optional[int]:
    """The tier-2 pack generation of the host arrays in hand (None when
    tier 2 did not produce them — exact reuse only)."""
    lin = meta.get("_pack_lineage")
    return lin[-1] if lin else None


def _lineage_parent(meta: dict) -> Optional[int]:
    """For a suffix-extended pack, the generation it extended."""
    lin = meta.get("_pack_lineage")
    if lin and lin[0] == "suffix":
        return lin[1]
    return None


# ---------------------------------------------------------------------------
# The merge-tree device-ops instance
# ---------------------------------------------------------------------------


@jax.jit
def _widen_resident_ops(ops: MTOps, doc_base: jnp.ndarray) -> MTOps:
    """In-graph narrow→wide migration of resident op buffers (the
    kernel's own ``_widen_ops`` inverse — exact by construction).  Zero
    bytes cross the link: the whole point is that a chunk whose suffix
    text landed at the shared arena tail (blowing the int16 offset
    bound and flipping the upload encoding wide) can keep splicing
    instead of re-uploading the full planes.  No donation here — an
    int16 buffer cannot alias an int32 output; the narrow originals
    free by refcount the moment the entry swaps."""
    return _widen_ops(ops, doc_base)


@jax.jit
def _widen_resident_state(state: MTState,
                          doc_base: jnp.ndarray) -> MTState:
    """The warm-state twin of :func:`_widen_resident_ops`."""
    return _widen_state(state, doc_base)


class MergeTreeDeviceOps:
    """The merge-tree family's tier-2.5 hooks: int16/int8 narrow
    encodings (with the in-graph narrow→wide migration), a single
    op-row splice axis, and the per-doc arena base as the aux plane."""

    @staticmethod
    def bypass(docs) -> bool:
        return any(d.binary_ops is not None for d in docs)

    @staticmethod
    def sig(state, ops) -> tuple:
        return tuple_sig(state, ops)

    @staticmethod
    def aux(meta):
        return np.asarray(meta["doc_base"], np.int32)

    @staticmethod
    def t_rows(host_ops) -> np.ndarray:
        return np.count_nonzero(
            np.asarray(host_ops.kind), axis=1).astype(np.int32)

    @staticmethod
    def entry_aux(meta):
        return None

    @staticmethod
    def _widened_sig(sig: tuple) -> tuple:
        """The signature the same arrays would carry in the WIDE (int32)
        transfer encoding — shapes unchanged, every non-bool dtype
        int32."""
        return tuple((f, dt if dt == "bool" else "int32", shape)
                     for f, dt, shape in sig)

    def migrate(self, cache: "DevicePackCache", tokens,
                entry: _ResidentEntry, sig: tuple, docs) -> None:
        if not (entry.sig != sig
                and self._widened_sig(entry.sig) == sig
                and cache.match(entry, docs) is not None):
            return
        # The ONLY signature change is a narrow→wide transfer-
        # encoding flip (full-scale suffix growth does this: the new
        # text lands at the shared arena tail, blowing the int16
        # offset bound).  Migrate the resident buffers to the wide
        # encoding IN-GRAPH — donated, zero bytes over the link —
        # so the window can still serve/splice.
        old_nbytes = entry.nbytes
        entry.ops = _widen_resident_ops(entry.ops, entry.base)
        if entry.state is not None:
            entry.state = _widen_resident_state(entry.state,
                                                entry.base)
        entry.sig = sig
        entry.nbytes = _dev_nbytes(entry.state, entry.ops, entry.base)
        cache.reaccount_migrated(tokens, entry, old_nbytes)

    def splice(self, cache: "DevicePackCache", entry: _ResidentEntry,
               docs, state, ops: MTOps, meta: dict,
               sharding) -> Optional[int]:
        """Upload only the suffix rows and extend the resident op
        buffers via the donated splice; returns uploaded bytes, or None
        when the extension does not apply (caller full-uploads).  The
        base state of a warm chunk is pinned by the token (it derives
        from the base summary alone), so only the op planes move."""
        t_new = self.t_rows(ops)
        t_old = entry.t_rows
        if np.any(t_new < t_old):
            return None
        rows_np, _L = gather_suffix_rows(MTOps, ops, t_old, t_new)
        if rows_np is None:
            return None  # suffix ~ whole buffer: full upload is cheaper
        uploaded = sum(v.nbytes for v in rows_np.values()) \
            + 2 * t_new.nbytes
        rows = MTOps(**{f: cache.put(v, sharding)
                        for f, v in rows_np.items()})
        start = cache.put(t_old, sharding)
        count = cache.put(t_new - t_old, sharding)
        new_ops = splice_row_planes(MTOps, entry.ops, rows, start, count)
        # The donated input buffers are DEAD past this point: the entry
        # swaps in the splice outputs and the old references are never
        # touched again.
        entry.ops = new_ops
        entry.t_rows = t_new
        return int(uploaded)


class DevicePackCache:
    """Byte-bounded LRU of device-resident packed chunk buffers (see the
    module docstring).  ``sharding`` (a ``jax.sharding.NamedSharding``)
    places entries on a mesh — the sharded fold passes its doc-sharded
    placement so mesh and single-device serve the identical tier.
    ``device_ops`` selects the family (default: merge-tree)."""

    def __init__(self, max_bytes: int = 192 << 20, sharding=None,
                 device_ops=None, pin_max_bytes: int = 64 << 20) -> None:
        self.max_bytes = int(max_bytes)
        #: device-byte budget for the PINNED tier (resident doc state of
        #: the streaming fold).  Separate from ``max_bytes`` so a wide
        #: pinned working set cannot starve the ordinary LRU tier, and
        #: vice versa.
        self.pin_max_bytes = int(pin_max_bytes)
        self._fam = device_ops if device_ops is not None \
            else MergeTreeDeviceOps()
        self._lock = threading.Lock()
        # tokens -> _ResidentEntry (insertion order = LRU order)
        self._entries: dict = {}  # guarded-by: _lock
        self._bytes = 0       # device bytes of unspilled entries
        self._host_bytes = 0  # host bytes of spilled entries
        self._pinned_bytes = 0  # device bytes of pinned, unspilled entries
        self._last_epoch = None  # guarded-by: _lock
        self._sharding = sharding
        self.counters = CounterSet(
            "served", "spliced", "misses", "bypass", "inserts",
            "evictions", "invalidations", "bytes_saved",
            "pins", "unpins", "spills", "unspills",
        )  # guarded-by: _lock (CounterSet is not internally synchronized)

    # -- placement -------------------------------------------------------------

    def set_sharding(self, sharding) -> None:
        """Pin the device placement (mesh path; idempotent — NamedSharding
        compares by value).  CHANGING an established placement drops the
        resident entries: buffers laid out for one placement must never
        serve another."""
        with self._lock:
            if sharding is self._sharding or sharding == self._sharding:
                return
            self._sharding = sharding
            dropped = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._host_bytes = 0
            self._pinned_bytes = 0
            self.counters.bump("evictions", dropped)

    @staticmethod
    def put(x, sharding):
        # ``sharding`` is the caller's one-per-acquire snapshot (taken
        # under the lock), so one entry can never end up split across
        # placements by a racing set_sharding.
        if sharding is not None:
            return jax.device_put(jnp.asarray(x), sharding)
        return jax.device_put(jnp.asarray(x))

    @classmethod
    def put_tree(cls, tree, sharding):
        if tree is None:
            return None
        return jax.tree.map(lambda leaf: cls.put(leaf, sharding), tree)

    # -- the pinned resident-state tier (round 16) -----------------------------

    def _pool_sub(self, entry: _ResidentEntry) -> None:  # holds-lock
        if entry.spilled:
            self._host_bytes -= entry.nbytes
        else:
            self._bytes -= entry.nbytes
            if entry.pinned:
                self._pinned_bytes -= entry.nbytes

    def _pool_add(self, entry: _ResidentEntry) -> None:  # holds-lock
        if entry.spilled:
            self._host_bytes += entry.nbytes
        else:
            self._bytes += entry.nbytes
            if entry.pinned:
                self._pinned_bytes += entry.nbytes

    def _spill_locked(self, entry: _ResidentEntry) -> None:
        """Move an entry's buffers to host numpy copies (device HBM
        freed by refcount once the caller's references die).  Holds
        _lock; MUST run on the device-interaction thread (d2h)."""
        self._pool_sub(entry)
        entry.state = jax.tree.map(np.asarray, entry.state) \
            if entry.state is not None else None
        entry.ops = jax.tree.map(np.asarray, entry.ops)
        entry.base = jax.tree.map(np.asarray, entry.base) \
            if entry.base is not None else None
        entry.spilled = True
        self._pool_add(entry)
        self.counters.bump("spills")

    def _enforce_pin_budget(self, keep) -> None:  # holds-lock: _lock
        """Spill oldest pinned entries until the pinned tier fits its
        device-byte budget; ``keep`` (a tokens key) is spilled LAST —
        it is the entry the caller is actively serving."""
        while self._pinned_bytes > self.pin_max_bytes:
            victim = next(
                (k for k, e in self._entries.items()
                 if e.pinned and not e.spilled and k != keep), None)
            if victim is None:
                victim = keep if keep in self._entries else None
                if victim is None or self._entries[victim].spilled:
                    break
            self._spill_locked(self._entries[victim])

    def pin(self, tokens) -> bool:
        """Mark the chunk's resident entry as pinned doc state: exempt
        from the LRU sweep, budgeted by ``pin_max_bytes`` with
        spill-to-host (oldest-pinned-first) when the pinned set grows
        past it.  Returns False when no entry exists for ``tokens``.
        MUST be called from the device-interaction thread (a budget
        overflow spills — a d2h copy)."""
        with self._lock:
            entry = self._entries.get(tokens)
            if entry is None:
                return False
            if not entry.pinned:
                entry.pinned = True
                if not entry.spilled:
                    self._pinned_bytes += entry.nbytes
                self.counters.bump("pins")
                self._enforce_pin_budget(tokens)
            return True

    def unpin(self, tokens) -> bool:
        """Return a pinned entry to ordinary LRU life (a spilled one
        stays spilled until its next acquire re-uploads it)."""
        with self._lock:
            entry = self._entries.get(tokens)
            if entry is None or not entry.pinned:
                return False
            entry.pinned = False
            if not entry.spilled:
                self._pinned_bytes -= entry.nbytes
            self.counters.bump("unpins")
            return True

    def _restore_spilled(self, entry: _ResidentEntry, sharding) -> int:
        """Re-upload a spilled entry's host copies (the spill's other
        half).  Returns the h2d bytes.  Caller thread = device thread;
        the lock is NOT held across the uploads (they are slow) — the
        entry object is private to the acquiring thread by the tier's
        single-device-thread contract."""
        entry.state = self.put_tree(entry.state, sharding)
        entry.ops = self.put_tree(entry.ops, sharding)
        entry.base = self.put_tree(entry.base, sharding)
        with self._lock:
            if self._entries.get(entry.tokens) is entry:
                self._host_bytes -= entry.nbytes
                entry.spilled = False
                self._bytes += entry.nbytes
                if entry.pinned:
                    self._pinned_bytes += entry.nbytes
                    self._enforce_pin_budget(entry.tokens)
                self._sweep_unpinned(keep=entry.tokens)
            else:
                entry.spilled = False
            self.counters.bump("unspills")
        return entry.nbytes

    def _sweep_unpinned(self, keep=None) -> None:  # holds-lock: _lock
        """Evict oldest UNPINNED entries until the device pool fits —
        the LRU sweep of the cache tier; the pinned tier never evicts
        (it spills instead, on its own budget)."""
        while self._bytes > self.max_bytes:
            victim = next(
                (k for k, e in self._entries.items()
                 if not e.pinned and not e.spilled and k != keep), None)
            if victim is None:
                break
            self._pool_sub(self._entries.pop(victim))
            self.counters.bump("evictions")

    # -- introspection ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            out = self.counters.snapshot()
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
            out["pinned_entries"] = sum(
                1 for e in self._entries.values() if e.pinned)
            out["pinned_bytes"] = self._pinned_bytes
            out["spilled_bytes"] = self._host_bytes
        return out

    # -- the dispatch-side handshake -------------------------------------------

    def acquire(self, state, ops, meta: dict, pin: bool = False):
        """Device-resident ``(state, ops, aux, h2d_bytes)`` for a packed
        chunk about to dispatch: the resident buffers on an exact hit
        (zero upload), a donated suffix splice on a lineage-proven
        extension, else a full upload that (re)stores the entry.
        Token-less / family-bypass chunks bypass and return the host
        arrays unchanged (``aux=None`` — the dispatcher derives it as
        before); ``h2d_bytes`` is what this call actually put on the
        link.  MUST be called from the single device-interaction thread
        (the pipeline's dispatch leg / the mesh fold).

        ``pin=True`` (the streaming fold) additionally pins the served
        entry into the resident-state tier — see :meth:`pin`.  A spilled
        pinned entry that matches is restored by re-uploading its host
        copies (cheaper than a repack; counted in ``h2d_bytes``)."""
        docs = meta["docs"]
        tokens = tuple(d.cache_token for d in docs)
        if any(t is None for t in tokens) or self._fam.bypass(docs):
            with self._lock:
                self.counters.bump("bypass")
            return state, ops, None, _np_nbytes(state) + _np_nbytes(ops)
        sig = self._fam.sig(state, ops)
        full_bytes = _np_nbytes(state) + _np_nbytes(ops)
        with self._lock:
            entry = self._entries.get(tokens)
            sharding = self._sharding
        if entry is not None and entry.sig != sig and not entry.spilled:
            self._fam.migrate(self, tokens, entry, sig, docs)
        if entry is not None and entry.sig == sig:
            kind = self.match(entry, docs)
            restored = 0
            if kind is not None and entry.spilled:
                restored = self._restore_spilled(entry, sharding)
            if kind == "exact":
                with self._lock:
                    self._touch(tokens)
                    self.counters.bump("served")
                    self.counters.bump("bytes_saved",
                                       max(0, full_bytes - restored))
                gen = _lineage_gen(meta)
                if gen is not None:
                    # Content is equal either way; tracking the freshest
                    # tier-2 generation keeps future suffix lineage
                    # checks matching.
                    entry.gen = gen
                if pin:
                    self.pin(tokens)
                return entry.state, entry.ops, entry.base, restored
            if kind == "suffix" and entry.gen is not None \
                    and _lineage_parent(meta) == entry.gen:
                uploaded = self._fam.splice(self, entry, docs, state,
                                            ops, meta, sharding)
                if uploaded is not None:
                    uploaded += restored
                    self._refresh_windows(entry, docs, meta)
                    with self._lock:
                        self._touch(tokens)
                        self.counters.bump("spliced")
                        self.counters.bump("bytes_saved",
                                           max(0, full_bytes - uploaded))
                    if pin:
                        self.pin(tokens)
                    return entry.state, entry.ops, entry.base, uploaded
        # Miss / signature moved / unprovable lineage: full upload.
        with self._lock:
            self.counters.bump("misses")
        state_dev = self.put_tree(state, sharding)
        ops_dev = self.put_tree(ops, sharding)
        aux_host = self._fam.aux(meta)
        base_dev = self.put_tree(aux_host, sharding)
        self._store(tokens, docs, sig, _lineage_gen(meta), state_dev,
                    ops_dev, base_dev, ops, meta, pin=pin)
        base_bytes = _np_nbytes(tuple(jax.tree.leaves(aux_host)))
        return state_dev, ops_dev, base_dev, full_bytes + base_bytes

    # -- matching --------------------------------------------------------------

    @staticmethod
    def match(entry: _ResidentEntry, docs) -> Optional[str]:
        """The shared tier-2/2.5 window rule (``match_windows``) over
        the resident entry's bookkeeping."""
        return match_windows(entry.n_ops, entry.first_seq,
                             entry.last_seq, docs)

    def _refresh_windows(self, entry: _ResidentEntry, docs,
                         meta: dict) -> None:
        """After a successful splice: advance the entry's window
        bookkeeping, lineage generation, and family aux counts to the
        combined (extended) chunk."""
        n_ops, first_seq, last_seq = [], [], []
        for doc in docs:
            n, first, last = _doc_window(doc)
            n_ops.append(n)
            first_seq.append(first)
            last_seq.append(last)
        entry.n_ops = n_ops
        entry.first_seq = first_seq
        entry.last_seq = last_seq
        entry.gen = _lineage_gen(meta)
        entry.aux = self._fam.entry_aux(meta)

    # -- bookkeeping -----------------------------------------------------------

    def reaccount_migrated(self, tokens, entry: _ResidentEntry,
                           old_nbytes: int) -> None:
        """Re-account an encoding-migrated entry (~2× the bytes) in
        ONE identity-guarded critical section: the adjustment applies
        only if the map still holds THE entry that was migrated, and the
        LRU sweep rebalances the budget (the migrated entry itself is
        never evicted mid-serve — if it alone exceeds the budget it is
        un-mapped, same policy as _store's never-admit rule, while this
        call keeps serving its arrays)."""
        with self._lock:
            if self._entries.get(tokens) is not entry:
                return
            delta = entry.nbytes - old_nbytes
            self._bytes += delta
            if entry.pinned:
                self._pinned_bytes += delta
                self._enforce_pin_budget(tokens)
            self._sweep_unpinned(keep=tokens)
            if self._bytes > self.max_bytes and not entry.pinned:
                self._pool_sub(self._entries.pop(tokens))
                self.counters.bump("evictions")

    def _touch(self, tokens) -> None:  # holds-lock: _lock
        entry = self._entries.pop(tokens, None)
        if entry is not None:
            self._entries[tokens] = entry

    def _store(self, tokens, docs, sig, gen, state_dev, ops_dev, base_dev,
               host_ops, meta: dict, pin: bool = False) -> None:
        n_ops, first_seq, last_seq = [], [], []
        for doc in docs:
            n, first, last = _doc_window(doc)
            n_ops.append(n)
            first_seq.append(first)
            last_seq.append(last)
        t_rows = self._fam.t_rows(host_ops)
        entry = _ResidentEntry(tokens, n_ops, first_seq, last_seq, t_rows,
                               sig, gen, state_dev, ops_dev, base_dev,
                               aux=self._fam.entry_aux(meta))
        with self._lock:
            old = self._entries.pop(tokens, None)
            if old is not None:
                self._pool_sub(old)
                # A re-store inherits the old entry's pin: the pin names
                # the DOC's resident state, not one encoding of it.
                entry.pinned = old.pinned
            entry.pinned = entry.pinned or pin
            if entry.nbytes > self.max_bytes:
                self.counters.bump("evictions")
                return
            self._entries[tokens] = entry
            self._bytes += entry.nbytes
            if entry.pinned:
                self._pinned_bytes += entry.nbytes
                if pin and (old is None or not old.pinned):
                    self.counters.bump("pins")
                self._enforce_pin_budget(tokens)
            self.counters.bump("inserts")
            self._sweep_unpinned(keep=tokens)

    # -- epoch invalidation ----------------------------------------------------

    def invalidate_epoch(self, current_epoch: str) -> int:
        """Drop entries holding any token pinned to a DIFFERENT storage
        generation (token component 0 is the epoch — same contract as
        tiers 0/1, riding the same server-side sweep).  O(1) while the
        epoch is unchanged."""
        with self._lock:
            if current_epoch == self._last_epoch:
                return 0
            self._last_epoch = current_epoch
            stale = [key for key in self._entries
                     if any(tok[0] != current_epoch for tok in key)]
            for key in stale:
                # Pins do not survive an epoch flip: the pinned state
                # was derived under the dead storage generation.
                self._pool_sub(self._entries.pop(key))
                self.counters.bump("invalidations")
        return len(stale)
