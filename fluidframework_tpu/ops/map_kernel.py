"""SharedMap LWW catch-up replay on device.

The first TPU kernel (SURVEY.md §7 layer 3): last-writer-wins key-set replay
expressed as *segment reductions* — no scan, no sequential dependence.  For a
batch of documents, the entire replay is:

    winner(key)  = the set/delete op with max seq per (doc, key)
    cleared(doc) = max seq over clear ops per doc
    present(key) = winner is a set  AND  winner.seq > cleared(doc)

Sequence numbers are unique, so "op with max seq" is exact.  Base state loaded
from a summary enters as synthetic set ops at seq 0.  The result maps back
through the interners into the *same canonical summary bytes* the CPU oracle
produces — byte-identity is asserted by tests.

Scaling note: ops from any number of documents concatenate into one flat
batch; document parallelism is free (segment ids encode the doc), and the
arrays shard over a device mesh along the op axis with psum-style segment
combines.  Shapes are padded to power-of-two buckets to avoid recompiles.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .interning import Interner, next_bucket

_NEG = np.int32(np.iinfo(np.int32).min)


@dataclass
class MapDocInput:
    """One document's catch-up work item."""

    doc_id: str
    ops: Sequence[SequencedMessage]  # map-kernel op contents, ascending seq
    base: Optional[Dict[str, Any]] = None  # data loaded from the summary


@dataclass
class _PackedBatch:
    key_gid: np.ndarray     # [N] global (doc, key) id for set/delete ops
    op_seq: np.ndarray      # [N]
    is_set: np.ndarray      # [N] 1=set, 0=delete
    val_idx: np.ndarray     # [N] interned value id (sets only)
    key_doc: np.ndarray     # [G] doc index per global key id
    clear_doc: np.ndarray   # [M] doc index per clear op
    clear_seq: np.ndarray   # [M]
    num_keys: int
    num_docs: int
    keys: List[tuple] = field(default_factory=list)   # gid -> (doc_idx, key str)
    values: Interner = field(default_factory=Interner)
    doc_ids: List[str] = field(default_factory=list)


def pack_map_batch(docs: Sequence[MapDocInput],
                   bucket_floor: int = 64) -> _PackedBatch:
    """Flatten a multi-document op log into device arrays (host side).

    ``bucket_floor`` sets the minimum flat-array bucket; mesh-sharded
    callers pass the mesh size so the op axis always splits evenly."""
    keys = Interner()
    values = Interner()
    key_gid, op_seq, is_set, val_idx = [], [], [], []
    clear_doc, clear_seq = [], []

    def add_set(doc_idx: int, key: str, seq: int, value: Any) -> None:
        key_gid.append(keys.intern((doc_idx, key)))
        op_seq.append(seq)
        is_set.append(1)
        val_idx.append(values.intern(value))

    for doc_idx, doc in enumerate(docs):
        if doc.base:
            for key, value in doc.base.items():
                add_set(doc_idx, key, 0, value)
        for msg in doc.ops:
            if msg.type is not MessageType.OP:
                continue
            op = msg.contents
            kind = op["kind"]
            if kind == "set":
                add_set(doc_idx, op["key"], msg.seq, op["value"])
            elif kind == "delete":
                key_gid.append(keys.intern((doc_idx, op["key"])))
                op_seq.append(msg.seq)
                is_set.append(0)
                val_idx.append(-1)
            elif kind == "clear":
                clear_doc.append(doc_idx)
                clear_seq.append(msg.seq)
            else:
                raise ValueError(f"unknown map op kind {kind!r}")

    floor = max(64, bucket_floor)

    def bucket(count: int) -> int:
        size = next_bucket(max(count, 1), floor=floor)
        if bucket_floor > 1 and size % bucket_floor:
            # Non-power-of-two mesh sizes (e.g. 5 devices) don't divide the
            # pow2 ladder — round up so the flat axis always shards evenly.
            size += bucket_floor - size % bucket_floor
        return size

    n = bucket(len(op_seq))
    m = bucket(len(clear_seq))
    g = bucket(len(keys))

    def pad(lst, size, fill):
        arr = np.full(size, fill, dtype=np.int32)
        arr[: len(lst)] = np.asarray(lst, dtype=np.int32) if lst else []
        return arr

    key_doc = pad([doc for doc, _ in keys.values], g, 0)
    return _PackedBatch(
        key_gid=pad(key_gid, n, g - 1 if len(keys) < g else 0),
        op_seq=pad(op_seq, n, int(_NEG)),
        is_set=pad(is_set, n, 0),
        val_idx=pad(val_idx, n, -1),
        key_doc=key_doc,
        clear_doc=pad(clear_doc, m, 0),
        clear_seq=pad(clear_seq, m, int(_NEG)),
        num_keys=g,
        num_docs=len(docs),
        keys=list(keys.values),
        values=values,
        doc_ids=[d.doc_id for d in docs],
    )


@functools.partial(jax.jit, static_argnames=("num_keys", "num_docs"))
def _map_lww_kernel(
    key_gid, op_seq, is_set, val_idx, key_doc, clear_doc, clear_seq,
    *, num_keys: int, num_docs: int,
):
    """present[g], winner_val[g] per global key — two segment reductions."""
    max_seq = jax.ops.segment_max(op_seq, key_gid, num_segments=num_keys)
    last_clear = jax.ops.segment_max(
        clear_seq, clear_doc, num_segments=num_docs
    )
    winner = op_seq == max_seq[key_gid]  # seqs are unique
    win_set = jax.ops.segment_max(
        jnp.where(winner, is_set, -1), key_gid, num_segments=num_keys
    )
    win_val = jax.ops.segment_max(
        jnp.where(winner, val_idx, -1), key_gid, num_segments=num_keys
    )
    present = (win_set == 1) & (max_seq > last_clear[key_doc])
    return present, win_val


def replay_map_batch(docs: Sequence[MapDocInput],
                     stats: Optional[dict] = None) -> List[SummaryTree]:
    """Full pipeline: pack → device LWW reduction → canonical summaries.

    Returns one SummaryTree per input doc whose bytes equal
    ``SharedMap.summarize()`` after the oracle applies the same ops.
    The LWW reduction has no oracle-fallback cases, so ``stats`` counts
    every doc as a device doc.
    """
    if stats is not None:
        stats["device_docs"] = stats.get("device_docs", 0) + len(docs)
    batch = pack_map_batch(docs)
    present, win_val = _map_lww_kernel(
        batch.key_gid,
        batch.op_seq,
        batch.is_set,
        batch.val_idx,
        batch.key_doc,
        batch.clear_doc,
        batch.clear_seq,
        num_keys=batch.num_keys,
        num_docs=batch.num_docs,
    )
    return summaries_from_lww(batch, present, win_val)


def summaries_from_lww(batch: _PackedBatch, present, win_val
                       ) -> List[SummaryTree]:
    """Device LWW reduction results → canonical per-doc summaries (shared
    by the single-chip and mesh-sharded paths)."""
    present = np.asarray(present)
    win_val = np.asarray(win_val)
    data_per_doc: List[Dict[str, Any]] = [
        dict() for _ in range(batch.num_docs)
    ]
    for gid, (doc_idx, key) in enumerate(batch.keys):
        if present[gid]:
            data_per_doc[doc_idx][key] = batch.values.lookup(int(win_val[gid]))
    out = []
    for data in data_per_doc:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json({"data": data}))
        out.append(tree)
    return out
