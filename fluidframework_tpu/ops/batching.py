"""Shared batch-partitioning: known-fallback docs to the oracle, the rest
through a device batch function, results scattered back in input order.

One implementation of the split/scatter bookkeeping for every kernel's
``replay_*_batch`` / ``replay_*_sharded`` entry point (the pattern was
previously hand-rolled per kernel; review-found)."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, TypeVar, Union

Doc = TypeVar("Doc")
Result = TypeVar("Result")


def count_fallback(stats: Optional[dict], reason: Union[bool, str]) -> None:
    """Bump the shared fallback counters: the total ``fallback_docs``
    plus — when the predicate names WHY (a reason string instead of a
    bare True) — a per-reason ``fallback_<reason>`` counter, so a bench
    can report revive vs multi-id-move vs MAX_DEPTH instead of one
    opaque number.  THE one counting point for pre-pack routing and the
    extractors' post-fold fallbacks alike (the split must sum to the
    total by construction, not by discipline)."""
    if stats is None:
        return
    stats["fallback_docs"] = stats.get("fallback_docs", 0) + 1
    if isinstance(reason, str) and reason:
        key = f"fallback_{reason}"
        stats[key] = stats.get(key, 0) + 1


def partition_replay(
    docs: Sequence[Doc],
    known_fallback: Callable[[Doc], Union[bool, str, None]],
    fallback_fn: Callable[[Doc], Result],
    batch_fn: Callable[[List[Doc]], List[Result]],
    stats: Optional[dict] = None,
) -> List[Result]:
    """Route docs matching ``known_fallback`` through ``fallback_fn`` (the
    oracle), fold the rest as one device batch, and return results in the
    original order.  Filtering first keeps fallback docs from inflating the
    shared power-of-two pack buckets and wasting their shard of the fold.
    ``known_fallback`` may return a plain truthy value or a REASON string;
    ``stats`` (optional dict) then accumulates ``fallback_docs`` plus a
    per-reason ``fallback_<reason>`` counter for the pre-pack routing
    (post-fold fallbacks are the extractors' to count, through the same
    :func:`count_fallback`)."""
    if not docs:
        return []
    out: List[Optional[Result]] = [None] * len(docs)
    device_idx: List[int] = []
    for i, doc in enumerate(docs):
        reason = known_fallback(doc)
        if reason:
            out[i] = fallback_fn(doc)
            count_fallback(stats, reason)
        else:
            device_idx.append(i)
    if device_idx:
        results = batch_fn([docs[i] for i in device_idx])
        assert len(results) == len(device_idx)
        for d, i in enumerate(device_idx):
            out[i] = results[d]
    return out
