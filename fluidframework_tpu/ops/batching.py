"""Shared batch-partitioning: known-fallback docs to the oracle, the rest
through a device batch function, results scattered back in input order.

One implementation of the split/scatter bookkeeping for every kernel's
``replay_*_batch`` / ``replay_*_sharded`` entry point (the pattern was
previously hand-rolled per kernel; review-found)."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, TypeVar

Doc = TypeVar("Doc")
Result = TypeVar("Result")


def partition_replay(
    docs: Sequence[Doc],
    known_fallback: Callable[[Doc], bool],
    fallback_fn: Callable[[Doc], Result],
    batch_fn: Callable[[List[Doc]], List[Result]],
    stats: Optional[dict] = None,
) -> List[Result]:
    """Route docs matching ``known_fallback`` through ``fallback_fn`` (the
    oracle), fold the rest as one device batch, and return results in the
    original order.  Filtering first keeps fallback docs from inflating the
    shared power-of-two pack buckets and wasting their shard of the fold.
    ``stats`` (optional dict) accumulates a ``fallback_docs`` counter for
    the pre-pack routing (post-fold fallbacks are the extractors' to
    count)."""
    if not docs:
        return []
    out: List[Optional[Result]] = [None] * len(docs)
    device_idx: List[int] = []
    for i, doc in enumerate(docs):
        if known_fallback(doc):
            out[i] = fallback_fn(doc)
            if stats is not None:
                stats["fallback_docs"] = stats.get("fallback_docs", 0) + 1
        else:
            device_idx.append(i)
    if device_idx:
        results = batch_fn([docs[i] for i in device_idx])
        assert len(results) == len(device_idx)
        for d, i in enumerate(device_idx):
            out[i] = results[d]
    return out
