"""Chunked, pipelined bulk catch-up replay — the PRODUCT's version of
the bench harness's e2e loop (SURVEY §3.2: catch-up is the north-star
path, and the service must not be slower than the benchmark of itself).

Round 14: the pipeline is KERNEL-FAMILY-GENERIC.  Everything below —
chunking, the thread-pool pack/extract legs, the single-device-thread
dispatch/fetch contract, the tier-2 :class:`PackCache`, the tier-2.5
device-resident handshake, the tier-0 digest-gated delta download, and
the ``pack/upload/dispatch/device_wait/download/extract`` +
``h2d_bytes``/``d2h_bytes`` stage schema — runs through a
:class:`~fluidframework_tpu.ops.family.KernelFamily` descriptor.
``pipelined_mergetree_replay`` is the merge-tree instance;
``ops/tree_pipeline.py`` registers the SharedTree rebaser as the second
(the PAPER §0 pair), and a third family (matrix) can ride for free.

Shape (round-5 pipeline, BASELINE.md):

- documents are chunked (``chunk_docs``) so jitted shapes stay bucketed
  and per-transfer sizes bounded;
- chunks are fact-scheduled (annotate-free docs grouped) so the majority
  volume folds with the props plane traced away — results return in the
  CALLER's order regardless;
- packing (C++, GIL-released) runs in a thread pool; extraction
  likewise; ALL device interaction — dispatch, ``copy_to_host_async``,
  the blocking fetch — stays on the calling thread.  The axon client
  degrades persistently (~70–90 ms/call) when a second thread fetches
  while another dispatches (BASELINE.md round-5 measurement), and a
  single device thread also serializes correctly on every backend;
- the blocking fetch trails the dispatch front by ``fetch_depth`` chunks
  so upload/fold/download overlap without a second device thread;
- oracle-fallback docs route around the device exactly like
  ``replay_mergetree_batch`` (shared ``partition_replay`` + post-fold
  overflow handling inside ``summaries_from_export``).
"""

from __future__ import annotations

import collections
import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from time import perf_counter
from typing import List, Optional, Sequence

import numpy as np

from .batching import partition_replay
from .family import KernelFamily
from .interning import Interner, next_bucket_fine
from .mergetree_kernel import (
    I8_LIMIT,
    I16_LIMIT,
    K_INSERT,
    K_NOOP,
    K_OBLITERATE,
    MTOps,
    MergeTreeDocInput,
    NOT_REMOVED,
    _export_flags,
    export_to_numpy,
    fill_sequence_op_rows,
    gather_export_rows,
    known_oracle_fallback,
    narrow_ops_for_upload,
    narrow_state_for_upload,
    oracle_fallback_summary,
    pack_mergetree_batch,
    replay_export,
    split_export_digest,
    summaries_from_export,
)


# ---------------------------------------------------------------------------
# Pack cache (tier 2 of the catch-up cache): packed-chunk reuse
# ---------------------------------------------------------------------------


def _copy_interner(src: Interner) -> Interner:
    out = Interner()
    out._by_key = dict(src._by_key)
    out.values = list(src.values)
    return out


def _copy_doc_pack(pack):
    from .mergetree_kernel import _DocPack

    out = _DocPack()
    out.clients = _copy_interner(pack.clients)
    out.interval_ops = list(pack.interval_ops)
    out.needs_fallback = pack.needs_fallback
    return out


#: monotone pack-generation ids — the lineage tokens tier 2 stamps into
#: ``meta["_pack_lineage"]`` so the device-resident tier (tier 2.5,
#: ops/device_cache.py) can PROVE a set of host arrays is the literal
#: suffix-extension of what it holds resident.  itertools.count.__next__
#: is atomic under CPython, so the stamp needs no extra locking.  ONE
#: counter across every family: a generation id never collides between
#: the merge-tree and tree caches.
_PACK_GEN = itertools.count(1)


class _PackEntry:
    """One cached packed window: the wide (pre-narrow) chunk arrays plus
    the per-doc window bookkeeping needed to match and extend it."""

    __slots__ = ("tokens", "n_ops", "first_seq", "last_seq", "t_rows",
                 "state", "ops", "meta", "nbytes", "gen")

    def __init__(self, tokens, n_ops, first_seq, last_seq, t_rows,
                 state, ops, meta, nbytes, gen=0):
        self.gen = gen
        self.tokens = tokens
        self.n_ops = n_ops
        self.first_seq = first_seq
        self.last_seq = last_seq
        self.t_rows = t_rows
        self.state = state
        self.ops = ops
        self.meta = meta
        self.nbytes = nbytes


def _doc_window(doc):
    n = len(doc.ops)
    if n == 0:
        return 0, 0, 0
    return n, doc.ops[0].seq, doc.ops[-1].seq


def match_windows(n_ops, first_seq, last_seq, chunk) -> Optional[str]:
    """THE window-matching rule shared by tier 2 (:class:`PackCache`)
    and tier 2.5 (``ops/device_cache.DevicePackCache``), for EVERY
    family (both carry ascending-seq message lists): "exact" when every
    doc's op window is unchanged vs the cached per-doc
    ``(n_ops, first_seq, last_seq)``, "suffix" when every window
    extends its cached one (same first seq, the old tail still in
    place, any new rows strictly past it — same-seq rows only ever
    arrive inside one sequenced message, which the cached window
    already held in full), else None.  One derivation point: the two
    tiers deciding differently would let resident device buffers
    disagree with the packed host arrays they mirror."""
    exact = True
    for d, doc in enumerate(chunk):
        n, first, _last = _doc_window(doc)
        cached_n = n_ops[d]
        if n < cached_n:
            return None
        if cached_n:
            if first != first_seq[d] \
                    or doc.ops[cached_n - 1].seq != last_seq[d]:
                return None
            if n > cached_n and doc.ops[cached_n].seq <= last_seq[d]:
                return None
        if n != cached_n:
            exact = False
    return "exact" if exact else "suffix"


class PackCache:
    """Suffix-aware cache of packed chunk outputs — tier 2 of the
    catch-up cache, attacking the pack leg of the host floor
    (BENCH_cpu_fullscale_r05c: pack is the largest busy stage).
    Family-generic since round 14: the default instance serves
    ``pack_mergetree_batch`` windows; construct with the tree family
    (``ops/tree_pipeline.tree_pack_cache``) to cache SharedTree packs —
    window matching, lineage stamping, LRU, and locking are THIS class,
    while packing/extension go through the family hooks.

    Chunks are keyed by the ordered tuple of per-doc ``cache_token``s
    (doc + base summary + storage generation identity, supplied by the
    catch-up service); any doc without a token — or any doc the family
    marks ``bypass`` (e.g. binary-stream docs, whose C++ pack is already
    the fast path) — bypasses the cache.

    Three outcomes per chunk:

    - **exact**: every doc's op window is unchanged → the cached arrays
      are returned as-is (zero pack work; only the meta's ``docs`` are
      re-pointed so extraction reads fresh ``final_seq``/``final_msn``).
    - **suffix**: every doc's window extends the cached one (same first
      seq, tail grew — the append-only op log guarantees the shared
      prefix is byte-identical under an equal token) → the family's
      ``extend`` packs ONLY the new suffix rows onto copies of the
      cached arrays, provided the chunk's shape buckets hold; any
      violation just falls back to a full repack — never corrupts.
    - **miss**: a full family pack whose result is cached.

    Extraction-side summaries are byte-identical in all three cases
    (pinned by tests): intern ids may differ from a fresh pack's, but
    ids never reach the summary bytes — everything resolves through the
    chunk's own tables.

    Thread-safe: lookups/stores lock, and suffix extensions serialize on
    their own mutex (they append to an entry's shared arena/interner);
    full packs and exact hits run lock-free.
    """

    def __init__(self, max_bytes: int = 192 << 20,
                 family: Optional[KernelFamily] = None) -> None:
        from ..utils.telemetry import CounterSet

        self.family = family if family is not None else MERGETREE_FAMILY
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # Serializes suffix extension: extend appends to the cached
        # entry's SHARED arena and value interner (append-only, so
        # readers are safe, but two concurrent extends of the same entry
        # would interleave writes).  Extends are the rare path — one
        # mutex for all of them costs nothing and makes the thread-safety
        # claim unconditional instead of relying on callers never
        # sharing a token tuple across concurrent pack() calls.
        self._extend_lock = threading.Lock()
        # tokens -> _PackEntry (insertion order = LRU order)
        self._entries: dict = {}  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self.counters = CounterSet(
            "exact_hits", "suffix_hits", "misses", "bypass", "inserts",
            "evictions",
        )  # guarded-by: _lock (CounterSet is not internally synchronized)

    def stats(self) -> dict:
        with self._lock:
            out = self.counters.snapshot()
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
        return out

    # -- public entry point ----------------------------------------------------

    def pack(self, chunk):
        """(state, ops, meta) for ``chunk`` — cached, suffix-extended, or
        freshly packed."""
        family = self.family
        tokens = tuple(d.cache_token for d in chunk)
        if any(t is None for t in tokens) \
                or any(family.bypass(d) for d in chunk):
            with self._lock:
                self.counters.bump("bypass")
            return family.pack(chunk)
        with self._lock:
            entry = self._entries.get(tokens)
        if entry is not None:
            kind = self._match(entry, chunk)
            if kind == "exact":
                with self._lock:
                    self._touch(tokens)
                    self.counters.bump("exact_hits")
                return entry.state, entry.ops, dict(
                    entry.meta, docs=list(chunk),
                    _pack_lineage=("exact", entry.gen))
            if kind == "suffix" and family.extend is not None:
                parent_gen = entry.gen
                with self._extend_lock:
                    extended = family.extend(entry, chunk)
                if extended is not None:
                    state, ops, meta = extended
                    gen = self._store(tokens, chunk, state, ops, meta)
                    # The lineage stamp: these arrays are the literal
                    # extension of generation ``parent_gen`` — the
                    # device-resident tier's suffix-splice license.
                    meta["_pack_lineage"] = ("suffix", parent_gen, gen)
                    with self._lock:
                        self.counters.bump("suffix_hits")
                    return state, ops, meta
        with self._lock:
            self.counters.bump("misses")
        state, ops, meta = family.pack(chunk)
        gen = self._store(tokens, chunk, state, ops, meta)
        meta["_pack_lineage"] = ("full", gen)
        return state, ops, meta

    # -- bookkeeping -----------------------------------------------------------

    def _touch(self, tokens) -> None:  # holds-lock: _lock
        entry = self._entries.pop(tokens, None)
        if entry is not None:
            self._entries[tokens] = entry

    def _store(self, tokens, chunk, state, ops, meta) -> int:
        """Insert/replace the entry; returns its pack generation (fresh
        even when the byte budget refuses the entry — the lineage stamp
        must still be unique per produced array set)."""
        n_ops, first_seq, last_seq = [], [], []
        for doc in chunk:
            n, first, last = _doc_window(doc)
            n_ops.append(n)
            first_seq.append(first)
            last_seq.append(last)
        t_rows = list(self.family.entry_rows(chunk, meta))
        # The stored meta never serves extraction directly — both the
        # exact-hit and suffix paths re-point ``docs`` at the fresh chunk
        # — so drop the doc inputs (and with them the per-op Python
        # message lists, the dominant retained memory the byte budget
        # would otherwise silently under-count).
        gen = next(_PACK_GEN)
        entry = _PackEntry(tokens, n_ops, first_seq, last_seq, t_rows,
                           state, ops, dict(meta, docs=None),
                           self.family.entry_nbytes(state, ops, meta),
                           gen=gen)
        with self._lock:
            old = self._entries.pop(tokens, None)
            if old is not None:
                self._bytes -= old.nbytes
            if entry.nbytes > self.max_bytes:
                self.counters.bump("evictions")
                return gen
            self._entries[tokens] = entry
            self._bytes += entry.nbytes
            self.counters.bump("inserts")
            while self._bytes > self.max_bytes and self._entries:
                oldest = next(iter(self._entries))
                dropped = self._entries.pop(oldest)
                self._bytes -= dropped.nbytes
                self.counters.bump("evictions")
        return gen

    @staticmethod
    def _match(entry: _PackEntry, chunk) -> Optional[str]:
        return match_windows(entry.n_ops, entry.first_seq,
                             entry.last_seq, chunk)


# ---------------------------------------------------------------------------
# Merge-tree tier-2 suffix extension (the family's ``extend`` hook)
# ---------------------------------------------------------------------------


def _extend_mergetree(entry: _PackEntry, chunk):
    """Pack only each doc's suffix ops on top of the cached arrays;
    None = shape/bucket constraints do not hold (caller full-packs)."""
    meta = entry.meta
    T = entry.ops.kind.shape[1]
    S = int(meta["_S"])
    K = int(meta["props_K"])
    key_ids = {k: i for i, k in enumerate(meta["prop_keys"])}
    # Pre-scan (no shared state touched): per-doc text-op counts and
    # the suffix's new property keys, so every bucket check happens
    # before any mutation.
    new_t_counts, suffixes = [], []
    new_keys = []
    for d, doc in enumerate(chunk):
        suffix = doc.ops[entry.n_ops[d]:]
        suffixes.append(suffix)
        t_count = entry.t_rows[d]
        for msg in suffix:
            contents = msg.contents
            if contents["kind"].startswith("interval"):
                continue
            t_count += 1
            for key in (contents.get("props") or {}):
                if key not in key_ids and key not in new_keys:
                    new_keys.append(key)
        new_t_counts.append(t_count)
    if len(key_ids) + len(new_keys) > K:
        return None  # props bucket would grow: repack
    if next_bucket_fine(max(max(new_t_counts), 1), floor=16) != T:
        return None  # op-row bucket would grow
    base_counts = [int(n) for n in np.asarray(entry.state.n)]
    s_need = max(bc + 2 * tc
                 for bc, tc in zip(base_counts, new_t_counts))
    if next_bucket_fine(max(s_need, 1), floor=32) != S:
        return None  # slot bucket would grow
    for key in new_keys:
        key_ids[key] = len(key_ids)

    # Commit: copy the op arrays (the cached entry must stay intact),
    # share the append-only arena/value interner and the untouched
    # base state, and fill only the suffix rows.
    op = {f: np.copy(getattr(entry.ops, f)) for f in MTOps._fields}
    arena = meta["arena"]
    values: Interner = meta["values"]
    doc_packs = [_copy_doc_pack(p) for p in meta["doc_packs"]]
    try:
        _fill_mergetree_suffixes(chunk, suffixes, entry, op, arena,
                                 values, doc_packs, key_ids)
    except ValueError:
        # An op shape this fill doesn't know (drift vs
        # pack_mergetree_batch's row fill) must degrade to a full
        # pack — which raises the same error if the op is genuinely
        # malformed — never crash only-when-warm.  The arena/interner
        # appends already made are unreferenced and harmless.
        return None
    new_meta = dict(
        meta,
        docs=list(chunk),
        doc_packs=doc_packs,
        prop_keys=sorted(key_ids, key=key_ids.__getitem__),
    )
    _refresh_mergetree_facts(entry.state, op, new_meta, chunk)
    return entry.state, MTOps(**op), new_meta


def _fill_mergetree_suffixes(chunk, suffixes, entry, op, arena, values,
                             doc_packs, key_ids) -> None:
    # THE shared row fill (mergetree_kernel.fill_sequence_op_rows) —
    # byte-drift between fresh and suffix-cached packs is impossible
    # by construction.
    for d, doc in enumerate(chunk):
        pack = doc_packs[d]
        if known_oracle_fallback(doc):
            pack.needs_fallback = True
        fill_sequence_op_rows(op, d, entry.t_rows[d] - 1, suffixes[d],
                              pack, arena, key_ids.__getitem__, values)


def _refresh_mergetree_facts(state, op, meta, chunk) -> None:
    """Re-derive the chunk facts over the COMBINED arrays — same
    predicates as ``pack_mergetree_batch``, except the i16 text bound
    checks the actual per-doc rebased span ends (suffix text is not
    contiguous with the doc's original arena span)."""
    doc_base = np.asarray(meta["doc_base"], np.int32)
    S = int(meta["_S"])
    is_ins = op["kind"] == K_INSERT
    op_end = np.where(
        is_ins, op["tstart"] + op["tlen"] - doc_base[:, None], 0
    )
    live = np.arange(state.tstart.shape[1],
                     dtype=np.int32)[None, :] < np.asarray(
                         state.n)[:, None]
    st_end = np.where(
        live,
        np.asarray(state.tstart) + np.asarray(state.tlen)
        - doc_base[:, None],
        0,
    )
    max_off = max(int(op_end.max(initial=0)),
                  int(st_end.max(initial=0)))
    max_seq = max(
        int(op["seq"].max(initial=0)),
        max((d.final_seq for d in chunk), default=0),
        max((d.base_seq for d in chunk), default=0),
    )
    max_clients = max(
        (len(p.clients) for p in meta["doc_packs"]), default=0
    )
    n_values = len(meta["values"])
    meta["i16_ok"] = (
        max_seq < I16_LIMIT and max_off < I16_LIMIT and S < I16_LIMIT
        and n_values < I16_LIMIT and max_clients < I16_LIMIT
    )
    real_ops = op["kind"] != K_NOOP
    max_tlen = max(int(op["tlen"].max(initial=0)),
                   int(np.asarray(state.tlen).max(initial=0)))
    meta["i8_ok"] = (
        meta["i16_ok"] and max_seq < I8_LIMIT and max_tlen < I8_LIMIT
        and n_values < I8_LIMIT and max_clients < I8_LIMIT
    )
    sequential = not bool(
        (real_ops & (op["ref_seq"] != op["seq"] - 1)).any()
    )
    meta["sequential"] = sequential
    meta["ob_rows"] = bool(
        (np.asarray(state.ob1_seq) != NOT_REMOVED).any()
        or (op["kind"] == K_OBLITERATE).any()
    )
    meta["ov_rows"] = bool(
        (np.asarray(state.rem2_client) >= 0).any()
    ) or not sequential
    meta["has_props"] = len(meta["prop_keys"]) > 0


# -- tier-0 delta-download routing: ONE derivation point --------------------
# The single-device pipeline below and the mesh fold
# (parallel/shard.py replay_family_sharded) both consume these — the
# byte-identity-critical cache logic (serve gate, entry publication, the
# changed-rows sub-meta) must never fork into hand-synced copies, for
# ANY family.


def delta_route(docs, dig_np, delta_cache):
    """The per-chunk tier-0 decision after the digest plane arrived:
    ``("full", {}, None)`` — nothing servable, the cold/fallback/oracle
    route; ``("served", served, None)`` — every document serves without
    a download; ``("partial", served, changed)`` — only ``changed``
    positions' rows must cross."""
    served = (delta_cache.serve_many(docs, dig_np)
              if delta_cache.any_candidate(docs) else {})
    if not served:
        return "full", served, None
    if len(served) == len(docs):
        return "served", served, None
    return "partial", served, [d for d in range(len(docs))
                               if d not in served]


def delta_store_all(delta_cache, docs, dig_np, trees) -> None:
    """(Re)publish every document's tier-0 entry — the cold-fill leg."""
    delta_cache.put_many(
        (doc, (int(dig_np[d, 0]), int(dig_np[d, 1])), trees[d])
        for d, doc in enumerate(docs))


def delta_sub_meta(meta, changed,
                   per_doc: Sequence[str] = ("doc_base",)) -> dict:
    """The per-doc meta rows of only the CHANGED positions (the gathered
    rows' extraction view); chunk-global meta passes through.
    ``per_doc`` names the family's per-doc ndarray meta entries that
    must slice alongside ``docs``/``doc_packs``."""
    docs = meta["docs"]
    rows = np.asarray(changed, np.intp)
    out = dict(
        meta,
        docs=[docs[d] for d in changed],
        doc_packs=[meta["doc_packs"][d] for d in changed],
    )
    for key in per_doc:
        if key in meta:
            out[key] = np.asarray(meta[key])[rows]
    return out


def delta_merge_changed(delta_cache, meta, dig_np, served, changed, got):
    """Served trees + freshly extracted changed trees → the chunk's
    result list, publishing the changed documents' new tier-0 entries."""
    docs = meta["docs"]
    res: List = [None] * len(docs)
    for d, tree in served.items():
        res[d] = tree
    for d, tree in zip(changed, got):
        res[d] = tree
    delta_cache.put_many(
        (docs[d], (int(dig_np[d, 0]), int(dig_np[d, 1])), tree)
        for d, tree in zip(changed, got))
    return res


# ---------------------------------------------------------------------------
# The family-generic pipelined fold
# ---------------------------------------------------------------------------


def pipelined_family_replay(
    family: KernelFamily,
    docs,
    *,
    chunk_docs: int = 1024,
    pack_threads: int = 4,
    extract_threads: int = 3,
    fetch_depth: int = 2,
    schedule: bool = True,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    packed_out: Optional[list] = None,
    pack_cache: Optional[PackCache] = None,
    delta_cache=None,
    device_cache=None,
    pin_resident: bool = False,
):
    """Canonical summaries for ``docs`` in the given order, through the
    generic four-tier pipeline for any registered kernel family.

    ``stats`` accumulates ``device_docs``/``fallback_docs`` (plus the
    per-reason ``fallback_<reason>`` split and ``delta_docs`` for
    documents served from the tier-0 delta cache without a download);
    ``stage`` (if given) accumulates busy seconds under
    ``pack``/``dispatch``/``upload``/``device_wait``/``download``/
    ``extract`` and the integer byte counters ``h2d_bytes``/``d2h_bytes``
    — the bench harness's instrumentation hook; ``packed_out`` (if
    given) collects ``(state, ops, meta, tag)`` per chunk in schedule
    order so a caller can reuse the pack work; ``pack_cache`` (if given,
    built over THIS family) reuses packed windows across calls for docs
    carrying a ``cache_token`` (see :class:`PackCache`);
    ``delta_cache`` (a ``service.catchup_cache.DeltaExportCache``, tier 0
    of the catch-up cache) turns on digest-gated delta download: the fold
    emits a per-doc state digest, only the tiny digest plane round-trips
    eagerly, and only CHANGED documents' export rows are gathered and
    downloaded — unchanged documents serve their cached summaries
    byte-identically.  Any miss/mismatch falls back to the full fetch.
    ``device_cache`` (an ``ops.device_cache.DevicePackCache`` built over
    this family's device ops, tier 2.5) keeps packed chunk arrays
    device-resident across calls: an exact tier-2 window hit dispatches
    with ZERO h2d pack bytes, a suffix hit uploads only the new rows
    through a donated in-place splice, and any mismatch falls back to
    the full upload — which without the tier is also the only route (and
    is what ``h2d_bytes`` then counts).  ``pin_resident=True`` (the
    streaming fold) pins every chunk this call serves into the device
    cache's resident-state tier — exempt from LRU, spill-to-host over
    its own byte budget (see ``DevicePackCache.pin``)."""

    # Seed HERE, not in the fold: a batch that routes entirely to
    # fallback never reaches _pipelined_fold, and the schema contract
    # (same keys single-device and mesh, every configuration) must hold
    # for it too.
    seed_stage(stage)

    def fold(batch):
        return _pipelined_fold(
            family, batch, chunk_docs, pack_threads, extract_threads,
            fetch_depth, schedule, stats, stage, packed_out, pack_cache,
            delta_cache, device_cache, pin_resident,
        )

    return partition_replay(
        docs, family.known_fallback, family.fallback_summary, fold,
        stats=stats,
    )


def pipelined_mergetree_replay(
    docs: Sequence[MergeTreeDocInput],
    *,
    chunk_docs: int = 1024,
    pack_threads: int = 4,
    extract_threads: int = 3,
    fetch_depth: int = 2,
    schedule: bool = True,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    packed_out: Optional[list] = None,
    pack_cache: Optional[PackCache] = None,
    delta_cache=None,
    device_cache=None,
    pin_resident: bool = False,
):
    """The merge-tree instance of :func:`pipelined_family_replay` — the
    original round-5 entry point, signature unchanged."""
    return pipelined_family_replay(
        MERGETREE_FAMILY, docs,
        chunk_docs=chunk_docs, pack_threads=pack_threads,
        extract_threads=extract_threads, fetch_depth=fetch_depth,
        schedule=schedule, stats=stats, stage=stage,
        packed_out=packed_out, pack_cache=pack_cache,
        delta_cache=delta_cache, device_cache=device_cache,
        pin_resident=pin_resident,
    )


def _bump(stage: Optional[dict], key: str, t0: float) -> None:
    if stage is not None:
        stage[key] = stage.get(key, 0.0) + (perf_counter() - t0)


def _count_d2h(stage: Optional[dict], nbytes: int) -> None:
    """Accumulate ACTUAL bytes fetched over the d2h link this call (an
    integer counter riding the stage dict next to the busy seconds)."""
    if stage is not None:
        stage["d2h_bytes"] = stage.get("d2h_bytes", 0) + int(nbytes)


def _count_h2d(stage: Optional[dict], nbytes: int) -> None:
    """The upload-side twin of :func:`_count_d2h`: bytes of pack data
    this call pushed over the h2d link — the observable the
    device-resident tier (ISSUE 13) exists to shrink."""
    if stage is not None:
        stage["h2d_bytes"] = stage.get("h2d_bytes", 0) + int(nbytes)


def _nbytes(handle) -> int:
    """Byte size of a device/host buffer handle (or tuple of them) from
    shape metadata alone — never forces a transfer."""
    leaves = handle if isinstance(handle, tuple) else (handle,)
    return int(sum(leaf.nbytes for leaf in leaves))


def _np_nbytes(tree) -> int:
    """Bytes of the NUMPY leaves of a state/ops tree — exactly what the
    dispatch jit will push over the h2d link (device-resident leaves
    pass through and cost nothing)."""
    if tree is None:
        return 0
    return int(sum(leaf.nbytes for leaf in tree
                   if isinstance(leaf, np.ndarray)))


def _block_until_ready(*handles) -> None:
    """Wait for device computation to finish WITHOUT transferring — the
    honest boundary between fold wait and the d2h copy (numpy leaves on
    the CPU backend pass through)."""
    for handle in handles:
        if handle is None:
            continue
        leaves = handle if isinstance(handle, tuple) else (handle,)
        for leaf in leaves:
            wait = getattr(leaf, "block_until_ready", None)
            if wait is not None:
                wait()


#: THE stage schema, identical for every family, single-device and mesh
#: (the byte counters ride as ints next to the busy seconds).
STAGE_KEYS = ("pack", "upload", "dispatch", "device_wait", "download",
              "extract")


def seed_stage(stage: Optional[dict]) -> None:
    """Pre-seed the full stage schema so every fold — with or without
    cache tiers, single-device or mesh — reports the SAME keys (a leg
    that never ran reads 0, instead of being absent)."""
    if stage is None:
        return
    for key in STAGE_KEYS:
        stage.setdefault(key, 0.0)
    stage.setdefault("h2d_bytes", 0)
    stage.setdefault("d2h_bytes", 0)


def _pipelined_fold(family, batch, chunk_docs, pack_threads,
                    extract_threads, fetch_depth, schedule, stats, stage,
                    packed_out, pack_cache=None, delta_cache=None,
                    device_cache=None, pin_resident=False):
    order = family.order(batch, schedule)
    sched = [batch[i] for i in order]
    starts = list(range(0, len(sched), chunk_docs))

    def pack_one(lo):
        t0 = perf_counter()
        chunk = sched[lo:lo + chunk_docs]
        if pack_cache is not None:
            state, ops, meta = pack_cache.pack(chunk)
        else:
            state, ops, meta = family.pack(chunk)
        state, ops = family.narrow(chunk, state, ops, meta)
        return state, ops, meta, perf_counter() - t0

    def extract_one(meta, arr):
        t0 = perf_counter()
        st: dict = {}
        res = family.extract(meta, arr, st)
        return res, st, perf_counter() - t0

    def extract_full_store(meta, arr, dig_np):
        """Full-download extraction that also (re)publishes every doc's
        tier-0 entry — the cold-fill leg of the delta path."""
        res, st, dt = extract_one(meta, arr)
        t0 = perf_counter()
        delta_store_all(delta_cache, meta["docs"], dig_np, res)
        return res, st, dt + (perf_counter() - t0)

    def extract_served(docs, served):
        """Whole chunk served from tier 0: zero download, zero extract."""
        return [served[d] for d in range(len(docs))], \
            {"delta_docs": len(docs)}, 0.0

    def extract_delta(meta, arr, changed, served, dig_np):
        """Extract ONLY the changed documents from their gathered rows;
        unchanged documents serve their cached summaries byte-identically
        (the cached tree came out of this same extraction under an equal
        digest + host anchor)."""
        t0 = perf_counter()
        st: dict = {}
        got = family.extract(
            delta_sub_meta(meta, changed, family.per_doc_meta), arr, st)
        res = delta_merge_changed(delta_cache, meta, dig_np, served,
                                  changed, got)
        st["delta_docs"] = st.get("delta_docs", 0) + len(served)
        return res, st, perf_counter() - t0

    out: List = []

    def collect(fut) -> None:
        res, st, dt = fut.result()
        out.extend(res)
        if stage is not None:
            stage["extract"] = stage.get("extract", 0.0) + dt
        if stats is not None:
            for k, v in st.items():
                stats[k] = stats.get(k, 0) + v

    pack_futs: collections.deque = collections.deque()
    ex_futs: collections.deque = collections.deque()
    inflight: collections.deque = collections.deque()
    with ThreadPoolExecutor(max_workers=pack_threads) as pack_pool, \
            ThreadPoolExecutor(max_workers=extract_threads) as ex_pool:
        try:
            next_i = 0
            while next_i < len(starts) and len(pack_futs) < pack_threads + 1:
                pack_futs.append(pack_pool.submit(pack_one, starts[next_i]))
                next_i += 1

            def fetch_one(meta, core, dig, cand) -> None:
                # Honest stage split: wait for the DEVICE to finish first
                # (fold + export compute), so "download" times the copy
                # alone and d2h_bytes attributes what actually crossed.
                t0 = perf_counter()
                _block_until_ready(core, dig)
                _bump(stage, "device_wait", t0)
                docs = meta["docs"]
                if dig is None:
                    t0 = perf_counter()
                    arr = family.fetch(core)  # the d2h link RPC(s)
                    _bump(stage, "download", t0)
                    _count_d2h(stage, _nbytes(arr))
                    ex_futs.append(ex_pool.submit(extract_one, meta, arr))
                else:
                    t0 = perf_counter()
                    dig_np = np.asarray(dig)  # the tiny eager round-trip
                    _bump(stage, "download", t0)
                    _count_d2h(stage, dig_np.nbytes)
                    # Host cache work stays OUTSIDE the download window
                    # (the stage times link traffic alone); one lock
                    # acquisition serves the whole chunk
                    # (delta_route, the shared tier-0 decision).
                    route, served, changed = (
                        delta_route(docs, dig_np, delta_cache)
                        if cand else ("full", {}, None))
                    if route == "full":
                        # Cold / all-changed / fallback route — and the
                        # golden oracle the delta path is tested against.
                        t0 = perf_counter()
                        arr = family.fetch(core)
                        _bump(stage, "download", t0)
                        _count_d2h(stage, _nbytes(arr))
                        ex_futs.append(ex_pool.submit(
                            extract_full_store, meta, arr, dig_np))
                    elif route == "served":
                        delta_cache.note_bytes_saved(_nbytes(core))
                        ex_futs.append(ex_pool.submit(
                            extract_served, docs, served))
                    else:
                        # Exact rows on host-viewable buffers; fine-
                        # bucketed device gather (or whole-buffer fetch
                        # when padding would move it all) elsewhere —
                        # the family's gather owns that choice and
                        # reports the bytes that really crossed.
                        t0 = perf_counter()
                        sub, fetched = family.gather_rows(
                            core, np.asarray(changed, np.int32))
                        _bump(stage, "download", t0)
                        _count_d2h(stage, fetched)
                        delta_cache.note_bytes_saved(
                            max(0, _nbytes(core) - fetched))
                        ex_futs.append(ex_pool.submit(
                            extract_delta, meta, sub, changed, served,
                            dig_np))
                if len(ex_futs) >= extract_threads + 1:
                    collect(ex_futs.popleft())

            want_digest = delta_cache is not None
            while pack_futs:
                fut = pack_futs.popleft()
                state, ops, meta, dt = fut.result()
                if next_i < len(starts):
                    pack_futs.append(
                        pack_pool.submit(pack_one, starts[next_i]))
                    next_i += 1
                if stage is not None:
                    stage["pack"] = stage.get("pack", 0.0) + dt
                # --- upload leg (tier 2.5): resident buffers on a warm
                # window, donated suffix splice on a grown one, full
                # device_put otherwise.  All device interaction stays on
                # THIS thread (the pipeline's single-device-thread
                # contract); `upload` times the explicit transfers and
                # h2d_bytes counts what really crossed — without the
                # tier, the full host arrays upload inside the jit call
                # below, so they are counted here either way.
                base_dev = None
                host_state, host_ops = state, ops
                if device_cache is not None:
                    t0 = perf_counter()
                    state, ops, base_dev, up_bytes = \
                        device_cache.acquire(state, ops, meta,
                                             pin=pin_resident)
                    _bump(stage, "upload", t0)
                    _count_h2d(stage, up_bytes)
                else:
                    _count_h2d(stage,
                               _np_nbytes(state) + _np_nbytes(ops))
                t0 = perf_counter()
                ex = family.dispatch(state, ops, meta, want_digest,
                                     base_dev)
                core, dig = family.split_digest(ex, want_digest)
                cand = want_digest and delta_cache.any_candidate(
                    meta["docs"])
                if dig is not None:
                    _start_host_copy(dig)
                if dig is None or not cand:
                    # No tier-0 candidate can skip the download: start
                    # the full async copy at dispatch like the plain
                    # path.  With candidates present, starting it would
                    # transfer the very bytes delta download exists to
                    # avoid.
                    _start_host_copy(core)
                _bump(stage, "dispatch", t0)
                if packed_out is not None:
                    # state included so a caller re-timing the fold can
                    # replay WARM chunks with the same executable the e2e
                    # used (None for cold chunks).  Always the HOST
                    # arrays: a resident-tier buffer may later be
                    # donated away by a suffix splice — a collected
                    # reference must never die under the caller.
                    packed_out.append((host_state, host_ops, meta,
                                       family.chunk_tag(meta)))
                inflight.append((meta, core, dig, cand))
                if len(inflight) > fetch_depth:
                    fetch_one(*inflight.popleft())
            while inflight:
                fetch_one(*inflight.popleft())
            while ex_futs:
                collect(ex_futs.popleft())
        finally:
            for f in pack_futs:
                f.cancel()
            for f in ex_futs:
                f.cancel()
    # Restore the caller's order.
    restored: List = [None] * len(batch)
    for pos, i in enumerate(order):
        restored[i] = out[pos]
    return restored


def _has_props(doc: MergeTreeDocInput) -> bool:
    for msg in doc.ops:
        op = msg.contents
        if not op["kind"].startswith("interval") and op.get("props"):
            return True
    return bool(any(r.get("p") for r in (doc.base_records or [])))


def _chunk_S(meta: dict) -> int:
    """The chunk's padded slot capacity (pack_mergetree_batch's S bucket),
    recovered from the packed meta for the cold-start export builder."""
    return int(meta["_S"])


def _start_host_copy(ex) -> None:
    leaves = ex if isinstance(ex, tuple) else (ex,)
    for leaf in leaves:
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is not None:
            copy()


# ---------------------------------------------------------------------------
# The merge-tree family instance
# ---------------------------------------------------------------------------


def _mt_order(batch, schedule: bool):
    order = list(range(len(batch)))
    if schedule and any(d.binary_ops is not None for d in batch):
        # Fact-homogeneous scheduling: annotate-free docs first, so their
        # chunks compile with the props plane traced away (~20% fold win
        # on the pure-text majority).  Stable sort; order restored by the
        # caller.  Binary docs carry the fact in their header (O(1));
        # message-list docs would need an O(ops) serial pre-scan on this
        # thread, so a batch with no binary docs keeps its order (the
        # pack pre-scan derives the facts in the parallel pool
        # regardless).
        order.sort(key=lambda i: batch[i].binary_prop_keys is not None
                   if batch[i].binary_ops is not None
                   else _has_props(batch[i]))
    return order


def _mt_narrow(chunk, state, ops, meta):
    warm = any(d.base_records for d in chunk)
    return (narrow_state_for_upload(state, meta) if warm else None,
            narrow_ops_for_upload(ops, meta))


def _mt_aux(meta, digest: bool):
    """The per-doc arena base the dispatch consumes next to state/ops —
    real bases when the narrow layout or the digest reads them, zeros
    otherwise (inert, but the jitted signature always takes the arg)."""
    if bool(meta.get("i16_ok")) or digest:
        return np.asarray(meta["doc_base"], np.int32)
    return np.zeros((len(meta["docs"]),), np.int32)


def _mt_dispatch(state, ops, meta, digest: bool, aux_dev):
    return replay_export(state, ops, meta, S=int(meta["_S"]),
                         digest=digest, doc_base=aux_dev)


def _mt_dispatch_sharded(mesh, state, ops, meta, digest: bool, aux_dev):
    from ..parallel.shard import sharded_export_step

    i16, ob_rows, ov_rows, i8, has_props = _export_flags(meta)
    sequential = bool(meta.get("sequential"))
    warm = state is not None
    step = sharded_export_step(mesh, int(meta["_S"]), i16, ob_rows,
                               ov_rows, i8, sequential, has_props, warm,
                               digest=digest)
    return step(state, ops, aux_dev) if warm else step(ops, aux_dev)


def _mt_entry_rows(chunk, meta):
    return [
        sum(1 for m in doc.ops
            if not m.contents["kind"].startswith("interval"))
        for doc in chunk
    ]


def _mt_entry_nbytes(state, ops, meta) -> int:
    return (
        sum(np.asarray(x).nbytes for x in ops)
        + sum(np.asarray(x).nbytes for x in state)
        + len(meta["arena"]) * 4
    )


def _mt_pad_token(k: int) -> tuple:
    """A deterministic cache token for mesh pad documents: the padded
    chunk's token tuple must stay all-non-None for tier-2/2.5 keying,
    and an empty pad doc's "stream" is trivially append-only under a
    fixed token.  Component 0 is a sentinel epoch, so the tier-0/2.5
    epoch sweeps treat pad entries as stale on any real epoch change."""
    return ("\x00pad", f"\x00pad{k}", 0, "")


MERGETREE_FAMILY = KernelFamily(
    name="mergetree",
    known_fallback=known_oracle_fallback,
    fallback_summary=oracle_fallback_summary,
    pack=pack_mergetree_batch,
    bypass=lambda d: d.binary_ops is not None,
    entry_rows=_mt_entry_rows,
    entry_nbytes=_mt_entry_nbytes,
    extend=_extend_mergetree,
    order=_mt_order,
    narrow=_mt_narrow,
    aux=_mt_aux,
    dispatch=_mt_dispatch,
    split_digest=split_export_digest,
    chunk_tag=_chunk_S,
    fetch=export_to_numpy,
    gather_rows=gather_export_rows,
    extract=lambda meta, arr, st: summaries_from_export(meta, arr,
                                                        stats=st),
    per_doc_meta=("doc_base",),
    make_pad=lambda: MergeTreeDocInput(doc_id="\x00pad", ops=[]),
    pad_token=_mt_pad_token,
    dispatch_sharded=_mt_dispatch_sharded,
)
