"""Chunked, pipelined bulk merge-tree replay — the PRODUCT's version of
the bench harness's e2e loop (SURVEY §3.2: catch-up is the north-star
path, and the service must not be slower than the benchmark of itself).

Shape (round-5 pipeline, BASELINE.md):

- documents are chunked (``chunk_docs``) so jitted shapes stay bucketed
  and per-transfer sizes bounded;
- chunks are fact-scheduled (annotate-free docs grouped) so the majority
  volume folds with the props plane traced away — results return in the
  CALLER's order regardless;
- packing (C++, GIL-released) runs in a thread pool; extraction
  likewise; ALL device interaction — dispatch, ``copy_to_host_async``,
  the blocking fetch — stays on the calling thread.  The axon client
  degrades persistently (~70–90 ms/call) when a second thread fetches
  while another dispatches (BASELINE.md round-5 measurement), and a
  single device thread also serializes correctly on every backend;
- the blocking fetch trails the dispatch front by ``fetch_depth`` chunks
  so upload/fold/download overlap without a second device thread;
- oracle-fallback docs route around the device exactly like
  ``replay_mergetree_batch`` (shared ``partition_replay`` + post-fold
  overflow handling inside ``summaries_from_export``).
"""

from __future__ import annotations

import collections
import os
from concurrent.futures import ThreadPoolExecutor
from time import perf_counter
from typing import Callable, List, Optional, Sequence

import numpy as np

from .batching import partition_replay
from .mergetree_kernel import (
    MergeTreeDocInput,
    export_to_numpy,
    known_oracle_fallback,
    narrow_ops_for_upload,
    narrow_state_for_upload,
    oracle_fallback_summary,
    pack_mergetree_batch,
    replay_export,
    summaries_from_export,
)


def pipelined_mergetree_replay(
    docs: Sequence[MergeTreeDocInput],
    *,
    chunk_docs: int = 1024,
    pack_threads: int = 4,
    extract_threads: int = 3,
    fetch_depth: int = 2,
    schedule: bool = True,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    packed_out: Optional[list] = None,
):
    """Canonical summaries for ``docs`` in the given order.

    ``stats`` accumulates ``device_docs``/``fallback_docs``; ``stage``
    (if given) accumulates busy seconds under ``pack``/``dispatch``/
    ``download``/``extract`` — the bench harness's instrumentation hook;
    ``packed_out`` (if given) collects ``(ops, meta, S)`` per chunk in
    schedule order so a caller can reuse the pack work."""

    def fold(batch):
        return _pipelined_fold(
            batch, chunk_docs, pack_threads, extract_threads, fetch_depth,
            schedule, stats, stage, packed_out,
        )

    return partition_replay(
        docs, known_oracle_fallback, oracle_fallback_summary, fold,
        stats=stats,
    )


def _bump(stage: Optional[dict], key: str, t0: float) -> None:
    if stage is not None:
        stage[key] = stage.get(key, 0.0) + (perf_counter() - t0)


def _pipelined_fold(batch, chunk_docs, pack_threads, extract_threads,
                    fetch_depth, schedule, stats, stage, packed_out):
    order = list(range(len(batch)))
    if schedule and any(d.binary_ops is not None for d in batch):
        # Fact-homogeneous scheduling: annotate-free docs first, so their
        # chunks compile with the props plane traced away (~20% fold win
        # on the pure-text majority).  Stable sort; order restored below.
        # Binary docs carry the fact in their header (O(1)); message-list
        # docs would need an O(ops) serial pre-scan on this thread, so a
        # batch with no binary docs keeps its order (the pack pre-scan
        # derives the facts in the parallel pool regardless).
        order.sort(key=lambda i: batch[i].binary_prop_keys is not None
                   if batch[i].binary_ops is not None
                   else _has_props(batch[i]))
    sched = [batch[i] for i in order]
    starts = list(range(0, len(sched), chunk_docs))

    def pack_one(lo):
        t0 = perf_counter()
        state, ops, meta = pack_mergetree_batch(sched[lo:lo + chunk_docs])
        chunk = sched[lo:lo + chunk_docs]
        warm = any(d.base_records for d in chunk)
        state = narrow_state_for_upload(state, meta) if warm else None
        ops = narrow_ops_for_upload(ops, meta)
        return state, ops, meta, perf_counter() - t0

    def extract_one(meta, arr):
        t0 = perf_counter()
        st: dict = {}
        res = summaries_from_export(meta, arr, stats=st)
        return res, st, perf_counter() - t0

    out: List = []

    def collect(fut) -> None:
        res, st, dt = fut.result()
        out.extend(res)
        if stage is not None:
            stage["extract"] = stage.get("extract", 0.0) + dt
        if stats is not None:
            for k, v in st.items():
                stats[k] = stats.get(k, 0) + v

    pack_futs: collections.deque = collections.deque()
    ex_futs: collections.deque = collections.deque()
    inflight: collections.deque = collections.deque()
    with ThreadPoolExecutor(max_workers=pack_threads) as pack_pool, \
            ThreadPoolExecutor(max_workers=extract_threads) as ex_pool:
        try:
            next_i = 0
            while next_i < len(starts) and len(pack_futs) < pack_threads + 1:
                pack_futs.append(pack_pool.submit(pack_one, starts[next_i]))
                next_i += 1

            def fetch_one(meta, ex) -> None:
                t0 = perf_counter()
                arr = export_to_numpy(ex)  # the d2h link RPC(s)
                _bump(stage, "download", t0)
                ex_futs.append(ex_pool.submit(extract_one, meta, arr))
                if len(ex_futs) >= extract_threads + 1:
                    collect(ex_futs.popleft())

            while pack_futs:
                fut = pack_futs.popleft()
                state, ops, meta, dt = fut.result()
                if next_i < len(starts):
                    pack_futs.append(
                        pack_pool.submit(pack_one, starts[next_i]))
                    next_i += 1
                if stage is not None:
                    stage["pack"] = stage.get("pack", 0.0) + dt
                t0 = perf_counter()
                S = _chunk_S(meta)
                ex = replay_export(state, ops, meta, S=S)
                _start_host_copy(ex)
                _bump(stage, "dispatch", t0)
                if packed_out is not None:
                    # state included so a caller re-timing the fold can
                    # replay WARM chunks with the same executable the e2e
                    # used (None for cold chunks).
                    packed_out.append((state, ops, meta, S))
                inflight.append((meta, ex))
                if len(inflight) > fetch_depth:
                    fetch_one(*inflight.popleft())
            while inflight:
                fetch_one(*inflight.popleft())
            while ex_futs:
                collect(ex_futs.popleft())
        finally:
            for f in pack_futs:
                f.cancel()
            for f in ex_futs:
                f.cancel()
    # Restore the caller's order.
    restored: List = [None] * len(batch)
    for pos, i in enumerate(order):
        restored[i] = out[pos]
    return restored


def _has_props(doc: MergeTreeDocInput) -> bool:
    for msg in doc.ops:
        op = msg.contents
        if not op["kind"].startswith("interval") and op.get("props"):
            return True
    return bool(any(r.get("p") for r in (doc.base_records or [])))


def _chunk_S(meta: dict) -> int:
    """The chunk's padded slot capacity (pack_mergetree_batch's S bucket),
    recovered from the packed meta for the cold-start export builder."""
    return int(meta["_S"])


def _start_host_copy(ex) -> None:
    leaves = ex if isinstance(ex, tuple) else (ex,)
    for leaf in leaves:
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is not None:
            copy()
