"""Native op packing: binary record streams + the liboppack C++ packer.

The ingestion side (sequencer/scriptorium, bench synthesis, or the catch-up
service's flatten step) encodes each string-channel op stream ONCE into the
flat binary record format documented in ``native/oppack.cpp``; packing a
10k-document batch for the device then runs entirely in C++ — one pass per
document filling the padded (D, T) arrays and the shared text arena, no
Python objects in the loop.

It also hosts the extraction fast path: ``oppack_extract`` turns the fused
final-state export buffer into canonical summary-body JSON bytes for a whole
chunk in one C++ pass (see ``extract_bodies``).

Build: the library compiles on demand from ``native/oppack.cpp`` with g++.
The artifact is keyed by a content hash of the source
(``liboppack-<hash>.so``) so a stale binary can never shadow newer source —
mtimes are meaningless after a git checkout.  If no toolchain is available
the pure-Python encoder/packer pair keeps everything working — the native
path is a strictly optional accelerator with bit-identical output (asserted
by tests/test_native_pack.py).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import struct
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..protocol.messages import MessageType, SequencedMessage

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "oppack.cpp")

_KINDS = {"insert": 1, "remove": 2, "annotate": 3, "obliterate": 4}
_HEADER = struct.Struct("<B8i")
_PAIR = struct.Struct("<2i")


# -- encoder (ingestion side; pure Python by design — runs once per op) -------


def encode_string_ops(
    ops: Sequence[SequencedMessage],
    client_intern,
    prop_key_intern=None,
    value_intern=None,
) -> bytes:
    """Sequence-channel ops → the flat binary record stream.

    ``client_intern`` / ``prop_key_intern`` / ``value_intern`` are
    ``Interner``-likes (callables via ``.intern``); symbol interning stays
    host-side so records carry dense ids only."""
    out = bytearray()
    for msg in ops:
        if msg.type is not MessageType.OP:
            continue
        op = msg.contents
        kind = _KINDS[op["kind"]]
        client = client_intern.intern(msg.client_id) \
            if msg.client_id is not None else -1
        if kind == 1:
            text = op["text"].encode("utf-8")
            a, b = op["pos"], 0
        else:
            text = b""
            a, b = op["start"], op["end"]
        props = op.get("props") or {}
        pairs = []
        for key, value in props.items():
            if prop_key_intern is None:
                raise ValueError("props present but no prop interner given")
            k = prop_key_intern.intern(key)
            v = -1 if value is None else value_intern.intern(value)
            pairs.append((k, v))
        out += _HEADER.pack(kind, msg.seq, msg.ref_seq, msg.min_seq,
                            client, a, b, len(pairs), len(text))
        for pair in pairs:
            out += _PAIR.pack(*pair)
        out += text
    return bytes(out)


def decode_string_ops(
    blob: bytes, clients: Sequence[str],
    prop_keys: Optional[Sequence[str]] = None,
    values: Optional[Sequence] = None,
) -> List[SequencedMessage]:
    """Inverse of :func:`encode_string_ops` — the oracle-fallback escape
    hatch for binary-only documents (rare; correctness over speed)."""
    out: List[SequencedMessage] = []
    off = 0
    kinds = {v: k for k, v in _KINDS.items()}
    while off < len(blob):
        kind, seq, ref, min_seq, client, a, b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        off += _HEADER.size
        props = {}
        for _ in range(n_props):
            k, v = _PAIR.unpack_from(blob, off)
            off += 8
            props[prop_keys[k]] = None if v == -1 else values[v]
        text = blob[off:off + text_len].decode("utf-8")
        off += text_len
        name = kinds[kind]
        if name == "insert":
            contents = {"kind": "insert", "pos": a, "text": text}
            if props:
                contents["props"] = props
        else:
            contents = {"kind": name, "start": a, "end": b}
            if props:
                contents["props"] = props
        out.append(SequencedMessage(
            seq=seq, client_id=clients[client] if client >= 0 else None,
            client_seq=seq, ref_seq=ref, min_seq=min_seq,
            type=MessageType.OP, contents=contents,
        ))
    return out


# -- the native library --------------------------------------------------------


_lib_handle: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_library() -> Optional[str]:
    if not os.path.exists(_SRC):
        return None
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    lib_path = os.path.join(
        _REPO_ROOT, "native", f"liboppack-{digest}.so"
    )
    if os.path.exists(lib_path):
        return lib_path
    tmp = lib_path + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        # g++ wrote the artifact through its own descriptors: reopen and
        # fsync before publishing, or a crash can install a torn .so.
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, lib_path)  # commit-point: native library publish
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    # Superseded hash builds: safe to drop (an mmap'd inode survives the
    # unlink for any process still using it).
    import glob

    for old in glob.glob(os.path.join(_REPO_ROOT, "native",
                                      "liboppack-*.so")):
        if old != lib_path:
            try:
                os.unlink(old)
            except OSError:
                pass
    return lib_path


def load_library() -> Optional[ctypes.CDLL]:
    """The compiled packer, or None (pure-Python fallback)."""
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.oppack_count.restype = ctypes.c_int
    lib.oppack_count.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.oppack_pack.restype = ctypes.c_int32
    lib.oppack_pack.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
    ] + [np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")] * 10 + [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_void_p, ctypes.c_int32,   # key_map, n_keys
        ctypes.c_void_p, ctypes.c_int32,   # val_map, n_vals
    ]
    lib.oppack_extract.restype = ctypes.c_int64
    lib.oppack_extract.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # export
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,   # arena
        ctypes.c_char_p,                                   # client_json
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_char_p,                                   # key_json
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_char_p,                                   # val_json
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # msn
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),  # skip
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),  # out
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # out_offs
    ]
    lib.oppack_widen.restype = ctypes.c_int32
    lib.oppack_widen.argtypes = [
        np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS"),  # src
        ctypes.c_int32, ctypes.c_int32,                          # D, S
        ctypes.c_int32, ctypes.c_int32,                          # R_src/canon
        ctypes.c_void_p, ctypes.c_int32,                         # misc, cols
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # desc
        ctypes.c_void_p,                                         # doc_base
        ctypes.c_int32, ctypes.c_int32,                          # sentinels
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # dst
    ]
    _lib_handle = lib
    return lib


def count_stream(blob: bytes) -> Tuple[int, int, int]:
    """(n_ops, text_bytes, text_chars) for one binary stream."""
    lib = load_library()
    if lib is not None:
        n_ops = ctypes.c_int32()
        text_bytes = ctypes.c_int64()
        text_chars = ctypes.c_int64()
        rc = lib.oppack_count(blob, len(blob), ctypes.byref(n_ops),
                              ctypes.byref(text_bytes),
                              ctypes.byref(text_chars))
        if rc != 0:
            raise ValueError("malformed binary op stream")
        return n_ops.value, text_bytes.value, text_chars.value
    return _count_py(blob)


def _count_py(blob: bytes) -> Tuple[int, int, int]:
    off, n, tb, tc = 0, 0, 0, 0
    while off < len(blob):
        (_kind, _seq, _ref, _msn, _cl, _a, _b, n_props,
         text_len) = _HEADER.unpack_from(blob, off)
        off += _HEADER.size + 8 * n_props
        text = blob[off:off + text_len]
        if len(text) != text_len:
            raise ValueError("malformed binary op stream")
        off += text_len
        tb += text_len
        tc += len(text.decode("utf-8"))
        n += 1
    return n, tb, tc


def binary_has_obliterate(blob: bytes) -> bool:
    """Header-only scan: does the stream contain an obliterate record?"""
    off = 0
    while off < len(blob):
        kind, _s, _r, _m, _c, _a, _b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        if kind == _KINDS["obliterate"]:
            return True
        off += _HEADER.size + 8 * n_props + text_len
    return False


#: the nine [D, T] op fields in oppack_pack's argument order
_ROW_FIELDS = ("kind", "seq", "client", "ref_seq", "min_seq", "a", "b",
               "tstart", "tlen")


def _raw_pack(lib):
    """A second prototype for the SAME ``oppack_pack`` symbol taking raw
    ``c_void_p`` row pointers.  The ndpointer prototype re-marshals every
    ndarray argument on every call (~40% of chunk pack time at 11 arrays
    × 1024 docs — profiled round 5); the batch packer precomputes each
    field's base address once per chunk and passes ``base + d*row_bytes``
    as plain ints instead."""
    fn = getattr(lib, "_oppack_pack_raw", None)
    if fn is None:
        proto = ctypes.CFUNCTYPE(
            ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            *([ctypes.c_void_p] * 10),
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32,
        )
        fn = proto(("oppack_pack", lib))
        lib._oppack_pack_raw = fn
    return fn


class ChunkPacker:
    """Per-chunk fast row packer: base addresses captured once from the
    batch op arrays (which must outlive the packer), one shared text
    scratch reused across docs."""

    def __init__(self, op: Dict[str, np.ndarray], lib):
        self._fn = _raw_pack(lib)
        self._T = int(op["kind"].shape[1])
        self._K = int(op["pvals"].shape[2])
        self._bases = [op[f].ctypes.data for f in _ROW_FIELDS]
        self._pvals_base = op["pvals"].ctypes.data
        self._keepalive = op  # pin the arrays behind the raw pointers
        self._scratch = np.zeros(1, np.uint8)

    def pack(self, blob: bytes, d: int, arena_base_chars: int,
             arena: bytearray, text_bytes: int,
             key_map: Optional[np.ndarray] = None,
             val_map: Optional[np.ndarray] = None) -> int:
        T, K = self._T, self._K
        if self._scratch.nbytes < max(text_bytes, 1):
            self._scratch = np.zeros(max(text_bytes, 1), np.uint8)
        arena_bytes = ctypes.c_int64()
        arena_chars = ctypes.c_int64()
        row_off = d * T * 4
        ptrs = [b + row_off for b in self._bases]
        ptrs.append(self._pvals_base + d * T * K * 4)
        km = None if key_map is None else \
            np.ascontiguousarray(key_map, np.int32)
        vm = None if val_map is None else \
            np.ascontiguousarray(val_map, np.int32)
        packed = self._fn(
            blob, len(blob), T, K, arena_base_chars, *ptrs,
            self._scratch.ctypes.data, self._scratch.nbytes,
            ctypes.byref(arena_bytes), ctypes.byref(arena_chars),
            None if km is None else km.ctypes.data,
            0 if km is None else len(km),
            None if vm is None else vm.ctypes.data,
            0 if vm is None else len(vm),
        )
        if packed < 0:
            raise ValueError("malformed binary op stream")
        arena += self._scratch[:arena_bytes.value].tobytes()
        return packed


def chunk_packer(op: Dict[str, np.ndarray]) -> Optional["ChunkPacker"]:
    """A ChunkPacker when liboppack is available, else None (callers fall
    back to the per-doc ``pack_doc_row`` pure-Python path)."""
    lib = load_library()
    return None if lib is None else ChunkPacker(op, lib)


def pack_doc_row(
    blob: bytes,
    row: Dict[str, np.ndarray],
    K: int,
    arena_base_chars: int,
    arena: bytearray,
    text_bytes: Optional[int] = None,
    key_map: Optional[np.ndarray] = None,
    val_map: Optional[np.ndarray] = None,
) -> int:
    """Fill one document's row of the batch arrays from its binary stream;
    appends text to ``arena`` (utf-8 bytes) and returns ops packed.

    ``row`` maps field name → the 1-D row views (``op['kind'][d]`` etc.,
    C-contiguous); ``pvals`` is the (T, K) row.  ``key_map``/``val_map``
    (int32 arrays) translate encoder-local property key / value ids into
    the batch-global intern spaces."""
    T = row["kind"].shape[0]
    lib = load_library()
    if lib is not None:
        if text_bytes is None:
            _n, text_bytes, _tc = count_stream(blob)
        scratch = np.zeros(max(text_bytes, 1), np.uint8)
        arena_bytes = ctypes.c_int64()
        arena_chars = ctypes.c_int64()
        km = None if key_map is None else \
            np.ascontiguousarray(key_map, np.int32)
        vm = None if val_map is None else \
            np.ascontiguousarray(val_map, np.int32)
        packed = lib.oppack_pack(
            blob, len(blob), T, K, arena_base_chars,
            row["kind"], row["seq"], row["client"], row["ref_seq"],
            row["min_seq"], row["a"], row["b"], row["tstart"], row["tlen"],
            row["pvals"].reshape(-1),
            scratch, len(scratch),
            ctypes.byref(arena_bytes), ctypes.byref(arena_chars),
            None if km is None else km.ctypes.data,
            0 if km is None else len(km),
            None if vm is None else vm.ctypes.data,
            0 if vm is None else len(vm),
        )
        if packed < 0:
            raise ValueError("malformed binary op stream")
        arena += scratch[:arena_bytes.value].tobytes()
        return packed
    return _pack_py(blob, row, K, arena_base_chars, arena, key_map, val_map)


def _pack_py(blob: bytes, row: Dict[str, np.ndarray], K: int,
             arena_base_chars: int, arena: bytearray,
             key_map: Optional[np.ndarray] = None,
             val_map: Optional[np.ndarray] = None) -> int:
    off, t, chars = 0, 0, 0
    while off < len(blob):
        kind, seq, ref, min_seq, client, a, b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        off += _HEADER.size
        row["kind"][t] = kind
        row["seq"][t] = seq
        row["ref_seq"][t] = ref
        row["min_seq"][t] = min_seq
        row["client"][t] = client
        row["a"][t] = a
        row["b"][t] = b
        for _ in range(n_props):
            k, v = _PAIR.unpack_from(blob, off)
            off += 8
            if key_map is not None:
                k = int(key_map[k])
            if val_map is not None and v >= 0:
                v = int(val_map[v])
            row["pvals"][t, k] = v
        if text_len:
            text = blob[off:off + text_len]
            off += text_len
            n_chars = len(text.decode("utf-8"))
            row["tstart"][t] = arena_base_chars + chars
            row["tlen"][t] = n_chars
            arena += text
            chars += n_chars
        else:
            row["tstart"][t] = 0
            row["tlen"][t] = 0
        t += 1
    return t


# -- native summary-body extraction -------------------------------------------


def extract_bodies(
    export_np: np.ndarray,
    arena_text: str,
    doc_clients: Sequence[Sequence[str]],
    prop_keys: Sequence[str],
    values: Sequence,
    msn: np.ndarray,
    skip: np.ndarray,
    not_removed: int,
) -> Optional[List[bytes]]:
    """Canonical summary-body JSON bytes for every doc of a chunk, via the
    C++ extractor; None when the native library is unavailable (callers
    fall back to the per-slot Python extraction).

    ``export_np``: the fused [D, F, S] int32 export buffer;
    ``doc_clients``: per-doc client-id tables in intern order;
    ``prop_keys`` / ``values``: the chunk-global intern tables;
    ``msn`` int32[D]; ``skip`` uint8[D] flags oracle-fallback docs."""
    from ..protocol.summary import canonical_json

    lib = load_library()
    if lib is None:
        return None
    D, F, S = export_np.shape
    K = F - 13
    export_np = np.ascontiguousarray(export_np, np.int32)

    def flatten(tokens: Sequence[bytes]):
        offs = np.zeros(len(tokens) + 1, np.int64)
        for i, tok in enumerate(tokens):
            offs[i + 1] = offs[i] + len(tok)
        return b"".join(tokens), offs

    def json_str(s) -> bytes:
        # Fast path for the overwhelmingly common simple client id: no
        # char needing JSON escaping (quote, backslash, controls) and
        # pure ASCII — byte-equal to canonical_json then.  Anything else
        # takes the canonical serializer.
        if isinstance(s, str) and s.isascii() and '"' not in s \
                and "\\" not in s and (not s or min(s) >= " "):
            return b'"%s"' % s.encode()
        return canonical_json(s)

    client_tokens: List[bytes] = []
    doc_start = np.zeros(D + 1, np.int32)
    for d, clients in enumerate(doc_clients):
        client_tokens.extend(json_str(c) for c in clients)
        doc_start[d + 1] = len(client_tokens)
    client_blob, client_offs = flatten(client_tokens)

    order = sorted(range(len(prop_keys)), key=lambda i: prop_keys[i])
    key_cols = np.asarray(order, np.int32) if order else \
        np.zeros(0, np.int32)
    key_blob, key_offs = flatten(
        [canonical_json(prop_keys[i]) for i in order]
    )
    # The export carries K (bucketed) property rows but only
    # len(prop_keys) real columns; pad key_cols so k indexes stay aligned.
    if K > len(order):
        # Point the padding at the unused bucket columns themselves —
        # they are always PROP_ABSENT in the export, so they emit nothing.
        pad = np.zeros(K, np.int32)
        pad[:len(order)] = key_cols
        pad[len(order):] = np.arange(len(order), K, dtype=np.int32)
        key_cols = pad
        key_offs = np.concatenate(
            [key_offs,
             np.full(K - len(order), key_offs[-1], np.int64)]
        )
    val_blob, val_offs = flatten([canonical_json(v) for v in values])

    arena_bytes = arena_text.encode("utf-8")
    msn = np.ascontiguousarray(msn, np.int32)
    skip = np.ascontiguousarray(skip, np.uint8)
    out_offs = np.zeros(D + 1, np.int64)
    cap = max(len(arena_bytes) * 2 + D * 64 + int(export_np.shape[2]) * D * 8,
              1 << 16)
    for _attempt in range(3):
        out = np.empty(cap, np.uint8)  # C++ writes [0, out_offs[D])
        rc = lib.oppack_extract(
            export_np, D, F, S, K,
            arena_bytes, len(arena_bytes), len(arena_text),
            client_blob, client_offs, doc_start,
            key_blob, key_offs, key_cols,
            val_blob, val_offs, len(values),
            msn, skip, not_removed,
            out, cap, out_offs,
        )
        if rc == 0:
            buf = out[:out_offs[D]].tobytes()  # copy used extent only
            return [
                buf[out_offs[d]:out_offs[d + 1]] for d in range(D)
            ]
        if rc == -1:
            raise ValueError("oppack_extract: malformed export buffer")
        cap = int(-rc - 2) + 1024
    raise RuntimeError("oppack_extract: capacity negotiation failed")
