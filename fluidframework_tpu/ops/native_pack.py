"""Native op packing: binary record streams + the liboppack C++ packer.

The ingestion side (sequencer/scriptorium, bench synthesis, or the catch-up
service's flatten step) encodes each string-channel op stream ONCE into the
flat binary record format documented in ``native/oppack.cpp``; packing a
10k-document batch for the device then runs entirely in C++ — one pass per
document filling the padded (D, T) arrays and the shared text arena, no
Python objects in the loop.

Build: ``liboppack.so`` compiles on demand from ``native/oppack.cpp`` with
g++ (cached next to the source, rebuilt when the source is newer).  If no
toolchain is available the pure-Python encoder/packer pair keeps everything
working — the native path is a strictly optional accelerator with
bit-identical output (asserted by tests/test_native_pack.py).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..protocol.messages import MessageType, SequencedMessage

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "oppack.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "liboppack.so")

_KINDS = {"insert": 1, "remove": 2, "annotate": 3}
_HEADER = struct.Struct("<B7i")
_PAIR = struct.Struct("<2i")


# -- encoder (ingestion side; pure Python by design — runs once per op) -------


def encode_string_ops(
    ops: Sequence[SequencedMessage],
    client_intern,
    prop_key_intern=None,
    value_intern=None,
) -> bytes:
    """Sequence-channel ops → the flat binary record stream.

    ``client_intern`` / ``prop_key_intern`` / ``value_intern`` are
    ``Interner``-likes (callables via ``.intern``); symbol interning stays
    host-side so records carry dense ids only."""
    out = bytearray()
    for msg in ops:
        if msg.type is not MessageType.OP:
            continue
        op = msg.contents
        kind = _KINDS[op["kind"]]
        client = client_intern.intern(msg.client_id) \
            if msg.client_id is not None else -1
        if kind == 1:
            text = op["text"].encode("utf-8")
            a, b = op["pos"], 0
        else:
            text = b""
            a, b = op["start"], op["end"]
        props = op.get("props") or {}
        pairs = []
        for key, value in props.items():
            if prop_key_intern is None:
                raise ValueError("props present but no prop interner given")
            k = prop_key_intern.intern(key)
            v = -1 if value is None else value_intern.intern(value)
            pairs.append((k, v))
        out += _HEADER.pack(kind, msg.seq, msg.ref_seq, client, a, b,
                            len(pairs), len(text))
        for pair in pairs:
            out += _PAIR.pack(*pair)
        out += text
    return bytes(out)


def decode_string_ops(
    blob: bytes, clients: Sequence[str],
    prop_keys: Optional[Sequence[str]] = None,
    values: Optional[Sequence] = None,
) -> List[SequencedMessage]:
    """Inverse of :func:`encode_string_ops` — the oracle-fallback escape
    hatch for binary-only documents (rare; correctness over speed)."""
    out: List[SequencedMessage] = []
    off = 0
    kinds = {v: k for k, v in _KINDS.items()}
    while off < len(blob):
        kind, seq, ref, client, a, b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        off += _HEADER.size
        props = {}
        for _ in range(n_props):
            k, v = _PAIR.unpack_from(blob, off)
            off += 8
            props[prop_keys[k]] = None if v == -1 else values[v]
        text = blob[off:off + text_len].decode("utf-8")
        off += text_len
        name = kinds[kind]
        if name == "insert":
            contents = {"kind": "insert", "pos": a, "text": text}
            if props:
                contents["props"] = props
        else:
            contents = {"kind": name, "start": a, "end": b}
            if props:
                contents["props"] = props
        out.append(SequencedMessage(
            seq=seq, client_id=clients[client] if client >= 0 else None,
            client_seq=seq, ref_seq=ref, min_seq=0,
            type=MessageType.OP, contents=contents,
        ))
    return out


# -- the native library --------------------------------------------------------


_lib_handle: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_library() -> Optional[str]:
    if not os.path.exists(_SRC):
        return None
    if os.path.exists(_LIB) and \
            os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", _LIB, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return _LIB
    except (OSError, subprocess.SubprocessError):
        return None


def load_library() -> Optional[ctypes.CDLL]:
    """The compiled packer, or None (pure-Python fallback)."""
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.oppack_count.restype = ctypes.c_int
    lib.oppack_count.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.oppack_pack.restype = ctypes.c_int32
    lib.oppack_pack.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
    ] + [np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")] * 9 + [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    _lib_handle = lib
    return lib


def count_stream(blob: bytes) -> Tuple[int, int, int]:
    """(n_ops, text_bytes, text_chars) for one binary stream."""
    lib = load_library()
    if lib is not None:
        n_ops = ctypes.c_int32()
        text_bytes = ctypes.c_int64()
        text_chars = ctypes.c_int64()
        rc = lib.oppack_count(blob, len(blob), ctypes.byref(n_ops),
                              ctypes.byref(text_bytes),
                              ctypes.byref(text_chars))
        if rc != 0:
            raise ValueError("malformed binary op stream")
        return n_ops.value, text_bytes.value, text_chars.value
    return _count_py(blob)


def _count_py(blob: bytes) -> Tuple[int, int, int]:
    off, n, tb, tc = 0, 0, 0, 0
    while off < len(blob):
        _kind, _seq, _ref, _cl, _a, _b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        off += _HEADER.size + 8 * n_props
        text = blob[off:off + text_len]
        if len(text) != text_len:
            raise ValueError("malformed binary op stream")
        off += text_len
        tb += text_len
        tc += len(text.decode("utf-8"))
        n += 1
    return n, tb, tc


def pack_doc_row(
    blob: bytes,
    row: Dict[str, np.ndarray],
    K: int,
    arena_base_chars: int,
    arena: bytearray,
    text_bytes: Optional[int] = None,
) -> int:
    """Fill one document's row of the batch arrays from its binary stream;
    appends text to ``arena`` (utf-8 bytes) and returns ops packed.

    ``row`` maps field name → the 1-D row views (``op['kind'][d]`` etc.,
    C-contiguous); ``pvals`` is the (T, K) row."""
    T = row["kind"].shape[0]
    lib = load_library()
    if lib is not None:
        if text_bytes is None:
            _n, text_bytes, _tc = count_stream(blob)
        scratch = np.zeros(max(text_bytes, 1), np.uint8)
        arena_bytes = ctypes.c_int64()
        arena_chars = ctypes.c_int64()
        packed = lib.oppack_pack(
            blob, len(blob), T, K, arena_base_chars,
            row["kind"], row["seq"], row["client"], row["ref_seq"],
            row["a"], row["b"], row["tstart"], row["tlen"],
            row["pvals"].reshape(-1),
            scratch, len(scratch),
            ctypes.byref(arena_bytes), ctypes.byref(arena_chars),
        )
        if packed < 0:
            raise ValueError("malformed binary op stream")
        arena += scratch[:arena_bytes.value].tobytes()
        return packed
    return _pack_py(blob, row, K, arena_base_chars, arena)


def _pack_py(blob: bytes, row: Dict[str, np.ndarray], K: int,
             arena_base_chars: int, arena: bytearray) -> int:
    off, t, chars = 0, 0, 0
    while off < len(blob):
        kind, seq, ref, client, a, b, n_props, text_len = \
            _HEADER.unpack_from(blob, off)
        off += _HEADER.size
        row["kind"][t] = kind
        row["seq"][t] = seq
        row["ref_seq"][t] = ref
        row["client"][t] = client
        row["a"][t] = a
        row["b"][t] = b
        for _ in range(n_props):
            k, v = _PAIR.unpack_from(blob, off)
            off += 8
            row["pvals"][t, k] = v
        if text_len:
            text = blob[off:off + text_len]
            off += text_len
            n_chars = len(text.decode("utf-8"))
            row["tstart"][t] = arena_base_chars + chars
            row["tlen"][t] = n_chars
            arena += text
            chars += n_chars
        else:
            row["tstart"][t] = 0
            row["tlen"][t] = 0
        t += 1
    return t
