"""Pallas TPU kernel for the merge-tree op-fold (SURVEY §7 hard-part #4).

The XLA ``lax.scan`` fold streams the whole carried state — 12 int32
``[S]`` columns plus an ``[S, K]`` props plane per document — through HBM
on every op step: ~``2 * S * (12+K) * 4`` bytes per applied op, the
roofline bench.py reports against.  A document's entire state is tiny
(S=256, K=1: ~13 KB), so the TPU-native shape is a kernel instance that
loads state into VMEM once, folds every op of the tail with a
``fori_loop``, and writes the final state back once: HBM traffic drops
from O(T x state) to O(state + ops) and the fold leaves the bandwidth
roofline entirely.

Each grid step owns a SUBLANE-PACKED BATCH of B=8 documents: blocks are
``(8, S)`` over ``(D, S)`` arrays, which satisfies Mosaic's block rule
directly (sublane dim divisible by 8, lane dim equal to the array's) and
fills the VPU's 8 sublanes instead of wasting 7 of them on a
one-doc-per-step layout (the round-5 compile failure was a ``(1, S)``
block).  ``D`` pads to a multiple of 8 with inert no-op documents.

Semantics are a faithful port of ``mergetree_kernel._apply_op`` /
``_split_at`` (the canonical scan step), restated Mosaic-conservatively
and batch-wide:

- every gather is a roll+select (the step's shifts are shift-right-by-one
  above an index) or a masked one-hot reduction (single-slot reads),
  reduced per-row (``axis=1, keepdims=True``);
- prefix sums are an unrolled Hillis-Steele ladder of masked rolls;
- first/nearest-slot searches are per-row min/max reductions over masked
  iotas;
- all iotas are 2D (``broadcasted_iota``); per-op values are ``(B, 1)``
  columns broadcasting against the ``(B, S)`` state planes.

Exact-parity tests (tests/test_pallas_fold.py) pin this port to the
canonical step on directed + fuzz streams, byte-identical through the
summary extraction.  CI runs the kernel in interpret mode (pure jax, any
backend); on real TPU the compiled path is gated behind
``FF_PALLAS_FOLD=1`` until a healthy-tunnel window lets it be measured
(BASELINE.md round-5 status; tools/pallas_probe.py is the window canary).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .mergetree_kernel import (
    K_ANNOTATE,
    K_INSERT,
    K_OBLITERATE,
    K_REMOVE,
    MTOps,
    MTState,
    NOT_REMOVED,
    PROP_ABSENT,
    PROP_NOT_TOUCHED,
)

_OP_FIELDS = ("kind", "seq", "client", "ref_seq", "min_seq", "a", "b",
              "tstart", "tlen")
_COL_FIELDS = ("tstart", "tlen", "ins_seq", "ins_client", "rem_seq",
               "rem_client", "rem2_seq", "rem2_client", "ob1_seq",
               "ob1_client", "ob2_seq", "ob2_client")

#: documents per grid step — the int32 sublane count; blocks are (8, S)
DOC_BLOCK = 8


def _iota(S: int) -> jnp.ndarray:
    return jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)


def _excl_cumsum(v: jnp.ndarray, S: int) -> jnp.ndarray:
    """Exclusive prefix sum over lanes as a Hillis-Steele ladder of
    masked rolls (statically unrolled; no native cumsum needed)."""
    slot = _iota(S)
    x = v
    d = 1
    while d < S:
        x = x + jnp.where(slot >= d, jnp.roll(x, d, axis=1), 0)
        d *= 2
    return x - v


def _at(f: jnp.ndarray, slot: jnp.ndarray, idx, valid, default):
    """Per-row f[idx] as a masked one-hot reduction (no gather): exact
    when ``valid`` (idx names a real slot), ``default`` otherwise.
    ``f`` is (B, S); ``idx``/``valid`` are (B, 1); result is (B, 1)."""
    hit = jnp.sum(jnp.where(slot == idx, f, 0), axis=1, keepdims=True)
    return jnp.where(valid, hit, jnp.int32(default))


def _shift_up_from(f: jnp.ndarray, slot: jnp.ndarray, idx) -> jnp.ndarray:
    """moved[i] = f[i] for i <= idx else f[i-1] — the pool shift-right a
    split/insert performs, as roll+select (per row; idx is (B, 1))."""
    return jnp.where(slot <= idx, f, jnp.roll(f, 1, axis=1))


def _visible(cols: dict, n, ref_seq, client, S: int) -> jnp.ndarray:
    slot = _iota(S)
    active = slot < n
    ins_vis = (cols["ins_seq"] <= ref_seq) | (cols["ins_client"] == client)
    removed = cols["rem_seq"] != NOT_REMOVED
    rem_vis = (
        (cols["rem_seq"] <= ref_seq)
        | (cols["rem_client"] == client)
        | (cols["rem2_client"] == client)
        # Ob-stamp authors are involved in the removal (the oracle's
        # fuzz-found rule; kernel gap found at fuzz seed 1500041).
        | (removed & (cols["ob1_client"] == client))
        | (removed & (cols["ob2_client"] == client))
    )
    return jnp.where(active & ins_vis & ~rem_vis, cols["tlen"], 0)


def _split_at(cols, props, n, char_pos, ref_seq, client, enable, S):
    """Port of mergetree_kernel._split_at on (B, S) rows; per-op values
    are (B, 1) columns."""
    slot = _iota(S)
    v = _visible(cols, n, ref_seq, client, S)
    cum = _excl_cumsum(v, S)
    inside = (cum < char_pos) & (char_pos < cum + v)
    first = jnp.min(jnp.where(inside, slot, S), axis=1, keepdims=True)
    do = enable & (first < S)
    idx = first  # unique when present; gated by ``do`` below
    off = char_pos - _at(cum, slot, idx, do, 0)

    new_cols = {f: _shift_up_from(cols[f], slot, idx) for f in _COL_FIELDS}
    is_left = slot == idx
    is_right = slot == idx + 1
    tlen = new_cols["tlen"]
    new_cols["tlen"] = jnp.where(
        is_left, off, jnp.where(is_right, tlen - off, tlen))
    new_cols["tstart"] = jnp.where(
        is_right, new_cols["tstart"] + off, new_cols["tstart"])
    new_props = jnp.where(slot[..., None] <= idx[..., None], props,
                          jnp.roll(props, 1, axis=1))

    cols = {f: jnp.where(do, new_cols[f], cols[f]) for f in _COL_FIELDS}
    props = jnp.where(do[..., None], new_props, props)
    n = jnp.where(do, n + 1, n)
    return cols, props, n


def _apply_op_rows(cols, props, n, overflow, op, pvals, S, K):
    """Port of mergetree_kernel._apply_op on (B, S)/(B, S, K) planes.
    ``op`` is a dict of (B, 1) per-doc values; ``pvals`` is (B, K);
    ``n``/``overflow`` are (B, 1)."""
    ref_seq, client = op["ref_seq"], op["client"]
    is_ins = op["kind"] == K_INSERT
    is_rem = op["kind"] == K_REMOVE
    is_ann = op["kind"] == K_ANNOTATE
    is_obl = op["kind"] == K_OBLITERATE
    is_rangey = is_rem | is_ann | is_obl

    cols, props, n = _split_at(cols, props, n, op["a"], ref_seq, client,
                               is_ins | is_rangey, S)
    cols, props, n = _split_at(cols, props, n, op["b"], ref_seq, client,
                               is_rangey, S)

    v = _visible(cols, n, ref_seq, client, S)
    cum = _excl_cumsum(v, S)
    slot = _iota(S)
    active = slot < n
    msn = op["min_seq"]
    ob1_live = (cols["ob1_seq"] != NOT_REMOVED) & (cols["ob1_seq"] > msn)
    ob2_live = (cols["ob2_seq"] != NOT_REMOVED) & (cols["ob2_seq"] > msn)
    expired = (
        (cols["rem_seq"] != NOT_REMOVED) & (cols["rem_seq"] <= msn)
        & (cols["ins_seq"] <= msn) & ~ob1_live & ~ob2_live
    )

    # --- insert: tie-break = first slot with cum >= pos.
    can = (cum >= op["a"]) & active
    jfirst = jnp.min(jnp.where(can, slot, S), axis=1, keepdims=True)
    j = jnp.where(jfirst < S, jfirst, n)

    # Obliterate-on-arrival neighbor rule.
    present = active & ~expired
    left_idx = jnp.max(jnp.where(present & (slot < j), slot, -1),
                       axis=1, keepdims=True)
    right_idx = jnp.min(jnp.where(present & (slot >= j), slot, S),
                        axis=1, keepdims=True)
    has_left = left_idx >= 0
    has_right = right_idx < S
    l1s = _at(cols["ob1_seq"], slot, left_idx, has_left, NOT_REMOVED)
    l2s = _at(cols["ob2_seq"], slot, left_idx, has_left, NOT_REMOVED)
    l1c = _at(cols["ob1_client"], slot, left_idx, has_left, NOT_REMOVED)
    l2c = _at(cols["ob2_client"], slot, left_idx, has_left, NOT_REMOVED)
    r1s = _at(cols["ob1_seq"], slot, right_idx, has_right, NOT_REMOVED)
    r2s = _at(cols["ob2_seq"], slot, right_idx, has_right, NOT_REMOVED)

    def killer_of(ls, lc):
        shared = (ls != NOT_REMOVED) & ((ls == r1s) | (ls == r2s))
        ok = shared & (ls > ref_seq) & (lc != client)
        return jnp.where(ok, ls, jnp.int32(NOT_REMOVED)), lc

    k1s, k1c = killer_of(l1s, l1c)
    k2s, k2c = killer_of(l2s, l2c)
    kill_seq = jnp.minimum(k1s, k2s)
    kill_client = jnp.where(k1s <= k2s, k1c, k2c)
    killed = kill_seq != NOT_REMOVED

    def shifted(f, newval):
        return jnp.where(slot == j, newval, _shift_up_from(f, slot, j))

    ins_cols = {
        "tstart": shifted(cols["tstart"], op["tstart"]),
        "tlen": shifted(cols["tlen"], op["tlen"]),
        "ins_seq": shifted(cols["ins_seq"], op["seq"]),
        "ins_client": shifted(cols["ins_client"], client),
        "rem_seq": shifted(cols["rem_seq"],
                           jnp.where(killed, kill_seq, NOT_REMOVED)),
        "rem_client": shifted(cols["rem_client"],
                              jnp.where(killed, kill_client, -1)),
        "rem2_seq": shifted(cols["rem2_seq"], NOT_REMOVED),
        "rem2_client": shifted(cols["rem2_client"], -1),
        "ob1_seq": shifted(cols["ob1_seq"],
                           jnp.where(killed, kill_seq, NOT_REMOVED)),
        "ob1_client": shifted(cols["ob1_client"],
                              jnp.where(killed, kill_client, -1)),
        "ob2_seq": shifted(cols["ob2_seq"], NOT_REMOVED),
        "ob2_client": shifted(cols["ob2_client"], -1),
    }
    ins_pvals = jnp.where(pvals == PROP_NOT_TOUCHED, PROP_ABSENT, pvals)
    ins_props = jnp.where(
        (slot == j)[..., None],
        ins_pvals[:, None, :],
        jnp.where(slot[..., None] <= j[..., None], props,
                  jnp.roll(props, 1, axis=1)),
    )
    cols = {f: jnp.where(is_ins, ins_cols[f], cols[f]) for f in _COL_FIELDS}
    props = jnp.where(is_ins[..., None], ins_props, props)
    n = jnp.where(is_ins, n + 1, n)

    # --- remove / annotate / obliterate over [a, b) in the view.
    covered = (cum >= op["a"]) & (cum + v <= op["b"]) & (v > 0) & active

    is_rem_like = is_rem | is_obl
    first_win = covered & (cols["rem_seq"] == NOT_REMOVED) & is_rem_like
    again = covered & (cols["rem_seq"] != NOT_REMOVED) & is_rem_like
    second = again & (cols["rem2_seq"] == NOT_REMOVED)
    third = again & (cols["rem2_seq"] != NOT_REMOVED)
    obl_zero = active & ~expired & (v == 0) \
        & (cum > op["a"]) & (cum < op["b"]) & is_obl
    obl_zero_alive = obl_zero & (cols["rem_seq"] == NOT_REMOVED)
    first_win = first_win | obl_zero_alive
    stamp = (covered & is_obl) | obl_zero
    to_ob1 = stamp & (cols["ob1_seq"] == NOT_REMOVED)
    to_ob2 = stamp & ~to_ob1 & (cols["ob2_seq"] == NOT_REMOVED) \
        & (cols["ob1_seq"] != op["seq"])
    ob_over = stamp & (cols["ob1_seq"] != NOT_REMOVED) \
        & (cols["ob2_seq"] != NOT_REMOVED) \
        & (cols["ob1_seq"] != op["seq"]) & (cols["ob2_seq"] != op["seq"])
    cols = dict(
        cols,
        rem_seq=jnp.where(first_win, op["seq"], cols["rem_seq"]),
        rem_client=jnp.where(first_win, client, cols["rem_client"]),
        rem2_seq=jnp.where(second, op["seq"], cols["rem2_seq"]),
        rem2_client=jnp.where(second, client, cols["rem2_client"]),
        ob1_seq=jnp.where(to_ob1, op["seq"], cols["ob1_seq"]),
        ob1_client=jnp.where(to_ob1, client, cols["ob1_client"]),
        ob2_seq=jnp.where(to_ob2, op["seq"], cols["ob2_seq"]),
        ob2_client=jnp.where(to_ob2, client, cols["ob2_client"]),
    )
    overflow = overflow | jnp.any(third, axis=1, keepdims=True) \
        | jnp.any(ob_over, axis=1, keepdims=True)

    touch = (pvals != PROP_NOT_TOUCHED)[:, None, :] \
        & (covered & is_ann)[..., None]
    props = jnp.where(touch, pvals[:, None, :], props)
    return cols, props, n, overflow


def _fold_kernel(S: int, K: int, T: int, B: int, *refs):
    """A sublane batch of B documents per grid step: state lives in VMEM
    values across the whole tail; every block is 2-D ``(B, ...)`` so the
    Mosaic block rule holds without padding tricks."""
    op_refs = refs[:len(_OP_FIELDS)]
    pvals_ref = refs[len(_OP_FIELDS)]
    in_cols = refs[len(_OP_FIELDS) + 1:len(_OP_FIELDS) + 1 + len(_COL_FIELDS)]
    in_props, in_n, in_over = refs[len(_OP_FIELDS) + 1 + len(_COL_FIELDS):
                                   len(_OP_FIELDS) + 4 + len(_COL_FIELDS)]
    outs = refs[len(_OP_FIELDS) + 4 + len(_COL_FIELDS):]

    cols = {f: r[...] for f, r in zip(_COL_FIELDS, in_cols)}
    props = in_props[...]
    n = in_n[...]          # (B, 1)
    overflow = in_over[...] != 0

    def body(t, carry):
        cols, props, n, overflow = carry
        op = {f: r[:, t].reshape(B, 1) for f, r in zip(_OP_FIELDS, op_refs)}
        pvals = pvals_ref[:, t, :]
        return _apply_op_rows(cols, props, n, overflow, op, pvals, S, K)

    cols, props, n, overflow = jax.lax.fori_loop(
        0, T, body, (cols, props, n, overflow))

    for f, r in zip(_COL_FIELDS, outs):
        r[...] = cols[f]
    outs[len(_COL_FIELDS)][...] = props
    outs[len(_COL_FIELDS) + 1][...] = n
    outs[len(_COL_FIELDS) + 2][...] = overflow.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def replay_vmapped_pallas(state: MTState, ops: MTOps,
                          interpret: bool = True) -> MTState:
    """Drop-in replacement for ``replay_vmapped``: same (state, ops)
    pytrees in, same final MTState out — the fold itself runs as one
    Pallas program instance per 8-document sublane batch with
    VMEM-resident state.  ``D`` pads to a multiple of 8 with inert no-op
    documents (noop op rows never match a kind; zero state rows never
    activate), sliced off on return."""
    D, S = state.tstart.shape
    K = state.props.shape[-1]
    T = ops.kind.shape[1]
    B = DOC_BLOCK
    Dp = ((D + B - 1) // B) * B
    pad = Dp - D

    def pad_rows(x, fill):
        if pad == 0:
            return x
        width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, width, constant_values=fill)

    row = pl.BlockSpec((B, S), lambda d: (d, 0))
    op_row = pl.BlockSpec((B, T), lambda d: (d, 0))
    props_blk = pl.BlockSpec((B, S, K), lambda d: (d, 0, 0))
    pvals_blk = pl.BlockSpec((B, T, K), lambda d: (d, 0, 0))
    scalar = pl.BlockSpec((B, 1), lambda d: (d, 0))

    in_specs = (
        [op_row] * len(_OP_FIELDS) + [pvals_blk]
        + [row] * len(_COL_FIELDS) + [props_blk, scalar, scalar]
    )
    out_specs = [row] * len(_COL_FIELDS) + [props_blk, scalar, scalar]
    out_shape = (
        [jax.ShapeDtypeStruct((Dp, S), jnp.int32)] * len(_COL_FIELDS)
        + [jax.ShapeDtypeStruct((Dp, S, K), jnp.int32),
           jax.ShapeDtypeStruct((Dp, 1), jnp.int32),
           jax.ShapeDtypeStruct((Dp, 1), jnp.int32)]
    )

    inputs = (
        [pad_rows(getattr(ops, f).astype(jnp.int32), 0)
         for f in _OP_FIELDS]
        + [pad_rows(ops.pvals.astype(jnp.int32), int(PROP_NOT_TOUCHED))]
        + [pad_rows(getattr(state, f).astype(jnp.int32),
                    int(NOT_REMOVED) if f.endswith("_seq")
                    and f != "ins_seq" else 0)
           for f in _COL_FIELDS]
        + [pad_rows(state.props.astype(jnp.int32), int(PROP_ABSENT)),
           pad_rows(state.n.astype(jnp.int32).reshape(D, 1), 0),
           pad_rows(state.overflow.astype(jnp.int32).reshape(D, 1), 0)]
    )

    outs = pl.pallas_call(
        functools.partial(_fold_kernel, S, K, T, B),
        grid=(Dp // B,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)

    cols = {f: o[:D] for f, o in zip(_COL_FIELDS, outs[:len(_COL_FIELDS)])}
    return MTState(
        **cols,
        props=outs[len(_COL_FIELDS)][:D],
        n=outs[len(_COL_FIELDS) + 1][:D].reshape(D),
        overflow=outs[len(_COL_FIELDS) + 2][:D].reshape(D).astype(bool),
    )


def pallas_fold_mode() -> str:
    """''/off (default), 'interpret', or 'tpu' (compiled Mosaic — gate it
    until measured on a healthy tunnel)."""
    import os

    mode = os.environ.get("FF_PALLAS_FOLD", "").lower()
    if mode in ("1", "tpu", "on"):
        return "tpu"
    if mode == "interpret":
        return "interpret"
    return ""
