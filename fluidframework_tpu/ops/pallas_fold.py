"""Pallas TPU kernel for the merge-tree op-fold (SURVEY §7 hard-part #4).

The XLA ``lax.scan`` fold streams the whole carried state — 12 int32
``[S]`` columns plus an ``[S, K]`` props plane per document — through HBM
on every op step: ~``2 * S * (12+K) * 4`` bytes per applied op, the
roofline bench.py reports against.  A document's entire state is tiny
(S=256, K=1: ~13 KB), so the TPU-native shape is a kernel instance that
loads state into VMEM once, folds every op of the tail with a
``fori_loop``, and writes the final state back once: HBM traffic drops
from O(T x state) to O(state + ops) and the fold leaves the bandwidth
roofline entirely.

Every block is 2-D and satisfies Mosaic's divisibility rule OUTRIGHT
(second-to-last dim a multiple of 8, last dim a multiple of 128):

- each grid step owns a SUBLANE-PACKED BATCH of B=8 documents, so the
  sublane dim is exactly 8 (the round-5 compile failure was a ``(1, S)``
  block; the recorded round-5 TPU error was its lane-dim sibling —
  ``block shape (1, 96)`` vs array ``(1024, 96)``);
- the lane dims pad to multiples of 128: ``S → Sp`` and ``T → Tp``
  round up, scalars (``n``/``overflow``) ride a 128-lane column with the
  value in lane 0.  Pad lanes are masked by construction — state lanes
  at ``slot >= n`` are inactive in every predicate, and the op loop runs
  only the REAL ``T`` steps (the pad rows are never read);
- the ``[S, K]`` props plane and ``[T, K]`` pvals plane are carried as K
  separate ``(8, lanes)`` planes (K is a static pack-time bucket), so no
  3-D block ever reaches Mosaic.

``D`` pads to a multiple of 8 with inert no-op documents.

Semantics are a faithful port of ``mergetree_kernel._apply_op`` /
``_split_at`` (the canonical scan step), restated Mosaic-conservatively
and batch-wide:

- every gather is a roll+select (the step's shifts are shift-right-by-one
  above an index) or a masked one-hot reduction (single-slot reads),
  reduced per-row (``axis=1, keepdims=True``);
- prefix sums are an unrolled Hillis-Steele ladder of masked rolls;
- first/nearest-slot searches are per-row min/max reductions over masked
  iotas;
- all iotas are 2D (``broadcasted_iota``); per-op values are ``(B, 1)``
  columns broadcasting against the ``(B, S)`` state planes.

Exact-parity tests (tests/test_pallas_fold.py) pin this port to the
canonical step on directed + fuzz streams, byte-identical through the
summary extraction, including shapes whose natural buckets violate the
divisibility rule (S=48, T=24, K=1) so the padding really executes.  CI
runs the kernel in interpret mode (pure jax, any backend); on real TPU
the compiled path is gated behind ``FF_PALLAS_FOLD=1`` until a
healthy-tunnel window lets it be measured (BASELINE.md round-5 status;
tools/pallas_probe.py is the window canary).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .mergetree_kernel import (
    K_ANNOTATE,
    K_INSERT,
    K_OBLITERATE,
    K_REMOVE,
    MTOps,
    MTState,
    NOT_REMOVED,
    PROP_ABSENT,
    PROP_NOT_TOUCHED,
)

_OP_FIELDS = ("kind", "seq", "client", "ref_seq", "min_seq", "a", "b",
              "tstart", "tlen")
_COL_FIELDS = ("tstart", "tlen", "ins_seq", "ins_client", "rem_seq",
               "rem_client", "rem2_seq", "rem2_client", "ob1_seq",
               "ob1_client", "ob2_seq", "ob2_client")

#: documents per grid step — the int32 sublane count; blocks are (8, lanes)
DOC_BLOCK = 8
#: every block's lane dim is a multiple of this (Mosaic's (8, 128) rule)
LANE = 128

# Host-side pad fills, precomputed ONCE at import as plain Python ints —
# the typed helper that keeps the traced entry point free of int()
# concretization (fluidlint FL-TRACE-HOSTSYNC: int() on a module constant
# is concrete at trace time, but the rule cannot see through the binding;
# hoisting the conversion out of trace scope makes the code and the rule
# agree).
_NOT_REMOVED_FILL: int = int(NOT_REMOVED)
_PROP_ABSENT_FILL: int = int(PROP_ABSENT)
_PROP_NOT_TOUCHED_FILL: int = int(PROP_NOT_TOUCHED)


def _state_pad_fill(field: str) -> int:
    """Pad fill for a state plane: the NOT_REMOVED sentinel for removal /
    obliterate stamp seqs (a zero would read as 'removed at seq 0'),
    zero elsewhere — pad slots are inactive (``slot >= n``) in every
    predicate regardless; the sentinel is belt and braces."""
    if field.endswith("_seq") and field != "ins_seq":
        return _NOT_REMOVED_FILL
    return 0


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _padded_dims(D: int, S: int, T: int):
    """The Mosaic-compliant padded shape: documents to a multiple of the
    8-row sublane batch, both lane dims (slots, op rows) to multiples of
    128 — so every BlockSpec below satisfies the (8, 128) divisibility
    rule by construction."""
    return _round_up(max(D, 1), DOC_BLOCK), _round_up(max(S, 1), LANE), \
        _round_up(max(T, 1), LANE)


def _iota(S: int) -> jnp.ndarray:
    return jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)


def _excl_cumsum(v: jnp.ndarray, S: int) -> jnp.ndarray:
    """Exclusive prefix sum over lanes as a Hillis-Steele ladder of
    masked rolls (statically unrolled; no native cumsum needed)."""
    slot = _iota(S)
    x = v
    d = 1
    while d < S:
        x = x + jnp.where(slot >= d, jnp.roll(x, d, axis=1), 0)
        d *= 2
    return x - v


def _at(f: jnp.ndarray, slot: jnp.ndarray, idx, valid, default):
    """Per-row f[idx] as a masked one-hot reduction (no gather): exact
    when ``valid`` (idx names a real slot), ``default`` otherwise.
    ``f`` is (B, S); ``idx``/``valid`` are (B, 1); result is (B, 1)."""
    hit = jnp.sum(jnp.where(slot == idx, f, 0), axis=1, keepdims=True)
    return jnp.where(valid, hit, jnp.int32(default))


def _shift_up_from(f: jnp.ndarray, slot: jnp.ndarray, idx) -> jnp.ndarray:
    """moved[i] = f[i] for i <= idx else f[i-1] — the pool shift-right a
    split/insert performs, as roll+select (per row; idx is (B, 1))."""
    return jnp.where(slot <= idx, f, jnp.roll(f, 1, axis=1))


def _visible(cols: dict, n, ref_seq, client, S: int) -> jnp.ndarray:
    slot = _iota(S)
    active = slot < n
    ins_vis = (cols["ins_seq"] <= ref_seq) | (cols["ins_client"] == client)
    removed = cols["rem_seq"] != NOT_REMOVED
    rem_vis = (
        (cols["rem_seq"] <= ref_seq)
        | (cols["rem_client"] == client)
        | (cols["rem2_client"] == client)
        # Ob-stamp authors are involved in the removal (the oracle's
        # fuzz-found rule; kernel gap found at fuzz seed 1500041).
        | (removed & (cols["ob1_client"] == client))
        | (removed & (cols["ob2_client"] == client))
    )
    return jnp.where(active & ins_vis & ~rem_vis, cols["tlen"], 0)


def _split_at(cols, props, n, char_pos, ref_seq, client, enable, S):
    """Port of mergetree_kernel._split_at on (B, S) rows; per-op values
    are (B, 1) columns; ``props`` is a tuple of K (B, S) planes."""
    slot = _iota(S)
    v = _visible(cols, n, ref_seq, client, S)
    cum = _excl_cumsum(v, S)
    inside = (cum < char_pos) & (char_pos < cum + v)
    first = jnp.min(jnp.where(inside, slot, S), axis=1, keepdims=True)
    do = enable & (first < S)
    idx = first  # unique when present; gated by ``do`` below
    off = char_pos - _at(cum, slot, idx, do, 0)

    new_cols = {f: _shift_up_from(cols[f], slot, idx) for f in _COL_FIELDS}
    is_left = slot == idx
    is_right = slot == idx + 1
    tlen = new_cols["tlen"]
    new_cols["tlen"] = jnp.where(
        is_left, off, jnp.where(is_right, tlen - off, tlen))
    new_cols["tstart"] = jnp.where(
        is_right, new_cols["tstart"] + off, new_cols["tstart"])

    cols = {f: jnp.where(do, new_cols[f], cols[f]) for f in _COL_FIELDS}
    props = tuple(
        jnp.where(do, _shift_up_from(p, slot, idx), p) for p in props
    )
    n = jnp.where(do, n + 1, n)
    return cols, props, n


def _apply_op_rows(cols, props, n, overflow, op, pvals, S, K):
    """Port of mergetree_kernel._apply_op on (B, S) planes.
    ``op`` is a dict of (B, 1) per-doc values; ``pvals`` is a tuple of K
    (B, 1) columns; ``props`` a tuple of K (B, S) planes;
    ``n``/``overflow`` are (B, 1)."""
    ref_seq, client = op["ref_seq"], op["client"]
    is_ins = op["kind"] == K_INSERT
    is_rem = op["kind"] == K_REMOVE
    is_ann = op["kind"] == K_ANNOTATE
    is_obl = op["kind"] == K_OBLITERATE
    is_rangey = is_rem | is_ann | is_obl

    cols, props, n = _split_at(cols, props, n, op["a"], ref_seq, client,
                               is_ins | is_rangey, S)
    cols, props, n = _split_at(cols, props, n, op["b"], ref_seq, client,
                               is_rangey, S)

    v = _visible(cols, n, ref_seq, client, S)
    cum = _excl_cumsum(v, S)
    slot = _iota(S)
    active = slot < n
    msn = op["min_seq"]
    ob1_live = (cols["ob1_seq"] != NOT_REMOVED) & (cols["ob1_seq"] > msn)
    ob2_live = (cols["ob2_seq"] != NOT_REMOVED) & (cols["ob2_seq"] > msn)
    expired = (
        (cols["rem_seq"] != NOT_REMOVED) & (cols["rem_seq"] <= msn)
        & (cols["ins_seq"] <= msn) & ~ob1_live & ~ob2_live
    )

    # --- insert: tie-break = first slot with cum >= pos.
    can = (cum >= op["a"]) & active
    jfirst = jnp.min(jnp.where(can, slot, S), axis=1, keepdims=True)
    j = jnp.where(jfirst < S, jfirst, n)

    # Obliterate-on-arrival neighbor rule.
    present = active & ~expired
    left_idx = jnp.max(jnp.where(present & (slot < j), slot, -1),
                       axis=1, keepdims=True)
    right_idx = jnp.min(jnp.where(present & (slot >= j), slot, S),
                        axis=1, keepdims=True)
    has_left = left_idx >= 0
    has_right = right_idx < S
    l1s = _at(cols["ob1_seq"], slot, left_idx, has_left, NOT_REMOVED)
    l2s = _at(cols["ob2_seq"], slot, left_idx, has_left, NOT_REMOVED)
    l1c = _at(cols["ob1_client"], slot, left_idx, has_left, NOT_REMOVED)
    l2c = _at(cols["ob2_client"], slot, left_idx, has_left, NOT_REMOVED)
    r1s = _at(cols["ob1_seq"], slot, right_idx, has_right, NOT_REMOVED)
    r2s = _at(cols["ob2_seq"], slot, right_idx, has_right, NOT_REMOVED)

    def killer_of(ls, lc):
        shared = (ls != NOT_REMOVED) & ((ls == r1s) | (ls == r2s))
        ok = shared & (ls > ref_seq) & (lc != client)
        return jnp.where(ok, ls, jnp.int32(NOT_REMOVED)), lc

    k1s, k1c = killer_of(l1s, l1c)
    k2s, k2c = killer_of(l2s, l2c)
    kill_seq = jnp.minimum(k1s, k2s)
    kill_client = jnp.where(k1s <= k2s, k1c, k2c)
    killed = kill_seq != NOT_REMOVED

    def shifted(f, newval):
        return jnp.where(slot == j, newval, _shift_up_from(f, slot, j))

    ins_cols = {
        "tstart": shifted(cols["tstart"], op["tstart"]),
        "tlen": shifted(cols["tlen"], op["tlen"]),
        "ins_seq": shifted(cols["ins_seq"], op["seq"]),
        "ins_client": shifted(cols["ins_client"], client),
        "rem_seq": shifted(cols["rem_seq"],
                           jnp.where(killed, kill_seq, NOT_REMOVED)),
        "rem_client": shifted(cols["rem_client"],
                              jnp.where(killed, kill_client, -1)),
        "rem2_seq": shifted(cols["rem2_seq"], NOT_REMOVED),
        "rem2_client": shifted(cols["rem2_client"], -1),
        "ob1_seq": shifted(cols["ob1_seq"],
                           jnp.where(killed, kill_seq, NOT_REMOVED)),
        "ob1_client": shifted(cols["ob1_client"],
                              jnp.where(killed, kill_client, -1)),
        "ob2_seq": shifted(cols["ob2_seq"], NOT_REMOVED),
        "ob2_client": shifted(cols["ob2_client"], -1),
    }
    ins_props = tuple(
        shifted(p, jnp.where(pv == PROP_NOT_TOUCHED, PROP_ABSENT, pv))
        for p, pv in zip(props, pvals)
    )
    cols = {f: jnp.where(is_ins, ins_cols[f], cols[f]) for f in _COL_FIELDS}
    props = tuple(
        jnp.where(is_ins, ip, p) for ip, p in zip(ins_props, props)
    )
    n = jnp.where(is_ins, n + 1, n)

    # --- remove / annotate / obliterate over [a, b) in the view.
    covered = (cum >= op["a"]) & (cum + v <= op["b"]) & (v > 0) & active

    is_rem_like = is_rem | is_obl
    first_win = covered & (cols["rem_seq"] == NOT_REMOVED) & is_rem_like
    again = covered & (cols["rem_seq"] != NOT_REMOVED) & is_rem_like
    second = again & (cols["rem2_seq"] == NOT_REMOVED)
    third = again & (cols["rem2_seq"] != NOT_REMOVED)
    obl_zero = active & ~expired & (v == 0) \
        & (cum > op["a"]) & (cum < op["b"]) & is_obl
    obl_zero_alive = obl_zero & (cols["rem_seq"] == NOT_REMOVED)
    first_win = first_win | obl_zero_alive
    stamp = (covered & is_obl) | obl_zero
    to_ob1 = stamp & (cols["ob1_seq"] == NOT_REMOVED)
    to_ob2 = stamp & ~to_ob1 & (cols["ob2_seq"] == NOT_REMOVED) \
        & (cols["ob1_seq"] != op["seq"])
    ob_over = stamp & (cols["ob1_seq"] != NOT_REMOVED) \
        & (cols["ob2_seq"] != NOT_REMOVED) \
        & (cols["ob1_seq"] != op["seq"]) & (cols["ob2_seq"] != op["seq"])
    cols = dict(
        cols,
        rem_seq=jnp.where(first_win, op["seq"], cols["rem_seq"]),
        rem_client=jnp.where(first_win, client, cols["rem_client"]),
        rem2_seq=jnp.where(second, op["seq"], cols["rem2_seq"]),
        rem2_client=jnp.where(second, client, cols["rem2_client"]),
        ob1_seq=jnp.where(to_ob1, op["seq"], cols["ob1_seq"]),
        ob1_client=jnp.where(to_ob1, client, cols["ob1_client"]),
        ob2_seq=jnp.where(to_ob2, op["seq"], cols["ob2_seq"]),
        ob2_client=jnp.where(to_ob2, client, cols["ob2_client"]),
    )
    overflow = overflow | jnp.any(third, axis=1, keepdims=True) \
        | jnp.any(ob_over, axis=1, keepdims=True)

    props = tuple(
        jnp.where((pv != PROP_NOT_TOUCHED) & (covered & is_ann), pv, p)
        for p, pv in zip(props, pvals)
    )
    return cols, props, n, overflow


def _fold_kernel(S: int, K: int, T: int, B: int, *refs):
    """A sublane batch of B documents per grid step: state lives in VMEM
    values across the whole tail; every block is 2-D ``(B, lanes)`` with
    128-multiple lanes, so the Mosaic block rule holds by construction.
    ``S`` is the PADDED slot lane count; ``T`` is the REAL op count — the
    loop never reads the pad rows of the (B, Tp) op blocks."""
    n_op = len(_OP_FIELDS)
    n_col = len(_COL_FIELDS)
    op_refs = refs[:n_op]
    pvals_refs = refs[n_op:n_op + K]
    in_cols = refs[n_op + K:n_op + K + n_col]
    in_props = refs[n_op + K + n_col:n_op + 2 * K + n_col]
    in_n, in_over = refs[n_op + 2 * K + n_col:n_op + 2 * K + n_col + 2]
    outs = refs[n_op + 2 * K + n_col + 2:]

    cols = {f: r[...] for f, r in zip(_COL_FIELDS, in_cols)}
    props = tuple(r[...] for r in in_props)
    n = in_n[:, :1]                 # value rides lane 0 of the 128-lane pad
    overflow = in_over[:, :1] != 0

    def body(t, carry):
        cols, props, n, overflow = carry
        op = {f: r[:, t].reshape(B, 1) for f, r in zip(_OP_FIELDS, op_refs)}
        pvals = tuple(r[:, t].reshape(B, 1) for r in pvals_refs)
        return _apply_op_rows(cols, props, n, overflow, op, pvals, S, K)

    cols, props, n, overflow = jax.lax.fori_loop(
        0, T, body, (cols, props, n, overflow))

    for f, r in zip(_COL_FIELDS, outs):
        r[...] = cols[f]
    for k in range(K):
        outs[len(_COL_FIELDS) + k][...] = props[k]
    lanes = outs[len(_COL_FIELDS) + K].shape[1]
    # Scalars broadcast across their 128-lane pad; the host reads lane 0.
    outs[len(_COL_FIELDS) + K][...] = jnp.broadcast_to(n, (B, lanes))
    outs[len(_COL_FIELDS) + K + 1][...] = jnp.broadcast_to(
        overflow.astype(jnp.int32), (B, lanes))


@functools.partial(jax.jit, static_argnames=("interpret",))
def replay_vmapped_pallas(state: MTState, ops: MTOps,
                          interpret: bool = True) -> MTState:
    """Drop-in replacement for ``replay_vmapped``: same (state, ops)
    pytrees in, same final MTState out — the fold itself runs as one
    Pallas program instance per 8-document sublane batch with
    VMEM-resident state.  ``D`` pads to a multiple of 8 with inert no-op
    documents (noop op rows never match a kind; zero state rows never
    activate); the slot and op lane dims pad to multiples of 128 (pad
    slots stay inactive — ``slot >= n`` — and pad op rows are never read:
    the loop bound is the real T).  All padding is sliced off on
    return."""
    D, S = state.tstart.shape
    K = state.props.shape[-1]
    T = ops.kind.shape[1]
    B = DOC_BLOCK
    Dp, Sp, Tp = _padded_dims(D, S, T)

    def pad2(x, rows, lanes, fill):
        pr, pl_ = rows - x.shape[0], lanes - x.shape[1]
        if pr == 0 and pl_ == 0:
            return x
        return jnp.pad(x, ((0, pr), (0, pl_)), constant_values=fill)

    inputs = (
        [pad2(getattr(ops, f).astype(jnp.int32), Dp, Tp, 0)
         for f in _OP_FIELDS]
        + [pad2(ops.pvals[:, :, k].astype(jnp.int32), Dp, Tp,
                _PROP_NOT_TOUCHED_FILL) for k in range(K)]
        + [pad2(getattr(state, f).astype(jnp.int32), Dp, Sp,
                _state_pad_fill(f)) for f in _COL_FIELDS]
        + [pad2(state.props[:, :, k].astype(jnp.int32), Dp, Sp,
                _PROP_ABSENT_FILL) for k in range(K)]
        + [pad2(state.n.astype(jnp.int32).reshape(D, 1), Dp, LANE, 0),
           pad2(state.overflow.astype(jnp.int32).reshape(D, 1), Dp, LANE,
                0)]
    )

    row = pl.BlockSpec((B, Sp), lambda d: (d, 0))
    op_row = pl.BlockSpec((B, Tp), lambda d: (d, 0))
    scalar = pl.BlockSpec((B, LANE), lambda d: (d, 0))

    in_specs = (
        [op_row] * (len(_OP_FIELDS) + K)
        + [row] * (len(_COL_FIELDS) + K) + [scalar, scalar]
    )
    out_specs = [row] * (len(_COL_FIELDS) + K) + [scalar, scalar]
    out_shape = (
        [jax.ShapeDtypeStruct((Dp, Sp), jnp.int32)]
        * (len(_COL_FIELDS) + K)
        + [jax.ShapeDtypeStruct((Dp, LANE), jnp.int32)] * 2
    )

    outs = pl.pallas_call(
        functools.partial(_fold_kernel, Sp, K, T, B),
        grid=(Dp // B,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)

    n_col = len(_COL_FIELDS)
    cols = {f: o[:D, :S] for f, o in zip(_COL_FIELDS, outs[:n_col])}
    return MTState(
        **cols,
        props=jnp.stack([outs[n_col + k][:D, :S] for k in range(K)],
                        axis=-1),
        n=outs[n_col + K][:D, 0],
        overflow=outs[n_col + K + 1][:D, 0].astype(bool),
    )


def pallas_fold_mode() -> str:
    """''/off (default), 'interpret', or 'tpu' (compiled Mosaic — gate it
    until measured on a healthy tunnel)."""
    import os

    mode = os.environ.get("FF_PALLAS_FOLD", "").lower()
    if mode in ("1", "tpu", "on"):
        return "tpu"
    if mode == "interpret":
        return "interpret"
    return ""
