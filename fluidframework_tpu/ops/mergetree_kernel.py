"""Merge-tree catch-up replay on device — the north-star kernel.

Re-expresses the CPU oracle's pointer-walk (dds/merge_tree.py, semantics
pinned by SEMANTICS.md) as a pure op-fold over *array-structured state*
(SURVEY.md §7 design stance): per document, a fixed-capacity segment pool
kept in sequence order as a struct-of-int32-arrays; each sequenced op is one
`lax.scan` step of fixed-shape vector work:

1. masked visible lengths for the op's view (ref_seq, client) — the
   "partial lengths" of the reference, recomputed as a masked prefix sum;
2. up to two *splits* (range/position boundaries falling inside segments),
   each a shift-by-one gather over the pool;
3. the op body as masked updates: insert = shift + write at the tie-break
   index (first slot whose exclusive prefix ≥ pos — catch-up has no pending
   segments, so the SEMANTICS.md tie-break degenerates to exactly this);
   remove = first-wins removal marking (+ exact-timed second-remover
   fields for overlap); annotate = masked property-column writes.

Catch-up is post-sequencing: the fold is sequential per document but
embarrassingly parallel across documents — `vmap` over the doc axis, then
pjit over a document-sharded mesh (parallel/).  Zamboni is intentionally
*absent* on device: tombstone collection never changes the visible order
(tie-break stops before tombstones; sub-window tombstones are invisible to
every reachable view), so the kernel keeps tombstones and the host-side
canonical normalizer (same one the oracle uses) drops them at summary
extraction.  Text bytes stay host-side in an arena; the device tracks
(start, len) spans only.

Interval ops don't run on device: they are folded host-side over the final
device state (ops/interval_replay.py), which retains every tombstone and so
reconstructs any historical view.  Documents where >2 removers overlap one
segment (device tracks two exactly; flag raised otherwise) or whose base
summary carries >1 overlap removers fall back to a full oracle replay —
correctness is never approximated.  Segment pool capacity = base segments +
2·ops (each op splits ≤ 2).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .interning import Interner, TextArena, next_bucket, next_bucket_fine
from .native_pack import count_stream

NOT_REMOVED = np.int32(np.iinfo(np.int32).max)
# Property-column sentinels (values are interned ids >= 0).
PROP_ABSENT = -1      # key not set on the segment
PROP_NOT_TOUCHED = -2  # annotate op does not touch this key

K_NOOP, K_INSERT, K_REMOVE, K_ANNOTATE, K_OBLITERATE = 0, 1, 2, 3, 4


class MTState(NamedTuple):
    """Per-document segment pool, in sequence order (slots [0, n))."""

    tstart: jnp.ndarray      # [S] arena offset
    tlen: jnp.ndarray        # [S] span length (chars)
    ins_seq: jnp.ndarray     # [S]
    ins_client: jnp.ndarray  # [S] per-doc client idx; -1 = universal epoch
    rem_seq: jnp.ndarray     # [S] NOT_REMOVED if alive
    rem_client: jnp.ndarray  # [S] -1 if alive
    rem2_seq: jnp.ndarray    # [S] second (overlap) remover seq / NOT_REMOVED
    rem2_client: jnp.ndarray # [S] second remover client / -1
    ob1_seq: jnp.ndarray     # [S] first obliterate stamp seq / NOT_REMOVED
    ob1_client: jnp.ndarray  # [S] first stamp client / -1
    ob2_seq: jnp.ndarray     # [S] second obliterate stamp seq / NOT_REMOVED
    ob2_client: jnp.ndarray  # [S] second stamp client / -1
    props: jnp.ndarray       # [S, K] interned value ids / PROP_ABSENT
    n: jnp.ndarray           # [] live slot count
    overflow: jnp.ndarray    # [] bool: >2 removers hit one segment


class MTOps(NamedTuple):
    """Packed op stream (scan xs), one row per sequenced op."""

    kind: jnp.ndarray     # [T]
    seq: jnp.ndarray      # [T]
    client: jnp.ndarray   # [T] per-doc client idx
    ref_seq: jnp.ndarray  # [T]
    min_seq: jnp.ndarray  # [T] stamped MSN (drives expiry parity w/ zamboni)
    a: jnp.ndarray        # [T] pos (insert) / start (remove, annotate)
    b: jnp.ndarray        # [T] end (remove, annotate)
    tstart: jnp.ndarray   # [T] arena offset of inserted text
    tlen: jnp.ndarray     # [T]
    pvals: jnp.ndarray    # [T, K] per-key values / PROP_NOT_TOUCHED


def _visible_len(state: MTState, ref_seq, client,
                 has_ob: bool = True) -> jnp.ndarray:
    slot = jnp.arange(state.tlen.shape[0])
    active = slot < state.n
    ins_vis = (state.ins_seq <= ref_seq) | (state.ins_client == client)
    rem_vis = (
        (state.rem_seq <= ref_seq)
        | (state.rem_client == client)
        | (state.rem2_client == client)
    )
    if has_ob:
        # An obliterate STAMP makes its author involved in the removal
        # even when another client's remove won it: the author's
        # optimistic view hid every covered slot, so views in the
        # author's name must hide the tombstone too (the oracle's
        # fuzz-found rule, merge_tree._removed_in_view; kernel gap found
        # at fuzz seed 1500041 — a lagged insert resolved 4 chars off).
        # Ob-free chunks (compile-time fact) skip the plane reads.
        removed = state.rem_seq != NOT_REMOVED
        rem_vis = rem_vis \
            | (removed & (state.ob1_client == client)) \
            | (removed & (state.ob2_client == client))
    return jnp.where(active & ins_vis & ~rem_vis, state.tlen, 0)


def _excl_cumsum(v: jnp.ndarray) -> jnp.ndarray:
    return jnp.cumsum(v) - v


def _split_at(state: MTState, char_pos, ref_seq, client, enable,
              has_ob: bool = True, has_ov: bool = True,
              has_props: bool = True) -> MTState:
    """Split the segment that ``char_pos`` falls strictly inside of (in the
    op's view), shifting the pool right by one.  No-op when the position
    lands on a boundary or ``enable`` is false.

    Constant planes are SHIFT-INVARIANT, so the chunk facts skip their
    shuffles outright: ob-free chunks never write the four ob columns
    (they stay NOT_REMOVED/-1), second-remover-free chunks (fully
    sequential views + no base "ro") never write rem2, props-free chunks
    never write the [S, K] plane."""
    S = state.tlen.shape[0]
    v = _visible_len(state, ref_seq, client, has_ob)
    cum = _excl_cumsum(v)
    inside = (cum < char_pos) & (char_pos < cum + v)
    do = enable & inside.any()
    idx = jnp.argmax(inside)  # unique when present
    off = char_pos - cum[idx]
    slot = jnp.arange(S)
    src = jnp.where(slot <= idx, slot, slot - 1)

    def shift(f):
        return jnp.take(f, src, axis=0)

    tstart, tlen = shift(state.tstart), shift(state.tlen)
    is_left = slot == idx
    is_right = slot == idx + 1
    new_tlen = jnp.where(is_left, off, jnp.where(is_right, tlen - off, tlen))
    new_tstart = jnp.where(is_right, tstart + off, tstart)
    out = MTState(
        tstart=new_tstart,
        tlen=new_tlen,
        ins_seq=shift(state.ins_seq),
        ins_client=shift(state.ins_client),
        rem_seq=shift(state.rem_seq),
        rem_client=shift(state.rem_client),
        rem2_seq=shift(state.rem2_seq) if has_ov else state.rem2_seq,
        rem2_client=shift(state.rem2_client) if has_ov
        else state.rem2_client,
        ob1_seq=shift(state.ob1_seq) if has_ob else state.ob1_seq,
        ob1_client=shift(state.ob1_client) if has_ob else state.ob1_client,
        ob2_seq=shift(state.ob2_seq) if has_ob else state.ob2_seq,
        ob2_client=shift(state.ob2_client) if has_ob else state.ob2_client,
        props=shift(state.props) if has_props else state.props,
        n=state.n + 1,
        overflow=state.overflow,
    )
    return jax.tree.map(lambda new, old: jnp.where(do, new, old), out, state)


def _apply_op(state: MTState, op, sequential: bool = False,
              has_ob: bool = True, has_props: bool = True,
              has_ov: bool = True) -> MTState:
    """One sequenced op — the scan step.

    ``sequential`` / ``has_ob`` / ``has_props`` / ``has_ov`` are
    COMPILE-TIME chunk facts (the same
    pack-time predicates that drive the export row elisions): a fully
    sequential chunk (every ref_seq == seq-1) can never arrival-kill an
    insert (no stamp exceeds any op's ref — base stamps included, since
    they are <= base_seq <= every tail ref), and an obliterate-free chunk
    never stamps — so the arrival-kill scan and the stamping block trace
    away instead of running masked-dead every step.  A chunk with NO
    property keys
    anywhere (no annotate ops, no base props — pack's interner is empty)
    keeps its constant PROP_ABSENT plane untouched: the per-op [S, K]
    plane shift and the annotate write trace away.  ``has_ov=False``
    (the ov_rows export predicate: fully sequential views + no base
    "ro", so a second remover cannot occur — a sequential remove can
    never even target an already-removed segment, it is invisible in the
    remover's view) keeps the two rem2 planes constant: their shifts and
    the second/third-remover writes trace away."""
    S = state.tlen.shape[0]
    ref_seq, client = op.ref_seq, op.client
    is_ins = op.kind == K_INSERT
    is_rem = op.kind == K_REMOVE
    is_ann = op.kind == K_ANNOTATE
    is_obl = op.kind == K_OBLITERATE
    is_rangey = is_rem | is_ann | is_obl

    # Boundary splits (shared by all op kinds).
    state = _split_at(state, op.a, ref_seq, client, is_ins | is_rangey,
                      has_ob, has_ov, has_props)
    state = _split_at(state, op.b, ref_seq, client, is_rangey,
                      has_ob, has_ov, has_props)

    v = _visible_len(state, ref_seq, client, has_ob)
    cum = _excl_cumsum(v)
    slot = jnp.arange(S)
    active = slot < state.n
    # Zamboni parity: slots the oracle has physically collected by this
    # fold position (expired tombstones at the op's stamped MSN) must act
    # as ABSENT — never stamped, never a neighbor in the arrival scan.
    msn = op.min_seq
    ob1_live = (state.ob1_seq != NOT_REMOVED) & (state.ob1_seq > msn)
    ob2_live = (state.ob2_seq != NOT_REMOVED) & (state.ob2_seq > msn)
    expired = (
        (state.rem_seq != NOT_REMOVED) & (state.rem_seq <= msn)
        & (state.ins_seq <= msn) & ~ob1_live & ~ob2_live
    )

    # --- insert: tie-break index = first slot with cum >= pos (catch-up has
    # no pending segments; stop before the first sequenced segment).
    can = (cum >= op.a) & active
    j = jnp.where(can.any(), jnp.argmax(can), state.n)
    src = jnp.where(slot <= j, slot, slot - 1)

    if sequential or not has_ob:
        # No stamp can exceed a sequential op's ref (and without
        # obliterates there are no stamps at all): arrival kills are
        # structurally impossible — the whole neighbor scan traces away.
        kill_seq = jnp.int32(NOT_REMOVED)
        kill_client = jnp.int32(-1)
        killed = jnp.bool_(False)
    else:
        # Obliterate-on-arrival (see dds/merge_tree.py docstring): the
        # insert dies iff its pool neighbors share a stamp the inserter
        # had not seen from another client; the EARLIEST shared stamp is
        # the remover.  Neighbors = nearest NON-EXPIRED slots around the
        # tie-break index.
        present = active & ~expired
        left_idx = jnp.max(jnp.where(present & (slot < j), slot, -1))
        right_idx = jnp.min(jnp.where(present & (slot >= j), slot, S))

        def stamp_at(f, idx, valid):
            return jnp.where(valid, f[jnp.clip(idx, 0, S - 1)],
                             jnp.int32(NOT_REMOVED))

        has_left = left_idx >= 0
        has_right = right_idx < S
        l1s = stamp_at(state.ob1_seq, left_idx, has_left)
        l2s = stamp_at(state.ob2_seq, left_idx, has_left)
        l1c = stamp_at(state.ob1_client, left_idx, has_left)
        l2c = stamp_at(state.ob2_client, left_idx, has_left)
        r1s = stamp_at(state.ob1_seq, right_idx, has_right)
        r2s = stamp_at(state.ob2_seq, right_idx, has_right)

        def killer_of(ls, lc):
            shared = (ls != NOT_REMOVED) & ((ls == r1s) | (ls == r2s))
            ok = shared & (ls > ref_seq) & (lc != client)
            return jnp.where(ok, ls, jnp.int32(NOT_REMOVED)), lc

        k1s, k1c = killer_of(l1s, l1c)
        k2s, k2c = killer_of(l2s, l2c)
        kill_seq = jnp.minimum(k1s, k2s)
        kill_client = jnp.where(k1s <= k2s, k1c, k2c)
        killed = kill_seq != NOT_REMOVED

    def shifted(f, newval):
        moved = jnp.take(f, src, axis=0)
        if f.ndim == 1:
            return jnp.where(slot == j, newval, moved)
        return jnp.where((slot == j)[:, None], newval, moved)

    ins_state = MTState(
        tstart=shifted(state.tstart, op.tstart),
        tlen=shifted(state.tlen, op.tlen),
        ins_seq=shifted(state.ins_seq, op.seq),
        ins_client=shifted(state.ins_client, client),
        rem_seq=shifted(state.rem_seq,
                        jnp.where(killed, kill_seq, NOT_REMOVED)),
        rem_client=shifted(state.rem_client,
                           jnp.where(killed, kill_client, -1)),
        # Constant planes are shift-invariant (new slots get the same
        # constant): skip their gathers under the facts.
        rem2_seq=shifted(state.rem2_seq, NOT_REMOVED) if has_ov
        else state.rem2_seq,
        rem2_client=shifted(state.rem2_client, -1) if has_ov
        else state.rem2_client,
        ob1_seq=shifted(state.ob1_seq,
                        jnp.where(killed, kill_seq, NOT_REMOVED))
        if has_ob else state.ob1_seq,
        ob1_client=shifted(state.ob1_client,
                           jnp.where(killed, kill_client, -1))
        if has_ob else state.ob1_client,
        ob2_seq=shifted(state.ob2_seq, NOT_REMOVED) if has_ob
        else state.ob2_seq,
        ob2_client=shifted(state.ob2_client, -1) if has_ob
        else state.ob2_client,
        # A constant PROP_ABSENT plane is shift-invariant: skip the
        # gather+where entirely on props-free chunks.
        props=shifted(
            state.props,
            jnp.where(op.pvals == PROP_NOT_TOUCHED, PROP_ABSENT, op.pvals),
        ) if has_props else state.props,
        n=state.n + 1,
        overflow=state.overflow,
    )
    state = jax.tree.map(
        lambda new, old: jnp.where(is_ins, new, old), ins_state, state
    )

    # --- remove / annotate / obliterate target: segments fully inside
    # [a, b) in the view (splits above made partial overlaps exact).
    # Computed on the pre-insert cum/v, which is correct because the masks
    # are exclusive by kind.
    covered = (cum >= op.a) & (cum + v <= op.b) & (v > 0) & active

    is_rem_like = is_rem | is_obl
    first_win = covered & (state.rem_seq == NOT_REMOVED) & is_rem_like
    again = covered & (state.rem_seq != NOT_REMOVED) & is_rem_like
    second = again & (state.rem2_seq == NOT_REMOVED)
    third = again & (state.rem2_seq != NOT_REMOVED)
    if has_ob:
        # Obliterate additionally stamps zero-width slots strictly inside
        # the range: tombstones (stamp only) and invisible concurrent
        # inserts (remove + stamp) — the oracle's zero-width pass.  Two
        # stamp slots; a third distinct obliterate on one slot overflows
        # to the oracle.
        obl_zero = active & ~expired & (v == 0) \
            & (cum > op.a) & (cum < op.b) & is_obl
        obl_zero_alive = obl_zero & (state.rem_seq == NOT_REMOVED)
        first_win = first_win | obl_zero_alive
        stamp = (covered & is_obl) | obl_zero
        to_ob1 = stamp & (state.ob1_seq == NOT_REMOVED)
        to_ob2 = stamp & ~to_ob1 & (state.ob2_seq == NOT_REMOVED) \
            & (state.ob1_seq != op.seq)
        ob_over = stamp & (state.ob1_seq != NOT_REMOVED) \
            & (state.ob2_seq != NOT_REMOVED) \
            & (state.ob1_seq != op.seq) & (state.ob2_seq != op.seq)
        state = state._replace(
            ob1_seq=jnp.where(to_ob1, op.seq, state.ob1_seq),
            ob1_client=jnp.where(to_ob1, client, state.ob1_client),
            ob2_seq=jnp.where(to_ob2, op.seq, state.ob2_seq),
            ob2_client=jnp.where(to_ob2, client, state.ob2_client),
            overflow=state.overflow | ob_over.any(),
        )
    state = state._replace(
        rem_seq=jnp.where(first_win, op.seq, state.rem_seq),
        rem_client=jnp.where(first_win, client, state.rem_client),
    )
    if has_ov:
        # Sequential view + no base "ro" (has_ov=False): a remove or
        # obliterate can never target an already-removed segment
        # (invisible to its author), so `second`/`third` are structurally
        # false — rem2 stays constant and these writes trace away.
        state = state._replace(
            rem2_seq=jnp.where(second, op.seq, state.rem2_seq),
            rem2_client=jnp.where(second, client, state.rem2_client),
            overflow=state.overflow | third.any(),
        )

    if has_props:
        touch = (op.pvals != PROP_NOT_TOUCHED)[None, :] \
            & (covered & is_ann)[:, None]
        state = state._replace(
            props=jnp.where(
                touch, jnp.broadcast_to(op.pvals, state.props.shape),
                state.props)
        )
    return state


def replay_scan(state: MTState, ops: MTOps, sequential: bool = False,
                has_ob: bool = True, has_props: bool = True,
                has_ov: bool = True) -> MTState:
    """Pure single-document op-fold (no jit): scan the op stream.
    ``sequential``/``has_ob``/``has_props``/``has_ov`` are compile-time
    chunk facts (see ``_apply_op``); the defaults are the full
    semantics."""

    def step(carry, op):
        return _apply_op(carry, op, sequential, has_ob, has_props,
                         has_ov), None

    final, _ = jax.lax.scan(step, state, ops)
    return final


def replay_vmapped(state: MTState, ops: MTOps, sequential: bool = False,
                   has_ob: bool = True, has_props: bool = True,
                   has_ov: bool = True) -> MTState:
    """Vmapped over the document axis — the unit the parallel/ package
    shards."""
    return jax.vmap(
        lambda s, o: replay_scan(s, o, sequential, has_ob, has_props,
                                 has_ov)
    )(state, ops)



def _cold_start(ops: "MTOps", S: int) -> "MTState":
    """Empty initial state built IN-GRAPH: documents with no base summary
    start from all zeros/sentinels — constructing it on device instead of
    transferring (D, S) arrays of zeros cuts the per-chunk upload to the op
    arrays alone (the link, not the fold, is the bottleneck on a tunneled
    chip)."""
    D = ops.kind.shape[0]
    K = ops.pvals.shape[2]
    return MTState(
        tstart=jnp.zeros((D, S), jnp.int32),
        tlen=jnp.zeros((D, S), jnp.int32),
        ins_seq=jnp.zeros((D, S), jnp.int32),
        ins_client=jnp.full((D, S), -1, jnp.int32),
        rem_seq=jnp.full((D, S), NOT_REMOVED, jnp.int32),
        rem_client=jnp.full((D, S), -1, jnp.int32),
        rem2_seq=jnp.full((D, S), NOT_REMOVED, jnp.int32),
        rem2_client=jnp.full((D, S), -1, jnp.int32),
        ob1_seq=jnp.full((D, S), NOT_REMOVED, jnp.int32),
        ob1_client=jnp.full((D, S), -1, jnp.int32),
        ob2_seq=jnp.full((D, S), NOT_REMOVED, jnp.int32),
        ob2_client=jnp.full((D, S), -1, jnp.int32),
        props=jnp.full((D, S, K), PROP_ABSENT, jnp.int32),
        n=jnp.zeros((D,), jnp.int32),
        overflow=jnp.zeros((D,), jnp.bool_),
    )


@functools.partial(jax.jit, static_argnums=(1,))
def _replay_batch_cold(ops: "MTOps", S: int) -> "MTState":
    return replay_vmapped(_cold_start(ops, S), ops)


# Export row layout: per-slot fields stacked into ONE array so the
# device→host link costs a single transfer per fold (the tunneled-chip link
# pays seconds of fixed latency per RPC — ten small arrays were 10× the
# cost of one fused array).  Rows 0..7 are the slot fields, rows 8..8+K-1
# the property columns, and the final row is misc: [n, overflow, live_len].
#
# Two element widths exist.  The int32 layout is the always-correct default;
# when every value a chunk can produce fits in int16 (pack-time check:
# head seq, per-doc text chars, S, intern-table sizes all < 2**15-1 —
# ``meta['i16_ok']``) the export is emitted as int16 with two transforms the
# host inverts after download (``widen_export``): text offsets are rebased
# per document (``tstart - doc_base[d]``; a doc's arena spans are contiguous
# because packing appends per doc) and NOT_REMOVED maps to I16_NOT_REMOVED.
# Halving the element width halves the dominant cost of the whole pipeline —
# the device→host fetch over the tunneled link (VERDICT r2: the link, not
# the fold, is the bottleneck).
EXPORT_SLOT_FIELDS = (
    "tstart", "tlen", "ins_seq", "ins_client",
    "rem_seq", "rem_client", "rem2_seq", "rem2_client",
    "ob1_seq", "ob1_client", "ob2_seq", "ob2_client",
)
#: the slot fields with no obliterate content — the export layout when a
#: chunk provably carries no obliterates (``meta["ob_rows"]`` False)
NON_OB_SLOT_FIELDS = EXPORT_SLOT_FIELDS[:8]
#: the obliterate rows elided from such exports, with their sentinel fills
OB_SLOT_FIELDS = EXPORT_SLOT_FIELDS[8:]
#: the overlap-remover rows, elided (``meta["ov_rows"]`` False) when the
#: chunk provably cannot produce a second remover: every op rides a fully
#: sequential view (ref_seq == seq-1 — an already-removed slot is never
#: visible, so ``second`` can't fire) and no base record carries "ro"
OV_SLOT_FIELDS = ("rem2_seq", "rem2_client")
#: rows holding seqs with the NOT_REMOVED sentinel (narrow remap set)
SENTINEL_SEQ_FIELDS = ("rem_seq", "rem2_seq", "ob1_seq", "ob2_seq")
I16_NOT_REMOVED = np.int16(np.iinfo(np.int16).max)
I16_LIMIT = int(np.iinfo(np.int16).max) - 1  # strict value bound for i16_ok
#: int8 pair-packing (``meta["i8_ok"]``): when every exported value other
#: than tstart/misc fits in a signed byte, pairs of slot/prop rows pack
#: into one int16 lane each — byte rows halve on the wire.
I8_NOT_REMOVED = np.int32(127)
I8_LIMIT = 126


def _export_fields(ob_rows: bool, ov_rows: bool):
    fields = list(EXPORT_SLOT_FIELDS if ob_rows else NON_OB_SLOT_FIELDS)
    if not ov_rows:
        fields = [f for f in fields if f not in OV_SLOT_FIELDS]
    return fields


def _export_state(final: MTState, doc_base: Optional[jnp.ndarray] = None,
                  i16: bool = False, ob_rows: bool = True,
                  ov_rows: bool = True, i8: bool = False,
                  props_rows: bool = True) -> jnp.ndarray:
    """[D, rows, S] fused view of everything summary extraction and
    interval replay need from the final device state (int32, or int16 when
    ``i16`` with per-doc-rebased tstart and remapped NOT_REMOVED
    sentinels).

    Transfer-shrinking layouts, each undone host-side by ``widen_export``
    (the device→host fetch is the pipeline's measured bottleneck):
    - ``ob_rows=False``: the four obliterate rows elided (no obliterate
      ops or base stamps in the chunk — pack-time fact);
    - ``ov_rows=False``: the two overlap-remover rows elided (fully
      sequential views + no base "ro" — a second remover cannot occur);
    - ``props_rows=False``: the K props-plane rows elided (props-free
      chunk — the plane is constant PROP_ABSENT);
    - ``i8``: every byte-sized row pairs into one int16 lane
      (``(a & 0xFF) << 8 | (b & 0xFF)``) — tstart and misc stay 16-bit."""
    i8 = i8 and i16  # byte packing presupposes the int16 transforms
    D, S = final.tlen.shape
    K = final.props.shape[2]
    slot = jnp.arange(S)[None, :]
    active = slot < final.n[:, None]
    live = jnp.where(
        active & (final.rem_seq == NOT_REMOVED), final.tlen, 0,
    ).sum(axis=1)
    misc = jnp.zeros((D, S), jnp.int32)
    misc = misc.at[:, 0].set(final.n)
    misc = misc.at[:, 1].set(final.overflow.astype(jnp.int32))
    misc = misc.at[:, 2].set(live)
    # Slots beyond n hold shift leftovers no consumer reads; zero their
    # tstart in BOTH widths so the two exports are bit-equivalent after
    # ``widen_export`` (and export bytes are deterministic).
    tstart = jnp.where(active, final.tstart, 0)
    named = {"tstart": tstart}
    fields = _export_fields(ob_rows, ov_rows)
    if i16:
        named["tstart"] = jnp.where(active, tstart - doc_base[:, None], 0)
        sentinel = I8_NOT_REMOVED if i8 else jnp.int32(I16_NOT_REMOVED)
        for f in SENTINEL_SEQ_FIELDS:
            if f not in fields:
                continue
            val = getattr(final, f)
            named[f] = jnp.where(val == NOT_REMOVED, sentinel, val)
    rows = [named.get(f, getattr(final, f)) for f in fields]
    if props_rows:
        rows += [final.props[:, :, k] for k in range(K)]
    if i8:
        byte_rows = rows[1:]
        if len(byte_rows) % 2:
            byte_rows.append(jnp.zeros((D, S), jnp.int32))
        packed = [
            ((byte_rows[i] & 0xFF) << 8) | (byte_rows[i + 1] & 0xFF)
            for i in range(0, len(byte_rows), 2)
        ]
        rows = [rows[0]] + packed
        # The misc values (n, overflow, live_len) ride a SEPARATE tiny
        # [D, 4] int32 output instead of a full S-column row — one less
        # row off the dominant fetch; widen_export stitches the canonical
        # misc row back host-side.
        out = jnp.stack(rows, axis=1).astype(jnp.int16)  # bound: i16_ok
        return out, misc[:, :4]
    rows.append(misc)
    out = jnp.stack(rows, axis=1)
    return out.astype(jnp.int16) if i16 else out  # bound: i16_ok


def export_to_numpy(export):
    """Fetch an export handle to numpy — the i8 layout is a
    ``(slot_rows, misc)`` pair of device buffers; other layouts a single
    fused buffer."""
    if isinstance(export, tuple):
        return tuple(np.asarray(x) for x in export)
    return np.asarray(export)


# ---------------------------------------------------------------------------
# Per-doc state digests (digest-gated delta download — ISSUE 6)
# ---------------------------------------------------------------------------

#: fixed per-plane salt ids for the digest mix.  Stable across layouts:
#: the digest reads the CANONICAL final state, never the transfer buffer,
#: so bucket growth / row elisions / byte packing cannot perturb it.
_DIGEST_PLANES = tuple(EXPORT_SLOT_FIELDS)
_DIGEST_PROPS_BASE = 16  # props column k salts at 16 + k


def _mix_u32(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix-style avalanche over uint32 lanes (wraparound on purpose;
    runs in-graph on device)."""
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _doc_digests(final: MTState, doc_base: jnp.ndarray) -> jnp.ndarray:
    """``[D, 2]`` int32 digest of each document's canonical final state —
    the device-computed summary identity the delta-download path compares
    before deciding which documents' export rows must cross the d2h link.

    Properties the delta path relies on (pinned by tests):

    - **masked**: only live slots (``slot < n``) contribute — dead-slot
      shift leftovers (which legitimately differ between a fresh pack and
      a suffix-extended one) never reach the hash;
    - **rebased**: ``tstart`` enters relative to the doc's arena base, so
      a document whose own bytes are unchanged digests identically even
      when other documents in the chunk moved its absolute arena offsets;
    - **bucket-invariant**: weights are per (plane, slot-index), so S/T
      padding growth around an unchanged document cannot perturb it; a
      props key the document never set contributes ZERO (set values hash
      shifted by +1 — intern ids are >= 0, so "value 0" stays distinct
      from "absent"), so K-bucket growth (another doc's new annotate
      key) cannot perturb it either;
    - 64 bits across two independently-salted lanes — a collision (the
      only way delta download could serve wrong bytes for inputs the
      host-side anchor check cannot distinguish) is a ~2^-64 event, and
      every structural failure (missing entry, anchor drift, digest
      mismatch) falls back to the full download.
    """
    D, S = final.tlen.shape
    K = final.props.shape[2]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    active = slot < final.n[:, None]
    live_len = jnp.where(
        active & (final.rem_seq == NOT_REMOVED), final.tlen, 0
    ).sum(axis=1)
    slot_u = slot.astype(jnp.uint32)
    accs = []
    for lane_salt in (jnp.uint32(0x9E3779B9), jnp.uint32(0x85EBCA6B)):
        acc = jnp.zeros((D,), jnp.uint32)
        for i, f in enumerate(_DIGEST_PLANES):
            plane = getattr(final, f)
            if f == "tstart":
                plane = plane - doc_base[:, None]
            v = jnp.where(active, plane, 0).astype(jnp.uint32)
            w = _mix_u32(slot_u * jnp.uint32(0x01000193)
                         + jnp.uint32(i) + lane_salt)
            acc = acc + (v * w).sum(axis=1, dtype=jnp.uint32)
        for k in range(K):
            plane = final.props[:, :, k]
            # Absent keys hash 0 (K-bucket invariance); set values shift
            # +1 so an explicit intern id 0 stays distinct from absent.
            v = jnp.where(active & (plane != PROP_ABSENT), plane + 1,
                          0).astype(jnp.uint32)
            w = _mix_u32(slot_u * jnp.uint32(0x01000193)
                         + jnp.uint32(_DIGEST_PROPS_BASE + k) + lane_salt)
            acc = acc + (v * w).sum(axis=1, dtype=jnp.uint32)
        acc = acc ^ _mix_u32(final.n.astype(jnp.uint32) + lane_salt)
        acc = acc ^ _mix_u32(live_len.astype(jnp.uint32) * jnp.uint32(3)
                             + lane_salt)
        acc = acc ^ jnp.where(final.overflow, jnp.uint32(0x5BD1E995),
                              jnp.uint32(0))
        accs.append(_mix_u32(acc))
    return jax.lax.bitcast_convert_type(
        jnp.stack(accs, axis=-1), jnp.int32)


def split_export_digest(export, digested: bool):
    """``(core, digest_or_None)`` for a ``replay_export`` handle.  With
    ``digest=True`` the digest rides as the LAST leaf of the returned
    tuple; the core keeps the exact shape the non-digest path produces
    (bare buffer, or ``(rows, misc)`` for i8 layouts) so every
    downstream consumer is unchanged."""
    if not digested:
        return export, None
    assert isinstance(export, tuple) and len(export) >= 2
    core = export[0] if len(export) == 2 else export[:-1]
    return core, export[-1]


@jax.jit
def _take_docs(a: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jnp.take(a, idx, axis=0)


def _host_view(a) -> Optional[np.ndarray]:
    """Zero-copy numpy view of a computed single-CPU-device array, or
    None when the buffer is not host-reachable.  On the CPU backend the
    "d2h link" IS host memory: a dlpack view + numpy row copy fetches
    exactly the requested rows with no XLA dispatch (a per-shape device
    gather would pay a ~0.5 s compile that swamps the bytes it saves)."""
    try:
        devs = a.devices()
        if len(devs) != 1 or next(iter(devs)).platform != "cpu":
            return None
        return np.from_dlpack(a)
    except Exception:
        return None


def gather_export_rows(export, idx: np.ndarray):
    """Fetch ONLY the documents in ``idx`` from a device export handle —
    the delta-download fetch.  Returns ``(rows, moved_bytes)`` where each
    leaf of ``rows`` has exactly ``len(idx)`` doc rows and ``moved_bytes``
    counts what actually crossed the d2h link.  On CPU-resident buffers
    this is a direct row copy out of a zero-copy host view; on
    accelerators it is a tiny in-graph gather along the doc axis (``idx``
    padded to a fine bucket internally so the gather's jit cache stays
    bounded — the pad rows DO cross, and are counted) followed by the
    d2h copy of just those rows."""
    leaves = export if isinstance(export, tuple) else (export,)
    rows = np.asarray(idx, np.intp)
    m = rows.shape[0]
    out, moved = [], 0
    dev_idx = None
    for a in leaves:
        view = _host_view(a)
        if view is not None:
            got = view[rows]
            moved += got.nbytes
        elif next_bucket_fine(m, floor=8) >= a.shape[0]:
            # The padded device gather would move as many rows as the
            # buffer holds: fetch full and slice host-side (no gather
            # dispatch).  Accelerator economics only — the host-view
            # branch above always copies exact rows.
            full = np.asarray(a)
            moved += full.nbytes
            got = full[rows]
        else:
            if dev_idx is None:
                pad = next_bucket_fine(m, floor=8) - m
                padded = np.concatenate(
                    [rows, np.repeat(rows[-1:], pad)]) if pad else rows
                dev_idx = jnp.asarray(padded, jnp.int32)
            dev = _take_docs(a, dev_idx)  # bucketed-by: next_bucket_fine
            full = np.asarray(dev)
            moved += full.nbytes
            got = full[:m]
        out.append(got)
    return (tuple(out) if isinstance(export, tuple) else out[0]), moved


def _widen_desc(ob_rows: bool, ov_rows: bool, i8: bool, props_rows: bool,
                n_props: int):
    """The per-canonical-row descriptor table oppack_widen consumes:
    [mode, arg, fill, flags] × (13 + K) rows.  Mirrors widen_export's
    field order exactly (same _export_fields derivation)."""
    fields = _export_fields(ob_rows, ov_rows)

    def src_of(f: str):
        if not i8:
            return 1, fields.index(f)                       # ROW16
        if f == "tstart":
            return 1, 0                                     # 16-bit lane
        i = fields.index(f) - 1                             # byte index
        return 2, (1 + i // 2) * 2 + (i % 2)                # PAIR8

    desc = []
    for f in EXPORT_SLOT_FIELDS:
        if f in fields:
            mode, arg = src_of(f)
            flags = (1 if f in SENTINEL_SEQ_FIELDS else 0) \
                | (2 if f == "tstart" else 0)
            desc.append((mode, arg, 0, flags))
        else:
            fill = int(NOT_REMOVED) if f.endswith("_seq") else -1
            desc.append((0, 0, fill, 0))
    for k in range(n_props):
        if props_rows:
            if i8:
                i = len(fields) - 1 + k
                desc.append((2, (1 + i // 2) * 2 + (i % 2), 0, 0))
            else:
                desc.append((1, len(fields) + k, 0, 0))
        else:
            desc.append((0, 0, int(PROP_ABSENT), 0))
    if i8:
        desc.append((3, 0, 0, 0))                           # stitched misc
    else:
        n_src = len(fields) + (n_props if props_rows else 0) + 1
        desc.append((1, n_src - 1, 0, 0))                   # misc row
    return np.asarray(desc, np.int32).reshape(-1)


def widen_export_native(export_np, doc_base, ob_rows: bool, ov_rows: bool,
                        i8: bool, n_props: int, props_rows: bool):
    """C++ single-pass widen of a narrow export buffer to the canonical
    [D, 13+K, S] int32 layout — byte-identical to ``widen_export``
    (pinned by tests), ~10× faster on the extraction hot path.  Returns
    None when inapplicable (already int32, or no native library)."""
    from .native_pack import load_library

    misc_np = None
    if isinstance(export_np, tuple):
        export_np, misc_np = export_np
    if export_np.dtype != np.int16:
        return None
    lib = load_library()
    if lib is None:
        return None
    D, R_src, S = export_np.shape
    desc = _widen_desc(ob_rows, ov_rows, i8, props_rows, n_props)
    R_canon = len(desc) // 4
    dst = np.empty((D, R_canon, S), np.int32)
    src = np.ascontiguousarray(export_np, np.int16)
    if i8:
        assert misc_np is not None, "i8 widen needs the misc output"
        misc = np.ascontiguousarray(misc_np, np.int16)
        misc_ptr, misc_cols = misc.ctypes.data, misc.shape[1]
    else:
        misc = None
        misc_ptr, misc_cols = None, 0
    base = None if doc_base is None else \
        np.ascontiguousarray(doc_base, np.int32)
    sentinel_src = int(I8_NOT_REMOVED) if i8 else int(I16_NOT_REMOVED)
    rc = lib.oppack_widen(
        src, D, S, R_src, R_canon, misc_ptr, misc_cols, desc,
        None if base is None else base.ctypes.data,
        sentinel_src, int(NOT_REMOVED), dst,
    )
    if rc != 0:
        raise ValueError("oppack_widen: malformed narrow export")
    return dst


def widen_export(export_np,
                 doc_base: Optional[np.ndarray],
                 ob_rows: bool = True, ov_rows: bool = True,
                 i8: bool = False,
                 n_props: Optional[int] = None,
                 props_rows: bool = True) -> np.ndarray:
    """Undo the export transfer transforms host-side, always returning the
    CANONICAL full int32 layout: unpack int8 pairs and stitch the separate
    misc output back into a row (``i8`` — needs ``n_props``, the padded
    props-plane width), widen int16 to int32, restore NOT_REMOVED
    sentinels, re-add per-doc arena bases, and reinsert elided
    obliterate/overlap/props rows with their sentinel fills.  Full-layout
    int32 buffers pass through untouched."""
    misc_np = None
    if isinstance(export_np, tuple):
        export_np, misc_np = export_np
    fields = _export_fields(ob_rows, ov_rows)
    if export_np.dtype == np.int32:
        out = export_np
    else:
        if i8:
            # Unpack byte pairs back into the (elided) int16-equivalent
            # row layout: [tstart, byte rows...] + the stitched misc row.
            assert n_props is not None, "i8 widen needs the props width"
            assert misc_np is not None, "i8 widen needs the misc output"
            u = export_np.astype(np.uint16)
            n_bytes = len(fields) - 1 + (n_props if props_rows else 0)
            rows = [export_np[:, 0, :].astype(np.int32)]
            for i in range(n_bytes):
                pair = u[:, 1 + i // 2, :]
                half = (pair >> 8) if i % 2 == 0 else (pair & 0xFF)
                rows.append(half.astype(np.uint8).astype(np.int8)
                            .astype(np.int32))
            D, _R, S = export_np.shape
            misc_row = np.zeros((D, S), np.int32)
            misc_row[:, :misc_np.shape[1]] = misc_np
            rows.append(misc_row)
            out = np.stack(rows, axis=1)
        else:
            out = export_np.astype(np.int32)
        sentinel = int(I8_NOT_REMOVED) if i8 else int(I16_NOT_REMOVED)
        for f in SENTINEL_SEQ_FIELDS:
            if f not in fields:
                continue
            row = out[:, fields.index(f), :]
            row[row == sentinel] = NOT_REMOVED
        if doc_base is not None:
            # Re-add the per-doc arena base to live slots only (slots
            # beyond n were zeroed on device and must stay zero to match
            # the int32 path).
            n = out[:, -1, 0]
            active = np.arange(out.shape[2])[None, :] < n[:, None]
            out[:, 0, :] += np.where(
                active, np.asarray(doc_base, np.int32)[:, None], 0
            )
    def reinsert(buf, fill_fields, split):
        D, _R, S = buf.shape
        filler = np.empty((D, len(fill_fields), S), np.int32)
        for i, f in enumerate(fill_fields):
            filler[:, i, :] = NOT_REMOVED if f.endswith("_seq") else -1
        return np.concatenate(
            [buf[:, :split], filler, buf[:, split:]], axis=1
        )

    if not props_rows:
        # Reinsert the constant PROP_ABSENT plane rows before the misc row.
        assert n_props is not None, "props-row reinsert needs the width"
        D, _R, S = out.shape
        filler = np.full((D, n_props, S), PROP_ABSENT, np.int32)
        out = np.concatenate([out[:, :-1], filler, out[:, -1:]], axis=1)
    if not ov_rows:
        out = reinsert(out, OV_SLOT_FIELDS,
                       fields.index("rem_client") + 1)  # rem2 slots next
    if not ob_rows:
        out = reinsert(out, OB_SLOT_FIELDS, len(NON_OB_SLOT_FIELDS))
    return out


def _fetch_format(sharding=None):
    """A Format forcing the default row-major layout on export outputs.

    The jit-chosen device layout makes the tunneled-link fetch degenerate
    ~20× (VERDICT r2: 10.65s vs 0.58s for identical bytes); copying into the
    default layout before the D2H makes the fetch ride the link at line
    rate.  Returns None when the backend has no layout support (CPU tests).
    ``sharding`` overrides the default single-device placement — the mesh
    export step passes its doc-sharded NamedSharding so the multi-chip
    fetch gets the same layout force."""
    import os

    if os.environ.get("FF_NO_FORCED_LAYOUT"):
        return None  # kill switch (bench canary flips this on a bad tunnel)
    try:
        from jax.experimental.layout import Format, Layout
        from jax.sharding import SingleDeviceSharding

        dev = jax.devices()[0]
        if dev.platform == "cpu":
            return None
        if sharding is None:
            sharding = SingleDeviceSharding(dev)
        return Format(Layout(major_to_minor=(0, 1, 2)), sharding)
    except Exception:
        return None


def _out_shardings_for(i8: bool, sharding=None, digest: bool = False):
    """out_shardings matching the export's output structure: the fused 3-D
    buffer gets the forced row-major Format; the tiny [D, 4] misc output
    (i8 layouts only) and the [D, 2] digest plane get 2-D ones.
    ``sharding`` threads through to ``_fetch_format`` for the mesh
    path."""
    fmt = _fetch_format(sharding)
    if fmt is None:
        return None
    if not i8 and not digest:
        return fmt
    from jax.experimental.layout import Format, Layout

    fmt2 = Format(Layout(major_to_minor=(0, 1)), fmt.sharding)
    out = [fmt] + ([fmt2] if i8 else []) + ([fmt2] if digest else [])
    return tuple(out)


def _fold_fn(mode: str, sequential: bool = False, has_ob: bool = True,
             has_props: bool = True, has_ov: bool = True):
    """The batch fold: the lax.scan path by default (specialized at
    compile time by the chunk facts — see ``_apply_op``); the Pallas
    VMEM-resident kernel (ops/pallas_fold.py) when FF_PALLAS_FOLD selects
    it — per-doc state stays on-chip across the whole tail instead of
    round-tripping HBM every op step (SURVEY §7 hard-part #4).  The pallas
    import stays inside the branches: the default scan path must not
    depend on jax.experimental.pallas importability."""
    if mode in ("tpu", "interpret"):
        from .pallas_fold import replay_vmapped_pallas

        interpret = mode == "interpret"
        return lambda state, ops: replay_vmapped_pallas(
            state, ops, interpret=interpret)
    return lambda state, ops: replay_vmapped(state, ops, sequential,
                                             has_ob, has_props, has_ov)


def _export_out(i8: bool, sharding=None, digest: bool = False):
    """out_shardings for an export jit: the forced fetch layout when the
    backend supports layouts (carried on ``sharding`` when given — the
    mesh path), else the bare sharding, else None."""
    fmt = _out_shardings_for(i8, sharding, digest)
    if fmt is not None:
        return fmt
    if sharding is None:
        return None
    n_out = 1 + (1 if i8 else 0) + (1 if digest else 0)
    return sharding if n_out == 1 else (sharding,) * n_out


def _export_with_digest(final, doc_base, i16, ob_rows, ov_rows, i8,
                        has_props, digest: bool):
    """Export a final state, optionally appending the [D, 2] digest plane
    as the LAST output leaf (see ``split_export_digest``)."""
    ex = _export_state(final, doc_base, i16, ob_rows, ov_rows, i8,
                       props_rows=has_props)
    if not digest:
        return ex
    dig = _doc_digests(final, doc_base)
    return ex + (dig,) if isinstance(ex, tuple) else (ex, dig)


@functools.lru_cache(maxsize=None)
def _export_cold_fn(S: int, i16: bool, ob_rows: bool = True,
                    fold_mode: str = "", ov_rows: bool = True,
                    i8: bool = False, sequential: bool = False,
                    has_props: bool = True, out_sharding=None,
                    digest: bool = False):
    """Compiled cold-start fold+export for one (S, width, layout) bucket,
    its output laid out for a line-rate fetch.  ``ob_rows``/``ov_rows``
    double as the fold facts (has_ob/has_ov): the export elides exactly
    the planes the fold provably never writes.  ``out_sharding`` (a
    NamedSharding) builds the mesh-sharded variant of the same pipeline —
    ONE derivation point for single-chip and multi-chip exports.
    ``digest`` appends the per-doc state digest plane (delta download)."""
    fold = _fold_fn(fold_mode, sequential, ob_rows, has_props, ov_rows)

    def f(ops, doc_base):
        ops = _widen_ops(ops, doc_base)
        return _export_with_digest(
            fold(_cold_start(ops, S), ops), doc_base, i16, ob_rows,
            ov_rows, i8, has_props, digest,
        )

    fmt = _export_out(i8, out_sharding, digest)
    return jax.jit(f, out_shardings=fmt) if fmt is not None else jax.jit(f)


@functools.lru_cache(maxsize=None)
def _export_warm_fn(i16: bool, ob_rows: bool = True, fold_mode: str = "",
                    ov_rows: bool = True, i8: bool = False,
                    sequential: bool = False, has_props: bool = True,
                    out_sharding=None, digest: bool = False):
    """Compiled warm-start (base state uploaded) fold+export; see
    ``_export_cold_fn`` for ``out_sharding``/``digest``."""
    fold = _fold_fn(fold_mode, sequential, ob_rows, has_props, ov_rows)

    def f(state, ops, doc_base):
        state = _widen_state(state, doc_base)
        ops = _widen_ops(ops, doc_base)
        return _export_with_digest(fold(state, ops), doc_base, i16,
                                   ob_rows, ov_rows, i8, has_props, digest)

    fmt = _export_out(i8, out_sharding, digest)
    return jax.jit(f, out_shardings=fmt) if fmt is not None else jax.jit(f)


def export_layout_rows(meta: dict) -> int:
    """Row count of the transfer buffer replay_export emits for this
    packed chunk's layout facts (elisions + byte packing)."""
    _i16, ob_rows, ov_rows, i8, props_rows = _export_flags(meta)
    fields = _export_fields(ob_rows, ov_rows)
    K = meta.get("props_K", 1) if props_rows else 0
    if i8:
        n_bytes = len(fields) - 1 + K
        return 1 + (n_bytes + 1) // 2  # misc rides the separate output
    return len(fields) + K + 1


def _export_flags(meta: dict):
    """The transfer-layout facts BOTH sides of the export handshake use
    (dispatch builds the buffer, extraction widens it) — one derivation
    point so they can never disagree.  The pallas fold ignores the chunk
    facts, so its mode forces the props rows back on at both ends."""
    from .pallas_fold import pallas_fold_mode

    i16 = bool(meta.get("i16_ok"))
    return (
        i16,
        bool(meta.get("ob_rows", True)),
        bool(meta.get("ov_rows", True)),
        i16 and bool(meta.get("i8_ok")),
        bool(meta.get("has_props", True)) or pallas_fold_mode() != "",
    )


#: upload-side narrow dtypes (h2d transfer encoding — see
#: ``narrow_ops_for_upload``); per-field, chosen once so the jit cache
#: sees exactly two op-stream signatures (all-int32 or this).
_UPLOAD_NARROW_DTYPES = {
    "kind": np.int8, "client": np.int8,
    "seq": np.int16, "ref_seq": np.int16, "min_seq": np.int16,
    "a": np.int16, "b": np.int16, "tstart": np.int16, "tlen": np.int16,
    "pvals": np.int16,
}


def narrow_ops_for_upload(ops: MTOps, meta: dict) -> MTOps:
    """Narrow a packed op stream for the h2d link: int32 → int16 rows
    (int8 for kind/client), with insert ``tstart`` rebased per document
    (``tstart - doc_base[d]`` — a doc's arena spans are contiguous, the
    same transform the int16 EXPORT layout applies on the way down).
    The device widens in-graph (``_widen_ops``), so this is purely a
    transfer encoding: ~55% off the op-stream upload, the h2d leg of the
    link-bound pipeline (BASELINE.md round-5: with the fold at ~2 ms,
    e2e is host+link).

    Applies only when the chunk's ``i16_ok`` value-bound fact holds AND
    a direct bounds re-check of every field passes (belt and braces —
    any violation falls back to the wide upload, never corrupts);
    device-resident or already-narrow streams pass through unchanged.
    ``FF_UPLOAD_NARROW=0`` disables."""
    import os

    if (not meta.get("i16_ok")
            or not isinstance(ops.kind, np.ndarray)
            or ops.seq.dtype != np.int32
            or os.environ.get("FF_UPLOAD_NARROW", "1") == "0"):
        return ops
    doc_base = np.asarray(meta["doc_base"], np.int32)
    is_ins = ops.kind == K_INSERT
    # Non-insert rows must carry tstart == 0 (pack invariant; the fold
    # reads op tstart only under is_ins) for the rebase to round-trip.
    if int(np.abs(np.where(is_ins, 0, ops.tstart)).max(initial=0)) != 0:
        return ops
    rebased = np.where(is_ins, ops.tstart - doc_base[:, None], 0)
    narrow = {"tstart": rebased}
    for f in MTOps._fields:
        if f != "tstart":
            narrow[f] = getattr(ops, f)
    for f, dt in _UPLOAD_NARROW_DTYPES.items():
        info = np.iinfo(dt)
        v = narrow[f]
        if not (int(v.min(initial=0)) >= info.min
                and int(v.max(initial=0)) <= info.max):
            return ops  # bounds re-check failed → wide upload
    return MTOps(**{f: narrow[f].astype(_UPLOAD_NARROW_DTYPES[f])
                    for f in MTOps._fields})


def narrow_state_for_upload(state: MTState, meta: dict) -> MTState:
    """Narrow a warm chunk's base state for the h2d link — the catch-up
    service's snapshot+tail shape uploads 13 ``(D, S)`` int32 planes per
    chunk, the dominant upload for warm chunks.  int32 → int16 with the
    NOT_REMOVED sentinel remapped (the inverse the device applies is the
    same transform the i16 export layout already round-trips) and slot
    ``tstart`` rebased per doc for live slots (dead slots are zero by the
    pack invariant, re-checked here).  ``props`` (value ids ≥ -1) and
    ``n`` narrow unconditionally under the same bound; ``overflow`` stays
    bool.  Any bounds violation falls back to the wide upload."""
    import os

    if (not meta.get("i16_ok")
            or not isinstance(state.tstart, np.ndarray)
            or state.ins_seq.dtype != np.int32
            or os.environ.get("FF_UPLOAD_NARROW", "1") == "0"):
        return state
    doc_base = np.asarray(meta["doc_base"], np.int32)
    S = state.tstart.shape[1]
    live = np.arange(S, dtype=np.int32)[None, :] < state.n[:, None]
    if int(np.abs(np.where(live, 0, state.tstart)).max(initial=0)) != 0:
        return state  # dead slots must be zero for the rebase round trip
    info = np.iinfo(np.int16)
    narrow = {}
    for f in EXPORT_SLOT_FIELDS:  # the 12 slot planes, export's own list
        v = getattr(state, f)
        if f == "tstart":
            v = np.where(live, v - doc_base[:, None], 0)
        elif f in SENTINEL_SEQ_FIELDS:
            # Real values must stay STRICTLY below the remapped sentinel
            # (I16_LIMIT, the same bound i16_ok is defined against) — a
            # genuine 32767 would widen back as NOT_REMOVED and
            # resurrect a removed segment.
            reals = np.where(v == NOT_REMOVED, 0, v)
            if int(reals.max(initial=0)) > I16_LIMIT:
                return state
            v = np.where(v == NOT_REMOVED, np.int32(I16_NOT_REMOVED), v)
        if not (info.min <= int(v.min(initial=0))
                and int(v.max(initial=0)) <= info.max):
            return state
        narrow[f] = v.astype(np.int16)
    if not (int(state.props.min(initial=0)) >= info.min
            and int(state.props.max(initial=0)) <= info.max
            and int(state.n.max(initial=0)) <= info.max):
        return state
    return MTState(
        **narrow,
        props=state.props.astype(np.int16),
        n=state.n.astype(np.int16),
        overflow=state.overflow,
    )


def _widen_state(state: MTState, doc_base: jnp.ndarray) -> MTState:
    """In-graph inverse of ``narrow_state_for_upload`` (identity on wide
    states); refuses unknown encodings loudly like ``_widen_ops``."""
    if state.ins_seq.dtype == jnp.int32:
        return state
    if state.ins_seq.dtype != jnp.int16:
        raise TypeError(
            f"state has ins_seq dtype {state.ins_seq.dtype}; expected "
            f"int32 (wide) or the int16 narrow_state_for_upload encoding"
        )
    w = {f: getattr(state, f).astype(jnp.int32)
         for f in EXPORT_SLOT_FIELDS}
    n = state.n.astype(jnp.int32)
    S = state.tstart.shape[1]
    live = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1) < n[:, None]
    w["tstart"] = jnp.where(live, w["tstart"] + doc_base[:, None], 0)
    for f in SENTINEL_SEQ_FIELDS:
        w[f] = jnp.where(w[f] == int(I16_NOT_REMOVED), NOT_REMOVED, w[f])
    return MTState(**w, props=state.props.astype(jnp.int32), n=n,
                   overflow=state.overflow)


def _widen_ops(ops: MTOps, doc_base: jnp.ndarray) -> MTOps:
    """In-graph inverse of ``narrow_ops_for_upload`` (identity on wide
    streams): one fused cast per field plus the insert-tstart un-rebase.
    Runs first inside the jitted fold+export wrappers, so both upload
    widths share one jit entry (the cache keys on input avals).

    The un-rebase applies ONLY to the exact encoding the narrower emits
    (int16 seq rows) — any other non-int32 stream was never rebased, so
    silently 'widening' it would corrupt every insert's arena offset;
    refuse loudly instead."""
    if ops.seq.dtype == jnp.int32:
        return ops
    if ops.seq.dtype != jnp.int16:
        raise TypeError(
            f"op stream has seq dtype {ops.seq.dtype}; expected int32 "
            f"(wide) or the int16 narrow_ops_for_upload encoding"
        )
    w = {f: getattr(ops, f).astype(jnp.int32) for f in MTOps._fields}
    w["tstart"] = jnp.where(w["kind"] == K_INSERT,
                            w["tstart"] + doc_base[:, None], 0)
    return MTOps(**w)


def replay_export(state: Optional[MTState], ops: MTOps, meta: dict,
                  S: Optional[int] = None,
                  digest: bool = False,
                  doc_base: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Dispatch the fold+export for a packed chunk (async); the result is
    the fused export buffer handle, int16 when the chunk qualifies (with
    obliterate/overlap row elision and int8 pair-packing per the pack-time
    layout facts).  Pass ``state=None`` for all-cold chunks (initial state
    built in-graph — no zero upload).  ``digest=True`` additionally emits
    the per-doc state digest plane as the last output leaf (split it off
    with ``split_export_digest`` — the delta-download gate fetches ONLY
    that tiny plane eagerly).  ``doc_base`` (optional) supplies a
    DEVICE-RESIDENT per-doc arena base (the tier-2.5 resident tier keeps
    it on device so an exact warm hit uploads nothing); it must equal
    ``meta["doc_base"]`` — passing the real bases is inert on layouts
    that ignore them."""
    from .pallas_fold import pallas_fold_mode

    i16, ob_rows, ov_rows, i8, has_props = _export_flags(meta)
    mode = pallas_fold_mode()
    # The digest rebases tstart per doc even on non-i16 chunks, so an
    # unchanged document digests identically across repacks that moved
    # its absolute arena offsets (_export_state reads doc_base only
    # under i16 — passing the real bases is inert for the buffer).
    if doc_base is None:
        doc_base = jnp.asarray(meta["doc_base"]) if (i16 or digest) else \
            jnp.zeros((ops.kind.shape[0],), jnp.int32)
    ops = narrow_ops_for_upload(ops, meta)  # h2d transfer encoding
    # The pallas fold ignores the chunk facts — normalize so mixed
    # workloads don't compile duplicate executables per cache key
    # (has_props is already mode-normalized inside _export_flags, the
    # shared dispatch/extraction derivation point).
    sequential = bool(meta.get("sequential")) and mode == ""
    if state is None:
        return _export_cold_fn(int(S), i16, ob_rows, mode, ov_rows,
                               i8, sequential, has_props,
                               digest=digest)(ops, doc_base)
    state = narrow_state_for_upload(state, meta)
    return _export_warm_fn(i16, ob_rows, mode, ov_rows, i8,
                           sequential, has_props,
                           digest=digest)(state, ops, doc_base)


def state_dict_from_export(export_np: np.ndarray) -> dict:
    """Adapt a downloaded export buffer back to the state_np dict shape the
    extraction/interval code consumes (zero-copy row views)."""
    K = export_np.shape[1] - len(EXPORT_SLOT_FIELDS) - 1
    out = {
        f: export_np[:, i, :] for i, f in enumerate(EXPORT_SLOT_FIELDS)
    }
    out["props"] = np.moveaxis(
        export_np[:, len(EXPORT_SLOT_FIELDS):len(EXPORT_SLOT_FIELDS) + K, :],
        1, 2,
    )
    misc = export_np[:, -1, :]
    out["n"] = misc[:, 0]
    out["overflow"] = misc[:, 1]
    out["live_len"] = misc[:, 2]
    return out


# ---------------------------------------------------------------------------
# Host side: packing and canonical summary extraction
# ---------------------------------------------------------------------------


@dataclass
class MergeTreeDocInput:
    """One document's catch-up work item: optional base summary + op tail."""

    doc_id: str
    ops: Sequence[SequencedMessage]   # sequence-op contents, ascending seq
    base_records: Optional[List[dict]] = None  # normalized summary body
    final_seq: int = 0    # head seq after the tail (for the summary header)
    final_msn: int = 0    # final minimumSequenceNumber
    base_seq: int = 0     # seq of the base summary (for oracle fallback)
    base_msn: int = 0     # minSeq of the base summary
    base_intervals: Optional[Dict[str, dict]] = None  # intervals blob content
    # Native fast path: the ops pre-encoded as the liboppack binary record
    # stream (ops/native_pack.py) + the encoder's doc-local intern tables
    # (client ids; property keys / values when the stream annotates).
    # Interval ops never ride the stream.  When set, ``ops`` may be empty
    # (the stream is authoritative) — C++ fills this doc's arrays,
    # translating doc-local property ids into the batch-global spaces.
    binary_ops: Optional[bytes] = None
    binary_clients: Optional[Sequence[str]] = None
    binary_prop_keys: Optional[Sequence[str]] = None
    binary_values: Optional[Sequence[Any]] = None
    #: attribution-enabled document (SURVEY §1 layer 8): the summary gains
    #: an "attribution" blob of pre-clamp insert seqs per merged sub-run
    #: (byte-identical to SharedString.summarize with an attributor).  The
    #: export already carries pre-clamp ins_seq — clamping is host-side —
    #: so this is pure extraction work; such docs take the Python record
    #: path (the C++ extractor emits bodies only).
    attribution: bool = False
    #: Opaque identity of the (document, base summary, storage generation)
    #: this tail extends — set by callers (the catch-up service) that want
    #: the pipeline's pack cache to reuse packed windows across calls.
    #: The contract: two inputs with equal tokens draw their ops from the
    #: SAME append-only sequenced stream over the SAME base, so a shared
    #: (first_seq .. last_seq) prefix is byte-identical.  None (the
    #: default) opts the doc out of pack caching entirely.
    cache_token: Optional[tuple] = None


class _DocPack:
    """Per-document host bookkeeping during packing."""

    def __init__(self) -> None:
        self.clients = Interner()
        self.interval_ops: List[SequencedMessage] = []
        self.needs_fallback = False

    def client_idx(self, client_id) -> int:
        if client_id is None:
            return -1
        return self.clients.intern(client_id)


def fill_sequence_op_rows(op, d: int, t: int, msgs, pack, arena,
                          key_id, values) -> int:
    """Fill doc ``d``'s op rows from a message list, starting after row
    ``t`` — THE per-op row fill, shared by the fresh pack below and the
    pack cache's suffix extension (ops/pipeline.py) so the two can never
    drift byte-wise.  Interval ops route into ``pack.interval_ops``;
    ``key_id`` maps a property key to its chunk-global column.  Returns
    the last row filled."""
    for msg in msgs:
        contents = msg.contents
        kind = contents["kind"]
        if kind.startswith("interval"):
            for cl in ([msg.client_id] if msg.client_id else []):
                pack.client_idx(cl)
            pack.interval_ops.append(msg)
            continue
        t += 1
        op["seq"][d, t] = msg.seq
        op["client"][d, t] = pack.client_idx(msg.client_id)
        op["ref_seq"][d, t] = msg.ref_seq
        op["min_seq"][d, t] = msg.min_seq
        if kind == "insert":
            op["kind"][d, t] = K_INSERT
            op["a"][d, t] = contents["pos"]
            op["tstart"][d, t] = arena.append(contents["text"])
            op["tlen"][d, t] = len(contents["text"])
        elif kind == "remove":
            op["kind"][d, t] = K_REMOVE
            op["a"][d, t] = contents["start"]
            op["b"][d, t] = contents["end"]
        elif kind == "obliterate":
            op["kind"][d, t] = K_OBLITERATE
            op["a"][d, t] = contents["start"]
            op["b"][d, t] = contents["end"]
        elif kind == "annotate":
            op["kind"][d, t] = K_ANNOTATE
            op["a"][d, t] = contents["start"]
            op["b"][d, t] = contents["end"]
        else:
            raise ValueError(f"unknown sequence op kind {kind!r}")
        for key, value in (contents.get("props") or {}).items():
            op["pvals"][d, t, key_id(key)] = (
                PROP_ABSENT if value is None else values.intern(value)
            )
    return t


def pack_mergetree_batch(docs: Sequence[MergeTreeDocInput]):
    """Pack documents into uniform-shape device arrays + host metadata.

    Returns (state_arrays, op_arrays, meta) where meta carries everything
    needed to rebuild canonical summaries from the final device state.
    """
    prop_keys = Interner()
    values = Interner()
    arena = TextArena()
    doc_packs = [_DocPack() for _ in docs]

    # Pre-scan for the shared property-key vocabulary K.  Binary-stream
    # docs contribute their encoder-local key tables.
    for doc in docs:
        if doc.base_records:
            for rec in doc.base_records:
                for key in rec.get("p", {}):
                    prop_keys.intern(key)
        if doc.binary_ops is not None:
            for key in (doc.binary_prop_keys or []):
                prop_keys.intern(key)
            continue
        for msg in doc.ops:
            op = msg.contents
            if op["kind"].startswith("interval"):
                continue
            for key in (op.get("props") or {}):
                prop_keys.intern(key)
    # Power-of-two buckets: jitted shapes stay stable across batches instead
    # of recompiling the vmapped scan per (D, S, T, K).
    K = next_bucket(max(len(prop_keys), 1), floor=1)
    binary_counts = {}
    for i, d in enumerate(docs):
        if d.binary_ops is not None:
            if d.base_records:
                # Base-record clients would shift the encoder's dense client
                # ids — a silent misattribution, so refuse (warm-start docs
                # take the message-list path).
                raise ValueError(
                    f"{d.doc_id}: binary_ops cannot be combined with "
                    f"base_records"
                )
            binary_counts[i] = count_stream(d.binary_ops)
    text_op_counts = [
        binary_counts[i][0] if i in binary_counts else
        sum(1 for m in d.ops if not m.contents["kind"].startswith("interval"))
        for i, d in enumerate(docs)
    ]
    # S and T use the finer bucket ladder: both are pure per-element costs
    # (T = scan length, S = export-transfer bytes — the pipeline bottleneck)
    # and neither needs to divide the mesh, so the extra shape variants buy
    # up to 25% less padding on the hot path.
    T = next_bucket_fine(max(text_op_counts, default=1), floor=16)
    base_counts = [len(d.base_records or []) for d in docs]
    S = max(
        (bc + 2 * t for bc, t in zip(base_counts, text_op_counts)), default=1
    )
    S = next_bucket_fine(max(S, 1), floor=32)

    D = len(docs)
    st = {
        "tstart": np.zeros((D, S), np.int32),
        "tlen": np.zeros((D, S), np.int32),
        "ins_seq": np.zeros((D, S), np.int32),
        "ins_client": np.full((D, S), -1, np.int32),
        "rem_seq": np.full((D, S), NOT_REMOVED, np.int32),
        "rem_client": np.full((D, S), -1, np.int32),
        "rem2_seq": np.full((D, S), NOT_REMOVED, np.int32),
        "rem2_client": np.full((D, S), -1, np.int32),
        "ob1_seq": np.full((D, S), NOT_REMOVED, np.int32),
        "ob1_client": np.full((D, S), -1, np.int32),
        "ob2_seq": np.full((D, S), NOT_REMOVED, np.int32),
        "ob2_client": np.full((D, S), -1, np.int32),
        "props": np.full((D, S, K), PROP_ABSENT, np.int32),
        "n": np.zeros((D,), np.int32),
        "overflow": np.zeros((D,), np.bool_),
    }
    op = {
        "kind": np.zeros((D, T), np.int32),
        "seq": np.zeros((D, T), np.int32),
        "client": np.zeros((D, T), np.int32),
        "ref_seq": np.zeros((D, T), np.int32),
        "min_seq": np.zeros((D, T), np.int32),
        "a": np.zeros((D, T), np.int32),
        "b": np.zeros((D, T), np.int32),
        "tstart": np.zeros((D, T), np.int32),
        "tlen": np.zeros((D, T), np.int32),
        "pvals": np.full((D, T, K), PROP_NOT_TOUCHED, np.int32),
    }

    doc_base = np.zeros((D,), np.int32)
    base_has_ob = False
    base_has_ro = False
    base_max_tlen = 0
    # One raw-pointer packer per chunk: base addresses captured once, no
    # per-doc ndarray marshalling (see native_pack.ChunkPacker).
    from .native_pack import chunk_packer, pack_doc_row

    packer = chunk_packer(op) if binary_counts else None
    for d, doc in enumerate(docs):
        pack = doc_packs[d]
        doc_base[d] = len(arena)
        if known_oracle_fallback(doc):
            # Docs routed here without the partition_replay pre-filter
            # still get the oracle (the docstring's pack-time parity).
            pack.needs_fallback = True
        for s, rec in enumerate(doc.base_records or []):
            st["tstart"][d, s] = arena.append(rec["t"])
            st["tlen"][d, s] = len(rec["t"])
            st["ins_seq"][d, s] = rec["s"]
            st["ins_client"][d, s] = pack.client_idx(rec["c"])
            if "rs" in rec:
                st["rem_seq"][d, s] = rec["rs"]
                st["rem_client"][d, s] = pack.client_idx(rec.get("rc"))
            ob = rec.get("ob", [])
            if ob:
                base_has_ob = True
                st["ob1_seq"][d, s] = ob[0][0]
                st["ob1_client"][d, s] = pack.client_idx(ob[0][1])
                if len(ob) > 1:
                    st["ob2_seq"][d, s] = ob[1][0]
                    st["ob2_client"][d, s] = pack.client_idx(ob[1][1])
                if len(ob) > 2:
                    pack.needs_fallback = True  # device tracks two stamps
            base_max_tlen = max(base_max_tlen, len(rec["t"]))
            ro = rec.get("ro", [])
            if ro:
                base_has_ro = True
                # Second-remover slot is exact for one overlap remover; the
                # base summary doesn't carry overlap seqs, but any value
                # below the base seq is faithful (it sequenced before every
                # tail op).  >1 overlap removers → oracle fallback.
                st["rem2_seq"][d, s] = doc.base_seq
                st["rem2_client"][d, s] = pack.client_idx(ro[0])
                if len(ro) > 1:
                    pack.needs_fallback = True
            for key, value in rec.get("p", {}).items():
                st["props"][d, s, prop_keys.intern(key)] = values.intern(value)
        st["n"][d] = len(doc.base_records or [])

        if doc.binary_ops is not None:
            # Native fast path: C++ fills this doc's rows in one pass,
            # translating encoder-local property ids to the batch-global
            # intern spaces via the maps.
            for client in (doc.binary_clients or []):
                pack.client_idx(client)
            key_map = val_map = None
            if doc.binary_prop_keys:
                key_map = np.asarray(
                    [prop_keys.intern(k) for k in doc.binary_prop_keys],
                    np.int32,
                )
            if doc.binary_values:
                val_map = np.asarray(
                    [values.intern(v) for v in doc.binary_values],
                    np.int32,
                )
            doc_bytes = bytearray()
            if packer is not None:
                packer.pack(doc.binary_ops, d, len(arena), doc_bytes,
                            text_bytes=binary_counts[d][1],
                            key_map=key_map, val_map=val_map)
            else:
                row = {key: op[key][d]
                       for key in ("kind", "seq", "client", "ref_seq",
                                   "min_seq", "a", "b", "tstart", "tlen",
                                   "pvals")}
                pack_doc_row(doc.binary_ops, row, K, len(arena), doc_bytes,
                             text_bytes=binary_counts[d][1],
                             key_map=key_map, val_map=val_map)
            arena.append(doc_bytes.decode("utf-8"))
            continue

        fill_sequence_op_rows(op, d, -1, doc.ops, pack, arena,
                              prop_keys.intern, values)

    # int16-export eligibility: every value the final state can hold must fit
    # strictly under the int16 sentinel (see the export layout comment).
    max_doc_chars = 0
    for d in range(D):
        end = doc_base[d + 1] if d + 1 < D else len(arena)
        max_doc_chars = max(max_doc_chars, int(end) - int(doc_base[d]))
    max_seq = max(
        int(op["seq"].max(initial=0)),
        max((d.final_seq for d in docs), default=0),
        max((d.base_seq for d in docs), default=0),
    )
    max_clients = max((len(p.clients) for p in doc_packs), default=0)
    i16_ok = (
        max_seq < I16_LIMIT
        and max_doc_chars < I16_LIMIT
        and S < I16_LIMIT
        and len(values) < I16_LIMIT
        and max_clients < I16_LIMIT
    )
    # int8 pair-packing eligibility: every byte-row value (seqs incl. the
    # remapped sentinel, client/prop ids, segment lengths) fits a signed
    # byte.  tstart/misc stay 16-bit, so only the byte rows bound this.
    real_ops = op["kind"] != K_NOOP
    max_tlen = max(int(op["tlen"].max(initial=0)), base_max_tlen)
    i8_ok = (
        i16_ok
        and max_seq < I8_LIMIT
        and max_tlen < I8_LIMIT
        and len(values) < I8_LIMIT
        and max_clients < I8_LIMIT
    )
    # Overlap-remover rows are live only if a second remover can occur:
    # an op authored against a LAGGING view (ref_seq < seq-1 — an
    # already-removed slot can still be visible to it), or a base record
    # carrying overlap removers.  Fully sequential chunks elide them.
    sequential = not bool(
        (real_ops & (op["ref_seq"] != op["seq"] - 1)).any()
    )
    meta = {
        "doc_packs": doc_packs,
        "prop_keys": list(prop_keys.values),
        "values": values,
        "arena": arena,
        "docs": docs,
        "doc_base": doc_base,
        "_S": S,  # the padded slot bucket (cold-start export builders)
        "i16_ok": i16_ok,
        "i8_ok": i8_ok,
        "props_K": K,
        # Export the 4 obliterate rows only when the chunk can touch them
        # (a pack-time fact: an obliterate op anywhere — including C++-
        # filled binary rows, which land in op["kind"] — or a base stamp).
        "ob_rows": base_has_ob or bool((op["kind"] == K_OBLITERATE).any()),
        "ov_rows": base_has_ro or not sequential,
        # Props-free chunk (no annotate ops, no base props — the interner
        # saw no keys from ANY source): the plane stays constant, the
        # per-op plane shift traces away.
        "has_props": len(prop_keys) > 0,
        # Compile-time fold specialization (see _apply_op): base stamps
        # cannot exceed any sequential tail ref, so ``sequential`` alone
        # licenses the arrival-kill skip even on warm docs.
        "sequential": sequential,
    }
    return MTState(**st), MTOps(**op), meta


def _extract_records(meta, state_np: dict, d: int,
                     return_keys: bool = False):
    """Device state → the oracle's normalized record list (host side).

    ``return_keys=True`` additionally returns the ATTRIBUTION KEYS,
    mirroring ``MergeTreeOracle.normalized_records(return_keys=True)``:
    for each emitted record whose seq got clamped, the pre-clamp insert
    seqs of its merged sub-runs as ``[record_idx, [[chars, seq], ...]]``
    (the export's ins_seq column is pre-clamp — clamping happens here)."""
    doc = meta["docs"][d]
    pack = meta["doc_packs"][d]
    arena: TextArena = meta["arena"]
    prop_keys = meta["prop_keys"]
    values: Interner = meta["values"]
    msn = doc.final_msn
    records: List[dict] = []
    run_keys: List[Optional[list]] = []
    n = int(state_np["n"][d])
    for s in range(n):
        rs = int(state_np["rem_seq"][d, s])
        removed = rs != NOT_REMOVED
        stamps = []
        for o in ("ob1", "ob2"):
            o_s = int(state_np[f"{o}_seq"][d, s])
            if o_s != NOT_REMOVED and o_s > msn:
                oc = int(state_np[f"{o}_client"][d, s])
                stamps.append([o_s, pack.clients.lookup(oc)])
        if removed and rs <= msn \
                and int(state_np["ins_seq"][d, s]) <= msn and not stamps:
            continue  # expired tombstone (active stamps pin it)
        ins_seq = int(state_np["ins_seq"][d, s])
        ins_client = int(state_np["ins_client"][d, s])
        if ins_seq <= msn:
            seq_out, client_out = 0, None
        else:
            seq_out = ins_seq
            client_out = pack.clients.lookup(ins_client)
        rec = {
            "t": arena.slice(
                int(state_np["tstart"][d, s]), int(state_np["tlen"][d, s])
            ),
            "s": seq_out,
            "c": client_out,
        }
        if removed:
            rec["rs"] = rs
            rc = int(state_np["rem_client"][d, s])
            rec["rc"] = pack.clients.lookup(rc) if rc >= 0 else None
        if stamps:
            rec["ob"] = stamps
        rc2 = int(state_np["rem2_client"][d, s])
        if rc2 >= 0:
            rec["ro"] = [pack.clients.lookup(rc2)]
        props = {}
        for k, key in enumerate(prop_keys):
            vid = int(state_np["props"][d, s, k])
            if vid != PROP_ABSENT:
                props[key] = values.lookup(vid)
        if props:
            rec["p"] = dict(sorted(props.items()))
        if records:
            prev = records[-1]
            if (
                prev["s"] == rec["s"]
                and prev["c"] == rec["c"]
                and prev.get("rs") == rec.get("rs")
                and prev.get("rc") == rec.get("rc")
                and prev.get("ob") == rec.get("ob")
                and prev.get("ro") == rec.get("ro")
                and prev.get("p") == rec.get("p")
            ):
                prev["t"] += rec["t"]
                runs = run_keys[-1]
                if runs is not None:
                    if runs[-1][1] == ins_seq:
                        runs[-1][0] += len(rec["t"])  # same author run
                    else:
                        runs.append([len(rec["t"]), ins_seq])
                continue
        records.append(rec)
        run_keys.append(
            [[len(rec["t"]), ins_seq]] if rec["s"] == 0 else None
        )
    if not return_keys:
        return records
    keys = [
        [i, runs] for i, runs in enumerate(run_keys)
        if runs is not None and any(seq for _chars, seq in runs)
    ]
    return records, keys


def known_oracle_fallback(doc: MergeTreeDocInput) -> bool:
    # Memoized per doc object: partition_replay pre-filters with this and
    # pack-time parity re-checks it — the op/binary scans must not run
    # twice on the packing hot path (review-found).
    cached = getattr(doc, "_fallback_verdict", None)
    if cached is not None:
        return cached
    verdict = _known_oracle_fallback_uncached(doc)
    doc._fallback_verdict = verdict
    return verdict


def _known_oracle_fallback_uncached(doc: MergeTreeDocInput) -> bool:
    """True when a doc is known *before packing* to need the oracle path:
    >1 overlap remover on a base record (the device tracks exactly two
    removers and the base format carries no overlap seqs), >2 obliterate
    stamps on a base record (two device stamp slots), or interval ops
    mixed with obliterate ops (reference-slide timing over obliterated
    segments is host-folded only through the oracle).  Pack-time's
    ``needs_fallback`` applies the same rules; filtering first keeps such
    docs from inflating the shared power-of-two buckets."""
    for r in doc.base_records or []:
        if len(r.get("ro", [])) > 1 or len(r.get("ob", [])) > 2:
            return True
    has_interval = doc.base_intervals is not None
    has_obl = False
    for msg in doc.ops:
        kind = msg.contents.get("kind", "")
        if kind.startswith("interval"):
            has_interval = True
        elif kind == "obliterate":
            has_obl = True
    if doc.binary_ops is not None and has_interval and not has_obl:
        from .native_pack import binary_has_obliterate

        has_obl = binary_has_obliterate(doc.binary_ops)
    if has_obl and has_interval:
        return True
    return False


def oracle_fallback_summary(doc: MergeTreeDocInput) -> SummaryTree:
    """Full oracle replay of one document — the exactness escape hatch for
    the rare shapes the device path flags (>2 overlap removers on one
    segment, or a base summary with >1)."""
    from ..dds.sequence import SharedString

    replica = SharedString(doc.doc_id)
    if doc.attribution:
        # Attribution-enabled docs must emit their keys blob on fallback
        # too (summarize keys on the flag alone; table reads are container
        # state, not needed here).
        from ..runtime.attributor import Attributor

        replica._attributor = Attributor()
    if doc.base_records is not None:
        replica.tree.load_records(doc.base_records, doc.base_seq, doc.base_msn)
        for label, obj in (doc.base_intervals or {}).items():
            replica.get_interval_collection(label).load_obj(obj)
    ops = doc.ops
    if doc.binary_ops is not None and not ops:
        from .native_pack import decode_string_ops

        ops = decode_string_ops(doc.binary_ops,
                                list(doc.binary_clients or []),
                                prop_keys=doc.binary_prop_keys,
                                values=doc.binary_values)
    for msg in ops:
        replica.process(msg, local=False)
    replica.advance(doc.final_seq, doc.final_msn)
    return replica.summarize()


def summaries_from_export(meta, export_np: np.ndarray,
                          stats: Optional[dict] = None) -> List[SummaryTree]:
    """Canonical summaries for a whole chunk from the fused export buffer.

    Bodies come from the C++ extractor (one pass over the buffer) when
    liboppack is available, else the per-slot Python extraction; interval
    blobs and oracle-fallback docs take the host paths either way.
    ``stats`` (optional dict) accumulates ``device_docs`` /
    ``fallback_docs`` counters — the true device-vs-oracle split."""
    from .interval_replay import FinalStateView, replay_intervals
    from .native_pack import extract_bodies

    docs = meta["docs"]
    D = len(docs)
    _i16, ob_rows_f, ov_rows_f, i8_f, props_rows_f = _export_flags(meta)
    widened = widen_export_native(
        export_np, meta.get("doc_base"), ob_rows_f, ov_rows_f, i8_f,
        meta.get("props_K"), props_rows_f)
    export_np = widened if widened is not None else widen_export(
        export_np, meta.get("doc_base"),
        ob_rows=ob_rows_f, ov_rows=ov_rows_f,
        i8=i8_f, n_props=meta.get("props_K"),
        props_rows=props_rows_f)
    state_np = state_dict_from_export(export_np)
    skip = np.zeros(D, np.uint8)
    for d in range(D):
        if meta["doc_packs"][d].needs_fallback or state_np["overflow"][d]:
            skip[d] = 1
    if stats is not None:
        n_skip = int(skip.sum())
        stats["fallback_docs"] = stats.get("fallback_docs", 0) + n_skip
        stats["device_docs"] = stats.get("device_docs", 0) + D - n_skip
    msn = np.asarray([doc.final_msn for doc in docs], np.int32)
    arena_text = meta["arena"].finalize()
    # Attribution docs take the Python record path below (their key blob
    # needs the pre-clamp seqs alongside the merge boundaries), so the
    # C++ pass must not extract their bodies just to discard them —
    # body_skip extends the fallback skip WITHOUT polluting the stats.
    body_skip = skip.copy()
    for d in range(D):
        if docs[d].attribution:
            body_skip[d] = 1
    bodies = extract_bodies(
        np.ascontiguousarray(export_np, np.int32), arena_text,
        [list(meta["doc_packs"][d].clients.values) for d in range(D)],
        meta["prop_keys"], list(meta["values"].values),
        msn, body_skip, int(NOT_REMOVED),
    )
    out: List[SummaryTree] = []
    live_len = state_np["live_len"]
    for d, doc in enumerate(docs):
        pack = meta["doc_packs"][d]
        if skip[d]:
            out.append(oracle_fallback_summary(doc))
            continue
        tree = SummaryTree()
        # Byte-equal to canonical_json({...}) (keys pre-sorted, minimal
        # separators) — pinned by test_header_fast_format; json.dumps per
        # doc was ~20% of chunk extraction.
        tree.add_blob(
            "header",
            b'{"length":%d,"minSeq":%d,"seq":%d}'
            % (int(live_len[d]), doc.final_msn, doc.final_seq),
        )
        if doc.attribution:
            # Attribution docs take the Python record path (pinned
            # bit-identical to the C++ bodies): the keys blob needs the
            # pre-clamp seqs alongside the merge boundaries.
            records, keys = _extract_records(meta, state_np, d,
                                             return_keys=True)
            tree.add_blob("body", canonical_json(records))
            if keys:
                tree.add_blob("attribution", canonical_json(keys))
        elif bodies is not None:
            tree.add_blob("body", bodies[d])
        else:
            tree.add_blob(
                "body", canonical_json(_extract_records(meta, state_np, d))
            )
        if pack.interval_ops or doc.base_intervals:
            view = FinalStateView(state_np, d, int(NOT_REMOVED))
            intervals = replay_intervals(
                view,
                pack.interval_ops,
                pack.client_idx,
                base_intervals=doc.base_intervals,
                base_seq=doc.base_seq,
            )
            if intervals:
                tree.add_blob("intervals", canonical_json(intervals))
        out.append(tree)
    return out


def replay_mergetree_batch(
    docs: Sequence[MergeTreeDocInput],
    stats: Optional[dict] = None,
) -> List[SummaryTree]:
    """Full pipeline: pack → vmapped device op-fold → fused export download
    → canonical summaries.

    Byte-identical to ``SharedString.summarize()`` after the oracle replays
    the same log (asserted by tests/test_mergetree_kernel.py).
    ``stats`` accumulates ``device_docs`` / ``fallback_docs`` (pre-pack
    routing + post-fold overflow fallbacks).
    """
    from .batching import partition_replay

    def fold_batch(batch):
        state, ops, meta = pack_mergetree_batch(batch)
        if not any(d.base_records for d in batch):
            # all-cold chunk: initial state is built in-graph (no zero
            # upload; the host link is the bottleneck, not the fold)
            export = replay_export(None, ops, meta, S=state.tstart.shape[1])
        else:
            export = replay_export(state, ops, meta)
        return summaries_from_export(meta, export_to_numpy(export),
                                     stats=stats)

    return partition_replay(
        docs, known_oracle_fallback, oracle_fallback_summary, fold_batch,
        stats=stats,
    )
