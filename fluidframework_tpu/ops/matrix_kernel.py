"""SharedMatrix catch-up replay on device — north-star config #4.

The matrix's two permutation vectors are merge-trees over handle runs
(SEMANTICS.md §SharedMatrix), and a run of ``n`` sequentially-allocated
handles is exactly a ``(tstart=base, tlen=n)`` span — so both axis folds
reuse the merge-tree kernel's state and op-apply (``mergetree_kernel``)
verbatim.  The matrix-specific piece is **cell resolution**: a ``setCell``
op's positions must be resolved to handles *in the op's view at its fold
position*.  That is a pure read, expressed as a new op kind ``K_RESOLVE``
that mutates nothing (``_apply_op`` ignores unknown kinds) and emits the
resolved handle as a ``lax.scan`` output:

    handle(pos) = tstart[slot] + (pos - cum[slot])   where pos lands in slot

Both axis streams of every document pack into one vmapped batch (doc d's row
stream at 2d, col stream at 2d+1 — same shapes, one compile).  The cell
store itself stays host-side: resolved (row_handle, col_handle) pairs come
back from the device, and the per-cell LWW/FWW winner fold is a cheap
host reduction over tiny per-cell chains (FWW acceptance depends on the
previous *accepted* write — a sequential rule that would serialize on
device but touches only a handful of ops per cell).

Summary extraction renumbers handles canonically (enumeration order over
non-expired segments) exactly like the oracle, so the bytes match
``SharedMatrix.summarize()`` — asserted by tests/test_matrix_kernel.py.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .interning import Interner, next_bucket
from .mergetree_kernel import (
    K_INSERT,
    K_REMOVE,
    MTOps,
    MTState,
    NOT_REMOVED,
    PROP_NOT_TOUCHED,
    _apply_op,
    _excl_cumsum,
    _visible_len,
)

K_RESOLVE = 5  # pure read: resolve position -> handle (no state change)
# (4 is K_OBLITERATE in the shared op-kind space; permutation streams never
# carry it, but the shared _apply_op must not mistake a resolve for one.)


def _resolve_handle(state: MTState, op) -> jnp.ndarray:
    v = _visible_len(state, op.ref_seq, op.client)
    cum = _excl_cumsum(v)
    inside = (cum <= op.a) & (op.a < cum + v)
    idx = jnp.argmax(inside)
    return jnp.where(
        inside.any() & (op.kind == K_RESOLVE),
        state.tstart[idx] + op.a - cum[idx],
        -1,
    )


def replay_scan_resolving(state: MTState, ops: MTOps):
    """Axis fold that also emits per-op resolved handles (ys)."""

    def step(carry, op):
        resolved = _resolve_handle(carry, op)
        return _apply_op(carry, op), resolved

    return jax.lax.scan(step, state, ops)


replay_resolving_vmapped = jax.vmap(replay_scan_resolving)
_replay_matrix_batch = jax.jit(replay_resolving_vmapped)


@dataclass
class MatrixDocInput:
    """One matrix document's catch-up work item."""

    doc_id: str
    ops: Sequence[SequencedMessage]  # matrix op contents, ascending seq
    base_summary: Optional[SummaryTree] = None
    final_seq: int = 0
    final_msn: int = 0


def known_matrix_fallback(doc: MatrixDocInput) -> bool:
    """Pre-pack oracle-fallback predicate: >1 overlap remover on a base
    permutation record (the device tracks exactly two removers and the base
    format carries no overlap seqs — same rule as the merge-tree kernel)."""
    if doc.base_summary is None:
        return False
    body = json.loads(doc.base_summary.blob_bytes("body"))
    return any(
        len(rec.get("ro", [])) > 1
        for axis in ("rows", "cols")
        for rec in body[axis]
    )


class _MatrixDocPack:
    """Per-document host bookkeeping during packing."""

    def __init__(self) -> None:
        self.clients = Interner()
        # setCell metadata in seq order: (seq, ref_seq, client_idx, val_id,
        # row_slot, col_slot) where *_slot index the axis op streams.
        self.cells: List[Tuple[int, int, int, int, int, int]] = []
        self.base_cells: List[list] = []  # [r, c, val_id, seq, client_idx]
        self.fww_from_seq: Optional[int] = None  # seq of the setPolicy switch
        self.base_policy = "lww"
        self.base_seq = 0

    def client_idx(self, client_id) -> int:
        if client_id is None:
            return -1
        return self.clients.intern(client_id)


def pack_matrix_batch(docs: Sequence[MatrixDocInput]):
    """Pack documents into one [2D, ...] axis-stream batch + host metadata."""
    values = Interner()
    packs = [_MatrixDocPack() for _ in docs]

    # Per-stream op/base-record counts decide shared bucket sizes.
    parsed: List[Tuple[dict, dict]] = []  # (header, body) per doc
    for doc in docs:
        if doc.base_summary is not None:
            header = json.loads(doc.base_summary.blob_bytes("header"))
            body = json.loads(doc.base_summary.blob_bytes("body"))
        else:
            header, body = {"seq": 0, "policy": "lww"}, {
                "rows": [], "cols": [], "cells": [],
            }
        parsed.append((header, body))

    def stream_ops(doc: MatrixDocInput, axis: str) -> int:
        n = 0
        for msg in doc.ops:
            kind = msg.contents["kind"]
            if kind == "setCell" or axis in kind.lower():
                n += 1
        return n

    T = next_bucket(
        max(
            [stream_ops(d, ax) for d in docs for ax in ("row", "col")],
            default=1,
        ),
        floor=16,
    )
    S = next_bucket(
        max(
            (
                len(body[axis]) + 2 * stream_ops(doc, ax)
                for doc, (_h, body) in zip(docs, parsed)
                for axis, ax in (("rows", "row"), ("cols", "col"))
            ),
            default=1,
        ),
        floor=32,
    )

    D2 = 2 * len(docs)
    st = {
        "tstart": np.zeros((D2, S), np.int32),
        "tlen": np.zeros((D2, S), np.int32),
        "ins_seq": np.zeros((D2, S), np.int32),
        "ins_client": np.full((D2, S), -1, np.int32),
        "rem_seq": np.full((D2, S), NOT_REMOVED, np.int32),
        "rem_client": np.full((D2, S), -1, np.int32),
        "rem2_seq": np.full((D2, S), NOT_REMOVED, np.int32),
        "rem2_client": np.full((D2, S), -1, np.int32),
        "ob1_seq": np.full((D2, S), NOT_REMOVED, np.int32),
        "ob1_client": np.full((D2, S), -1, np.int32),
        "ob2_seq": np.full((D2, S), NOT_REMOVED, np.int32),
        "ob2_client": np.full((D2, S), -1, np.int32),
        "props": np.zeros((D2, S, 1), np.int32),  # unused by matrix
        "n": np.zeros((D2,), np.int32),
        "overflow": np.zeros((D2,), np.bool_),
    }
    op = {
        "kind": np.zeros((D2, T), np.int32),
        "seq": np.zeros((D2, T), np.int32),
        "client": np.zeros((D2, T), np.int32),
        "ref_seq": np.zeros((D2, T), np.int32),
        "min_seq": np.zeros((D2, T), np.int32),
        "a": np.zeros((D2, T), np.int32),
        "b": np.zeros((D2, T), np.int32),
        "tstart": np.zeros((D2, T), np.int32),
        "tlen": np.zeros((D2, T), np.int32),
        "pvals": np.full((D2, T, 1), PROP_NOT_TOUCHED, np.int32),
    }

    for d, (doc, (header, body)) in enumerate(zip(docs, parsed)):
        pack = packs[d]
        pack.base_seq = header.get("seq", 0)
        pack.base_policy = header.get("policy", "lww")
        if pack.base_policy == "fww":
            pack.fww_from_seq = 0
        for val in body.get("cells", []):
            r, c, value, seq, client = val
            pack.base_cells.append(
                [r, c, values.intern(value), seq, pack.client_idx(client)]
            )

        next_handle = {"row": 0, "col": 0}
        for axis, ax, s_idx in (("rows", "row", 2 * d), ("cols", "col", 2 * d + 1)):
            for s, rec in enumerate(body[axis]):
                st["tstart"][s_idx, s] = next_handle[ax]
                st["tlen"][s_idx, s] = rec["n"]
                next_handle[ax] += rec["n"]
                st["ins_seq"][s_idx, s] = rec["s"]
                st["ins_client"][s_idx, s] = pack.client_idx(rec["c"])
                if "rs" in rec:
                    st["rem_seq"][s_idx, s] = rec["rs"]
                    st["rem_client"][s_idx, s] = pack.client_idx(rec.get("rc"))
                ro = rec.get("ro", [])
                if ro:
                    # Any seq below the base seq is faithful (sequenced
                    # before every tail op); >1 removers -> pre-pack fallback.
                    st["rem2_seq"][s_idx, s] = pack.base_seq
                    st["rem2_client"][s_idx, s] = pack.client_idx(ro[0])
            st["n"][s_idx] = len(body[axis])

        t = {"row": -1, "col": -1}
        for msg in doc.ops:
            if msg.type is not MessageType.OP:
                continue
            contents = msg.contents
            kind = contents["kind"]
            client = pack.client_idx(msg.client_id)
            if kind == "setPolicy":
                if pack.fww_from_seq is None:
                    pack.fww_from_seq = msg.seq
                continue
            if kind == "setCell":
                slots = {}
                for ax, pos_key in (("row", "row"), ("col", "col")):
                    t[ax] += 1
                    s_idx = 2 * d + (0 if ax == "row" else 1)
                    tt = t[ax]
                    op["kind"][s_idx, tt] = K_RESOLVE
                    op["seq"][s_idx, tt] = msg.seq
                    op["client"][s_idx, tt] = client
                    op["ref_seq"][s_idx, tt] = msg.ref_seq
                    op["a"][s_idx, tt] = contents[pos_key]
                    slots[ax] = tt
                pack.cells.append(
                    (
                        msg.seq,
                        msg.ref_seq,
                        client,
                        values.intern(contents["value"]),
                        slots["row"],
                        slots["col"],
                    )
                )
                continue
            ax = "row" if "Row" in kind else "col"
            s_idx = 2 * d + (0 if ax == "row" else 1)
            t[ax] += 1
            tt = t[ax]
            op["seq"][s_idx, tt] = msg.seq
            op["client"][s_idx, tt] = client
            op["ref_seq"][s_idx, tt] = msg.ref_seq
            if kind.startswith("insert"):
                op["kind"][s_idx, tt] = K_INSERT
                op["a"][s_idx, tt] = contents["pos"]
                op["tstart"][s_idx, tt] = next_handle[ax]
                op["tlen"][s_idx, tt] = contents["count"]
                next_handle[ax] += contents["count"]
            elif kind.startswith("remove"):
                op["kind"][s_idx, tt] = K_REMOVE
                op["a"][s_idx, tt] = contents["start"]
                op["b"][s_idx, tt] = contents["end"]
            else:
                raise ValueError(f"unknown matrix op kind {kind!r}")

    meta = {"packs": packs, "values": values, "docs": docs}
    return MTState(**{k: v for k, v in st.items()}), MTOps(**op), meta


def _axis_records(
    state_np: dict, s_idx: int, msn: int, clients: Interner
) -> Tuple[List[dict], Dict[int, int]]:
    """Final device axis state → canonical records + handle→canonical map
    (mirrors PermutationVector.canonical_records)."""
    records: List[dict] = []
    handle_map: Dict[int, int] = {}
    n = int(state_np["n"][s_idx])
    for s in range(n):
        rs = int(state_np["rem_seq"][s_idx, s])
        removed = rs != NOT_REMOVED
        if removed and rs <= msn:
            continue
        base = int(state_np["tstart"][s_idx, s])
        count = int(state_np["tlen"][s_idx, s])
        for h in range(base, base + count):
            handle_map[h] = len(handle_map)
        ins_seq = int(state_np["ins_seq"][s_idx, s])
        if ins_seq <= msn:
            seq_out, client_out = 0, None
        else:
            seq_out = ins_seq
            client_out = clients.lookup(int(state_np["ins_client"][s_idx, s]))
        rec: dict = {"n": count, "s": seq_out, "c": client_out}
        if removed:
            rec["rs"] = rs
            rc = int(state_np["rem_client"][s_idx, s])
            rec["rc"] = clients.lookup(rc) if rc >= 0 else None
        rc2 = int(state_np["rem2_client"][s_idx, s])
        if rc2 >= 0:
            rec["ro"] = [clients.lookup(rc2)]
        if records:
            prev = records[-1]
            if (
                prev["s"] == rec["s"]
                and prev["c"] == rec["c"]
                and prev.get("rs") == rec.get("rs")
                and prev.get("rc") == rec.get("rc")
                and prev.get("ro") == rec.get("ro")
            ):
                prev["n"] += rec["n"]
                continue
        records.append(rec)
    return records, handle_map


def _fold_cells(pack: _MatrixDocPack, resolved_rh, resolved_ch):
    """Host cell-winner fold: tiny per-cell chains, LWW before the policy
    switch seq and FWW after (acceptance depends on the previous accepted
    write, so the chain is sequential — and short)."""
    store: Dict[Tuple[int, int], Tuple[int, int, int]] = {}  # (val, seq, cl)
    for r, c, val, seq, client in pack.base_cells:
        store[(r, c)] = (val, seq, client)
    fww_from = pack.fww_from_seq
    for seq, ref_seq, client, val, row_slot, col_slot in pack.cells:
        rh = int(resolved_rh[row_slot])
        ch = int(resolved_ch[col_slot])
        if rh < 0 or ch < 0:
            continue  # position beyond the op's view: deterministic no-op
        if fww_from is not None and seq > fww_from:
            entry = store.get((rh, ch))
            if entry is not None and entry[1] > ref_seq and entry[2] != client:
                continue  # first sequenced writer wins
        store[(rh, ch)] = (val, seq, client)
    return store


def oracle_matrix_fallback(doc: MatrixDocInput) -> SummaryTree:
    """Full oracle replay — exactness escape hatch (same role as the
    merge-tree kernel's)."""
    from ..dds.matrix import SharedMatrix

    replica = SharedMatrix(doc.doc_id)
    if doc.base_summary is not None:
        replica.load(doc.base_summary)
    for msg in doc.ops:
        replica.process(msg, local=False)
    replica.advance(doc.final_seq, doc.final_msn)
    return replica.summarize()


def summary_from_matrix_state(meta, state_np, resolved_np, d: int,
                              stats: Optional[dict] = None) -> SummaryTree:
    """``stats`` counts this doc as device/fallback WHERE the routing
    decision is made, so the counters can never drift from the actual
    serving path."""
    doc: MatrixDocInput = meta["docs"][d]
    pack: _MatrixDocPack = meta["packs"][d]
    values: Interner = meta["values"]
    if bool(state_np["overflow"][2 * d]) or bool(state_np["overflow"][2 * d + 1]):
        if stats is not None:
            stats["fallback_docs"] = stats.get("fallback_docs", 0) + 1
        return oracle_matrix_fallback(doc)
    if stats is not None:
        stats["device_docs"] = stats.get("device_docs", 0) + 1
    msn = doc.final_msn
    row_records, row_map = _axis_records(state_np, 2 * d, msn, pack.clients)
    col_records, col_map = _axis_records(state_np, 2 * d + 1, msn, pack.clients)
    store = _fold_cells(pack, resolved_np[2 * d], resolved_np[2 * d + 1])
    cells = []
    for (rh, ch), (val, seq, client) in store.items():
        if rh not in row_map or ch not in col_map:
            continue
        if seq <= msn:
            seq, client_out = 0, None
        else:
            client_out = pack.clients.lookup(client) if client >= 0 else None
        cells.append(
            [row_map[rh], col_map[ch], values.lookup(val), seq, client_out]
        )
    cells.sort(key=lambda e: (e[0], e[1]))

    def visible(s_idx: int) -> int:
        n = int(state_np["n"][s_idx])
        return sum(
            int(state_np["tlen"][s_idx, s])
            for s in range(n)
            if int(state_np["rem_seq"][s_idx, s]) == NOT_REMOVED
        )

    policy = "fww" if pack.fww_from_seq is not None else "lww"
    header = {
        "seq": doc.final_seq,
        "minSeq": msn,
        "rows": visible(2 * d),
        "cols": visible(2 * d + 1),
        "policy": policy,
    }
    body = {"rows": row_records, "cols": col_records, "cells": cells}
    tree = SummaryTree()
    tree.add_blob("header", canonical_json(header))
    tree.add_blob("body", canonical_json(body))
    return tree


def replay_matrix_batch(docs: Sequence[MatrixDocInput],
                        stats: Optional[dict] = None) -> List[SummaryTree]:
    """Full pipeline: pack → vmapped dual-axis device fold → host cell fold →
    canonical summaries.  Byte-identical to ``SharedMatrix.summarize()``
    (asserted by tests/test_matrix_kernel.py).  ``stats`` accumulates
    ``device_docs`` / ``fallback_docs`` (pre-pack routing + per-axis
    overflow fallbacks)."""
    from .batching import partition_replay

    def fold_batch(batch):
        state, ops, meta = pack_matrix_batch(batch)
        final, resolved = _replay_matrix_batch(state, ops)
        state_np = {k: np.asarray(v) for k, v in final._asdict().items()}
        resolved_np = np.asarray(resolved)
        return [
            summary_from_matrix_state(meta, state_np, resolved_np, d,
                                      stats=stats)
            for d in range(len(batch))
        ]

    return partition_replay(
        docs, known_matrix_fallback, oracle_matrix_fallback, fold_batch,
        stats=stats,
    )
