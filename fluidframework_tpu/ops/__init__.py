"""The TPU batch-merge path.

This package is the BASELINE.json north star: DDS op streams packed into
device-resident tensors and folded by JAX-traced kernels, vmapped/sharded over
thousands of documents.  Semantics are pinned by SEMANTICS.md and the CPU
oracles in ``fluidframework_tpu.dds``; every kernel's summary bytes must equal
the oracle's (asserted by tests replaying fuzz-generated op logs through
both).

Modules:
- ``interning``        — host-side vocab building (client ids, keys, values).
- ``map_kernel``       — SharedMap LWW catch-up replay (segment reductions,
                         no scan: the whole batch is two segment-maxes).
- ``mergetree_kernel`` — merge-tree catch-up replay (the centerpiece): a
                         lax.scan op-fold over an array-pool segment store.
- ``matrix_kernel``    — SharedMatrix dual-axis fold + host cell fold.
- ``tree_kernel``      — SharedTree edit-fold over linked sibling arrays
                         (O(1) scatters per edit — the id-addressed payoff).
- ``batching``         — shared fallback-partitioning for batch entry points.
"""
