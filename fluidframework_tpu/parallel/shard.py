"""Document-sharded replay: pjit over a ``docs`` mesh axis.

The batch state/op tensors are laid out ``[D, ...]`` with D the document
axis; sharding them ``P("docs")`` makes XLA partition the vmapped op-fold
with no communication (each chip folds its shard of documents).  The
merge-tree path exports per-doc transfer buffers doc-sharded (fully
collective-free — each chip encodes its shard); where a step needs
cross-chip assembly (matrix resolved cells, tree/map replicated
outputs) it is a single all-gather over ICI, expressed as a replication
sharding constraint.

Multi-slice (DCN) scale-out: :func:`dcn_mesh` builds a 2-D
``("slice", "docs")`` mesh — the slice axis spans TPU slices connected
over DCN, the docs axis spans chips within a slice over ICI.  Every step
builder shards the document dimension over *all* mesh axes (pure data
parallelism across the whole fleet), so the fold itself never
communicates; only the small replicated assembly outputs (per-doc
lengths / overflow flags) cross DCN, and XLA gathers them
hierarchically — ICI within a slice first, then one small DCN exchange —
which is exactly how the reference's capability maps to TPU fabric
(SURVEY.md §5 distributed-comm: Kafka/Redis fan-out → ICI collectives
within a slice, DCN only for cross-slice assembly).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.mergetree_kernel import (
    MTOps,
    MTState,
    MergeTreeDocInput,
    _export_cold_fn,
    _export_warm_fn,
)
from ..protocol.summary import SummaryTree

DOC_AXIS = "docs"
SLICE_AXIS = "slice"


def doc_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, document-sharded."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (DOC_AXIS,))


def dcn_mesh(n_slices: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 2-D ``(slice, docs)`` mesh for multi-slice deployments: outer axis
    across slices (DCN), inner axis across a slice's chips (ICI).

    Devices are grouped by their hardware slice when the platform exposes
    ``slice_index`` (real multi-slice TPU), so the inner mesh axis never
    straddles a DCN boundary; flat device lists (tests, single slice)
    reshape in order."""
    if devices is None:
        devices = jax.devices()
    devices = sorted(
        devices, key=lambda d: (getattr(d, "slice_index", 0) or 0, d.id)
    )
    if n_slices <= 0 or len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    per_slice = len(devices) // n_slices
    hw_slices = {getattr(d, "slice_index", 0) or 0 for d in devices}
    if len(hw_slices) > 1:
        # Real multi-slice hardware: every mesh row must stay within one
        # hardware slice, or "ICI" docs-axis collectives silently cross
        # DCN and the performance contract of this mesh is violated.
        for row_start in range(0, len(devices), per_slice):
            row = devices[row_start:row_start + per_slice]
            if len({getattr(d, "slice_index", 0) or 0 for d in row}) > 1:
                raise ValueError(
                    f"n_slices={n_slices} does not match the hardware "
                    f"slice grouping ({len(hw_slices)} slices of "
                    f"{len(devices) // len(hw_slices)} devices); a mesh "
                    "row would straddle a DCN boundary"
                )
    grid = np.asarray(devices).reshape(n_slices, per_slice)
    return Mesh(grid, (SLICE_AXIS, DOC_AXIS))


def _doc_spec(mesh: Mesh) -> P:
    """Shard the leading (document/op) dimension over ALL mesh axes — on a
    1-D mesh this is P("docs"); on a dcn_mesh it is P(("slice", "docs")),
    i.e. data parallelism across the whole fleet."""
    return P(tuple(mesh.axis_names))


def _pad_docs(docs: Sequence, multiple: int, make_pad):
    """Pad the doc list to a multiple of the mesh size with empty documents
    (noop streams) so the doc axis shards evenly."""
    docs = list(docs)
    while len(docs) % multiple:
        docs.append(make_pad())
    return docs


def _shard_put(mesh: Mesh, tree):
    shard = NamedSharding(mesh, _doc_spec(mesh))
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), shard), tree)


def sharded_export_step(mesh: Mesh, S: int, i16: bool, ob_rows: bool,
                        ov_rows: bool, i8: bool, sequential: bool,
                        has_props: bool, warm: bool,
                        digest: bool = False):
    """Mesh-sharded fold+EXPORT: the SAME cached builders as the
    single-chip path (``_export_cold_fn`` / ``_export_warm_fn``) with
    the doc-sharded placement threaded through as ``out_sharding`` — one
    derivation point, so the mesh path can never drift from the
    single-chip export pipeline.  The step widens narrow uploads
    in-graph, folds with the chunk-fact specialization, and emits the
    fused transfer buffer doc-sharded (~10× less d2h than the 13 full
    int32 state planes it replaced), with the forced row-major fetch
    layout where the backend supports layouts.  ``digest`` appends the
    per-doc state digest plane (the tier-0 delta-download gate), sharded
    like the buffer.  The fold and export are per-doc elementwise along
    the doc axis: no collective is inserted; each chip folds and encodes
    its shard."""
    shard = NamedSharding(mesh, _doc_spec(mesh))
    if warm:
        return _export_warm_fn(i16, ob_rows, "", ov_rows, i8, sequential,
                               has_props, out_sharding=shard,
                               digest=digest)
    return _export_cold_fn(S, i16, ob_rows, "", ov_rows, i8, sequential,
                           has_props, out_sharding=shard, digest=digest)


def replay_family_sharded(
    family,
    docs: Sequence,
    mesh: Optional[Mesh] = None,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    pack_cache=None,
    delta_cache=None,
    device_cache=None,
) -> List[SummaryTree]:
    """THE generic mesh-sharded catch-up fold (round 14): pack → narrow
    → shard over the mesh → family fold+export in-graph → shared host
    extraction, serving the IDENTICAL four-tier cache stack and
    stage-counter schema as the single-device pipeline — ``pack_cache``
    (tier 2 suffix reuse), ``delta_cache`` (tier 0 digest-gated delta
    download; only the digest plane and changed documents' rows cross
    d2h), ``device_cache`` (tier 2.5 resident upload buffers, placed
    doc-sharded; exact hits upload nothing, suffix hits splice in place)
    — with ``stage`` accumulating the
    ``pack``/``upload``/``dispatch``/``device_wait``/``download``/
    ``extract`` busy split plus ``h2d_bytes``/``d2h_bytes``.

    Every family-shaped decision rides the
    :class:`~fluidframework_tpu.ops.family.KernelFamily` hooks (the same
    descriptor the single-device pipeline consumes, plus
    ``dispatch_sharded``/``make_pad``/``pad_token``), so the merge-tree
    and tree mesh paths cannot drift from each other or from their
    single-device twins.  ``stats`` accumulates ``device_docs`` /
    ``fallback_docs`` (+ the per-reason split) exactly like the batch
    entry points, plus ``delta_docs`` for tier-0 serves."""
    from ..ops.batching import partition_replay
    from ..ops.pipeline import (
        _block_until_ready,
        _bump,
        _count_d2h,
        _count_h2d,
        _nbytes,
        _np_nbytes,
        delta_merge_changed,
        delta_route,
        delta_store_all,
        delta_sub_meta,
        perf_counter,
        seed_stage,
    )

    seed_stage(stage)
    if mesh is None:
        mesh = doc_mesh()
    shard = NamedSharding(mesh, _doc_spec(mesh))
    if device_cache is not None:
        device_cache.set_sharding(shard)

    def _bump_stats(st: dict) -> None:
        if stats is not None:
            for k, v in st.items():
                stats[k] = stats.get(k, 0) + v

    def fold_batch_export(batch):
        n_real = len(batch)
        pad_base = len(batch)
        padded = _pad_docs(batch, mesh.size, family.make_pad)
        # Pad docs carry a deterministic token so the padded chunk's
        # token tuple keys tiers 2/2.5 (any None would bypass both) —
        # but only when every REAL doc is tokened; a mixed chunk
        # bypasses anyway and must keep doing so.
        if family.pad_token is not None \
                and all(d.cache_token is not None for d in batch):
            for k in range(pad_base, len(padded)):
                padded[k].cache_token = family.pad_token(k)
        t0 = perf_counter()
        if pack_cache is not None:
            state, ops, meta = pack_cache.pack(padded)
        else:
            state, ops, meta = family.pack(padded)
        state_n, ops_n = family.narrow(padded, state, ops, meta)
        _bump(stage, "pack", t0)
        want_digest = delta_cache is not None

        # --- upload leg: resident tier or explicit sharded device_put;
        # h2d_bytes counts what really crossed either way.
        t0 = perf_counter()
        aux_dev = None
        if device_cache is not None:
            state_u, ops_u, aux_dev, up_bytes = device_cache.acquire(
                state_n, ops_n, meta)
            if isinstance(jax.tree.leaves(ops_u)[0], np.ndarray):
                # Bypass route (token-less chunk): shard-place like the
                # plain path so the step still runs mesh-partitioned.
                ops_u = _shard_put(mesh, ops_u)
                state_u = _shard_put(mesh, state_u) \
                    if state_u is not None else None
        else:
            up_bytes = _np_nbytes(state_n) + _np_nbytes(ops_n)
            ops_u = _shard_put(mesh, ops_n)
            state_u = _shard_put(mesh, state_n) \
                if state_n is not None else None
        if aux_dev is None:
            aux_host = family.aux(meta, want_digest)
            up_bytes += _np_nbytes(tuple(jax.tree.leaves(aux_host)))
            aux_dev = _shard_put(mesh, aux_host)
        _bump(stage, "upload", t0)
        _count_h2d(stage, up_bytes)

        # --- dispatch + honest device wait.
        t0 = perf_counter()
        export = family.dispatch_sharded(mesh, state_u, ops_u, meta,
                                         want_digest, aux_dev)
        core, dig = family.split_digest(export, want_digest)
        _bump(stage, "dispatch", t0)
        t0 = perf_counter()
        _block_until_ready(core, dig)
        _bump(stage, "device_wait", t0)

        # Pad trimming: served/changed/extraction all operate on the
        # REAL prefix (pads sit at the tail), so stats and the tier-0
        # entries never see a pad; the sliced view extracts identically
        # (chunk-global meta untouched, per-doc offsets absolute).
        meta_real = dict(
            meta,
            docs=meta["docs"][:n_real],
            doc_packs=meta["doc_packs"][:n_real],
        )
        for key in family.per_doc_meta:
            if key in meta:
                meta_real[key] = np.asarray(meta[key])[:n_real]
        real_docs = meta_real["docs"]

        def trim(ex_np):
            return tuple(a[:n_real] for a in ex_np) \
                if isinstance(ex_np, tuple) else ex_np[:n_real]

        def extract(meta_x, arr, extra=()):
            t1 = perf_counter()
            st: dict = {}
            res = family.extract(meta_x, arr, st)
            for fn in extra:
                fn(res)
            _bump(stage, "extract", t1)
            _bump_stats(st)
            return res

        def fetch_full():
            # d2h_bytes counts the PADDED buffer — that is what crosses
            # the link; pads trim host-side after the transfer.
            t1 = perf_counter()
            raw = family.fetch(core)
            _bump(stage, "download", t1)
            _count_d2h(stage, _nbytes(raw))
            return trim(raw)

        if dig is None:
            return extract(meta_real, fetch_full())
        t0 = perf_counter()
        dig_full = np.asarray(dig)  # the full padded plane crosses
        _bump(stage, "download", t0)
        _count_d2h(stage, dig_full.nbytes)
        dig_np = dig_full[:n_real]
        # The shared tier-0 decision + entry publication
        # (ops/pipeline.py delta_* helpers — one derivation point with
        # the single-device pipeline); pads never enter the handshake.
        route, served, changed = delta_route(real_docs, dig_np,
                                             delta_cache)
        if route == "full":
            # Cold / all-changed / fallback route — and the golden
            # oracle the delta path is tested against.
            def store(res):
                delta_store_all(delta_cache, real_docs, dig_np, res)

            return extract(meta_real, fetch_full(), extra=(store,))
        if route == "served":
            delta_cache.note_bytes_saved(_nbytes(core))
            _bump_stats({"delta_docs": len(real_docs)})
            return [served[d] for d in range(len(real_docs))]
        t0 = perf_counter()
        sub, fetched = family.gather_rows(
            core, np.asarray(changed, np.int32))
        _bump(stage, "download", t0)
        _count_d2h(stage, fetched)
        delta_cache.note_bytes_saved(max(0, _nbytes(core) - fetched))
        t0 = perf_counter()
        st: dict = {}
        got = family.extract(
            delta_sub_meta(meta_real, changed, family.per_doc_meta),
            sub, st)
        res = delta_merge_changed(delta_cache, meta_real, dig_np, served,
                                  changed, got)
        st["delta_docs"] = st.get("delta_docs", 0) + len(served)
        _bump(stage, "extract", t0)
        _bump_stats(st)
        return res

    return partition_replay(
        docs, family.known_fallback, family.fallback_summary,
        fold_batch_export, stats=stats,
    )


def replay_mergetree_sharded(
    docs: Sequence[MergeTreeDocInput],
    mesh: Optional[Mesh] = None,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    pack_cache=None,
    delta_cache=None,
    device_cache=None,
) -> List[SummaryTree]:
    """Multi-chip merge-tree catch-up replay — the merge-tree instance
    of :func:`replay_family_sharded` (round 13 paid the mesh-parity
    debt; round 14 made the body family-generic).  Byte-compatible with
    the single-chip path and the CPU oracle; fetches the same fused
    (elided/int16/int8) export buffer as single-chip and uploads the
    narrow encodings."""
    from ..ops.pipeline import MERGETREE_FAMILY

    return replay_family_sharded(
        MERGETREE_FAMILY, docs, mesh=mesh, stats=stats, stage=stage,
        pack_cache=pack_cache, delta_cache=delta_cache,
        device_cache=device_cache,
    )


@functools.lru_cache(maxsize=64)
def map_sharded_replay_step(mesh: Mesh, num_keys: int, num_docs: int):
    """Jitted, mesh-sharded LWW map reduction (cached per shape — a fresh
    jit closure every call would recompile identical shapes).

    The map kernel's inputs are FLAT op arrays (one row per set/delete op,
    grouped by global key id), so the shard axis is the op axis: each chip
    reduces its op shard and XLA assembles the per-key winners with
    cross-chip collectives (the segment reductions' combiner ops ride ICI),
    returning replicated per-key results for the host summarizer."""
    from ..ops.map_kernel import _map_lww_kernel

    shard = NamedSharding(mesh, _doc_spec(mesh))
    replicated = NamedSharding(mesh, P())

    def _step(key_gid, op_seq, is_set, val_idx, key_doc,
              clear_doc, clear_seq):
        return _map_lww_kernel(
            key_gid, op_seq, is_set, val_idx, key_doc, clear_doc, clear_seq,
            num_keys=num_keys, num_docs=num_docs,
        )

    return jax.jit(
        _step,
        in_shardings=(shard, shard, shard, shard, replicated,
                      shard, shard),
        out_shardings=(replicated, replicated),
    )


def replay_map_sharded(docs, mesh: Optional[Mesh] = None,
                       stats: Optional[dict] = None) -> List[SummaryTree]:
    """Multi-chip SharedMap catch-up replay; byte-compatible with
    ``replay_map_batch`` and the CPU oracle.  ``stats`` accumulates
    ``device_docs`` exactly like the batch entry point (the LWW
    reduction has no fallback cases), so the mesh service path reports
    the same split as single-chip."""
    from ..ops.map_kernel import pack_map_batch, summaries_from_lww

    if not docs:
        return []
    if stats is not None:
        stats["device_docs"] = stats.get("device_docs", 0) + len(docs)
    if mesh is None:
        mesh = doc_mesh()
    # Bucket floor = mesh size so the flat op axis splits evenly over
    # power-of-two meshes of ANY size (buckets otherwise floor at 64).
    batch = pack_map_batch(docs, bucket_floor=mesh.size)
    shard = NamedSharding(mesh, _doc_spec(mesh))
    replicated = NamedSharding(mesh, P())

    def put(arr, sh):
        return jax.device_put(jnp.asarray(arr), sh)

    step = map_sharded_replay_step(mesh, batch.num_keys, batch.num_docs)
    present, win_val = step(
        put(batch.key_gid, shard), put(batch.op_seq, shard),
        put(batch.is_set, shard), put(batch.val_idx, shard),
        put(batch.key_doc, replicated),
        put(batch.clear_doc, shard), put(batch.clear_seq, shard),
    )
    return summaries_from_lww(batch, present, win_val)


@functools.lru_cache(maxsize=8)
def matrix_sharded_replay_step(mesh: Mesh):
    """Jitted, mesh-sharded matrix fold (cached per mesh — a fresh jit
    closure every call would recompile identical shapes): the dual-axis
    permutation streams
    (packed ``[2D, ...]``, two axis rows per matrix) partitioned along the
    doc axis; per-op resolved cell handles are assembled cross-chip for the
    host cell fold — the ICI all-gather."""
    from ..ops.matrix_kernel import replay_resolving_vmapped

    shard = NamedSharding(mesh, _doc_spec(mesh))
    replicated = NamedSharding(mesh, P())

    def _step(state: MTState, ops: MTOps):
        final, resolved = replay_resolving_vmapped(state, ops)
        resolved = jax.lax.with_sharding_constraint(resolved, replicated)
        return final, resolved

    state_shardings = MTState(
        tstart=shard, tlen=shard, ins_seq=shard, ins_client=shard,
        rem_seq=shard, rem_client=shard, rem2_seq=shard, rem2_client=shard,
        ob1_seq=shard, ob1_client=shard, ob2_seq=shard, ob2_client=shard,
        props=shard, n=shard, overflow=shard,
    )
    ops_shardings = MTOps(
        kind=shard, seq=shard, client=shard, ref_seq=shard, min_seq=shard,
        a=shard, b=shard, tstart=shard, tlen=shard, pvals=shard,
    )
    return jax.jit(
        _step,
        in_shardings=(state_shardings, ops_shardings),
        out_shardings=(state_shardings, replicated),
    )


def replay_matrix_sharded(
    docs, mesh: Optional[Mesh] = None, step=None,
    stats: Optional[dict] = None,
) -> List[SummaryTree]:
    """Multi-chip SharedMatrix catch-up replay (see replay_mergetree_sharded).

    Matrices pack as TWO axis rows each, so the doc list pads to half the
    mesh size to keep the [2D] axis evenly sharded.  ``stats``
    accumulates ``device_docs``/``fallback_docs`` like the batch entry
    point (pre-pack routing + per-axis overflow fallbacks)."""
    from ..ops.batching import partition_replay
    from ..ops.matrix_kernel import (
        MatrixDocInput,
        known_matrix_fallback,
        oracle_matrix_fallback,
        pack_matrix_batch,
        summary_from_matrix_state,
    )

    if mesh is None:
        mesh = doc_mesh()
    the_step = step if step is not None else (
        matrix_sharded_replay_step(mesh) if docs else None
    )

    def fold_batch(batch):
        import math

        n_real = len(batch)
        # Matrices pack TWO axis rows each: pad the doc count so 2·D is
        # divisible by the mesh size for ANY size (odd meshes need D to be
        # a multiple of the size itself).
        doc_mult = mesh.size // math.gcd(mesh.size, 2)
        padded = _pad_docs(
            batch, max(1, doc_mult),
            lambda: MatrixDocInput(doc_id="\x00pad", ops=[]),
        )
        state, ops, meta = pack_matrix_batch(padded)
        final, resolved = the_step(_shard_put(mesh, state),
                                   _shard_put(mesh, ops))
        state_np = {k: np.asarray(v) for k, v in final._asdict().items()}
        resolved_np = np.asarray(resolved)
        return [
            summary_from_matrix_state(meta, state_np, resolved_np, d,
                                      stats=stats)
            for d in range(n_real)
        ]

    return partition_replay(
        docs, known_matrix_fallback, oracle_matrix_fallback, fold_batch,
        stats=stats,
    )


@functools.lru_cache(maxsize=8)
def tree_sharded_replay_step(mesh: Mesh):
    """Jitted, mesh-sharded tree replay step (cached per mesh): the
    edit-fold partitioned
    along the doc axis; per-doc overflow flags (the host needs every one to
    route fallbacks) assembled cross-chip — the ICI all-gather."""
    from ..ops.tree_kernel import TreeEdits, TreeState
    from ..ops.tree_kernel import replay_vmapped as tree_replay_vmapped

    shard = NamedSharding(mesh, _doc_spec(mesh))
    replicated = NamedSharding(mesh, P())

    def _step(state: TreeState, edits: TreeEdits):
        final = tree_replay_vmapped(state, edits)
        overflow = jax.lax.with_sharding_constraint(
            final.overflow, replicated
        )
        return final, overflow

    state_shardings = TreeState(
        head=shard, next=shard, prev=shard, node_container=shard,
        container_parent=shard, value=shard, value_seq=shard,
        insert_seq=shard, removed_seq=shard, overflow=shard,
    )
    edit_shardings = TreeEdits(
        kind=shard, seq=shard, container=shard, anchor=shard,
        first=shard, tail=shard, value=shard, purge_msn=shard,
    )
    return jax.jit(
        _step,
        in_shardings=(state_shardings, edit_shardings),
        out_shardings=(state_shardings, replicated),
    )


@functools.lru_cache(maxsize=16)
def tree_sharded_export_step(mesh: Mesh, digest: bool):
    """Jitted, mesh-sharded tree fold+EXPORT (cached per mesh/digest):
    the vmapped edit-fold partitioned along the doc axis, the final
    forest planes emitted doc-sharded (each chip encodes its shard;
    the host trims pads after the transfer), and — under ``digest`` —
    the per-doc ``[D, 2]`` digest plane appended LAST, sharded like the
    planes.  The tree family's ``dispatch_sharded`` hook; the fold is
    per-doc elementwise, so no collective is inserted."""
    from ..ops.tree_kernel import TreeEdits, TreeState
    from ..ops.tree_kernel import replay_vmapped as tree_replay_vmapped
    from ..ops.tree_pipeline import tree_doc_digests

    shard = NamedSharding(mesh, _doc_spec(mesh))

    def _step(state: TreeState, edits: TreeEdits, n_nodes, n_cont):
        final = tree_replay_vmapped(state, edits)
        out = tuple(final)
        if digest:
            out = out + (tree_doc_digests(final, n_nodes, n_cont),)
        return out

    n_out = len(TreeState._fields) + (1 if digest else 0)
    return jax.jit(
        _step,
        in_shardings=(
            TreeState(*([shard] * len(TreeState._fields))),
            TreeEdits(*([shard] * len(TreeEdits._fields))),
            shard, shard,
        ),
        out_shardings=(shard,) * n_out,
    )


def replay_tree_sharded(
    docs, mesh: Optional[Mesh] = None,
    stats: Optional[dict] = None,
    stage: Optional[dict] = None,
    pack_cache=None,
    delta_cache=None,
    device_cache=None,
) -> List[SummaryTree]:
    """Multi-chip SharedTree catch-up replay — the SECOND instance of
    :func:`replay_family_sharded` (ISSUE 14): the tree route serves the
    identical four-tier stack and stage schema as the merge-tree mesh
    fold.  ``stats`` accumulates ``device_docs``/``fallback_docs`` (with
    the per-reason split: revive / multi-id move / MAX_DEPTH overflow /
    purged-parent inserts / limbo bases) like the batch entry point."""
    from ..ops.tree_pipeline import TREE_FAMILY

    return replay_family_sharded(
        TREE_FAMILY, docs, mesh=mesh, stats=stats, stage=stage,
        pack_cache=pack_cache, delta_cache=delta_cache,
        device_cache=device_cache,
    )
