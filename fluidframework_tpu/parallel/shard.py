"""Document-sharded replay: pjit over a ``docs`` mesh axis.

The batch state/op tensors are laid out ``[D, ...]`` with D the document
axis; sharding them ``P("docs")`` makes XLA partition the vmapped op-fold
with no communication (each chip folds its shard of documents), and the
final cross-chip assembly (per-doc summary digests/lengths replicated for
the host summarizer) is a single all-gather over ICI, expressed as a
replication sharding constraint.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.mergetree_kernel import (
    MTOps,
    MTState,
    MergeTreeDocInput,
    NOT_REMOVED,
    known_oracle_fallback,
    oracle_fallback_summary,
    pack_mergetree_batch,
    replay_vmapped,
    summary_from_state,
)
from ..protocol.summary import SummaryTree

DOC_AXIS = "docs"


def doc_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, document-sharded."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (DOC_AXIS,))


def sharded_replay_step(mesh: Mesh):
    """Build the jitted, mesh-sharded full replay step.

    Returns ``step(state, ops) -> (final_state, lengths)`` where the fold is
    partitioned along the doc axis and ``lengths`` (per-doc visible length —
    the scalar assembled cross-chip for summarizer headers) comes back
    replicated, forcing the ICI all-gather.
    """
    shard = NamedSharding(mesh, P(DOC_AXIS))
    replicated = NamedSharding(mesh, P())

    def _step(state: MTState, ops: MTOps):
        final = replay_vmapped(state, ops)
        slot = jnp.arange(final.tlen.shape[1])[None, :]
        alive = (slot < final.n[:, None]) & (final.rem_seq == NOT_REMOVED)
        lengths = jnp.sum(jnp.where(alive, final.tlen, 0), axis=1)
        # Merged per-doc state assembled over ICI for the (host) summarizer.
        lengths = jax.lax.with_sharding_constraint(lengths, replicated)
        return final, lengths

    state_shardings = MTState(
        tstart=shard, tlen=shard, ins_seq=shard, ins_client=shard,
        rem_seq=shard, rem_client=shard, rem2_seq=shard, rem2_client=shard,
        props=shard, n=shard, overflow=shard,
    )
    ops_shardings = MTOps(
        kind=shard, seq=shard, client=shard, ref_seq=shard, a=shard, b=shard,
        tstart=shard, tlen=shard, pvals=shard,
    )
    return jax.jit(
        _step,
        in_shardings=(state_shardings, ops_shardings),
        out_shardings=(state_shardings, replicated),
    )


def _pad_docs(docs: Sequence[MergeTreeDocInput], multiple: int):
    """Pad the doc list to a multiple of the mesh size with empty documents
    (noop streams) so the doc axis shards evenly."""
    docs = list(docs)
    while len(docs) % multiple:
        docs.append(MergeTreeDocInput(doc_id="\x00pad", ops=[]))
    return docs


def replay_mergetree_sharded(
    docs: Sequence[MergeTreeDocInput],
    mesh: Optional[Mesh] = None,
    step=None,
) -> List[SummaryTree]:
    """Multi-chip catch-up replay: pack → shard over the mesh → fold →
    canonical summaries.  Byte-compatible with the single-chip path and the
    CPU oracle."""
    if not docs:
        return []
    if mesh is None:
        mesh = doc_mesh()
    # Known-fallback docs (pre-pack predicate) go straight to the oracle so
    # they don't inflate the shared buckets or waste their shard's fold.
    out: List[Optional[SummaryTree]] = [None] * len(docs)
    device_idx = []
    for i, doc in enumerate(docs):
        if known_oracle_fallback(doc):
            out[i] = oracle_fallback_summary(doc)
        else:
            device_idx.append(i)
    docs = [docs[i] for i in device_idx]
    if not docs:
        return out
    n_real = len(docs)
    padded = _pad_docs(docs, mesh.size)
    state, ops, meta = pack_mergetree_batch(padded)
    if step is None:
        step = sharded_replay_step(mesh)
    shard = NamedSharding(mesh, P(DOC_AXIS))
    state = jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), shard), state)
    ops = jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), shard), ops)
    final, lengths = step(state, ops)
    state_np = {k: np.asarray(v) for k, v in final._asdict().items()}
    lengths = np.asarray(lengths)
    for d in range(n_real):
        out[device_idx[d]] = summary_from_state(
            meta, state_np, d, length=int(lengths[d])
        )
    return out
