"""Device-mesh parallelism: document sharding over TPU chips.

The reference scales by partitioning documents across Kafka partitions and
service replicas (SURVEY.md §5 'Distributed communication backend'); the
TPU-native equivalent is a ``jax.sharding.Mesh`` with a ``docs`` axis —
catch-up replay is embarrassingly document-parallel, so the op-fold shards
along the doc axis with zero cross-chip traffic during the fold, and merged
state (summary roots / lengths / resolved handles) is assembled with XLA
collectives over ICI at the end.  Multi-slice scale-out rides the same
shardings over DCN.
"""

from .shard import (
    dcn_mesh,
    doc_mesh,
    map_sharded_replay_step,
    matrix_sharded_replay_step,
    replay_map_sharded,
    replay_matrix_sharded,
    replay_mergetree_sharded,
    replay_tree_sharded,
    tree_sharded_replay_step,
)

__all__ = [
    "dcn_mesh",
    "doc_mesh",
    "map_sharded_replay_step",
    "matrix_sharded_replay_step",
    "replay_map_sharded",
    "replay_matrix_sharded",
    "replay_mergetree_sharded",
    "replay_tree_sharded",
    "tree_sharded_replay_step",
]
