"""Developer tooling: replay CLI, benchmark harness (SURVEY.md §2.4 —
replay-tool / fluid-runner / @fluid-tools/benchmark capability)."""
