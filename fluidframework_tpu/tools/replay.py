"""Replay tool — rebuild any historical document state from a file store.

Capability-equivalent of the reference's ``replay-tool`` / ``fluid-runner``
(SURVEY.md §2.4: replay an op log offline against snapshots — debugging,
perf work, state forensics; upstream paths UNVERIFIED — empty reference
mount).

Usage:
    python -m fluidframework_tpu.tools.replay <store-dir> <doc-id> \
        [--to-seq N] [--json] [--show ds/channel] [--history]

Reads the durable file store (FileDocumentServiceFactory layout), loads the
document as of ``--to-seq`` (default: head) through the replay driver, and
prints a state report: sequence point, summary digest, datastores/channels,
and optionally one channel's content."""

from __future__ import annotations

import argparse
import json
import sys

from ..drivers.file_driver import FileSummaryStorage
from ..drivers.replay_driver import ReplayDocumentService
from ..loader import Loader
from ..service.oplog import OpLog


class _ReplayFactory:
    def __init__(self, oplog, storage, to_seq):
        self.oplog, self.storage, self.to_seq = oplog, storage, to_seq

    def resolve(self, doc_id):
        return ReplayDocumentService(doc_id, self.oplog, self.storage,
                                     to_seq=self.to_seq)


def replay(store_dir: str, doc_id: str, to_seq=None) -> dict:
    """Programmatic entry: returns the state report dict."""
    import os

    oplog = OpLog(os.path.join(store_dir, "ops.jsonl"))
    storage = FileSummaryStorage(store_dir)
    loader = Loader(_ReplayFactory(oplog, storage, to_seq))
    container = loader.resolve(doc_id)
    runtime = container.runtime
    summary = runtime.summarize()
    report = {
        "docId": doc_id,
        "seq": runtime.ref_seq,
        "minSeq": runtime.min_seq,
        "summaryDigest": summary.digest(),
        "quorum": runtime.election.quorum,
        "catchupOps": container.catchup_ops,
        "datastores": {
            ds_id: {ch_id: ch.TYPE for ch_id, ch in ds.channels.items()}
            for ds_id, ds in sorted(runtime.datastores.items())
        },
    }
    report["_runtime"] = runtime  # for --show / programmatic callers
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("store_dir")
    parser.add_argument("doc_id")
    parser.add_argument("--to-seq", type=int, default=None)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--show", default=None, metavar="DS/CHANNEL",
                        help="print one channel's content")
    parser.add_argument("--history", action="store_true",
                        help="print the document's summary commit chain")
    args = parser.parse_args(argv)

    if args.history:
        if args.show:
            parser.error("--show does not combine with --history")
        storage = FileSummaryStorage(args.store_dir)
        commits = storage.history(args.doc_id)
        if args.to_seq is not None:
            commits = [c for c in commits if c.ref_seq <= args.to_seq]
        if args.json:
            print(json.dumps([
                {"commit": c.digest(), "tree": c.tree, "parent": c.parent,
                 "refSeq": c.ref_seq, "message": c.message}
                for c in commits
            ], sort_keys=True))
        else:
            for c in commits:
                print(f"{c.digest()[:12]}  tree {c.tree[:12]}  "
                      f"@seq {c.ref_seq}  {c.message}")
        return 0

    report = replay(args.store_dir, args.doc_id, args.to_seq)
    runtime = report.pop("_runtime")
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(f"doc {report['docId']} @ seq {report['seq']} "
              f"(minSeq {report['minSeq']})")
        print(f"summary {report['summaryDigest']}")
        print(f"quorum  {report['quorum']}")
        for ds_id, channels in report["datastores"].items():
            for ch_id, type_name in channels.items():
                print(f"  {ds_id}/{ch_id}  [{type_name}]")
    if args.show:
        ds_id, channel_id = args.show.split("/", 1)
        channel = runtime.get_datastore(ds_id).get_channel(channel_id)
        text = getattr(channel, "text", None)
        if text is not None:
            print(text)
        else:
            print(channel.summarize().blob_bytes("header").decode("utf-8"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
