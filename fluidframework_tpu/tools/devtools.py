"""Runtime inspector — the devtools capability (SURVEY.md §2.4; upstream
paths UNVERIFIED — empty reference mount).

``inspect_runtime`` renders one live ContainerRuntime as a JSON-safe
snapshot a host can surface in a debug panel: connection + window state,
quorum membership and propose/accept state, per-datastore channel types
with per-channel quick views, pending (un-acked) work, and summarizer
stats when a SummaryManager is attached.  Read-only: inspecting never
mutates runtime state.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def _channel_view(channel) -> Dict[str, Any]:
    view: Dict[str, Any] = {"type": getattr(channel, "TYPE", "?")}
    text = getattr(channel, "text", None)
    if isinstance(text, str):
        view["length"] = len(text)
        view["preview"] = text[:80]
    if hasattr(channel, "row_count"):
        view["rows"] = channel.row_count
        view["cols"] = channel.col_count
    if hasattr(channel, "_kernel") and hasattr(channel._kernel, "data"):
        data = channel._kernel.data
        view["keys"] = len(data)
        view["preview"] = dict(list(sorted(data.items()))[:8])
    if hasattr(channel, "value"):
        try:
            view["value"] = channel.value
        except Exception:
            pass
    pending = getattr(channel, "_pending_groups", None)
    if pending is not None:
        view["pendingOps"] = len(pending)
    return view


def inspect_runtime(runtime, summary_manager=None) -> Dict[str, Any]:
    """A read-only snapshot of a live runtime for debug surfaces."""
    out: Dict[str, Any] = {
        "clientId": runtime.client_id,
        "attached": runtime.is_attached,
        "refSeq": runtime.ref_seq,
        "minSeq": runtime.min_seq,
        "inboundQueued": len(runtime._inbound),
        "outboxOps": len(runtime._outbox),
        "pendingWireMessages": len(runtime._pending_wire),
        "quorum": runtime.election.quorum,
        "elected": runtime.election.elected,
        "proposals": {
            "accepted": runtime.quorum_proposals.accepted(),
            "pending": runtime.quorum_proposals.pending(),
        },
        "datastores": {},
    }
    for ds_id, ds in sorted(runtime.datastores.items()):
        out["datastores"][ds_id] = {
            "rooted": ds.rooted,
            "channels": {
                channel_id: _channel_view(channel)
                for channel_id, channel in sorted(ds.channels.items())
            },
        }
    dm = getattr(runtime, "_service", None)
    state = getattr(dm, "state", None)
    if state is not None:
        out["connection"] = {
            "state": state.value,
            "nacks": getattr(dm, "nacks", 0),
            "gapsRepaired": getattr(dm, "gaps_repaired", 0),
            "lastDeliveredSeq": getattr(dm, "last_delivered_seq", 0),
        }
    if summary_manager is not None:
        out["summarizer"] = {
            "isSummarizer": summary_manager._is_summarizer,
            "summariesWritten": summary_manager.summaries_written,
            "opsSinceSummary": summary_manager.ops_since_summary,
            "nacksReceived": summary_manager.nacks_received,
            "lastAckedHandle": summary_manager.last_acked_handle,
            "lastUploadBytes": summary_manager.last_upload_bytes,
        }
    return out
