"""Statistical micro-benchmark harness.

Capability-equivalent of the reference's ``@fluid-tools/benchmark``
(SURVEY.md §2.4/§4: execution-time + memory modes with statistical
reporting, the ``.perf.spec`` convention; upstream paths UNVERIFIED —
empty reference mount).

    result = benchmark(lambda: replica.process(msg), min_runs=20)
    print(result.report())          # mean/p50/p95/stddev
    mem = benchmark_memory(build_big_state)
"""

from __future__ import annotations

import dataclasses
import json
import math
import sys
import time
import tracemalloc
from typing import Callable, List, Optional


@dataclasses.dataclass
class BenchmarkResult:
    name: str
    runs: int
    #: per-run durations, seconds
    samples: List[float]

    @property
    def mean(self) -> float:
        return sum(self.samples) / len(self.samples)

    @property
    def stddev(self) -> float:
        mean = self.mean
        if len(self.samples) < 2:
            return 0.0
        var = sum((s - mean) ** 2 for s in self.samples) \
            / (len(self.samples) - 1)
        return math.sqrt(var)

    def percentile(self, p: float) -> float:
        ordered = sorted(self.samples)
        idx = min(len(ordered) - 1, max(0, round(p / 100 * (len(ordered) - 1))))
        return ordered[idx]

    @property
    def p50(self) -> float:
        return self.percentile(50)

    @property
    def p95(self) -> float:
        return self.percentile(95)

    @property
    def ops_per_sec(self) -> float:
        return 1.0 / self.mean if self.mean > 0 else float("inf")

    def report(self) -> str:
        return (
            f"{self.name}: {self.runs} runs | mean "
            f"{self.mean * 1e3:.3f}ms | p50 {self.p50 * 1e3:.3f}ms | "
            f"p95 {self.p95 * 1e3:.3f}ms | stddev {self.stddev * 1e3:.3f}ms"
        )


def benchmark(
    fn: Callable[[], object],
    name: str = "benchmark",
    min_runs: int = 10,
    max_runs: int = 1000,
    min_time_s: float = 0.5,
    warmup_runs: int = 2,
    setup: Optional[Callable[[], object]] = None,
) -> BenchmarkResult:
    """Timed mode: run until both min_runs and min_time_s are satisfied
    (or max_runs), measuring each run.  ``setup`` runs untimed before each
    measured run (fresh state per run)."""
    for _ in range(warmup_runs):
        arg = setup() if setup else None
        fn() if arg is None else fn(arg)  # type: ignore[call-arg]
    samples: List[float] = []
    total = 0.0
    while (len(samples) < min_runs or total < min_time_s) \
            and len(samples) < max_runs:
        arg = setup() if setup else None
        t0 = time.perf_counter()
        fn() if arg is None else fn(arg)  # type: ignore[call-arg]
        dt = time.perf_counter() - t0
        samples.append(dt)
        total += dt
    return BenchmarkResult(name=name, runs=len(samples), samples=samples)


def render_bench_json(report: dict, compact: bool = False) -> str:
    """THE one BENCH-JSON serialization: sorted keys, stable layout
    (indent-2 document, or one line for ``compact`` single-metric
    benches), trailing newline — so every ``BENCH_*.json`` in the
    trajectory diffs cleanly run over run.  Metrics a run skipped must
    already be present as ``None`` in ``report`` (schema-stable nulls);
    this is the serialization point, not a schema checker."""
    if compact:
        return json.dumps(report, sort_keys=True) + "\n"
    return json.dumps(report, indent=2, sort_keys=True) + "\n"


def write_bench_json(report: dict, out: Optional[str] = None,
                     compact: bool = False) -> str:
    """Render ``report`` (see :func:`render_bench_json`) and write it to
    the ``out`` path, or to stdout when ``out`` is None.  Returns the
    rendered text.  Shared by tools/service_e2e.py, tools/chaos.py and
    tools/loadgen.py — one writer, one schema discipline."""
    text = render_bench_json(report, compact=compact)
    if out is not None:
        with open(out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return text


@dataclasses.dataclass
class ColdWarmResult:
    """Steady-state pair: the first (cold) run against the best of the
    following (warm) runs — the cache-amortization shape of serving
    workloads (catch-up re-reads, snapshot caches)."""

    name: str
    cold_s: float
    warm_s: float  # best warm run
    warm_runs: int
    #: d2h / h2d bytes the cold / per-warm run moved (from a pipeline
    #: ``stage`` dict's ``d2h_bytes``/``h2d_bytes`` counters) — None
    #: when no stage was attached, so JSON consumers see the fields
    #: null-stable, not absent.
    cold_d2h_bytes: Optional[int] = None
    warm_d2h_bytes: Optional[int] = None  # LAST warm run (deterministic)
    cold_h2d_bytes: Optional[int] = None
    warm_h2d_bytes: Optional[int] = None  # LAST warm run (deterministic)

    @property
    def speedup(self) -> float:
        return self.cold_s / self.warm_s if self.warm_s > 0 \
            else float("inf")

    def report(self) -> str:
        out = (
            f"{self.name}: cold {self.cold_s * 1e3:.3f}ms | warm "
            f"{self.warm_s * 1e3:.3f}ms (best of {self.warm_runs}) | "
            f"{self.speedup:.1f}x"
        )
        if self.cold_d2h_bytes is not None:
            out += (f" | d2h cold {self.cold_d2h_bytes} B, warm "
                    f"{self.warm_d2h_bytes} B | h2d cold "
                    f"{self.cold_h2d_bytes} B, warm "
                    f"{self.warm_h2d_bytes} B")
        return out


def benchmark_cold_warm(
    fn: Callable[[], object],
    name: str = "cold-warm",
    warm_runs: int = 3,
    stage: Optional[dict] = None,
) -> ColdWarmResult:
    """Cold/warm mode: time ``fn`` once cold, then ``warm_runs`` more
    times taking the best — no setup hook on purpose (the state carried
    between runs IS the measurement).  ``stage`` (a pipeline stage dict
    whose ``d2h_bytes``/``h2d_bytes`` counters ``fn`` advances)
    additionally attributes the cold run's and the last warm run's link
    bytes EACH WAY — the delta-download and resident-upload observables,
    deterministic where the timings are not."""

    def _bytes(key: str) -> int:
        return int(stage.get(key, 0)) if stage is not None else 0

    b0, u0 = _bytes("d2h_bytes"), _bytes("h2d_bytes")
    t0 = time.perf_counter()
    fn()
    cold = time.perf_counter() - t0
    cold_bytes = _bytes("d2h_bytes") - b0
    cold_up = _bytes("h2d_bytes") - u0
    warm = float("inf")
    warm_bytes = warm_up = 0
    for _ in range(max(1, warm_runs)):
        b0, u0 = _bytes("d2h_bytes"), _bytes("h2d_bytes")
        t0 = time.perf_counter()
        fn()
        warm = min(warm, time.perf_counter() - t0)
        warm_bytes = _bytes("d2h_bytes") - b0
        warm_up = _bytes("h2d_bytes") - u0
    return ColdWarmResult(
        name=name, cold_s=cold, warm_s=warm, warm_runs=max(1, warm_runs),
        cold_d2h_bytes=cold_bytes if stage is not None else None,
        warm_d2h_bytes=warm_bytes if stage is not None else None,
        cold_h2d_bytes=cold_up if stage is not None else None,
        warm_h2d_bytes=warm_up if stage is not None else None,
    )


@dataclasses.dataclass
class MemoryResult:
    name: str
    peak_bytes: int
    retained_bytes: int

    def report(self) -> str:
        return (f"{self.name}: peak {self.peak_bytes / 1e6:.2f}MB | "
                f"retained {self.retained_bytes / 1e6:.2f}MB")


def benchmark_memory(fn: Callable[[], object],
                     name: str = "memory") -> MemoryResult:
    """Memory mode: peak allocation during fn and bytes retained by its
    return value's lifetime (tracemalloc)."""
    tracemalloc.start()
    tracemalloc.reset_peak()
    before, _ = tracemalloc.get_traced_memory()
    result = fn()
    after, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    del result
    return MemoryResult(name=name, peak_bytes=peak - before,
                        retained_bytes=max(0, after - before))
