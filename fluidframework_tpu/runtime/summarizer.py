"""Summarization: election, heuristics, upload, ack tracking.

Capability-equivalent of the reference's summary stack (SURVEY.md §3.3:
``SummaryManager`` → ``OrderedClientElection`` → ``RunningSummarizer``
heuristics → ``submitSummary`` → storage upload → "summarize" op → ack;
upstream paths UNVERIFIED — empty reference mount).  One client — the
oldest in the quorum — summarizes; everyone else tracks acks so any
client can take over on re-election."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..protocol.messages import MessageType, RawOperation, SequencedMessage, NackError
from ..protocol.summary import SummaryStorage
from .container import ContainerRuntime, OrderedClientElection

__all__ = ["SummarizerOptions", "SummaryManager", "OrderedClientElection"]


@dataclasses.dataclass
class SummarizerOptions:
    """RunningSummarizer heuristics (the reference's ISummaryConfiguration
    capability: opsSinceLastSummary / maxOps thresholds)."""

    ops_per_summary: int = 50    # summarize every N sequenced ops
    min_ops: int = 1             # never summarize with fewer new ops
    #: record last_full_bytes alongside incremental uploads (costs one
    #: full-tree encode per summary; disable for very large documents)
    track_upload_ratio: bool = True
    #: after a summary NACK, retry once this many ops have sequenced —
    #: doubling per consecutive nack (deterministic in-proc backoff),
    #: resetting on the next ack
    nack_retry_ops: int = 4


class SummaryManager:
    """Watches the op stream on one client; when that client is elected and
    the heuristics fire, writes a summary and announces it.

    Wire-in: ``manager = SummaryManager(runtime, storage, doc_id)`` then the
    runtime's ``message_observers`` hook drives it — no polling."""

    def __init__(
        self,
        runtime: ContainerRuntime,
        storage: SummaryStorage,
        doc_id: str,
        options: Optional[SummarizerOptions] = None,
    ) -> None:
        self.runtime = runtime
        self.storage = storage
        self.doc_id = doc_id
        self.options = options or SummarizerOptions()
        self.last_summary_seq = 0
        self.last_ack_handle: Optional[str] = None
        # Scribe-confirmed state (set only when a Scribe is in the loop and
        # stamps summaryAck/summaryNack back into the stream).
        self.last_acked_handle: Optional[str] = None
        self.last_acked_seq = 0
        self.nacks_received = 0
        self.consecutive_nacks = 0
        self.ops_since_summary = 0
        self.summaries_written = 0
        # Incremental-upload accounting (set by summarize_now).
        self.last_upload_bytes = 0
        self.last_full_bytes = 0
        runtime.message_observers.append(self._on_message)

    # -- the message hook ------------------------------------------------------

    @property
    def election(self) -> OrderedClientElection:
        return self.runtime.election

    def _on_message(self, msg: SequencedMessage) -> None:
        if msg.type is MessageType.OP:
            self.ops_since_summary += 1
        elif msg.type is MessageType.SUMMARIZE:
            # Every client tracks announced summaries (for takeover).  With
            # no Scribe in the loop, sequencing the summarize op is the
            # acceptance point; with one, summaryAck below confirms it.
            self.last_summary_seq = msg.contents["seq"]
            self.last_ack_handle = msg.contents["handle"]
            self.ops_since_summary = 0
        elif msg.type is MessageType.SUMMARY_ACK:
            self.last_acked_handle = msg.contents["handle"]
            self.last_acked_seq = msg.contents["seq"]
            self.consecutive_nacks = 0
        elif msg.type is MessageType.SUMMARY_NACK:
            # No immediate retry (a persistent nack reason would loop);
            # the next ops_per_summary window naturally re-attempts — the
            # deterministic in-proc analogue of the reference's backoff.
            # Roll the takeover baseline back to the last *accepted* summary
            # so a re-elected summarizer never builds on the rejected one.
            self.nacks_received += 1
            self.consecutive_nacks += 1
            self.last_summary_seq = self.last_acked_seq
            self.last_ack_handle = self.last_acked_handle
        # Normal cadence, or — after a NACK — an exponential-backoff
        # retry window (nack_retry_ops * 2^(nacks-1) sequenced ops), so a
        # transient rejection re-attempts without waiting out the full
        # summary window and a persistent one cannot hot-loop.
        threshold = self.options.ops_per_summary
        if self.consecutive_nacks:
            threshold = min(threshold, self.options.nack_retry_ops
                            * (2 ** (self.consecutive_nacks - 1)))
        if (
            self._is_summarizer
            and msg.type is not MessageType.SUMMARIZE
            and self.ops_since_summary >= threshold
            and self.ops_since_summary >= self.options.min_ops
        ):
            self.summarize_now()

    @property
    def _is_summarizer(self) -> bool:
        return (
            self.runtime.is_attached
            and self.election.elected == self.runtime.client_id
        )

    # -- the summarize action --------------------------------------------------

    def summarize_now(self) -> Optional[str]:
        """Write + upload + announce one summary; returns its handle.

        Uploads INCREMENTALLY against the last announced summary when its
        tree is still in the store: unchanged subtrees ride as handle
        references (the reference's incremental-summary capability), and
        ``last_upload_bytes`` / ``last_full_bytes`` record the saving."""
        from ..protocol.summary import (
            canonical_json,
            tree_to_incremental_obj,
            tree_to_obj,
        )

        tree = self.runtime.summarize()
        ref_seq = self.runtime.ref_seq
        if self.options.track_upload_ratio:
            # Telemetry denominator: serializing the FULL tree costs the
            # O(tree) encode the incremental path avoids — flip the option
            # off for very large documents.
            self.last_full_bytes = len(canonical_json(tree_to_obj(tree)))
        else:
            self.last_full_bytes = 0
        base = None
        has = getattr(self.storage, "has", None)
        upload_obj = getattr(self.storage, "upload_obj", None)
        if has is not None and upload_obj is not None \
                and self.last_ack_handle is not None \
                and has(self.last_ack_handle):
            base = self.storage.read(self.last_ack_handle)
        if base is not None:
            obj = tree_to_incremental_obj(tree, base)
            self.last_upload_bytes = len(canonical_json(obj))
            handle = upload_obj(self.doc_id, obj, ref_seq)
        else:
            # Driver storages without incremental support, or no usable
            # base: full upload.
            self.last_upload_bytes = self.last_full_bytes
            handle = self.storage.upload(self.doc_id, tree, ref_seq)
        self.summaries_written += 1
        try:
            self.runtime._service.submit(
                RawOperation(
                    client_id=self.runtime.client_id,
                    client_seq=self._next_summary_client_seq(),
                    ref_seq=ref_seq,
                    type=MessageType.SUMMARIZE,
                    contents={"handle": handle, "seq": ref_seq},
                )
            )
        except NackError:
            # The announcement was refused (throttle / retryAfter hold).
            # The uploaded tree is not lost — a later attempt re-announces;
            # count it as a nack so the retry follows the backoff window
            # instead of hot-looping inside the delivery observer.
            self.consecutive_nacks += 1
            self.nacks_received += 1
            self.ops_since_summary = 0
            return None
        return handle

    def _next_summary_client_seq(self) -> int:
        # Summary ops ride the same per-client sequence space as channel
        # ops so the sequencer's dedup floor stays consistent.
        self.runtime._client_seq += 1
        return self.runtime._client_seq
