"""Outbound op pipeline: batch compression and chunking.

Capability-equivalent of the reference's ``opLifecycle/`` (``OpCompressor``,
``OpSplitter``; SURVEY.md §2.1 container-runtime; upstream paths UNVERIFIED
— empty reference mount).  Wire forms of a flushed batch:

- ``{"type": "groupedBatch", "ops": [...], "idRange"?}``        — plain
- ``{"type": "compressedBatch", "data": <b64 zlib of plain>}``  — compressed
  when the plain encoding exceeds the compression threshold
- ``{"type": "chunk", "id", "index", "total", "data"}``         — N messages
  when the (possibly compressed) encoding exceeds the max message size;
  the batch is processed at the FINAL chunk's sequence number

Both the container runtime and the bulk catch-up service decode through
:func:`decode_contents` / :class:`ChunkReassembler` so the device replay
path folds exactly what clients fold."""

from __future__ import annotations

import base64
import json
import zlib
from typing import Dict, List, Optional

from ..protocol.summary import canonical_json

#: Wire-format version of batch envelopes.  Writers stamp it; readers
#: accept anything at or below (absent = 1, the pre-version format) and
#: refuse newer — a rolled-back replica must fail loudly, not misparse.
BATCH_WIRE_VERSION = 1


def check_batch_version(contents: dict) -> None:
    v = contents.get("v", 1)
    if v > BATCH_WIRE_VERSION:
        raise ValueError(
            f"batch wire version {v} is newer than supported "
            f"{BATCH_WIRE_VERSION}"
        )


def encode_batch(contents: dict, compression_threshold: int,
                 chunk_size: int) -> List[dict]:
    """One logical batch → the message contents list to submit (len 1
    unless chunked)."""
    payload = canonical_json(contents)
    if len(payload) >= compression_threshold:
        contents = {
            "type": "compressedBatch",
            "data": base64.b64encode(
                zlib.compress(payload, level=6)
            ).decode("ascii"),
        }
        payload = canonical_json(contents)
    if len(payload) < chunk_size:
        return [contents]
    # Slice the encoded BYTES (chunk_size bounds payload bytes regardless of
    # character width) and carry each slice base64'd — byte slices need not
    # fall on UTF-8 boundaries.
    pieces = [payload[i:i + chunk_size]
              for i in range(0, len(payload), chunk_size)]
    return [
        {"type": "chunk", "index": i, "total": len(pieces),
         "data": base64.b64encode(piece).decode("ascii")}
        for i, piece in enumerate(pieces)
    ]


def maybe_decompress(contents: dict) -> dict:
    if isinstance(contents, dict) \
            and contents.get("type") == "compressedBatch":
        return json.loads(zlib.decompress(
            base64.b64decode(contents["data"])
        ))
    return contents


class ChunkReassembler:
    """Per-client chunk accumulation (the receive side of OpSplitter)."""

    def __init__(self) -> None:
        self._partial: Dict[str, List[Optional[str]]] = {}

    def feed(self, client_id: str, chunk: dict) -> Optional[dict]:
        """Returns the reassembled (and decompressed) batch contents when
        the final chunk arrives, else None.

        Wire fields are untrusted: a chunk whose index/total is malformed
        or whose total disagrees with the client's partial train resets
        that client's state and is dropped — corrupting reassembly (or
        raising into the container) on a bad peer's message would take
        down good replicas."""
        total, index = chunk.get("total"), chunk.get("index")
        if (not isinstance(total, int) or not isinstance(index, int)
                or isinstance(total, bool) or isinstance(index, bool)
                or total < 1 or not 0 <= index < total):
            self._partial.pop(client_id, None)
            return None
        parts = self._partial.setdefault(client_id, [None] * total)
        if len(parts) != total:
            self._partial.pop(client_id, None)
            return None
        parts[index] = chunk["data"]
        if any(p is None for p in parts):
            return None
        del self._partial[client_id]
        payload = b"".join(base64.b64decode(p) for p in parts)
        return maybe_decompress(json.loads(payload))

    def drop(self, client_id: str) -> None:
        """A departed client's partial chunks can never complete."""
        self._partial.pop(client_id, None)


def decode_stream(messages):
    """Decode a sequenced message stream offline (catch-up service path):
    yields (msg, batch_contents) for every message that completes a logical
    batch — at the final chunk's seq for chunked batches."""
    import dataclasses

    reassembler = ChunkReassembler()
    for msg in messages:
        contents = msg.contents
        if not isinstance(contents, dict):
            continue
        if contents.get("type") == "chunk":
            full = reassembler.feed(msg.client_id, contents)
            if full is not None:
                yield dataclasses.replace(msg, contents=full), full
            continue
        contents = maybe_decompress(contents)
        if contents.get("type") == "groupedBatch":
            check_batch_version(contents)
            yield (msg if contents is msg.contents
                   else dataclasses.replace(msg, contents=contents)), contents
