"""The runtime shell: channel registry, datastores, container runtime,
summarization (SURVEY.md §2.1 layers 5–6).

The mock runtimes in ``fluidframework_tpu.testing`` remain the lightweight
harness for DDS-only tests; this package is the production-shaped stack the
loader/service layers drive."""

from .registry import ChannelFactory, ChannelRegistry, default_registry
from .datastore import ChannelDeltaConnection, FluidDataStoreRuntime
from .container import ContainerRuntime, OrderedClientElection
from .summarizer import SummarizerOptions, SummaryManager

__all__ = [
    "ChannelFactory",
    "ChannelRegistry",
    "default_registry",
    "ChannelDeltaConnection",
    "FluidDataStoreRuntime",
    "ContainerRuntime",
    "OrderedClientElection",
    "SummarizerOptions",
    "SummaryManager",
]
