"""Container runtime: datastore hosting, op batching, inbound processing.

Capability-equivalent of the reference's ``ContainerRuntime`` + ``Outbox``
+ ``BatchManager`` (SURVEY.md §2.1 container-runtime, §3.1 hot path;
upstream paths UNVERIFIED — empty reference mount):

- routes channel ops out through a **grouped-batch outbox**: ops accumulate
  and flush as ONE sequenced message carrying the batch (atomic delivery,
  one network round-trip per flush — the reference's grouped batching);
  each sub-op keeps its own client_seq so channel ack FIFOs match 1:1;
- processes inbound messages from an explicit queue (``drain()``), keeping
  optimistic-state windows testable — delivery timing is the caller's
  (DeltaManager's) concern, total order is the sequencer's;
- fans the (seq, min_seq) window out to every channel (zamboni plumbing);
- assembles the container summary tree (per-datastore subtrees + metadata)
  and loads from it.

The connection surface is deliberately thin — ``connect()`` takes anything
with ``submit(RawOperation)`` / ``subscribe(fn)`` (the in-proc Sequencer, a
LocalOrderer, or a driver's delta connection).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
from typing import Deque, Dict, List, Optional

from ..protocol.messages import MessageType, RawOperation, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .datastore import FluidDataStoreRuntime
from .id_compressor import IdCompressor
from .registry import ChannelRegistry, default_registry


class OrderedClientElection:
    """Oldest connected client wins (the reference's election rule).
    Membership is driven by the sequenced JOIN/LEAVE stream, so every
    client computes the same winner at the same fold position."""

    def __init__(self) -> None:
        self._order: List[str] = []

    def observe(self, msg: SequencedMessage) -> None:
        if msg.type is MessageType.JOIN:
            cid = msg.contents["clientId"]
            if cid not in self._order:
                self._order.append(cid)
        elif msg.type is MessageType.LEAVE:
            cid = msg.contents["clientId"]
            if cid in self._order:
                self._order.remove(cid)

    @property
    def elected(self) -> Optional[str]:
        return self._order[0] if self._order else None

    @property
    def quorum(self) -> List[str]:
        return list(self._order)


class ContainerRuntime:
    """The per-client runtime instance."""

    def __init__(self, registry: Optional[ChannelRegistry] = None) -> None:
        self.registry = registry if registry is not None else default_registry()
        self.datastores: Dict[str, FluidDataStoreRuntime] = {}
        self.client_id: Optional[str] = None
        self._service = None
        self.ref_seq = 0          # last processed seq
        self.min_seq = 0
        self._client_seq = 0      # runtime-level op counter (sub-op acks)
        self._client_ids: set = set()  # all ids this runtime has used
        self._inbound: Deque[SequencedMessage] = collections.deque()
        self._outbox: List[dict] = []
        self._batching = 0
        self.election = OrderedClientElection()  # quorum, join-ordered
        self.message_observers: List = []  # fn(msg) after each message
        # Distributed id compression: locals mint free; creation ranges
        # ride outbound batches and finalize identically on every client.
        self.id_compressor = IdCompressor()

    # -- datastores ------------------------------------------------------------

    def create_datastore(self, datastore_id: str) -> FluidDataStoreRuntime:
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} already exists")
        ds = FluidDataStoreRuntime(datastore_id, self.registry)
        ds._attach(self)
        self.datastores[datastore_id] = ds
        return ds

    def get_datastore(self, datastore_id: str) -> FluidDataStoreRuntime:
        return self.datastores[datastore_id]

    # -- connection ------------------------------------------------------------

    def connect(self, service, client_id: str) -> None:
        """Attach to an ordering service: anything with
        ``submit(RawOperation)`` and ``subscribe(fn)``.

        Subscribe-then-join: the live subscription starts first, the
        service's durable log backfills everything after our current
        sequence point (catch-up), and only then is the JOIN announced —
        so this client observes its own JOIN and every quorum event in
        order.  A runtime that ``load()``ed a summary first backfills just
        the tail."""
        self._service = service
        self.client_id = client_id
        self._client_ids.add(client_id)
        service.subscribe(self._inbound.append)
        log = getattr(service, "log", None)
        if log is not None:
            for msg in log:
                if msg.seq > self.ref_seq:
                    self._inbound.append(msg)
        if hasattr(service, "connect"):
            service.connect(client_id)
        for ds in self.datastores.values():
            ds._attach(self)

    @property
    def is_attached(self) -> bool:
        return self._service is not None

    # -- outbound: the outbox --------------------------------------------------

    def _submit_op(self, envelope: dict) -> int:
        """Called by datastores for each channel op; returns the sub-op
        client_seq the channel records for its ack FIFO."""
        self._client_seq += 1
        self._outbox.append(
            {"clientSeq": self._client_seq, **envelope}
        )
        if not self._batching:
            self.flush()
        return self._client_seq

    @contextlib.contextmanager
    def order_sequentially(self):
        """Batch every op submitted inside into one grouped message —
        atomic remote delivery (the reference's orderSequentially)."""
        self._batching += 1
        try:
            yield
        finally:
            self._batching -= 1
            if not self._batching:
                self.flush()

    def flush(self) -> None:
        if not self._outbox or self._service is None:
            return
        # A connection-aware service (DeltaManager) holds the outbox while
        # offline; ops ride out on the post-reconnect flush instead.
        if not getattr(self._service, "can_send", True):
            return
        batch, self._outbox = self._outbox, []
        contents = {"type": "groupedBatch", "ops": batch}
        id_range = self.id_compressor.take_next_creation_range()
        if id_range is not None:
            contents["idRange"] = id_range
        try:
            self._service.submit(
                RawOperation(
                    client_id=self.client_id,
                    client_seq=batch[0]["clientSeq"],
                    ref_seq=self.ref_seq,
                    type=MessageType.OP,
                    contents=contents,
                )
            )
        except BaseException:
            # A failed send must not lose the batch: the ops are still
            # optimistically applied locally and must resubmit eventually.
            self._outbox = batch + self._outbox
            raise

    # -- inbound ---------------------------------------------------------------

    @property
    def inbound_count(self) -> int:
        return len(self._inbound)

    def drain(self, count: Optional[int] = None) -> int:
        """Process queued inbound messages in order; returns how many."""
        n = 0
        while self._inbound and (count is None or n < count):
            self.process(self._inbound.popleft())
            n += 1
        return n

    def process(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.ref_seq:
            return  # tail overlapping a loaded summary / duplicate delivery
        self.ref_seq = max(self.ref_seq, msg.seq)
        self.min_seq = max(self.min_seq, msg.min_seq)
        self.election.observe(msg)
        if msg.type is MessageType.OP and isinstance(msg.contents, dict) \
                and msg.contents.get("type") == "groupedBatch":
            if "idRange" in msg.contents:
                self.id_compressor.finalize_range(msg.contents["idRange"])
            local = msg.client_id in self._client_ids
            for sub in msg.contents["ops"]:
                ds = self.datastores.get(sub["ds"])
                if ds is not None:
                    ds.process(
                        dataclasses.replace(msg, client_seq=sub["clientSeq"]),
                        sub, local,
                    )
        elif msg.type in (MessageType.JOIN, MessageType.LEAVE):
            # Consensus-style channels react to quorum membership (held
            # items / task assignments of a departed client re-queue).
            for ds in self.datastores.values():
                for channel in ds.channels.values():
                    observe = getattr(channel, "observe_protocol", None)
                    if observe is not None:
                        observe(msg)
        self._advance_all(msg.seq, msg.min_seq)
        for fn in list(self.message_observers):
            fn(msg)

    def _advance_all(self, seq: int, min_seq: int) -> None:
        for ds in self.datastores.values():
            ds.advance(seq, min_seq)

    # -- reconnect -------------------------------------------------------------

    def reconnect(self, service, client_id: str) -> None:
        """Catch-up-then-resubmit: the caller must first deliver (via the
        new service subscription or a log replay into ``process``) every
        message up to the head — acks for previously-sequenced pending ops
        land during that catch-up — then this resubmits the remainder."""
        self.connect(service, client_id)
        self.drain()
        for ds in self.datastores.values():
            ds.resubmit_pending()
        self.flush()

    # -- summaries -------------------------------------------------------------

    def summarize(self) -> SummaryTree:
        tree = SummaryTree()
        meta = {"seq": self.ref_seq, "minSeq": self.min_seq}
        tree.add_blob(".metadata", canonical_json(meta))
        # Protocol state: the quorum snapshot (new clients can't replay
        # pre-summary JOINs — the log below the summary is collectible).
        tree.add_blob(
            ".protocol", canonical_json({"quorum": self.election.quorum})
        )
        tree.add_blob(
            ".idCompressor", canonical_json(self.id_compressor.serialize())
        )
        ds_tree = tree.add_tree(".datastores")
        for ds_id in sorted(self.datastores):
            ds_tree.children[ds_id] = self.datastores[ds_id].summarize(
                self.min_seq
            )
        return tree

    def load(self, summary: SummaryTree) -> int:
        """Load from a summary; returns the summary's sequence point (the
        caller replays the op tail after it)."""
        meta = json.loads(summary.blob_bytes(".metadata"))
        self.ref_seq = meta["seq"]
        self.min_seq = meta["minSeq"]
        protocol = json.loads(summary.blob_bytes(".protocol"))
        self.election._order = list(protocol["quorum"])
        if ".idCompressor" in summary.children:
            self.id_compressor = IdCompressor.deserialize(
                json.loads(summary.blob_bytes(".idCompressor"))
            )
        self.datastores = {}
        ds_root = summary.get(".datastores")
        for ds_id, subtree in sorted(ds_root.children.items()):
            ds = FluidDataStoreRuntime(ds_id, self.registry)
            ds._attach(self)
            ds.load(subtree)
            self.datastores[ds_id] = ds
        return meta["seq"]
