"""Container runtime: datastore hosting, op batching, inbound processing.

Capability-equivalent of the reference's ``ContainerRuntime`` + ``Outbox``
+ ``BatchManager`` (SURVEY.md §2.1 container-runtime, §3.1 hot path;
upstream paths UNVERIFIED — empty reference mount):

- routes channel ops out through a **grouped-batch outbox**: ops accumulate
  and flush as ONE sequenced message carrying the batch (atomic delivery,
  one network round-trip per flush — the reference's grouped batching);
  each sub-op keeps its own client_seq so channel ack FIFOs match 1:1;
- processes inbound messages from an explicit queue (``drain()``), keeping
  optimistic-state windows testable — delivery timing is the caller's
  (DeltaManager's) concern, total order is the sequencer's;
- fans the (seq, min_seq) window out to every channel (zamboni plumbing);
- assembles the container summary tree (per-datastore subtrees + metadata)
  and loads from it.

The connection surface is deliberately thin — ``connect()`` takes anything
with ``submit(RawOperation)`` / ``subscribe(fn)`` (the in-proc Sequencer, a
LocalOrderer, or a driver's delta connection).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
from typing import Deque, Dict, List, Optional

import base64

from ..protocol.messages import MessageType, RawOperation, SequencedMessage
from ..protocol.quorum import QuorumProposals
from ..protocol.summary import SummaryTree, canonical_json
from .attributor import Attributor
from .blobs import BlobManager
from .datastore import FluidDataStoreRuntime
from .gc import GarbageCollector, GCOptions
from .id_compressor import IdCompressor
from .op_pipeline import (
    BATCH_WIRE_VERSION,
    ChunkReassembler,
    check_batch_version,
    encode_batch,
    maybe_decompress,
)
from .registry import ChannelRegistry, default_registry


@dataclasses.dataclass
class ContainerRuntimeOptions:
    """Typed runtime options (the reference's IContainerRuntimeOptions
    capability: compression, chunking, GC switches)."""

    #: compress batches whose canonical encoding reaches this many bytes
    compression_threshold: int = 64 * 1024
    #: split encoded batches into chunks below this many bytes
    chunk_size: int = 768 * 1024
    gc: GCOptions = dataclasses.field(default_factory=GCOptions)
    #: op attribution (SURVEY §1 layer 8, upstream enableRuntimeAttribution):
    #: a DOCUMENT-level choice stamped into .metadata at creation so every
    #: replica agrees — mixed on/off replicas would diverge on summary
    #: bytes.  Loading adopts the document's stamp over this option.
    attribution: bool = False


class OrderedClientElection:
    """Oldest connected client wins (the reference's election rule).
    Membership is driven by the sequenced JOIN/LEAVE stream, so every
    client computes the same winner at the same fold position."""

    def __init__(self) -> None:
        self._order: List[str] = []

    def observe(self, msg: SequencedMessage) -> None:
        if msg.type is MessageType.JOIN:
            cid = msg.contents["clientId"]
            if cid not in self._order:
                self._order.append(cid)
        elif msg.type is MessageType.LEAVE:
            cid = msg.contents["clientId"]
            if cid in self._order:
                self._order.remove(cid)

    @property
    def elected(self) -> Optional[str]:
        return self._order[0] if self._order else None

    @property
    def quorum(self) -> List[str]:
        return list(self._order)


class ContainerRuntime:
    """The per-client runtime instance."""

    def __init__(self, registry: Optional[ChannelRegistry] = None,
                 options: Optional[ContainerRuntimeOptions] = None) -> None:
        self.registry = registry if registry is not None else default_registry()
        self.options = options or ContainerRuntimeOptions()
        self.datastores: Dict[str, FluidDataStoreRuntime] = {}
        self.client_id: Optional[str] = None
        self._service = None
        self.ref_seq = 0          # last processed seq
        self.min_seq = 0
        self._client_seq = 0      # runtime-level op counter (sub-op acks)
        self._client_ids: set = set()  # all ids this runtime has used
        # Rehydrate adoption (the reference's PendingStateManager): wire
        # copies of a crashed session's ops are OUR acks, but they carry
        # that session's client ids and clientSeqs — this map translates
        # (old client id, old clientSeq) to the clientSeq the re-applied
        # op got in THIS runtime, so channel ack FIFOs match.
        self._adopted_ids: set = set()
        self._adopted_acks: Dict[tuple, int] = {}
        self._inbound: Deque[SequencedMessage] = collections.deque()
        self._outbox: List[dict] = []
        self._batching = 0
        self.election = OrderedClientElection()  # quorum, join-ordered
        # Propose/accept protocol state (code details etc.): pending until
        # the MSN passes the proposal seq, then committed — identically on
        # every replica (protocol/quorum.py).
        self.quorum_proposals = QuorumProposals()
        self.message_observers: List = []  # fn(msg) after each message
        # Distributed id compression: locals mint free; creation ranges
        # ride outbound batches and finalize identically on every client.
        self.id_compressor = IdCompressor()
        # Op attribution (SURVEY §1 layer 8): seq -> (user, timestamp),
        # summarized columnar, resolved from DDS reads via seq stamps.
        # Enabled per-DOCUMENT (options at create; the .metadata stamp on
        # load) — see ContainerRuntimeOptions.attribution.
        self.attribution_enabled = self.options.attribution
        self.attributor = Attributor()
        self.blob_manager = BlobManager(self)
        self.gc = GarbageCollector(self, self.options.gc)
        self._chunks = ChunkReassembler()
        # Encoded wire messages not yet accepted by the service: a failed
        # send resumes HERE (same bytes, same client_seqs) so partially-
        # delivered chunk trains and consumed idRanges are never re-encoded.
        # Entries are (raw_op, first_gen): first_gen is the idRange start a
        # batch's lead message carries (None otherwise) so a discarded
        # unsent batch can roll its range back into the compressor.
        self._pending_wire: List[tuple] = []
        # Runtime meta-ops (dsAttach/channelAttach/blobAttach/gcSweep)
        # awaiting their sequenced echo — resubmitted on reconnect like
        # channel ops (they'd otherwise be lost with the cleared outbox).
        self._pending_runtime: Dict[int, dict] = {}

    # -- datastores ------------------------------------------------------------

    def create_datastore(self, datastore_id: str,
                         rooted: bool = True) -> FluidDataStoreRuntime:
        """``rooted=False`` datastores survive only while some rooted
        datastore's channels hold a ``fluidHandle`` to them (GC sweeps the
        rest)."""
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} already exists")
        ds = FluidDataStoreRuntime(datastore_id, self.registry, rooted=rooted)
        ds._attach(self)
        self.datastores[datastore_id] = ds
        if self.is_attached and self.client_id is not None:
            # Live creation: announce so every replica materializes it.
            self._submit_runtime_op({
                "runtime": "dsAttach",
                "ds": datastore_id,
                "rooted": rooted,
            })
        return ds

    def get_datastore(self, datastore_id: str) -> FluidDataStoreRuntime:
        return self.datastores[datastore_id]

    # -- connection ------------------------------------------------------------

    def connect(self, service, client_id: str) -> None:
        """Attach to an ordering service: anything with
        ``submit(RawOperation)`` and ``subscribe(fn)``.

        Subscribe-then-join: the live subscription starts first, the
        service's durable log backfills everything after our current
        sequence point (catch-up), and only then is the JOIN announced —
        so this client observes its own JOIN and every quorum event in
        order.  A runtime that ``load()``ed a summary first backfills just
        the tail."""
        self._service = service
        self.client_id = client_id
        self._client_ids.add(client_id)
        service.subscribe(self._inbound.append)
        log = getattr(service, "log", None)
        if log is not None:
            for msg in log:
                if msg.seq > self.ref_seq:
                    self._inbound.append(msg)
        if hasattr(service, "connect"):
            service.connect(client_id)
        for ds in self.datastores.values():
            ds._attach(self)

    @property
    def is_attached(self) -> bool:
        return self._service is not None

    # -- outbound: the outbox --------------------------------------------------

    def _submit_op(self, envelope: dict, ref_seq=None) -> int:
        """Called by datastores for each channel op; returns the sub-op
        client_seq the channel records for its ack FIFO.  Each sub-op
        carries the view (refSeq) it was authored against — resubmitted
        ops pin their original view so position contents stay correct."""
        self._client_seq += 1
        client_seq = self._client_seq  # flush below may advance the counter
        self._outbox.append(
            {"clientSeq": client_seq,
             "refSeq": self.ref_seq if ref_seq is None else ref_seq,
             **envelope}
        )
        if not self._batching:
            self.flush()
        return client_seq

    @contextlib.contextmanager
    def order_sequentially(self):
        """Batch every op submitted inside into one grouped message —
        atomic remote delivery (the reference's orderSequentially)."""
        self._batching += 1
        try:
            yield
        finally:
            self._batching -= 1
            if not self._batching:
                self.flush()

    def flush(self) -> None:
        if self._service is None:
            return
        # A connection-aware service (DeltaManager) holds the outbox while
        # offline; ops ride out on the post-reconnect flush instead.
        if not getattr(self._service, "can_send", True):
            return
        # Resume any wire messages a previous failed flush left behind —
        # identical bytes, so receivers' chunk reassembly stays coherent
        # and already-taken idRanges are preserved.
        self._drain_wire()
        if not self._outbox:
            return
        batch, self._outbox = self._outbox, []
        contents = {"type": "groupedBatch", "v": BATCH_WIRE_VERSION,
                    "ops": batch}
        id_range = self.id_compressor.take_next_creation_range()
        if id_range is not None:
            contents["idRange"] = id_range
        for i, wire_contents in enumerate(
                encode_batch(contents, self.options.compression_threshold,
                             self.options.chunk_size)):
            if i == 0:
                client_seq = batch[0]["clientSeq"]
                first_gen = id_range["firstGen"] if id_range else None
            else:
                # Extra chunk messages ride fresh runtime client_seqs
                # (the sequencer dedups per message).
                self._client_seq += 1
                client_seq = self._client_seq
                first_gen = None
            self._pending_wire.append((
                RawOperation(
                    client_id=self.client_id,
                    client_seq=client_seq,
                    ref_seq=self.ref_seq,
                    type=MessageType.OP,
                    contents=wire_contents,
                ),
                first_gen,
            ))
        self._drain_wire()

    def _drain_wire(self) -> None:
        while self._pending_wire:
            try:
                self._service.submit(self._pending_wire[0][0])
            except (ConnectionError, TimeoutError, OSError):
                # Transient transport failure: the encoded messages stay
                # queued (identical bytes, same client_seqs) and the next
                # flush resumes the send — the submitter's pending-op
                # bookkeeping must not unwind for a retryable error.
                return
            self._pending_wire.pop(0)  # only after the send was accepted

    def discard_outbound(self) -> None:
        """Drop the held outbox and unsent wire messages (reconnect /
        rehydrate — resubmit re-issues everything), rolling any idRanges
        the discarded batches consumed back into the compressor so the
        next flush re-attaches those locals."""
        gens = [g for _op, g in self._pending_wire if g is not None]
        if gens:
            self.id_compressor.rollback_ranges(min(gens))
        self._pending_wire.clear()
        self._outbox.clear()

    def perform_gc_sweep(self) -> List[str]:
        """Submit a sequenced sweep for datastores whose unreferenced grace
        has expired.  Deletion happens when the op folds — at the same
        position on every replica (summarize() itself never mutates).
        Returns the ids proposed for sweeping."""
        ready = self.gc.sweep_ready(self.ref_seq)
        if ready and self._service is not None:
            self._submit_runtime_op({"runtime": "gcSweep", "ids": ready})
        return ready

    def _submit_runtime_op(self, envelope: dict) -> None:
        """Runtime meta-op: rides the outbox like channel ops, tracked for
        resubmit-on-reconnect until its sequenced echo arrives."""
        self._client_seq += 1
        self._outbox.append({"clientSeq": self._client_seq, **envelope})
        self._pending_runtime[self._client_seq] = envelope
        if not self._batching:
            self.flush()

    def resubmit_pending_runtime_ops(self) -> None:
        """Reconnect: re-issue unacked meta-ops in original order (before
        channel resubmits — attaches must precede their channels' ops).
        Receivers treat every meta-op idempotently, so a duplicate from a
        sequenced-but-unacked original is harmless."""
        pending = sorted(self._pending_runtime.items())
        self._pending_runtime.clear()
        for _old_seq, envelope in pending:
            self._submit_runtime_op(envelope)

    def _submit_channel_attach(self, ds_id: str, channel_id: str,
                               type_name: str) -> None:
        self._submit_runtime_op({
            "runtime": "channelAttach",
            "ds": ds_id,
            "channel": channel_id,
            "channelType": type_name,
        })

    def _submit_blob_attach(self, sha: str, content: bytes) -> None:
        """Replicate an attachment blob (BlobManager upload path)."""
        if self._service is None:
            return  # detached: the blob rides the attach summary
        self._submit_runtime_op({
            "runtime": "blobAttach",
            "sha": sha,
            "data": base64.b64encode(content).decode("ascii"),
        })

    # -- inbound ---------------------------------------------------------------

    @property
    def inbound_count(self) -> int:
        return len(self._inbound)

    def drain(self, count: Optional[int] = None) -> int:
        """Process queued inbound messages in order; returns how many."""
        n = 0
        while self._inbound and (count is None or n < count):
            self.process(self._inbound.popleft())
            n += 1
        return n

    def adopt_stashed_session(self, old_ids, aliases: Dict[tuple, int]
                              ) -> None:
        """Adopt a crashed session's identities: messages from ``old_ids``
        become local, and ``aliases`` ((old client id, old clientSeq) ->
        this runtime's clientSeq) routes their channel acks to the
        re-applied ops.  ``aliases`` is held BY REFERENCE — the rehydrate
        replay fills it incrementally while draining the tail, so copies
        would miss entries."""
        self._adopted_ids.update(old_ids)
        self._client_ids.update(old_ids)
        if self._adopted_acks:
            # Repeated adoption: fold the existing entries INTO the new
            # live dict and track that one — updating the old dict would
            # snapshot `aliases` and miss entries the replay adds later.
            aliases.update(self._adopted_acks)
        self._adopted_acks = aliases

    def process(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.ref_seq:
            return  # tail overlapping a loaded summary / duplicate delivery
        self.ref_seq = max(self.ref_seq, msg.seq)
        self.min_seq = max(self.min_seq, msg.min_seq)
        self.election.observe(msg)
        self.quorum_proposals.observe(msg)
        contents = msg.contents
        if msg.type is MessageType.OP and isinstance(contents, dict):
            if contents.get("type") == "chunk":
                # Partial chunks still advance the window; the batch
                # processes at the FINAL chunk's sequence number.
                contents = self._chunks.feed(msg.client_id, contents)
            else:
                contents = maybe_decompress(contents)
        # Attribute AFTER chunk reassembly: only the final chunk's seq is
        # ever stamped on DDS state — recording partial-chunk seqs would
        # store rows nothing can resolve, in every future summary.
        if self.attribution_enabled and contents is not None:
            self.attributor.observe(msg)
        if msg.type is MessageType.OP and isinstance(contents, dict) \
                and contents.get("type") == "groupedBatch":
            check_batch_version(contents)
            if "idRange" in contents:
                self.id_compressor.finalize_range(contents["idRange"])
            local = msg.client_id in self._client_ids
            for sub in contents["ops"]:
                if local and "runtime" in sub:
                    self._pending_runtime.pop(sub["clientSeq"], None)
                if sub.get("runtime") == "blobAttach":
                    self.blob_manager.process_attach(sub["sha"], sub["data"])
                    continue
                if sub.get("runtime") == "gcSweep":
                    self.gc.apply_sweep(sub["ids"])
                    continue
                if sub.get("runtime") == "dsAttach":
                    existing = self.datastores.get(sub["ds"])
                    if existing is None:
                        ds = FluidDataStoreRuntime(
                            sub["ds"], self.registry,
                            rooted=sub.get("rooted", True),
                        )
                        ds._attach(self)
                        self.datastores[sub["ds"]] = ds
                    elif existing.rooted != sub.get("rooted", True):
                        # Two clients created the same id with different
                        # GC rootedness: an app-level id collision — fail
                        # loudly rather than letting GC diverge.
                        raise RuntimeError(
                            f"conflicting dsAttach for {sub['ds']!r}: "
                            f"rooted={existing.rooted} vs "
                            f"{sub.get('rooted', True)}"
                        )
                    continue
                if sub.get("runtime") == "channelAttach":
                    ds = self.datastores.get(sub["ds"])
                    if ds is not None:
                        ds._materialize_remote_channel(
                            sub["channelType"], sub["channel"]
                        )
                    continue
                ds = self.datastores.get(sub["ds"])
                if ds is not None:
                    sub_cs = sub["clientSeq"]
                    sub_local = local
                    if msg.client_id in self._adopted_ids:
                        translated = self._adopted_acks.get(
                            (msg.client_id, sub_cs)
                        )
                        if translated is None:
                            # Adopted-session op with no re-applied
                            # counterpart (shouldn't occur for channel
                            # ops): apply as remote rather than tripping
                            # an ack FIFO it was never entered into.
                            sub_local = False
                        else:
                            sub_cs = translated
                    ds.process(
                        dataclasses.replace(
                            msg,
                            client_seq=sub_cs,
                            ref_seq=sub.get("refSeq", msg.ref_seq),
                        ),
                        sub, sub_local,
                    )
        elif msg.type in (MessageType.JOIN, MessageType.LEAVE):
            # Consensus-style channels react to quorum membership (held
            # items / task assignments of a departed client re-queue).
            if msg.type is MessageType.LEAVE:
                self._chunks.drop(msg.contents["clientId"])
            for ds in self.datastores.values():
                for channel in ds.channels.values():
                    observe = getattr(channel, "observe_protocol", None)
                    if observe is not None:
                        observe(msg)
        self._advance_all(msg.seq, msg.min_seq)
        for fn in list(self.message_observers):
            fn(msg)

    def _advance_all(self, seq: int, min_seq: int) -> None:
        for ds in self.datastores.values():
            ds.advance(seq, min_seq)

    # -- reconnect -------------------------------------------------------------

    def reconnect(self, service, client_id: str) -> None:
        """Catch-up-then-resubmit: the caller must first deliver (via the
        new service subscription or a log replay into ``process``) every
        message up to the head — acks for previously-sequenced pending ops
        land during that catch-up — then this resubmits the remainder."""
        self.connect(service, client_id)
        self.drain()
        for ds in self.datastores.values():
            ds.resubmit_pending()
        self.flush()

    # -- quorum proposals ------------------------------------------------------

    def propose(self, key: str, value) -> None:
        """Submit a quorum proposal (code details etc.).  It sequences like
        any op, stays pending until the MSN passes its seq, then commits on
        every replica (``quorum_proposals.get(key)``).  An unsequenced
        proposal dropped by a reconnect is NOT resubmitted — proposals are
        idempotent to re-propose, and the reference likewise rejects
        in-flight proposals on connection loss.

        client_seq ordering: the outbox flushes FIRST so held channel ops
        take their (lower) client_seqs before the proposal — a proposal
        jumping the queue would advance the sequencer's dedup floor and
        silently drop the later batch flush.  For the same reason proposing
        inside ``order_sequentially`` or while unable to send refuses."""
        if self._service is None or self.client_id is None:
            raise RuntimeError("propose requires a connected container")
        if self._batching:
            raise RuntimeError("cannot propose inside order_sequentially")
        if not getattr(self._service, "can_send", True):
            raise ConnectionError(
                "cannot propose while disconnected or read-only"
            )
        self.flush()
        self._client_seq += 1
        raw = RawOperation(
            client_id=self.client_id,
            client_seq=self._client_seq,
            ref_seq=self.ref_seq,
            type=MessageType.PROPOSAL,
            contents={"key": key, "value": value},
        )
        self._pending_wire.append((raw, None))
        self._drain_wire()

    # -- summaries -------------------------------------------------------------

    #: Container summary FORMAT version: readers accept at-or-below
    #: (absent = 1) and refuse newer — see load().
    SUMMARY_FORMAT_VERSION = 1

    @staticmethod
    def container_metadata(seq: int, min_seq: int,
                           attribution: bool = False) -> dict:
        """The .metadata blob content — ONE construction point shared with
        the catch-up service (their root digests must stay identical)."""
        meta = {"seq": seq, "minSeq": min_seq,
                "format": ContainerRuntime.SUMMARY_FORMAT_VERSION}
        if attribution:
            meta["attribution"] = True  # absent = off (legacy bytes stable)
        return meta

    def summarize(self) -> SummaryTree:
        tree = SummaryTree()
        meta = self.container_metadata(self.ref_seq, self.min_seq,
                                       attribution=self.attribution_enabled)
        tree.add_blob(".metadata", canonical_json(meta))
        # Protocol state: quorum membership + propose/accept state (new
        # pre-summary JOINs — the log below the summary is collectible).
        tree.add_blob(
            ".protocol", canonical_json({
                "proposals": self.quorum_proposals.serialize(),
                "quorum": self.election.quorum,
            })
        )
        tree.add_blob(
            ".idCompressor", canonical_json(self.id_compressor.serialize())
        )
        if self.attribution_enabled:
            tree.add_blob(
                ".attribution", canonical_json(self.attributor.serialize())
            )
        ds_summaries = {
            ds_id: self.datastores[ds_id].summarize(self.min_seq)
            for ds_id in sorted(self.datastores)
        }
        # GC stamping over sequenced state at the summary point: identical
        # for any replica summarizing at the same seq with the same
        # inherited gc state (single-writer summarizer model).  Sweeping is
        # NOT done here — see perform_gc_sweep().
        gc_state = self.gc.run(ds_summaries, self.ref_seq)
        tree.add_blob(".gc", canonical_json(gc_state))
        tree.children[".blobs"] = self.blob_manager.summarize(
            self.gc.surviving_blob_shas(self.ref_seq)
        )
        ds_tree = tree.add_tree(".datastores")
        for ds_id in sorted(ds_summaries):
            ds_tree.children[ds_id] = ds_summaries[ds_id]
        return tree

    def load(self, summary: SummaryTree) -> int:
        """Load from a summary; returns the summary's sequence point (the
        caller replays the op tail after it)."""
        meta = json.loads(summary.blob_bytes(".metadata"))
        fmt = meta.get("format", 1)  # absent = the pre-version format
        if fmt > self.SUMMARY_FORMAT_VERSION:
            raise ValueError(
                f"summary format {fmt} is newer than supported "
                f"{self.SUMMARY_FORMAT_VERSION}"
            )
        self.ref_seq = meta["seq"]
        self.min_seq = meta["minSeq"]
        protocol = json.loads(summary.blob_bytes(".protocol"))
        self.election._order = list(protocol["quorum"])
        # Missing key = an N-1 summary written before proposals existed.
        self.quorum_proposals = QuorumProposals.deserialize(
            protocol.get("proposals")
        )
        if ".idCompressor" in summary.children:
            self.id_compressor = IdCompressor.deserialize(
                json.loads(summary.blob_bytes(".idCompressor"))
            )
        # The DOCUMENT decides attribution (metadata stamp beats local
        # options — mixed on/off replicas would diverge on summary bytes).
        # Missing blob = a pre-attribution or attribution-off summary:
        # start empty (reads on older content return None, never lie).
        self.attribution_enabled = bool(meta.get("attribution", False))
        self.attributor = Attributor.deserialize(
            json.loads(summary.blob_bytes(".attribution"))
            if ".attribution" in summary.children else None
        )
        if ".gc" in summary.children:
            self.gc.load_state(json.loads(summary.blob_bytes(".gc")))
        if ".blobs" in summary.children:
            self.blob_manager.load(summary.get(".blobs"))
        self.datastores = {}
        ds_root = summary.get(".datastores")
        for ds_id, subtree in sorted(ds_root.children.items()):
            ds = FluidDataStoreRuntime(ds_id, self.registry)
            ds._attach(self)
            ds.load(subtree)
            self.datastores[ds_id] = ds
        return meta["seq"]
