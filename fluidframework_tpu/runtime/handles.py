"""Fluid-handle equivalents: serializable references between stored values
and datastores/channels/blobs.

Capability-equivalent of the reference's ``IFluidHandle`` + handle
serialization in shared-object-base (SURVEY.md §2.1; upstream paths
UNVERIFIED — empty reference mount).  A handle is a plain JSON token so it
survives any channel's value encoding:

    {"fluidHandle": "/<datastore>/<channel>"}     — a channel reference
    {"fluidBlob": "<sha256>"}                     — an attachment blob

The GC walks these tokens through summary bytes (format-agnostic: any DDS
that stores values as canonical JSON is scannable without per-DDS code).
"""

from __future__ import annotations

import re
from typing import List, Set

HANDLE_KEY = "fluidHandle"
BLOB_KEY = "fluidBlob"

_HANDLE_RE = re.compile(rb'"fluidHandle":"(/[^"]+)"')
_BLOB_RE = re.compile(rb'"fluidBlob":"([0-9a-f]{64})"')


def channel_handle(ds_id: str, channel_id: str) -> dict:
    return {HANDLE_KEY: f"/{ds_id}/{channel_id}"}


def datastore_handle(ds_id: str) -> dict:
    return {HANDLE_KEY: f"/{ds_id}"}


def blob_handle(sha: str) -> dict:
    return {BLOB_KEY: sha}


def is_handle(value) -> bool:
    return isinstance(value, dict) and (HANDLE_KEY in value
                                        or BLOB_KEY in value)


def scan_handles(blob: bytes) -> List[str]:
    """All datastore/channel handle paths referenced in serialized bytes."""
    return [m.decode("utf-8") for m in _HANDLE_RE.findall(blob)]


def scan_blob_refs(blob: bytes) -> Set[str]:
    """All attachment-blob shas referenced in serialized bytes."""
    return {m.decode("ascii") for m in _BLOB_RE.findall(blob)}
