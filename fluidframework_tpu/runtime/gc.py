"""Garbage collection: mark-and-sweep over the handle graph.

Capability-equivalent of the reference's ``GarbageCollector``
(container-runtime ``gc/``; SURVEY.md §2.1; upstream paths UNVERIFIED —
empty reference mount), adapted to this runtime's summary model:

- **Mark**: reachability over datastores, walked from the *root set*
  (datastores flagged rooted at creation) through
  ``{"fluidHandle": "/ds[/channel]"}`` tokens found in channel summary
  bytes.  Scanning serialized summaries makes marking format-agnostic.
- **Unreferenced tracking** (run at summarize time, mutating only GC
  bookkeeping — never live runtime state): a datastore/blob that falls out
  of the reachable set is stamped ``unreferencedAtSeq``; reachability
  again clears the stamp (inactive→revived).  Stamps ride the summary.
- **Sweep** is a *sequenced runtime op*: when a stamp has outlived
  ``sweep_grace_ops``, ``ContainerRuntime.perform_gc_sweep()`` submits
  ``{"runtime": "gcSweep", "ids": [...]}``; every replica deletes the
  datastores at the same fold position — summarizing never mutates
  replica state, and a nacked summary can't orphan the summarizer
  (review-found).
- **Attachment blobs** get the same grace: an unreferenced blob's bytes
  stay in summaries until its stamp expires, so a reference written in
  the post-summary op tail still resolves (review-found: zero-grace
  dropped bytes a later-sequenced handle needed).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from ..protocol.summary import SummaryTree
from .handles import scan_blob_refs, scan_handles


@dataclasses.dataclass
class GCOptions:
    enabled: bool = True
    #: sequenced ops an unreferenced datastore/blob survives before sweep
    sweep_grace_ops: int = 200


class GarbageCollector:
    """Mark + stamp + sweep bookkeeping; one per container runtime."""

    def __init__(self, runtime, options: Optional[GCOptions] = None) -> None:
        self.runtime = runtime
        self.options = options or GCOptions()
        # ds_id -> seq at which it became unreferenced
        self.unreferenced_at: Dict[str, int] = {}
        # blob sha -> seq at which it became unreferenced
        self.blob_unreferenced_at: Dict[str, int] = {}
        self.swept: List[str] = []

    # -- the mark phase --------------------------------------------------------

    def _reachable(self, ds_summaries: Dict[str, SummaryTree]) -> Set[str]:
        """Datastores reachable from the root set via handle tokens."""
        roots = {ds_id for ds_id, ds in self.runtime.datastores.items()
                 if getattr(ds, "rooted", True)}
        edges: Dict[str, Set[str]] = {}
        for ds_id, tree in ds_summaries.items():
            refs: Set[str] = set()
            for blob in _walk_blobs(tree):
                for path in scan_handles(blob):
                    refs.add(path.lstrip("/").split("/")[0])
            edges[ds_id] = refs
        seen: Set[str] = set()
        frontier = [r for r in roots if r in ds_summaries]
        while frontier:
            ds_id = frontier.pop()
            if ds_id in seen:
                continue
            seen.add(ds_id)
            frontier.extend(t for t in edges.get(ds_id, ())
                            if t in ds_summaries and t not in seen)
        return seen

    def referenced_blob_shas(
        self, ds_summaries: Dict[str, SummaryTree]
    ) -> Set[str]:
        shas: Set[str] = set()
        for tree in ds_summaries.values():
            shas |= scan_blob_refs(_walk_concat(tree))
        return shas

    # -- stamp update at summarize time (GC bookkeeping only) ------------------

    def run(self, ds_summaries: Dict[str, SummaryTree],
            current_seq: int) -> dict:
        """Refresh unreferenced stamps; returns the serializable gc state.
        Never touches live runtime state — sweeping is a sequenced op."""
        if self.options.enabled:
            reachable = self._reachable(ds_summaries)
            for ds_id in ds_summaries:
                if ds_id in reachable:
                    self.unreferenced_at.pop(ds_id, None)
                else:
                    self.unreferenced_at.setdefault(ds_id, current_seq)
            referenced = self.referenced_blob_shas(ds_summaries)
            for sha in self.runtime.blob_manager.shas():
                if sha in referenced:
                    self.blob_unreferenced_at.pop(sha, None)
                else:
                    self.blob_unreferenced_at.setdefault(sha, current_seq)
        return {
            "swept": sorted(self.swept),
            "unreferenced": {k: self.unreferenced_at[k]
                             for k in sorted(self.unreferenced_at)},
            "unreferencedBlobs": {
                k: self.blob_unreferenced_at[k]
                for k in sorted(self.blob_unreferenced_at)
            },
        }

    @staticmethod
    def empty_state() -> dict:
        return {"swept": [], "unreferenced": {}, "unreferencedBlobs": {}}

    # -- sweep readiness / execution -------------------------------------------

    def sweep_ready(self, current_seq: int) -> List[str]:
        grace = self.options.sweep_grace_ops
        return sorted(ds_id for ds_id, since in self.unreferenced_at.items()
                      if current_seq - since >= grace)

    def apply_sweep(self, ds_ids: List[str]) -> None:
        """The sequenced gcSweep op: identical fold position everywhere."""
        for ds_id in ds_ids:
            self.runtime.datastores.pop(ds_id, None)
            self.unreferenced_at.pop(ds_id, None)
            if ds_id not in self.swept:
                self.swept.append(ds_id)

    def surviving_blob_shas(self, current_seq: int) -> Set[str]:
        """Blobs that belong in the summary: referenced, or unreferenced
        but still inside the grace window."""
        grace = self.options.sweep_grace_ops
        return {
            sha for sha in self.runtime.blob_manager.shas()
            if current_seq - self.blob_unreferenced_at.get(sha, current_seq)
            < grace
        }

    # -- persistence -----------------------------------------------------------

    def load_state(self, state: dict) -> None:
        self.unreferenced_at = dict(state.get("unreferenced", {}))
        self.blob_unreferenced_at = dict(state.get("unreferencedBlobs", {}))
        self.swept = list(state.get("swept", []))


def _walk_blobs(tree: SummaryTree):
    for child in tree.children.values():
        if isinstance(child, SummaryTree):
            yield from _walk_blobs(child)
        else:
            yield child.content


def _walk_concat(tree: SummaryTree) -> bytes:
    return b"\x00".join(_walk_blobs(tree))
