"""The channel-factory registry — the framework's plugin boundary.

Capability-equivalent of the reference's ``IChannelFactory`` (SURVEY.md §2.1
datastore: "the north-star plugin boundary"; upstream paths UNVERIFIED —
empty reference mount).  A factory knows how to ``create`` an empty channel
of its type and ``load`` one from a summary subtree; the registry maps the
wire-level type string (stored in each channel's attributes blob) to its
factory.  The ``*-tpu`` variants registered by default are the DDSes whose
catch-up replay routes through the device kernels."""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..dds.shared_object import SharedObject
from ..protocol.summary import SummaryTree


class ChannelFactory:
    """Creates/loads channels of one type."""

    def __init__(self, type_name: str,
                 ctor: Callable[[str], SharedObject]) -> None:
        self.type = type_name
        self._ctor = ctor

    def create(self, channel_id: str) -> SharedObject:
        return self._ctor(channel_id)

    def load(self, channel_id: str, summary: SummaryTree) -> SharedObject:
        channel = self._ctor(channel_id)
        channel.load(summary)
        return channel


class ChannelRegistry:
    """type string → factory."""

    def __init__(self) -> None:
        self._factories: Dict[str, ChannelFactory] = {}

    def register(self, factory: ChannelFactory) -> "ChannelRegistry":
        self._factories[factory.type] = factory
        return self

    def register_type(self, cls) -> "ChannelRegistry":
        """Register a SharedObject subclass by its TYPE attribute."""
        return self.register(ChannelFactory(cls.TYPE, cls))

    def get(self, type_name: str) -> ChannelFactory:
        factory = self._factories.get(type_name)
        if factory is None:
            raise KeyError(f"no channel factory for type {type_name!r}")
        return factory

    def types(self):
        return sorted(self._factories)


def default_registry() -> ChannelRegistry:
    """All built-in ``*-tpu`` channel types."""
    from ..dds.cell_counter import SharedCell, SharedCounter
    from ..dds.consensus import (
        ConsensusQueue,
        ConsensusRegisterCollection,
        TaskManager,
    )
    from ..dds.map import SharedDirectory, SharedMap
    from ..dds.matrix import SharedMatrix
    from ..dds.sequence import SharedString
    from ..dds.tree import SharedTree

    registry = ChannelRegistry()
    for cls in (SharedMap, SharedDirectory, SharedString, SharedMatrix,
                SharedTree, SharedCell, SharedCounter, ConsensusQueue,
                ConsensusRegisterCollection, TaskManager):
        registry.register_type(cls)
    return registry
