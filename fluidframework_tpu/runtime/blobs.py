"""Attachment blobs: out-of-band binary payloads referenced by handle.

Capability-equivalent of the reference's ``BlobManager``
(container-runtime; SURVEY.md §2.1; upstream paths UNVERIFIED — empty
reference mount): large binary values (images, files) are not DDS ops —
they are content-addressed attachments uploaded once and referenced from
DDS values via ``{"fluidBlob": "<sha>"}`` handles.

Deviation from the reference, on purpose: the reference uploads blobs to
storage out-of-band and carries a BlobAttach op; in-proc the blob payload
rides the summary's ``.blobs`` subtree (content-addressed, so incremental
summaries dedup it) and a sequenced attach op replicates the bytes to all
clients immediately.  Unreferenced blobs are dropped at summarize time by
the GC scan."""

from __future__ import annotations

import base64
import hashlib
from typing import Dict, Set

from ..protocol.summary import SummaryTree
from .handles import blob_handle


class BlobManager:
    """Content-addressed attachment store, replicated via sequenced ops."""

    def __init__(self, runtime) -> None:
        self.runtime = runtime
        self._blobs: Dict[str, bytes] = {}

    def create_blob(self, content: bytes) -> dict:
        """Store + replicate; returns the ``{"fluidBlob": sha}`` handle to
        embed in DDS values."""
        sha = hashlib.sha256(content).hexdigest()
        if sha not in self._blobs:
            self._blobs[sha] = content
            self.runtime._submit_blob_attach(sha, content)
        return blob_handle(sha)

    def get_blob(self, handle_or_sha) -> bytes:
        sha = handle_or_sha.get("fluidBlob") \
            if isinstance(handle_or_sha, dict) else handle_or_sha
        return self._blobs[sha]

    def has_blob(self, sha: str) -> bool:
        return sha in self._blobs

    def shas(self):
        return self._blobs.keys()

    def process_attach(self, sha: str, content_b64: str) -> None:
        self._blobs.setdefault(sha, base64.b64decode(content_b64))

    # -- summary ---------------------------------------------------------------

    def summarize(self, surviving: Set[str]) -> SummaryTree:
        """``surviving`` comes from the GC: referenced blobs plus
        unreferenced ones still inside the sweep grace window (a late
        handle write in the post-summary tail can still revive them)."""
        tree = SummaryTree()
        for sha in sorted(self._blobs):
            if sha in surviving:
                tree.add_blob(sha, self._blobs[sha])
        return tree

    def load(self, tree: SummaryTree) -> None:
        self._blobs = {
            sha: node.content for sha, node in tree.children.items()
        }
