"""Op attribution — who wrote what, when (SURVEY.md §1 layer 8).

Capability-equivalent of the reference's ``@fluid-experimental/attributor``
(upstream path UNVERIFIED — empty reference mount): a container-level map
``seq -> (user, timestamp)`` recorded for every sequenced op, serialized
into the container summary so attribution survives summarize/load
round-trips, and resolved from DDS reads (a SharedString segment's insert
seq, a SharedTree node's insert/value seq).

Representation is COLUMNAR, not per-op dicts: ascending delta-encoded
seqs, an interned client table with per-op indices, and delta-encoded
integer timestamps.  For the common sequential-editing case every column
delta is a small non-negative int, so the canonical-JSON blob stays
compact at tens of thousands of ops — and the columns are exactly the
arrays a future device-side attribution join would upload.

ACCEPTED v1 LIMITATION (ADVICE r4): the table grows one row per sequenced
op for the document's lifetime and is re-serialized whole into every
summary.  Sound pruning must drop only rows no DDS attribution key can
still reference — which requires a deterministic referenced-seq census
across every datastore, replicated bit-identically by the catch-up
service's summary builder (summaries must stay byte-identical across
replicas and the service).  Until that census exists, attribution-enabled
documents pay O(lifetime ops) summary bytes (a few bytes/op after delta
encoding); the option defaults off.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional

from ..protocol.messages import MessageType, SequencedMessage


class Attributor:
    """Seq-keyed attribution log with columnar summary serialization."""

    def __init__(self) -> None:
        self._seqs: List[int] = []        # ascending op seqs
        self._client_idx: List[int] = []  # index into _clients per op
        self._timestamps: List[int] = []  # stamped sequencer clock per op
        self._clients: List[str] = []     # interned client/user table
        self._client_map: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._seqs)

    # -- record side -----------------------------------------------------------

    def observe(self, msg: SequencedMessage) -> None:
        """Record a sequenced op's attribution.  Idempotent under replay
        (a seq at or below the newest recorded one is ignored — catch-up
        tails overlapping a loaded summary re-deliver ops)."""
        if msg.type is not MessageType.OP or msg.client_id is None:
            return
        if self._seqs and msg.seq <= self._seqs[-1]:
            return
        idx = self._client_map.get(msg.client_id)
        if idx is None:
            idx = len(self._clients)
            self._clients.append(msg.client_id)
            self._client_map[msg.client_id] = idx
        self._seqs.append(msg.seq)
        self._client_idx.append(idx)
        self._timestamps.append(int(msg.timestamp))

    # -- read side -------------------------------------------------------------

    def get(self, seq: int) -> Optional[dict]:
        """Attribution for the op stamped ``seq``:
        ``{"user", "timestamp", "seq"}`` or None if unknown (detached
        inserts, pre-attribution summaries, server messages)."""
        i = bisect.bisect_left(self._seqs, seq)
        if i == len(self._seqs) or self._seqs[i] != seq:
            return None
        return {
            "user": self._clients[self._client_idx[i]],
            "timestamp": self._timestamps[i],
            "seq": seq,
        }

    # -- summary round-trip ----------------------------------------------------

    def serialize(self) -> dict:
        def deltas(xs: List[int]) -> List[int]:
            prev, out = 0, []
            for x in xs:
                out.append(x - prev)
                prev = x
            return out

        return {
            "v": 1,
            "clients": list(self._clients),
            "seqD": deltas(self._seqs),
            "client": list(self._client_idx),
            "tsD": deltas(self._timestamps),
        }

    @staticmethod
    def deserialize(state: Optional[dict]) -> "Attributor":
        out = Attributor()
        if not state:
            return out  # pre-attribution summary: start empty
        if state.get("v", 1) > 1:
            raise ValueError(f"attribution format {state['v']} unsupported")

        def undeltas(ds: List[int]) -> List[int]:
            acc, out_ = 0, []
            for d in ds:
                acc += d
                out_.append(acc)
            return out_

        out._clients = list(state["clients"])
        out._client_map = {c: i for i, c in enumerate(out._clients)}
        out._seqs = undeltas(state["seqD"])
        out._client_idx = list(state["client"])
        out._timestamps = undeltas(state["tsD"])
        return out
