"""Datastore runtime: hosts channels (DDS instances) and routes their ops.

Capability-equivalent of the reference's ``FluidDataStoreRuntime`` /
``ChannelDeltaConnection`` (SURVEY.md §2.1 datastore; upstream paths
UNVERIFIED — empty reference mount): channel creation through the factory
registry, attach lifecycle, per-channel op routing, and the per-datastore
summary subtree (channel subtrees + an attributes blob recording each
channel's type for load)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..dds.shared_object import SharedObject
from ..protocol.messages import SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .registry import ChannelRegistry


class ChannelDeltaConnection:
    """The per-channel submit handle: wraps ops in the channel envelope and
    forwards them to the datastore's outbound path."""

    def __init__(self, datastore: "FluidDataStoreRuntime",
                 channel_id: str) -> None:
        self._datastore = datastore
        self._channel_id = channel_id

    def submit(self, contents, ref_seq=None) -> int:
        return self._datastore._submit_channel_op(self._channel_id, contents,
                                                  ref_seq)

    @property
    def ref_seq(self):
        return self._datastore._container.ref_seq

    @property
    def min_seq(self):
        return self._datastore._container.min_seq


class FluidDataStoreRuntime:
    """One datastore: a bag of named channels behind one address."""

    def __init__(self, datastore_id: str, registry: ChannelRegistry,
                 rooted: bool = True) -> None:
        self.id = datastore_id
        self.registry = registry
        self.rooted = rooted  # GC root-set membership
        self.channels: Dict[str, SharedObject] = {}
        self._container = None  # set by the container runtime on attach

    # -- channel lifecycle -----------------------------------------------------

    def create_channel(self, type_name: str, channel_id: str) -> SharedObject:
        if channel_id in self.channels:
            raise ValueError(f"channel {channel_id!r} already exists")
        channel = self.registry.get(type_name).create(channel_id)
        self.channels[channel_id] = channel
        # Dynamically created channels (container already live) announce
        # themselves with a sequenced attach op so every remote replica
        # materializes the channel before its first op arrives; channels
        # created while detached ride the attach summary instead.
        if self._container is not None and self._container.is_attached:
            self._container._submit_channel_attach(
                self.id, channel_id, type_name
            )
        self._connect_channel(channel)
        return channel

    def _materialize_remote_channel(self, type_name: str,
                                    channel_id: str) -> None:
        """A peer's channelAttach op: create the (empty) channel."""
        existing = self.channels.get(channel_id)
        if existing is not None:
            if existing.TYPE != type_name:
                # Two clients concurrently created the same channel id with
                # different types — an app-level id collision that cannot
                # merge.  Fail loudly (the reference asserts) instead of
                # silently routing one type's ops into the other.
                raise RuntimeError(
                    f"conflicting channelAttach for "
                    f"{self.id!r}/{channel_id!r}: {existing.TYPE} vs "
                    f"{type_name}"
                )
            return  # our own attach echo (or identical concurrent create)
        channel = self.registry.get(type_name).create(channel_id)
        self.channels[channel_id] = channel
        self._connect_channel(channel)

    def get_channel(self, channel_id: str) -> SharedObject:
        return self.channels[channel_id]

    def _connect_channel(self, channel: SharedObject) -> None:
        if self._container is not None and \
                getattr(self._container, "attribution_enabled", False):
            # Attribution resolver: DDS reads translate their seq stamps
            # (segment insert seqs, tree node seqs) to (user, timestamp)
            # through the container-level attributor.  Only wired on
            # attribution-enabled documents — the wiring also gates the
            # channels' attribution summary blobs.
            channel._attributor = self._container.attributor
        if self._container is not None and self._container.client_id:
            channel.connect(
                ChannelDeltaConnection(self, channel.id),
                self._container.client_id,
            )

    def _attach(self, container) -> None:
        self._container = container
        for channel in self.channels.values():
            self._connect_channel(channel)

    # -- op routing ------------------------------------------------------------

    def _submit_channel_op(self, channel_id: str, contents,
                           ref_seq=None) -> int:
        return self._container._submit_op(
            {"ds": self.id, "channel": channel_id, "contents": contents},
            ref_seq=ref_seq,
        )

    def process(self, msg: SequencedMessage, envelope: dict,
                local: bool) -> None:
        channel = self.channels.get(envelope["channel"])
        if channel is None:
            raise KeyError(
                f"datastore {self.id!r}: op for unknown channel "
                f"{envelope['channel']!r}"
            )
        channel.process(
            dataclasses.replace(msg, contents=envelope["contents"]), local
        )

    def advance(self, seq: int, min_seq: int,
                skip_channel: Optional[str] = None) -> None:
        for channel_id, channel in self.channels.items():
            if channel_id == skip_channel:
                continue
            advance = getattr(channel, "advance", None)
            if advance:
                advance(seq, min_seq)

    def resubmit_pending(self, force_rebase: bool = False) -> None:
        for channel in self.channels.values():
            channel.resubmit_pending(force_rebase=force_rebase)

    # -- summaries -------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        channel_types = {}
        for channel_id in sorted(self.channels):
            channel = self.channels[channel_id]
            tree.children[channel_id] = channel.summarize(min_seq)
            channel_types[channel_id] = channel.TYPE
        tree.add_blob(".attributes", canonical_json(
            {"channels": channel_types, "rooted": self.rooted}
        ))
        return tree

    def load(self, summary: SummaryTree) -> None:
        import json

        attributes = json.loads(summary.blob_bytes(".attributes"))
        self.rooted = attributes.get("rooted", True)
        self.channels = {}
        for channel_id, type_name in attributes["channels"].items():
            subtree = summary.children[channel_id]
            channel = self.registry.get(type_name).load(channel_id, subtree)
            self.channels[channel_id] = channel
            self._connect_channel(channel)
