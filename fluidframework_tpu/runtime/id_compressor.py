"""Distributed ID compression: UUID-sized stable ids → small ints.

Capability-equivalent of the reference's ``id-compressor`` package
(SURVEY.md §2.1: session-space/op-space ids, local vs final ids, cluster
allocation; upstream paths UNVERIFIED — empty reference mount).

Model:
- Each session (client) mints **local ids**: negative ints -1, -2, … —
  usable immediately, no coordination.
- When the session's ops flush, the runtime attaches the session's new
  **creation range** to the batch; when the batch is *sequenced*, every
  client (including the author) **finalizes** the range identically:
  final ids are allocated from **clusters** — contiguous blocks of the
  positive final-id space reserved per session, so consecutive locals
  map to consecutive finals and lookup tables stay tiny.
- A compressed id decompresses to a stable string ``<session>:<gen>``
  that is identical on every client forever; recompress inverts it.

The cluster table is a plain dict fold over sequenced ranges — cheap,
deterministic, and serialized into summaries."""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Tuple


class IdCompressor:
    """Per-session compressor with a shared, sequenced cluster table."""

    def __init__(self, session_id: Optional[str] = None,
                 cluster_capacity: int = 512) -> None:
        self.session_id = session_id or uuid.uuid4().hex
        self.cluster_capacity = cluster_capacity
        self._gen_count = 0          # locals minted by THIS session
        self._taken_through = 0      # locals already handed to a range
        # session -> list of [base_final, base_gen, capacity, used]
        self._clusters: Dict[str, List[list]] = {}
        self._next_final = 0
        # final id -> (session, gen) reverse lookup is derivable from the
        # cluster table; no separate map needed.

    # -- local allocation ------------------------------------------------------

    def generate(self) -> int:
        """Mint a new id in session space (negative local id)."""
        self._gen_count += 1
        return -self._gen_count

    def take_next_creation_range(self) -> Optional[dict]:
        """The unfinalized locals minted since the last take — attach to
        the next outbound batch.  None if nothing new."""
        if self._gen_count == self._taken_through:
            return None
        first = self._taken_through + 1
        count = self._gen_count - self._taken_through
        self._taken_through = self._gen_count
        return {"session": self.session_id, "firstGen": first,
                "count": count}

    def rollback_ranges(self, first_gen: int) -> None:
        """Un-take ranges from ``first_gen`` onward: their wire batches were
        discarded before reaching the sequencer (reconnect / rehydrate), so
        the next take re-attaches those locals — otherwise they would never
        finalize and their op-space forms could never resolve remotely."""
        self._taken_through = min(self._taken_through, first_gen - 1)

    # -- sequenced finalization (identical on every client) --------------------

    def finalize_range(self, range_: dict) -> None:
        session = range_["session"]
        first_gen, count = range_["firstGen"], range_["count"]
        clusters = self._clusters.setdefault(session, [])
        remaining = count
        gen = first_gen
        while remaining > 0:
            if clusters and self._cluster_free(clusters[-1]) > 0:
                cluster = clusters[-1]
            else:
                cluster = [self._next_final, gen,
                           max(self.cluster_capacity, remaining), 0]
                self._next_final += cluster[2]
                clusters.append(cluster)
            take = min(remaining, self._cluster_free(cluster))
            cluster[3] += take
            gen += take
            remaining -= take

    @staticmethod
    def _cluster_free(cluster: list) -> int:
        return cluster[2] - cluster[3]

    # -- space normalization ---------------------------------------------------

    def normalize_to_op_space(self, id_: int) -> int:
        """Session-space → op-space: a finalized local becomes its final id
        (what goes on the wire); an unfinalized local stays local."""
        if id_ >= 0:
            return id_
        final = self._final_of(self.session_id, -id_)
        return final if final is not None else id_

    def normalize_to_session_space(self, id_: int, origin: str) -> int:
        """Op-space id from ``origin`` → this session's view: our own
        finals become locals (negative); others' stay final."""
        if id_ < 0:
            if origin != self.session_id:
                raise ValueError(
                    f"local id {id_} from foreign session {origin!r}"
                )
            return id_
        located = self._locate_final(id_)
        if located is not None and located[0] == self.session_id:
            return -located[1]
        return id_

    # -- stable (de)compression ------------------------------------------------

    def decompress(self, id_: int) -> str:
        if id_ < 0:
            return f"{self.session_id}:{-id_}"
        located = self._locate_final(id_)
        if located is None:
            raise KeyError(f"final id {id_} is not allocated")
        return f"{located[0]}:{located[1]}"

    def recompress(self, stable: str) -> int:
        session, gen_s = stable.rsplit(":", 1)
        gen = int(gen_s)
        if session == self.session_id:
            final = self._final_of(session, gen)
            return -gen if final is None else final
        final = self._final_of(session, gen)
        if final is None:
            raise KeyError(f"stable id {stable!r} is not finalized")
        return final

    # -- internals -------------------------------------------------------------

    def _final_of(self, session: str, gen: int) -> Optional[int]:
        for base_final, base_gen, _cap, used in \
                self._clusters.get(session, []):
            if base_gen <= gen < base_gen + used:
                return base_final + (gen - base_gen)
        return None

    def _locate_final(self, final: int) -> Optional[Tuple[str, int]]:
        for session, clusters in self._clusters.items():
            for base_final, base_gen, _cap, used in clusters:
                if base_final <= final < base_final + used:
                    return session, base_gen + (final - base_final)
        return None

    # -- persistence -----------------------------------------------------------

    def serialize(self) -> dict:
        """Shared (sequenced) state only — local counters are per-session
        and die with the session, exactly like the reference's serialized
        compressor without local state."""
        return {
            "clusters": {s: [list(c) for c in cs]
                         for s, cs in sorted(self._clusters.items())},
            "nextFinal": self._next_final,
            "capacity": self.cluster_capacity,
        }

    @staticmethod
    def deserialize(state: dict,
                    session_id: Optional[str] = None) -> "IdCompressor":
        comp = IdCompressor(session_id=session_id,
                            cluster_capacity=state["capacity"])
        comp._clusters = {s: [list(c) for c in cs]
                          for s, cs in state["clusters"].items()}
        comp._next_final = state["nextFinal"]
        return comp
