"""The driver contract (duck-typed).

A *document service* gives the loader three things for one document:

- ``connection()``      — the live delta connection: ``submit(RawOperation)``,
  ``subscribe(fn)`` / ``unsubscribe(fn)``, ``connect(client_id)`` /
  ``disconnect(client_id)``, and signals (``submit_signal`` /
  ``subscribe_signals``).
- ``delta_storage``     — ranged reads of the durable sequenced-op log:
  ``get(from_seq, to_seq)`` (the catch-up feed).
- ``storage``           — the summary store scoped to the document:
  ``latest() -> (tree, ref_seq)``, ``upload(tree, ref_seq) -> handle``,
  ``read(handle)``.

``DocumentService``/``DocumentStorage`` here are the shared concrete glue
drivers compose; a driver only has to supply an endpoint-like connection
object and the two stores.
"""

from __future__ import annotations

from typing import List, Optional

from ..protocol.messages import SequencedMessage
from ..protocol.summary import SummaryStorage, SummaryTree
from ..service.oplog import OpLog


class DocumentStorage:
    """A summary store scoped to one document."""

    def __init__(self, storage: SummaryStorage, doc_id: str) -> None:
        self._storage = storage
        self.doc_id = doc_id

    def latest(self, at_or_below: Optional[int] = None):
        return self._storage.latest(self.doc_id, at_or_below=at_or_below)

    def upload(self, tree: SummaryTree, ref_seq: int) -> str:
        return self._storage.upload(self.doc_id, tree, ref_seq)

    def read(self, handle: str):
        return self._storage.read(handle)


class DeltaStorage:
    """Ranged reads over the durable op log, scoped to one document."""

    def __init__(self, oplog: OpLog, doc_id: str) -> None:
        self._oplog = oplog
        self.doc_id = doc_id

    def get(self, from_seq: int = 0,
            to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return self._oplog.get(self.doc_id, from_seq, to_seq)

    def head(self) -> int:
        return self._oplog.head(self.doc_id)


class DocumentService:
    """One document's driver surface: connection + the two stores."""

    def __init__(self, doc_id: str, connection, delta_storage: DeltaStorage,
                 storage: DocumentStorage) -> None:
        self.doc_id = doc_id
        self._connection = connection
        self.delta_storage = delta_storage
        self.storage = storage

    def connection(self):
        return self._connection
