"""Local driver: binds the loader to an in-process ordering service.

Capability parity with the reference's local-driver +
``LocalDeltaConnectionServer`` pair (SURVEY.md §2.3/§2.4: the full
loader→driver→server loop in one process, no network)."""

from __future__ import annotations

from ..protocol.summary import SummaryTree
from ..service.orderer import LocalOrderingService
from .definitions import DeltaStorage, DocumentService, DocumentStorage


class LocalDocumentServiceFactory:
    """``IDocumentServiceFactory`` capability over a LocalOrderingService."""

    def __init__(self, service: LocalOrderingService) -> None:
        self.service = service

    def create_document(
        self, doc_id: str, initial_summary: SummaryTree, ref_seq: int = 0
    ) -> DocumentService:
        """Attach: register the document and store its initial summary (the
        reference's attach flow uploads the create-new summary)."""
        self.service.create_document(doc_id)
        self.service.storage.upload(doc_id, initial_summary, ref_seq)
        return self.resolve(doc_id)

    def resolve(self, doc_id: str) -> DocumentService:
        endpoint = self.service.endpoint(doc_id)
        return DocumentService(
            doc_id,
            connection=endpoint,
            delta_storage=DeltaStorage(self.service.oplog, doc_id),
            storage=DocumentStorage(self.service.storage, doc_id),
        )
