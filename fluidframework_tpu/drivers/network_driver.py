"""Network driver: binds the loader to an ordering server over TCP.

Capability parity with the reference's routerlicious-driver (SURVEY.md
§2.4; upstream paths UNVERIFIED — empty reference mount): the client side
of the frame protocol in ``service/server.py``.  One socket per factory is
shared by every document; a reader thread routes responses to waiting
callers and enqueues broadcast events, and a dispatcher thread delivers
them to subscribers — so a subscriber callback may issue further blocking
requests (the DeltaManager's gap repair does) without deadlocking the
reader.

Delivery threading: op/signal callbacks fire on the dispatcher thread.
The intended consumer is the Loader's DeltaManager, whose delivery
watermark dedups the overlap between a deltas snapshot and the live tail,
and whose subscribers only append to the runtime's inbound queue (drained
by the application thread).
"""

from __future__ import annotations

import itertools
import json
import queue
import socket
import threading
import weakref
from typing import Callable, Dict, List, Optional

from ..protocol import errors as wire_errors
from ..protocol.messages import (DocRelocatedError, NackError, RawOperation,
                                 SequencedMessage, ShardFencedError)
from ..protocol.summary import SummaryTree, tree_from_obj, tree_to_obj
from ..protocol.wire import (LEN as _LEN, WIRE_VERSION,
                             decode_sequenced_message,
                             encode_raw_operation, frame_bytes)


class RpcError(RuntimeError):
    """Server-side error surfaced to the caller.  The PLAIN class means a
    deterministic server rejection (auth failure, unknown method, a
    server-side exception): retrying the same bytes cannot help, so the
    retry layer never touches it — only the transport-shaped subclasses
    below are retried."""


class RpcTransportError(RpcError, ConnectionError):
    """Transport-level failure (send faulted, frame lost): the request
    may never have reached the server — resending the same bytes is the
    correct recovery, and the sequencer's client_seq dedup makes it safe
    even for submits.  A ConnectionError, so the runtime's wire-drain
    keeps encoded ops queued."""


class ConnectionLostError(RpcTransportError):
    """The transport under this client DIED (socket closed, send failed
    at the fd, reader drained the pending map).  Like any transport
    error the queued ops survive, but a blind in-place retry is
    pointless: the host must reconnect first."""


class RpcTimeoutError(RpcError, TimeoutError):
    """No response within the client timeout: the server may be slow or
    the response frame lost.  Retried — the resend either dedups
    (response was lost after sequencing) or lands fresh."""


class EpochMismatchError(RpcError):
    """The server's storage generation changed under us (store recreated):
    every cached snapshot/delta this client holds is from a dead
    generation and must not be mixed with the new one (odsp EpochTracker
    capability).  Callers must reload the document from scratch."""

    def __init__(self, message: str, server_epoch: Optional[str]) -> None:
        super().__init__(message)
        self.server_epoch = server_epoch


class UnknownWireCodeError(RpcError):
    """The peer sent an error code outside the protocol/errors.py
    registry: the two sides disagree about the failure vocabulary
    (version skew, a corrupt frame, a buggy server).  A plain RpcError
    subclass on purpose — pacing or resending against an UNKNOWN
    contract is how retry budgets burn, so this is never retried; the
    host must surface it."""

    def __init__(self, channel: str, code: object) -> None:
        super().__init__(
            f"unregistered wire error code {code!r} on {channel} channel")
        self.channel = channel
        self.code = code


class _RpcClient:
    """Shared framed-JSON socket with response routing + event dispatch.

    ``retry`` (a :class:`~..service.retry.RetryPolicy`) bounds-retries
    the initial connect and every request on transient transport
    failures — safe for submits too, because the sequencer dedups by
    (client_id, client_seq), so a response lost on the wire resends the
    same bytes and gets the duplicate dropped server-side.  Nacks, epoch
    mismatches, and shard fences are NEVER retried here: those belong to
    the DeltaManager/loader layer.  ``faults`` arms the ``rpc.send`` /
    ``rpc.recv`` injection sites (testing/faults.py)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 mc=None, faults=None, retry=None, rng=None) -> None:
        import random as _random

        from ..utils.telemetry import LockedCounterSet, MonitoringContext

        self._faults = faults
        self._retry = retry
        self._retry_rng = rng if rng is not None else _random.Random(0)
        #: retry.* counters (attempts/retries/exhausted) — bench surface
        self.retry_counters = LockedCounterSet()
        if retry is not None:
            self._sock = retry.run(
                lambda: socket.create_connection((host, port), timeout=10),
                operation=f"connect {host}:{port}",
                rng=self._retry_rng,
                retry_on=(OSError,),
                counters=self.retry_counters,
            )
        else:
            self._sock = socket.create_connection((host, port), timeout=10)
        self._sock.settimeout(None)
        self._timeout = timeout
        self._mc = (mc or MonitoringContext()).child("rpc")
        self._write_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._pending: Dict[int, queue.Queue] = {}  # guarded-by: _pending_lock
        self._pending_lock = threading.Lock()
        self._events: queue.Queue = queue.Queue()
        # Subscription state: mutated by caller threads (subscribe/
        # unsubscribe), read by the dispatcher thread per event — its own
        # lock so delivery never contends with the request/response path.
        self._state_lock = threading.Lock()
        self._handlers: Dict[str, List[Callable[[dict], None]]] = {}  # guarded-by: _state_lock
        self._closed = False
        self._sock_closed = False  # guarded-by: _state_lock
        # injected-'delay' one-frame reorder buffer (reader thread +
        # safety timer race for the flush)
        self._held_lock = threading.Lock()
        self._held: Optional[dict] = None  # guarded-by: _held_lock
        #: last exception a telemetry sink raised from the dispatcher
        #: (dispatcher-thread-confined write; read via last_sink_error
        #: for post-mortem — a dead sink must not also hide ITS failure)
        self._last_sink_error: Optional[BaseException] = None
        #: storage generation this CONNECTION is pinned to (odsp
        #: EpochTracker): adopted from the first storage response and then
        #: attached to EVERY doc/storage request — deltas, submits, and
        #: catchup included, not just the summary RPCs, so op-stream
        #: generation mixing fails loudly too.
        self.epoch: Optional[str] = None
        #: invalidation callbacks (one per _RemoteStorage on this socket):
        #: an epochMismatch observed on ANY RPC — deltas, submits,
        #: discovery, storage — drops EVERY instance's caches and the pin,
        #: centrally, before the error propagates.  Held as WEAK method refs
        #: so a long-lived shared connection does not pin every per-doc
        #: storage instance (and its snapshot cache) forever (ADVICE r4).
        self._epoch_listeners: List["weakref.WeakMethod"] = []  # guarded-by: _state_lock
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True
        )
        self._dispatcher.start()

    # -- wire ------------------------------------------------------------------

    def _read_loop(self) -> None:
        rfile = None
        try:
            # Buffered file interface: exact-size reads without quadratic
            # bytes-concatenation on large frames (big summaries).
            rfile = self._sock.makefile("rb")

            def read_exact(n: int) -> bytes:
                data = rfile.read(n)
                if data is None or len(data) != n:
                    raise ConnectionError("server closed")
                return data

            while True:
                (length,) = _LEN.unpack(read_exact(_LEN.size))
                frame = json.loads(read_exact(length))
                # Event frames carry their doc id — a doc-scoped plan
                # point counts ONLY that document's broadcast frames,
                # which is what makes "drop the 3rd op event of doc X"
                # replayable (response frames count globally).
                fault = (self._faults.fire("rpc.recv",
                                           doc=frame.get("doc"))
                         if self._faults is not None else None)
                if fault is not None:
                    if fault.kind == "disconnect":
                        raise ConnectionError("injected rpc disconnect")
                    if fault.kind == "drop":
                        continue  # lost on the wire: waiters time out /
                        # subscribers gap-repair from durable storage
                    if fault.kind == "duplicate":
                        self._route(frame)  # delivered twice: watermarks
                        # and response-slot idempotence absorb the copy
                    if fault.kind == "delay":
                        # Reorder, never loss: delivered after the next
                        # frame — or by the timer if the connection goes
                        # idle (a delay on the FINAL frame must not turn
                        # into a permanent drop).  Check-and-hold in one
                        # critical section; an occupied buffer delivers
                        # this frame normally.
                        with self._held_lock:
                            holding = self._held is None
                            if holding:
                                self._held = frame
                        if holding:
                            timer = threading.Timer(
                                self.HELD_FLUSH_SECONDS, self._flush_held)
                            timer.daemon = True
                            timer.start()
                            continue
                self._route(frame)
                self._flush_held()
        except (ConnectionError, OSError, ValueError) as exc:
            self._closed = True
            # Fail every waiter so no caller hangs on a dead socket.
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            for slot in pending.values():
                slot.put({"ok": False, "code": "connectionLost",
                          "error": f"connection lost: {exc}"})
            self._events.put(None)
        finally:
            # The buffered reader pins the socket's io refcount; a reader
            # that exits without closing it leaks the buffer for the
            # process lifetime (fluidleak FL-LEAK-ESCAPE).  The socket
            # itself stays owned by close().
            if rfile is not None:
                try:
                    rfile.close()
                except OSError:
                    pass

    #: how long an injected 'delay' holds a frame when NO later frame
    #: arrives to release it (idle connection): reorder semantics with a
    #: bounded worst case, never a permanent drop.
    HELD_FLUSH_SECONDS = 0.25

    def _flush_held(self) -> None:
        """Release the one-frame reorder buffer: called by the reader
        after routing the NEXT frame, and by the safety timer when the
        connection went idle — the None-swap under the lock makes
        exactly one of them deliver it."""
        with self._held_lock:
            frame, self._held = self._held, None
        if frame is not None:
            self._route(frame)

    def _route(self, frame: dict) -> None:
        """Deliver one inbound frame: responses to their waiting slot,
        events to the dispatcher queue.  Duplicate-delivery safe: a
        response whose slot is gone (already answered) is dropped, and
        event consumers dedup by delivery watermark."""
        if "re" in frame:
            with self._pending_lock:
                slot = self._pending.pop(frame["re"], None)
            if slot is not None:
                try:
                    slot.put_nowait(frame)
                except queue.Full:
                    pass  # duplicated response already delivered
        elif "event" in frame:
            self._events.put(frame)

    def _dispatch_loop(self) -> None:
        while True:
            frame = self._events.get()
            if frame is None:
                return
            if frame["event"] == "fence":
                # Shard failover (server push): the storage generation
                # changed.  Unpin + drop every cache on this connection
                # BEFORE delivering to per-doc subscribers — proactive
                # reconnect-through-the-fence, so the next RPC adopts the
                # new epoch instead of tripping over epochMismatch.
                self._invalidate_epoch_state()
            key = f"{frame['event']}:{frame.get('doc', '')}"
            # Snapshot under the lock, deliver outside it: a handler that
            # issues further RPCs (or re-subscribes) must not deadlock or
            # corrupt the dispatch loop (fluidrace: the live list is
            # mutated by on()/off() on caller threads).
            with self._state_lock:
                handlers = list(self._handlers.get(key, ()))
            for fn in handlers:
                try:
                    fn(frame)
                except Exception as exc:
                    # A broken subscriber must not kill delivery — but its
                    # failure must surface, not vanish (fluidleak
                    # FL-LEAK-SWALLOW): hosts that inject a logger see
                    # every dropped delivery with its event key.
                    try:
                        self._mc.logger.send({
                            "eventName": "subscriberError", "event": key,
                            "error": str(exc),
                            "errorType": type(exc).__name__,
                        })
                    except Exception as sink_exc:
                        # A broken SINK must not kill the dispatcher
                        # either (a dead dispatcher silently halts every
                        # delivery on the connection); stash the sink's
                        # failure for post-mortem instead of dying.
                        self._last_sink_error = sink_exc

    @property
    def last_sink_error(self) -> Optional[BaseException]:
        """The most recent exception a telemetry sink raised from the
        dispatcher thread, or None.  Hosts poll this post-mortem: the
        dispatcher armors itself against a broken sink, so this is the
        only place the sink's own failure surfaces."""
        return self._last_sink_error

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        if self._retry is None or self._closed:
            # A dead socket can never heal by resending — fail fast
            # rather than burn the budget against a closed fd.
            return self._request_once(method, params, timeout=timeout)
        return self._retry.run(
            lambda: self._request_once(method, params, timeout=timeout),
            operation=f"rpc {method}",
            rng=self._retry_rng,
            # Only TRANSPORT-shaped failures resend the same bytes
            # (duplicates dedup server-side).  A plain RpcError is a
            # deterministic server rejection — retrying would burn the
            # budget and then mask the real error as a ConnectionError.
            retry_on=(RpcTransportError, RpcTimeoutError, OSError,
                      TimeoutError),
            # These are not transport noise: nack holds belong to the
            # DeltaManager, mismatches/fences to the loader's re-resolve
            # — and a DEAD socket (ConnectionLostError) can never heal by
            # resending in place: fail fast so the host reconnects,
            # instead of sleeping out the budget against a closed fd.
            no_retry=(EpochMismatchError, NackError, ShardFencedError,
                      ConnectionLostError),
            counters=self.retry_counters,
        )

    def _request_once(self, method: str, params: dict,
                      timeout: Optional[float] = None):
        """``timeout`` overrides the client default for THIS request —
        supervision probes (the front door's heartbeat ping) must detect
        a hung shard process in seconds, not the 30 s RPC default."""
        if self._closed:
            raise ConnectionLostError("connection lost")
        fault = (self._faults.fire("rpc.send", doc=params.get("doc"))
                 if self._faults is not None else None)
        if fault is not None:
            if fault.kind == "disconnect":
                self.close()
                raise ConnectionLostError("injected disconnect before send")
            if fault.kind == "fail":
                raise RpcTransportError("injected send failure")
        rid = next(self._ids)
        slot: queue.Queue = queue.Queue(maxsize=1)
        with self._pending_lock:
            self._pending[rid] = slot
        if self._closed:
            # The reader died between the first check and slot
            # registration; its drain may have run already — fail fast
            # instead of waiting out the timeout on a dead socket.
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise ConnectionLostError("connection lost")
        if self.epoch is not None and method not in ("auth", "ping"):
            params = {**params, "epoch": self.epoch}
        frame = frame_bytes(
            {"v": WIRE_VERSION, "id": rid, "method": method,
             "params": params}
        )
        try:
            if fault is not None and fault.kind == "drop":
                pass  # lost on the wire: the slot wait below times out
            else:
                with self._write_lock:
                    self._sock.sendall(frame)
        except OSError as exc:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise ConnectionLostError(f"send failed: {exc}")
        try:
            frame = slot.get(
                timeout=timeout if timeout is not None else self._timeout)
        except queue.Empty:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise RpcTimeoutError(f"timeout waiting for {method}")
        if not frame.get("ok"):
            nack = frame.get("nack")
            if nack is not None:
                nack_code = nack.get("code")
                if nack_code not in wire_errors.codes("nack"):
                    # A nack whose pacing class we don't know: silently
                    # defaulting to "throttled" would pace the retry
                    # budget on garbage.  Loud, typed, never retried.
                    self.retry_counters.bump("rpc.unknown_code")
                    self._mc.logger.send({
                        "eventName": "unknownWireCode",
                        "channel": "nack", "code": repr(nack_code),
                    })
                    raise UnknownWireCodeError("nack", nack_code)
                raise NackError(nack.get("reason", "nacked"),
                                retry_after=nack.get("retryAfter", 0.0),
                                code=nack_code,
                                admission=nack.get("admission"))
            if frame.get("code") == "epochMismatch":
                # Dead generation: unpin and drop EVERY cache riding this
                # connection before anyone can retry unpinned against the
                # new generation with stale state still live.
                self._invalidate_epoch_state()
                raise EpochMismatchError(
                    frame.get("error", "storage epoch mismatch"),
                    frame.get("epoch"),
                )
            if frame.get("code") == "shardFenced":
                # Mid-failover race on the server: the router has (or is
                # about to have) a recovered owner — typed so callers can
                # re-resolve/retry instead of failing like a dead server.
                raise ShardFencedError(
                    frame.get("doc", ""),
                    frame.get("error", "shard fenced"),
                )
            if frame.get("code") == "wrongShard":
                # Out-of-process redirect: this server no longer owns the
                # document (live migration, or a stale direct-to-shard
                # route after failover).  Recovery is the fence path —
                # re-resolve the owner through the front door and retry
                # there; a blind in-place resend can never succeed.
                raise DocRelocatedError(
                    frame.get("doc", ""),
                    frame.get("error", "document served by another shard"),
                )
            if frame.get("code") == "connectionLost":
                # The reader died and drained this waiter: transport
                # death, not a server rejection — queued ops survive.
                raise ConnectionLostError(
                    frame.get("error", "connection lost"))
            if frame.get("code") == "internal":
                # Server-side catch-all: a handler fault framed typed.
                # Deterministic rejection — plain RpcError, never
                # retried, never mistaken for transport.
                raise RpcError(
                    frame.get("error", "internal server error"))
            frame_code = frame.get("code")
            if frame_code is not None \
                    and not wire_errors.is_registered(frame_code):
                # The server speaks a code this driver's registry does
                # not: version skew or corruption.  Same loud path as an
                # unknown nack code — never folded into a generic error.
                self.retry_counters.bump("rpc.unknown_code")
                self._mc.logger.send({
                    "eventName": "unknownWireCode",
                    "channel": "frame", "code": repr(frame_code),
                })
                raise UnknownWireCodeError("frame", frame_code)
            raise RpcError(frame.get("error", "unknown server error"))
        return frame.get("result")

    def _invalidate_epoch_state(self) -> None:
        """Unpin the connection's storage generation and invalidate every
        per-doc cache riding it — shared by the epochMismatch error path
        and the proactive server-push fence event.  ONE critical section
        does both the snapshot and the dead-weakref prune (resolving the
        refs pins each live listener for the delivery below), then the
        callbacks run OUTSIDE the lock — a listener that re-registers
        must not self-deadlock on the plain Lock, and anything registered
        during delivery simply appends to the live list untouched."""
        self.epoch = None
        callbacks = []
        with self._state_lock:
            live = []
            for ref in list(self._epoch_listeners):
                invalidate = ref()
                if invalidate is not None:
                    live.append(ref)
                    callbacks.append(invalidate)
            self._epoch_listeners[:] = live
        for invalidate in callbacks:
            invalidate()

    def on(self, event: str, doc_id: str, fn: Callable[[dict], None]) -> None:
        with self._state_lock:
            self._handlers.setdefault(f"{event}:{doc_id}", []).append(fn)

    def off(self, event: str, doc_id: str, fn: Callable[[dict], None]) -> None:
        with self._state_lock:
            handlers = self._handlers.get(f"{event}:{doc_id}", [])
            if fn in handlers:
                handlers.remove(fn)

    def add_epoch_listener(self, ref: "weakref.WeakMethod") -> None:
        """Register an invalidation callback (weak method ref) — under the
        state lock so registration never races the mismatch sweep's
        prune-and-replace."""
        with self._state_lock:
            self._epoch_listeners.append(ref)

    def close(self) -> None:
        self._closed = True
        with self._state_lock:
            # Idempotent (fluidleak FL-LEAK-DOUBLE-CLOSE discipline):
            # close() is reachable from the factory, from error-path
            # callers, and from teardown sweeps — only the first call
            # touches the socket.  `_closed` alone cannot be the guard:
            # a dead reader sets it without ever closing the fd.
            if self._sock_closed:
                return
            self._sock_closed = True
        try:
            # shutdown() (not just close()) wakes the reader thread out
            # of its blocking recv with EOF; close() alone leaves it
            # parked on the dead fd forever — a daemon-thread leak the
            # threaded stress test pins (tests/test_concurrency.py).
            # The reader's exit then enqueues the dispatcher's sentinel,
            # so both driver threads wind down.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _ReconnectingRpc:
    """A live :class:`_RpcClient` plus everything needed to STAND UP ITS
    REPLACEMENT — registered event handlers, tapped documents, the epoch
    pin, epoch-invalidation listeners — so a transport swap (dead door
    socket, relocated document) rebuilds the session without the caller
    losing its subscriptions.  Subclasses decide WHEN to swap and WHERE
    to dial; this base keeps the replay state and exposes the exact
    surface :class:`NetworkConnection` / :class:`_RemoteStorage` consume
    (``request``/``on``/``off``/``add_epoch_listener``/``epoch``/
    ``close``)."""

    def __init__(self, timeout: float = 30.0, mc=None, faults=None,
                 retry=None, rng=None) -> None:
        self._timeout = timeout
        self._mc = mc
        self._faults = faults
        self._retry_policy = retry
        self._rng = rng
        self._client: Optional[_RpcClient] = None
        #: replayed onto every replacement transport
        self._handlers: List[tuple] = []
        self._taps: set = set()
        self._epoch_refs: List["weakref.WeakMethod"] = []
        #: transport swaps taken (test/bench pin: the drill went >= 1)
        self.failovers = 0

    def _dial(self, addr) -> _RpcClient:
        return _RpcClient(addr[0], addr[1], timeout=self._timeout,
                          mc=self._mc, faults=self._faults,
                          retry=self._retry_policy, rng=self._rng)

    def _adopt(self, client: _RpcClient) -> None:
        """Install a replacement transport: carry the epoch pin (the
        storage generation is store-wide, not per-socket), replay event
        handlers and epoch listeners, then re-establish every tap the
        old session held — the server side of a tap died with the old
        socket, so a client that does not re-subscribe goes silently
        deaf (the exact failure the demotion kick exists to prevent)."""
        old = self._client
        if old is not None:
            client.epoch = old.epoch
            try:
                old.close()
            except OSError:
                pass
        self._client = client
        for event, doc_id, fn in list(self._handlers):
            client.on(event, doc_id, fn)
        for ref in self._epoch_refs:
            if ref() is not None:
                client.add_epoch_listener(ref)
        for doc_id in sorted(self._taps):
            client.request("subscribe_doc", {"doc": doc_id})

    # -- the _RpcClient surface ------------------------------------------------

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        raise NotImplementedError

    def _note_tap(self, method: str, params: dict) -> None:
        if method == "subscribe_doc" and params.get("doc"):
            self._taps.add(params["doc"])

    def on(self, event: str, doc_id: str, fn) -> None:
        self._handlers.append((event, doc_id, fn))
        if self._client is not None:
            self._client.on(event, doc_id, fn)

    def off(self, event: str, doc_id: str, fn) -> None:
        entry = (event, doc_id, fn)
        if entry in self._handlers:
            self._handlers.remove(entry)
        if self._client is not None:
            self._client.off(event, doc_id, fn)

    def add_epoch_listener(self, ref: "weakref.WeakMethod") -> None:
        self._epoch_refs.append(ref)
        if self._client is not None:
            self._client.add_epoch_listener(ref)

    @property
    def epoch(self) -> Optional[str]:
        return self._client.epoch if self._client is not None else None

    @epoch.setter
    def epoch(self, value: Optional[str]) -> None:
        if self._client is not None:
            self._client.epoch = value

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


class DoorFailoverRpc(_ReconnectingRpc):
    """The replica-list front-door transport (ISSUE 18): one live door
    socket, a list of door addresses, and dead-socket rotation.  Only
    :class:`ConnectionLostError` rotates — it is the one failure that
    can never heal in place (the socket under us is GONE, which is
    exactly what a replica SIGKILL looks like from the client).  Typed
    service refusals (nack / fence / wrongShard) and in-place-retryable
    transport noise stay with the active door."""

    def __init__(self, addrs: List[tuple], **kwargs) -> None:
        super().__init__(**kwargs)
        if not addrs:
            raise ValueError("need at least one door address")
        self._addrs = [tuple(a) for a in addrs]
        self._at = 0
        self._connect_initial()

    def _connect_initial(self) -> None:
        last: Optional[BaseException] = None
        for idx, addr in enumerate(self._addrs):
            try:
                self._client = self._dial(addr)
                self._at = idx
                return
            except OSError as exc:
                last = exc
        raise ConnectionLostError(f"no door reachable: {last}")

    def _rotate(self) -> bool:
        for step in range(1, len(self._addrs) + 1):
            idx = (self._at + step) % len(self._addrs)
            try:
                client = self._dial(self._addrs[idx])
            except OSError:
                continue
            self._at = idx
            self._adopt(client)
            self.failovers += 1
            return True
        return False

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        last: Optional[BaseException] = None
        for _attempt in range(len(self._addrs) + 1):
            try:
                result = self._client.request(method, params,
                                              timeout=timeout)
            except ConnectionLostError as exc:
                last = exc
                if not self._rotate():
                    break
                continue
            self._note_tap(method, params)
            return result
        raise last


class DirectShardRpc(_ReconnectingRpc):
    """The direct-to-shard DATA path for one document (ISSUE 18): the
    front door answers ``locate`` (control plane), the client dials the
    owning shardhost itself, and every doc-scoped RPC — submits, deltas,
    taps, summaries — skips the relay hop entirely.  Placement is a
    LEASE, not a fact: on ``wrongShard`` (live migration), ``fence``
    (failover recovery), or the shard socket dying, the client
    re-resolves through the door and retries against the new owner —
    bounded hops, because a route that never settles is an outage, not
    a redirect loop."""

    MAX_HOPS = 4

    def __init__(self, door, doc_id: str, **kwargs) -> None:
        super().__init__(**kwargs)
        self._door = door
        self.doc_id = doc_id
        self.shard: Optional[str] = None

    def _resolve(self) -> None:
        where = self._door.request("locate", {"doc": self.doc_id})
        addr = (where["host"], where["port"])
        self.shard = where["shard"]
        client = self._dial(addr)
        had = self._client is not None
        self._adopt(client)
        if had:
            self.failovers += 1

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        last: Optional[BaseException] = None
        for _hop in range(self.MAX_HOPS):
            if self._client is None:
                self._resolve()
            try:
                result = self._client.request(method, params,
                                              timeout=timeout)
            except (ShardFencedError, ConnectionLostError) as exc:
                # DocRelocatedError ⊂ ShardFencedError: stale placement.
                # ConnectionLost: the shard process died under us.  Both
                # recover the same way — ask the door who owns the
                # document NOW (its failover machinery re-homes orphans
                # on route resolution) and retry there.
                last = exc
                try:
                    self._resolve()
                except (RpcError, OSError) as resolve_exc:
                    last = resolve_exc
                continue
            self._note_tap(method, params)
            return result
        raise last


class NetworkConnection:
    """The per-document delta connection (DocumentEndpoint surface)."""

    def __init__(self, rpc: _RpcClient, doc_id: str) -> None:
        self._rpc = rpc
        self.doc_id = doc_id
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        self._signal_subscribers: List[Callable[[dict], None]] = []
        self._tapped = False
        #: diagnostics for hosts/tests: server-pushed backpressure and
        #: failover notifications observed on this document.
        self.demotions_seen = 0
        self.fences_seen = 0
        rpc.on("op", doc_id, self._on_op_event)
        rpc.on("signal", doc_id, self._on_signal_event)
        rpc.on("demoted", doc_id, self._on_demoted_event)
        rpc.on("fence", doc_id, self._on_fence_event)

    def _ensure_tap(self) -> None:
        if not self._tapped:
            self._rpc.request("subscribe_doc", {"doc": self.doc_id})
            self._tapped = True

    def _on_op_event(self, frame: dict) -> None:
        msg = decode_sequenced_message(frame["msg"])
        for fn in list(self._subscribers):
            fn(msg)

    def _on_signal_event(self, frame: dict) -> None:
        for fn in list(self._signal_subscribers):
            fn(frame["signal"])

    def _on_demoted_event(self, frame: dict) -> None:
        """The server demoted this connection's live tap (our buffer was
        the laggard): re-subscribe, then KICK the backfill — deliver the
        current head op through the live path so the DeltaManager's gap
        repair fetches the whole missed range from durable delta storage
        NOW (catch-up-from-oplog).  Without the kick, a document that
        goes quiet after the demoting burst would stay missing the
        dropped span forever (gap repair only fires on a later live
        message).  Subscribers dedup by their delivery watermark, so the
        kick is harmless when nothing was missed.  Runs on the
        dispatcher thread, which may issue blocking requests by design."""
        self.demotions_seen += 1
        try:
            head = self._rpc.request("subscribe_doc", {"doc": self.doc_id})
            head = max(int(head or 0), int(frame.get("head") or 0))
            if head > 0:
                for msg in self.deltas(from_seq=head - 1, to_seq=head):
                    for fn in list(self._subscribers):
                        fn(msg)
        except RpcError:
            # Connection is going away; reconnect handles resubscription.
            self._tapped = False

    def _on_fence_event(self, frame: dict) -> None:
        """Shard failover notification.  The epoch unpin/cache sweep
        already ran centrally in the dispatcher (_invalidate_epoch_state);
        the live broadcast continues from the recovered owner on the
        server side, so the op stream needs no client action — the
        counter is for hosts that want to log/alert."""
        self.fences_seen += 1

    # -- DocumentEndpoint surface ----------------------------------------------

    @property
    def log(self) -> List[SequencedMessage]:
        return self.deltas()

    @property
    def head_seq(self) -> int:
        return self._rpc.request("head", {"doc": self.doc_id})

    def connect(self, client_id: str, session: Optional[str] = None) -> None:
        self._ensure_tap()
        self._rpc.request(
            "connect",
            {"doc": self.doc_id, "client": client_id, "session": session},
        )

    def disconnect(self, client_id: str) -> None:
        self._rpc.request(
            "disconnect", {"doc": self.doc_id, "client": client_id}
        )

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        result = self._rpc.request(
            "submit", {"doc": self.doc_id, "op": encode_raw_operation(op)}
        )
        return decode_sequenced_message(result) if result else None

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._ensure_tap()
        self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        if fn in self._subscribers:
            self._subscribers.remove(fn)

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        self._rpc.request(
            "update_ref_seq",
            {"doc": self.doc_id, "client": client_id, "ref_seq": ref_seq},
        )

    def deltas(self, from_seq: int = 0,
               to_seq: Optional[int] = None) -> List[SequencedMessage]:
        msgs = self._rpc.request(
            "deltas",
            {"doc": self.doc_id, "from_seq": from_seq, "to_seq": to_seq},
        )
        return [decode_sequenced_message(m) for m in msgs]

    def submit_signal(self, client_id: str, content,
                      target_client_id: Optional[str] = None) -> None:
        self._ensure_tap()
        self._rpc.request(
            "signal",
            {"doc": self.doc_id, "client": client_id, "content": content,
             "target": target_client_id},
        )

    def subscribe_signals(self, fn: Callable[[dict], None]) -> None:
        self._ensure_tap()
        self._signal_subscribers.append(fn)

    def unsubscribe_signals(self, fn: Callable[[dict], None]) -> None:
        if fn in self._signal_subscribers:
            self._signal_subscribers.remove(fn)


class _RemoteDeltaStorage:
    """Ranged reads of the durable log over the wire."""

    def __init__(self, conn: NetworkConnection) -> None:
        self._conn = conn

    def get(self, from_seq: int = 0,
            to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return self._conn.deltas(from_seq, to_seq)

    def head(self) -> int:
        return self._conn.head_seq


class _RemoteStorage:
    """The summary store over the wire, with a client-side snapshot cache
    (odsp-driver capability): summaries fetched or uploaded are remembered
    by handle, and ``latest`` advertises the cached handles so an unchanged
    snapshot never crosses the wire again."""

    #: retained snapshots per document connection
    CACHE_LIMIT = 8

    def __init__(self, rpc: _RpcClient, doc_id: str) -> None:
        self._rpc = rpc
        self.doc_id = doc_id
        self._last_uploaded: Optional[SummaryTree] = None
        self._snapshot_cache: "dict[str, SummaryTree]" = {}
        rpc.add_epoch_listener(weakref.WeakMethod(self._drop_caches))

    def _drop_caches(self) -> None:
        self._snapshot_cache.clear()
        self._last_uploaded = None

    @property
    def _epoch(self) -> Optional[str]:
        """The pin lives on the shared _RpcClient so EVERY RPC on this
        connection (deltas/submit/catchup too) carries it — a recreated
        store answers epochMismatch instead of silently serving state our
        cached snapshots/deltas cannot be mixed with."""
        return self._rpc.epoch

    @_epoch.setter
    def _epoch(self, value: Optional[str]) -> None:
        self._rpc.epoch = value

    def _remember(self, handle: str, tree: SummaryTree) -> None:
        self._snapshot_cache[handle] = tree
        while len(self._snapshot_cache) > self.CACHE_LIMIT:
            self._snapshot_cache.pop(next(iter(self._snapshot_cache)))

    def latest(self, at_or_below: Optional[int] = None):
        # Epoch mismatch handling is CENTRAL (_RpcClient drops every
        # instance's caches + the pin before raising), so storage methods
        # just let EpochMismatchError propagate loudly.
        result = self._rpc.request(
            "latest_summary",
            {"doc": self.doc_id, "at_or_below": at_or_below,
             "have": list(self._snapshot_cache)},
        )
        if result is None:
            return None, 0
        if self._epoch is None:
            self._epoch = result.get("epoch")
        handle = result.get("handle")
        if handle is None:
            return None, 0  # no summary yet — but the epoch is adopted
        if "summary" in result:
            tree = tree_from_obj(result["summary"])
            if handle:
                self._remember(handle, tree)
        else:
            tree = self._snapshot_cache[handle]  # server said we have it
        return tree, result["ref_seq"]

    def upload(self, tree: SummaryTree, ref_seq: int) -> str:
        """Incremental against the doc's latest server-side summary when we
        have it cached: unchanged subtrees cross the wire as handles."""
        from ..protocol.summary import tree_to_incremental_obj, tree_to_obj

        obj = tree_to_incremental_obj(tree, self._last_uploaded)
        try:
            result = self._rpc.request(
                "upload_summary",
                {"doc": self.doc_id, "summary": obj, "ref_seq": ref_seq},
            )
        except EpochMismatchError:
            raise  # dead generation: NEVER fall back to a full resend
        except RpcError:
            if self._last_uploaded is None:
                raise
            # The server no longer has the base objects (restore/eviction):
            # resend in full and stop assuming the cache.
            self._last_uploaded = None
            result = self._rpc.request(
                "upload_summary",
                {"doc": self.doc_id, "summary": tree_to_obj(tree),
                 "ref_seq": ref_seq},
            )
        handle = result["handle"]
        if self._epoch is None:
            self._epoch = result.get("epoch")  # writer path adopts too
        self._last_uploaded = tree
        self._remember(handle, tree)
        return handle

    def read(self, handle: str):
        cached = self._snapshot_cache.get(handle)
        if cached is not None:
            return cached
        tree = tree_from_obj(self._rpc.request(
            "read_summary", {"handle": handle}
        ))
        self._remember(handle, tree)
        return tree

    def read_partial(self, handle: str, path: str):
        """Partial snapshot fetch: one subtree/blob by path — the odsp
        snapshot-virtualization capability (bounded download for huge
        documents)."""
        return tree_from_obj(self._rpc.request(
            "read_summary", {"handle": handle, "path": path}
        ))


class NetworkDocumentServiceFactory:
    """``IDocumentServiceFactory`` capability over a TCP ordering server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7070,
                 timeout: float = 30.0, tenant: Optional[str] = None,
                 secret: Optional[str] = None, mc=None, faults=None,
                 retry=None, retry_rng=None,
                 replicas: Optional[List[tuple]] = None,
                 direct: bool = False) -> None:
        """``replicas`` (ISSUE 18): additional front-door ``(host,
        port)`` addresses over the same shard fleet — a dead door socket
        fails over to the next reachable one, taps re-established.
        ``direct`` routes every DOC-scoped call straight to the owning
        shardhost (resolved via the door's ``locate``), demoting the
        door to control plane: creation, discovery, placement."""
        self._transport_kw = dict(timeout=timeout, mc=mc, faults=faults,
                                  retry=retry, rng=retry_rng)
        addrs = [(host, port)] + [tuple(a) for a in (replicas or ())]
        if len(addrs) > 1:
            self._rpc = DoorFailoverRpc(addrs, **self._transport_kw)
        else:
            self._rpc = _RpcClient(host, port, timeout=timeout, mc=mc,
                                   faults=faults, retry=retry,
                                   rng=retry_rng)
        self.direct = bool(direct)
        self._direct_rpcs: Dict[str, DirectShardRpc] = {}
        self._connections: Dict[str, NetworkConnection] = {}
        if tenant is not None:
            # Riddler capability: authenticate the connection before any
            # document traffic; the server namespaces docs per tenant.
            try:
                self._rpc.request("auth",
                                  {"tenant": tenant, "secret": secret})
            except BaseException:
                self._rpc.close()  # no factory object escapes to close()
                raise

    def _doc_rpc(self, doc_id: str):
        """The transport DOC-scoped traffic rides: the door itself, or
        (direct mode) a per-document connection to the owning shard."""
        if not self.direct:
            return self._rpc
        rpc = self._direct_rpcs.get(doc_id)
        if rpc is None:
            rpc = DirectShardRpc(self._rpc, doc_id, **self._transport_kw)
            self._direct_rpcs[doc_id] = rpc
        return rpc

    def _connection(self, doc_id: str) -> NetworkConnection:
        conn = self._connections.get(doc_id)
        if conn is None:
            conn = NetworkConnection(self._doc_rpc(doc_id), doc_id)
            self._connections[doc_id] = conn
        return conn

    def create_document(self, doc_id: str, initial_summary: SummaryTree,
                        ref_seq: int = 0):
        self._rpc.request(
            "create_document",
            {"doc": doc_id, "summary": tree_to_obj(initial_summary),
             "ref_seq": ref_seq},
        )
        return self.resolve(doc_id)

    def resolve(self, doc_id: str):
        if not self._rpc.request("has_document", {"doc": doc_id}):
            raise KeyError(f"document {doc_id!r} does not exist")
        from .definitions import DocumentService

        conn = self._connection(doc_id)
        return DocumentService(
            doc_id,
            connection=conn,
            delta_storage=_RemoteDeltaStorage(conn),
            storage=_RemoteStorage(self._doc_rpc(doc_id), doc_id),
        )

    def close(self) -> None:
        # getattr: tests assemble partial factories via __new__ to probe
        # the unauthenticated path — close() still has to work there.
        for rpc in getattr(self, "_direct_rpcs", {}).values():
            try:
                rpc.close()
            except OSError:
                pass
        self._rpc.close()
