"""Driver layer — the client↔service boundary.

Capability-equivalent of the reference's driver contract
(``IDocumentServiceFactory → IDocumentService → {delta connection, delta
storage, storage}``; SURVEY.md §1 layer 3, §2.4; upstream paths UNVERIFIED —
empty reference mount).  Drivers are duck-typed (see :mod:`definitions`):

- :mod:`local_driver`  — binds to an in-process :class:`LocalOrderingService`
  (the reference's local-driver + server-local-server pattern).
- :mod:`replay_driver` — read-only reconstruction of any historical sequence
  point from a static op log (replay-driver / replay-tool capability).
- :mod:`file_driver`   — durable single-host deployment: file-backed op log
  and content-addressed summary store that reopen across processes.
- :mod:`network_driver` — clients in OTHER processes over TCP, against the
  :mod:`..service.server` front door (routerlicious-driver capability).
"""

from .definitions import DocumentService, DocumentStorage
from .file_driver import FileDocumentServiceFactory, FileSummaryStorage
from .local_driver import LocalDocumentServiceFactory
from .network_driver import NetworkDocumentServiceFactory
from .replay_driver import ReplayDocumentService

__all__ = [
    "DocumentService",
    "DocumentStorage",
    "FileDocumentServiceFactory",
    "FileSummaryStorage",
    "LocalDocumentServiceFactory",
    "NetworkDocumentServiceFactory",
    "ReplayDocumentService",
]
