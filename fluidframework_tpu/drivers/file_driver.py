"""File driver: a durable single-host deployment of the whole service.

Capability parity with the reference's file-driver + Historian/gitrest
storage (SURVEY.md §2.3/§2.4: summaries stored as content-addressed
objects — literally git's blob/tree model — plus per-document commit
history).  Everything lives under one directory:

    <root>/ops.jsonl          — the durable op log (OpLog format)
    <root>/objects/<digest>   — content-addressed summary nodes (JSON)
    <root>/commits.jsonl      — commit-chain records (doc, handle, refSeq,
                                parent, message) — git-style history
    <root>/refs.jsonl         — ref updates (doc, ref, commit); last wins

Reopening the directory restores the full service: documents recover from
the op log, summaries + commit history + refs from the object store."""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Optional, Union

from ..protocol.summary import (
    SummaryBlob,
    SummaryCommit,
    SummaryStorage,
    SummaryTree,
)
from ..utils.jsonl import iter_jsonl_tolerant, repair_jsonl_tail
from ..service.oplog import OpLog
from ..service.orderer import LocalOrderingService
from .local_driver import LocalDocumentServiceFactory


_iter_jsonl = iter_jsonl_tolerant


def _append_jsonl(path: str, rec: dict) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def _serialize_node(node: Union[SummaryTree, SummaryBlob]) -> bytes:
    if isinstance(node, SummaryBlob):
        obj = {"kind": "blob",
               "content": base64.b64encode(node.content).decode("ascii")}
    else:
        obj = {"kind": "tree",
               "children": {name: child.digest()
                            for name, child in sorted(node.children.items())}}
    return json.dumps(obj, sort_keys=True).encode("utf-8")


class FileSummaryStorage(SummaryStorage):
    """Content-addressed summary store persisted to a directory.

    Durability discipline (SEMANTICS.md "Durability & retry"): object
    writes are write-then-rename (a reader can never observe a partial
    object), crash-orphaned ``.tmp`` files are swept on reopen, and cold
    loads verify the content digest against the handle — a corrupt object
    file is QUARANTINED (moved aside, surfaced as the missing-handle
    ``KeyError`` contract) rather than served or crashed on.  ``faults``
    (a ``testing.faults.FaultInjector``) arms the ``storage.store`` /
    ``storage.read`` fault sites."""

    def __init__(self, root: str, faults=None) -> None:
        super().__init__()
        self.root = root
        self._faults = faults
        self._objects_dir = os.path.join(root, "objects")
        self._quarantine_dir = os.path.join(root, "quarantine")
        self._commits_path = os.path.join(root, "commits.jsonl")
        self._refs_path = os.path.join(root, "refs.jsonl")
        os.makedirs(self._objects_dir, exist_ok=True)
        # Crash hygiene: a publish that died between tmp-write and rename
        # leaves an orphan no read can ever reach — sweep, don't accrete.
        for name in sorted(os.listdir(self._objects_dir)):
            if ".tmp." in name:
                try:
                    os.remove(os.path.join(self._objects_dir, name))
                except OSError:
                    pass
        # Persist the storage epoch: a reopened store keeps its generation;
        # a wiped/recreated directory mints a new one (odsp EpochTracker).
        # Written ATOMICALLY (temp + rename), and an empty file — a crash
        # between create and write — is rewritten rather than silently
        # minting a fresh epoch on every restart.
        self._epoch_path = os.path.join(root, "epoch")
        stored = ""
        if os.path.exists(self._epoch_path):
            with open(self._epoch_path, "r", encoding="utf-8") as f:
                stored = f.read().strip()
        if stored:
            self.epoch = stored
        else:
            self._persist_epoch()
        # Repair crash-torn tails BEFORE appends resume: without this the
        # next append merges onto a torn line, silently losing the new
        # record on the following reopen (review r4 finding).
        repair_jsonl_tail(self._commits_path)
        repair_jsonl_tail(self._refs_path)
        for rec in _iter_jsonl(self._commits_path):
            # Rebuild the commit chain.  Old-format records carry no
            # "parent" field: chain them linearly onto the doc's rebuilt
            # head (exactly how they were written).
            parent = rec.get("parent", self.head(rec["doc"]))
            self._record_commit(SummaryCommit(
                doc_id=rec["doc"], tree=rec["handle"],
                parent=parent, ref_seq=rec["refSeq"],
                message=rec.get("message", ""),
            ))
        for rec in _iter_jsonl(self._refs_path):
            # Last record wins per (doc, ref).  Same validation create_ref
            # enforces: a pin whose commit never made it to commits.jsonl
            # (torn write) is dropped rather than left to KeyError readers.
            if rec["commit"] in self._commit_objects:
                self._set_ref(rec["doc"], rec["ref"], rec["commit"])
        #: refresh_doc memo: (commits, refs) file sizes already ingested
        self._chain_sizes = self._chain_file_sizes()  # guarded-by: _lock

    def _persist_epoch(self) -> None:
        tmp_path = self._epoch_path + ".tmp"
        with open(tmp_path, "w", encoding="utf-8") as f:
            f.write(self.epoch)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, self._epoch_path)  # commit-point: epoch publish
        # fsync the DIRECTORY too: the rename itself must be durable,
        # or a crash could lose the epoch file and a reopen would mint
        # a new generation for a store whose data survived.
        dfd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def bump_epoch(self, token: str) -> str:
        """Generation fence, persisted: a restart after a shard failover
        must reopen into the POST-fence generation, or clients that
        already reconnected through the fence would be told their fresh
        caches are stale (or worse, pre-fence pins would validate)."""
        super().bump_epoch(token)
        self._persist_epoch()
        return token

    def _chain_file_sizes(self) -> tuple:
        def size(path):
            try:
                return os.path.getsize(path)
            except OSError:
                return 0
        return (size(self._commits_path), size(self._refs_path))

    def refresh_doc(self, doc_id: str) -> None:
        """Merge commit-chain records other PROCESSES appended to the
        shared files (fluidproc adoption/migration): in the
        out-of-process tier every shard host holds its own instance over
        the SAME root, and a document's chain is appended by its single
        owner — when ownership moves, the new owner's in-memory view is
        stale for exactly the moved documents.  Append-only files + one
        writer per document (the freeze/kill precedes the move) make
        this a pure catch-up read: known records skip by digest, the
        last ref record wins.

        The scan ingests EVERY document's new records (not just
        ``doc_id``) and memoizes by file size, so a mass failover pays
        ONE file pass for its whole adoption wave instead of one per
        document; own-instance uploads keep the memo current."""
        with self._lock:
            sizes = self._chain_file_sizes()
            if sizes == getattr(self, "_chain_sizes", None):
                return
            replay_heads: dict = {}
            for rec in _iter_jsonl(self._commits_path):
                doc = rec["doc"]
                parent = rec.get("parent",
                                 replay_heads.get(doc, self.head(doc)))
                commit = SummaryCommit(
                    doc_id=doc, tree=rec["handle"], parent=parent,
                    ref_seq=rec["refSeq"], message=rec.get("message", ""),
                )
                digest = commit.digest()
                replay_heads[doc] = digest
                if digest not in self._commit_objects:
                    self._record_commit(commit)
            for rec in _iter_jsonl(self._refs_path):
                if rec["commit"] in self._commit_objects:
                    self._set_ref(rec["doc"], rec["ref"], rec["commit"])
            self._chain_sizes = sizes

    # -- persistence hooks -----------------------------------------------------

    def upload(self, doc_id: str, tree: SummaryTree, ref_seq: int,
               message: str = "") -> str:
        with self._lock:  # chain update + durable record stay one unit
            handle = super().upload(doc_id, tree, ref_seq, message=message)
            # Persist the commit the base class actually recorded (it is
            # the new head) — never a parallel reconstruction that could
            # diverge.
            commit = self.read_commit(self.head(doc_id))
            _append_jsonl(self._commits_path, {
                "doc": commit.doc_id, "handle": commit.tree,
                "refSeq": commit.ref_seq, "parent": commit.parent,
                "message": commit.message,
            })  # commit-point: summary commit record
            # Deliberately NOT refreshing the scan memo here: the file
            # size now also covers bytes OTHER processes appended since
            # our last scan, and marking those as seen would make the
            # next refresh skip records it never ingested (an adopted
            # doc's summary chain would silently vanish).  An own append
            # merely costs the next refresh one re-scan.
            return handle

    def create_ref(self, doc_id: str, name: str, commit_digest: str) -> None:
        with self._lock:
            super().create_ref(doc_id, name, commit_digest)
            _append_jsonl(self._refs_path,
                          {"doc": doc_id, "ref": name,
                           "commit": commit_digest})  # commit-point: ref pin record

    def _store(self, node: Union[SummaryTree, SummaryBlob]) -> str:
        digest = super()._store(node)
        path = os.path.join(self._objects_dir, digest)
        if not os.path.exists(path):  # content-addressed: write-once
            fault = (self._faults.fire("storage.store")
                     if self._faults is not None else None)
            # Atomic publish: executor-thread uploads run concurrently
            # with event-loop reads of the same content-addressed object —
            # a reader must never observe a partially-written file.
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            if fault is not None:
                self._faulted_store(fault, tmp, node)
            with open(tmp, "wb") as f:
                f.write(_serialize_node(node))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # commit-point: summary object publish
        return digest

    def _faulted_store(self, fault, tmp: str,
                       node: Union[SummaryTree, SummaryBlob]) -> None:
        """Injected upload failure: ``fail`` dies before any bytes,
        ``torn`` leaves a partial ``.tmp`` (the pre-rename crash shape —
        never visible to reads, swept on reopen).  Either way the object
        file does not exist and the upload surfaces an OSError the
        caller's retry re-publishes cleanly."""
        from ..testing.faults import FaultError

        if fault.kind == "torn":
            data = _serialize_node(node)
            frac = fault.arg if 0.0 < fault.arg < 1.0 else 0.5
            with open(tmp, "wb") as f:
                f.write(data[:max(1, int(len(data) * frac))])
                f.flush()
                os.fsync(f.fileno())
        raise FaultError("storage.store", fault.kind)

    # -- lazy reads from disk (latest() inherits these via read()) -------------

    def read(self, handle: str) -> Union[SummaryTree, SummaryBlob]:
        # Probe / load / publish, each a SINGLE critical section (the
        # begin/publish shape of the orderer's single-flight recovery):
        # the disk read happens OUTSIDE the lock — holding the store-wide
        # lock across I/O would serialize every head()/upload() behind
        # one cold load.  Content-addressing makes the load race benign:
        # two threads loading the same handle produce identical nodes,
        # and the publish's setdefault atomically re-validates so exactly
        # one survives.
        cached = self._probe_memo(handle)
        if cached is not None:
            return cached
        return self._publish_memo(handle, self._load_from_disk(handle))

    def _probe_memo(self, handle: str
                    ) -> Optional[Union[SummaryTree, SummaryBlob]]:
        with self._lock:
            return self._objects.get(handle)

    def _publish_memo(self, handle: str,
                      node: Union[SummaryTree, SummaryBlob]
                      ) -> Union[SummaryTree, SummaryBlob]:
        """One atomic claim: install-or-adopt — a racing duplicate load
        loses to whichever byte-identical node published first."""
        with self._lock:
            return self._objects.setdefault(handle, node)

    def latest_with_handle(self, doc_id: str, at_or_below: int = None):
        fault = (self._faults.fire("storage.read", doc=doc_id)
                 if self._faults is not None else None)
        if fault is not None and fault.kind == "fail":
            from ..testing.faults import FaultError

            raise FaultError("storage.read", "fail", doc_id)
        if fault is not None and fault.kind == "stale":
            # A lagging replica: serve the PARENT summary when one exists
            # — the client replays a longer op tail and must converge to
            # the same state (the catch-up path's whole correctness
            # claim; pinned by the chaos oracle).
            newest = True
            for commit in self._walk(self.head(doc_id)):
                if at_or_below is not None and commit.ref_seq > at_or_below:
                    continue
                if newest and commit.parent is not None:
                    newest = False
                    continue
                return self.read(commit.tree), commit.ref_seq, commit.tree
            return None, 0, None
        return super().latest_with_handle(doc_id, at_or_below=at_or_below)

    def _quarantine(self, digest: str, path: str, why: str) -> None:
        """A corrupt content-addressed object: move it aside (forensics,
        and so the next write-once publish can heal the handle) and
        surface the store's missing-handle contract — callers already
        treat KeyError as 'fetch it another way', which is exactly what a
        torn record must degrade to.  Never serve, never crash."""
        os.makedirs(self._quarantine_dir, exist_ok=True)
        dest = os.path.join(self._quarantine_dir, digest)
        try:
            os.replace(path, dest)
        except OSError:
            pass  # best-effort: losing the evidence must not mask the miss
        raise KeyError(
            f"summary object {digest} was corrupt ({why}); quarantined")

    def _load_from_disk(self, digest: str) -> Union[SummaryTree, SummaryBlob]:
        path = os.path.join(self._objects_dir, digest)
        if not os.path.exists(path):
            raise KeyError(digest)
        with open(path, "rb") as f:
            raw = f.read()
        try:
            obj = json.loads(raw)
            if obj["kind"] == "blob":
                node: Union[SummaryTree, SummaryBlob] = SummaryBlob(
                    base64.b64decode(obj["content"]))
                children = {}
            else:
                node = SummaryTree()
                children = dict(obj["children"])
        except (ValueError, KeyError, TypeError) as exc:
            self._quarantine(digest, path, f"undecodable: {exc!r}")
        for name, child_digest in children.items():
            # A missing/quarantined CHILD propagates its own KeyError —
            # this (structurally valid) parent is not the corrupt record.
            node.children[name] = self.read(child_digest)
        # Checksum gate: content-addressing means the handle IS the
        # checksum — a decodable-but-wrong object (bit rot, torn write
        # that still parses) must not be served under a digest it does
        # not hash to.
        if node.digest() != digest:
            self._quarantine(digest, path, "digest mismatch")
        return node


class FileDocumentServiceFactory(LocalDocumentServiceFactory):
    """The whole service stack rooted in one directory; reopen to resume."""

    def __init__(self, root: str, faults=None) -> None:
        os.makedirs(root, exist_ok=True)
        self.root = root
        service = LocalOrderingService(
            oplog=OpLog(os.path.join(root, "ops.jsonl"), faults=faults),
            storage=FileSummaryStorage(root, faults=faults),
        )
        super().__init__(service)

    def close(self) -> None:
        # Idempotent end to end: OpLog.close() no-ops once its file handle
        # is None'd, so a factory closed from both a host teardown and a
        # with-block/atexit sweep flushes and closes exactly once
        # (fluidleak FL-LEAK-DOUBLE-CLOSE discipline; pinned by
        # tests/test_lifecycle.py).
        self.service.oplog.close()
