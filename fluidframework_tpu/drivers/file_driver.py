"""File driver: a durable single-host deployment of the whole service.

Capability parity with the reference's file-driver + Historian/gitrest
storage (SURVEY.md §2.3/§2.4: summaries stored as content-addressed
objects — literally git's blob/tree model — plus per-document commit
history).  Everything lives under one directory:

    <root>/ops.jsonl          — the durable op log (OpLog format)
    <root>/objects/<digest>   — content-addressed summary nodes (JSON)
    <root>/commits.jsonl      — (doc_id, handle, ref_seq) commit records

Reopening the directory restores the full service: documents recover from
the op log, summaries from the object store."""

from __future__ import annotations

import base64
import json
import os
from typing import Optional, Union

from ..protocol.summary import SummaryBlob, SummaryStorage, SummaryTree
from ..service.oplog import OpLog
from ..service.orderer import LocalOrderingService
from .local_driver import LocalDocumentServiceFactory


def _serialize_node(node: Union[SummaryTree, SummaryBlob]) -> bytes:
    if isinstance(node, SummaryBlob):
        obj = {"kind": "blob",
               "content": base64.b64encode(node.content).decode("ascii")}
    else:
        obj = {"kind": "tree",
               "children": {name: child.digest()
                            for name, child in sorted(node.children.items())}}
    return json.dumps(obj, sort_keys=True).encode("utf-8")


class FileSummaryStorage(SummaryStorage):
    """Content-addressed summary store persisted to a directory."""

    def __init__(self, root: str) -> None:
        super().__init__()
        self.root = root
        self._objects_dir = os.path.join(root, "objects")
        self._commits_path = os.path.join(root, "commits.jsonl")
        os.makedirs(self._objects_dir, exist_ok=True)
        if os.path.exists(self._commits_path):
            with open(self._commits_path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    self._commits.setdefault(rec["doc"], []).append(
                        (rec["handle"], rec["refSeq"])
                    )

    # -- persistence hooks -----------------------------------------------------

    def upload(self, doc_id: str, tree: SummaryTree, ref_seq: int) -> str:
        handle = super().upload(doc_id, tree, ref_seq)
        with open(self._commits_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(
                {"doc": doc_id, "handle": handle, "refSeq": ref_seq},
                sort_keys=True,
            ) + "\n")
        return handle

    def _store(self, node: Union[SummaryTree, SummaryBlob]) -> str:
        digest = super()._store(node)
        path = os.path.join(self._objects_dir, digest)
        if not os.path.exists(path):  # content-addressed: write-once
            with open(path, "wb") as f:
                f.write(_serialize_node(node))
        return digest

    # -- lazy reads from disk (latest() inherits these via read()) -------------

    def read(self, handle: str) -> Union[SummaryTree, SummaryBlob]:
        cached = self._objects.get(handle)
        if cached is not None:
            return cached
        node = self._load_from_disk(handle)
        self._objects[handle] = node
        return node

    def _load_from_disk(self, digest: str) -> Union[SummaryTree, SummaryBlob]:
        path = os.path.join(self._objects_dir, digest)
        if not os.path.exists(path):
            raise KeyError(digest)
        with open(path, "rb") as f:
            obj = json.loads(f.read())
        if obj["kind"] == "blob":
            return SummaryBlob(base64.b64decode(obj["content"]))
        tree = SummaryTree()
        for name, child_digest in obj["children"].items():
            tree.children[name] = self.read(child_digest)
        return tree


class FileDocumentServiceFactory(LocalDocumentServiceFactory):
    """The whole service stack rooted in one directory; reopen to resume."""

    def __init__(self, root: str) -> None:
        os.makedirs(root, exist_ok=True)
        self.root = root
        service = LocalOrderingService(
            oplog=OpLog(os.path.join(root, "ops.jsonl")),
            storage=FileSummaryStorage(root),
        )
        super().__init__(service)

    def close(self) -> None:
        self.service.oplog.close()
