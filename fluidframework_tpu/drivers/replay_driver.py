"""Replay driver: deterministic reconstruction of historical states.

Capability parity with the reference's replay-driver / replay-tool
(SURVEY.md §2.4: replay an op log offline against snapshots, rebuild any
historical sequence number).  The "connection" is inert: nothing can be
submitted, nothing new arrives; the log *is* the document."""

from __future__ import annotations

from typing import List, Optional

from ..protocol.messages import RawOperation, SequencedMessage
from ..protocol.summary import SummaryStorage
from ..service.oplog import OpLog
from .definitions import DeltaStorage, DocumentStorage


class _ReadOnlyConnection:
    """A delta connection that rejects writes and never delivers."""

    def __init__(self, log: List[SequencedMessage]) -> None:
        self.log = log  # backfill feed for ContainerRuntime.connect

    def connect(self, client_id: str, session=None) -> None:
        pass  # no quorum to join: replay is not a live participant

    def disconnect(self, client_id: str) -> None:
        pass

    def subscribe(self, fn) -> None:
        pass  # nothing live will ever arrive

    def unsubscribe(self, fn) -> None:
        pass

    def submit(self, op: RawOperation):
        raise PermissionError("replay driver is read-only")

    def submit_signal(self, *a, **k):
        raise PermissionError("replay driver is read-only")

    def subscribe_signals(self, fn) -> None:
        pass


class _BoundedDeltaStorage(DeltaStorage):
    """Clamps reads to the replay horizon."""

    def __init__(self, oplog: OpLog, doc_id: str,
                 to_seq: Optional[int]) -> None:
        super().__init__(oplog, doc_id)
        self._to_seq = to_seq

    def get(self, from_seq: int = 0, to_seq: Optional[int] = None):
        horizon = self._to_seq
        if horizon is not None:
            to_seq = horizon if to_seq is None else min(to_seq, horizon)
        return super().get(from_seq, to_seq)

    def head(self) -> int:
        head = super().head()
        return head if self._to_seq is None else min(head, self._to_seq)


class _BoundedDocumentStorage(DocumentStorage):
    """Never serves a summary newer than the replay horizon."""

    def __init__(self, storage: SummaryStorage, doc_id: str,
                 to_seq: Optional[int]) -> None:
        super().__init__(storage, doc_id)
        self._to_seq = to_seq

    def latest(self, at_or_below: Optional[int] = None):
        bound = self._to_seq
        if at_or_below is not None:
            bound = at_or_below if bound is None else min(bound, at_or_below)
        return self._storage.latest(self.doc_id, at_or_below=bound)

    def upload(self, tree, ref_seq: int) -> str:
        raise PermissionError("replay driver is read-only")


class ReplayDocumentService:
    """Driver surface over a static (oplog, storage) pair, optionally
    truncated at ``to_seq`` — load a container "as of" any sequence point."""

    def __init__(
        self,
        doc_id: str,
        oplog: OpLog,
        storage: SummaryStorage,
        to_seq: Optional[int] = None,
    ) -> None:
        self.doc_id = doc_id
        self.delta_storage = _BoundedDeltaStorage(oplog, doc_id, to_seq)
        self.storage = _BoundedDocumentStorage(storage, doc_id, to_seq)
        self._connection = _ReadOnlyConnection(self.delta_storage.get())

    def connection(self):
        return self._connection
