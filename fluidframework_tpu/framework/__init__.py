"""Framework / app-facing API layer.

Capability-equivalent of the reference's ``aqueduct`` + ``fluid-static`` +
service-clients + ``presence`` + ``undo-redo`` packages (SURVEY.md §1 layer
8, §2.4; upstream paths UNVERIFIED — empty reference mount)."""

from .data_object import DataObject, DataObjectFactory
from .fluid_static import ContainerSchema, FluidClient, FluidContainer
from .presence import Presence
from .undo_redo import UndoRedoStackManager

__all__ = [
    "ContainerSchema",
    "DataObject",
    "DataObjectFactory",
    "FluidClient",
    "FluidContainer",
    "Presence",
    "UndoRedoStackManager",
]
