"""FluidClient / FluidContainer — the fluid-static + service-client
capability: schema-declared containers with ``initial_objects``.

The reference's ``TinyliciousClient``/``AzureClient`` expose
``createContainer(schema)`` / ``getContainer(id, schema)`` returning a
``FluidContainer`` whose ``initialObjects`` are DDS instances declared in
the schema.  Same shape here, over any driver factory (local, file, …)."""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Optional

from ..loader.loader import Container, Loader
from ..runtime.registry import ChannelRegistry

_INITIAL_DS = "initial-objects"
_client_counter = itertools.count(1)


@dataclasses.dataclass
class ContainerSchema:
    """``initial_objects``: name → channel type string (e.g.
    {"notes": "sequence-tpu", "votes": "map-tpu"})."""

    initial_objects: Dict[str, str]


class FluidContainer:
    """App-facing facade over a loaded Container."""

    def __init__(self, container: Container,
                 schema: ContainerSchema) -> None:
        self._container = container
        self.schema = schema
        ds = container.runtime.get_datastore(_INITIAL_DS)
        self.initial_objects = {
            name: ds.get_channel(name) for name in schema.initial_objects
        }

    @property
    def audience(self):
        return self._container.audience

    @property
    def connected(self) -> bool:
        return self._container.connected

    @property
    def client_id(self):
        return self._container.client_id

    def create_channel(self, type_name: str, channel_id: str):
        """Dynamic object creation (the reference's container.create)."""
        ds = self._container.runtime.get_datastore(_INITIAL_DS)
        return ds.create_channel(type_name, channel_id)

    def sync(self) -> int:
        """Pump inbound delivery (hosts drive this from their loop)."""
        return self._container.drain()

    def submit_signal(self, content, target_client_id=None) -> None:
        self._container.delta_manager.submit_signal(content,
                                                    target_client_id)

    def on_signal(self, fn) -> None:
        self._container.delta_manager.subscribe_signals(fn)

    def disconnect(self) -> None:
        self._container.disconnect()

    def reconnect(self) -> None:
        self._container.reconnect()

    def close(self) -> None:
        self._container.close()

    def close_and_get_pending_state(self) -> dict:
        return self._container.close_and_get_pending_state()


class FluidClient:
    """create_container / get_container over a driver factory."""

    def __init__(self, driver_factory,
                 registry: Optional[ChannelRegistry] = None,
                 client_id_prefix: str = "client",
                 runtime_options=None) -> None:
        """``runtime_options`` (ContainerRuntimeOptions) reaches every
        runtime this client creates — e.g. ``attribution=True`` stamps
        created documents as attribution-enabled."""
        self.loader = Loader(driver_factory, registry,
                             runtime_options=runtime_options)
        self._prefix = client_id_prefix

    def _next_client_id(self) -> str:
        return f"{self._prefix}-{next(_client_counter)}"

    def create_container(self, doc_id: str,
                         schema: ContainerSchema) -> FluidContainer:
        def build(runtime):
            ds = runtime.create_datastore(_INITIAL_DS)
            for name, type_name in schema.initial_objects.items():
                ds.create_channel(type_name, name)

        container = self.loader.create(doc_id, self._next_client_id(), build)
        return FluidContainer(container, schema)

    def get_container(self, doc_id: str,
                      schema: ContainerSchema,
                      pending_state: Optional[dict] = None) -> FluidContainer:
        container = self.loader.resolve(
            doc_id, self._next_client_id(), pending_state=pending_state
        )
        return FluidContainer(container, schema)
