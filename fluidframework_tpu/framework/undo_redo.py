"""Undo-redo: revertible tracking over DDS change events.

Capability-equivalent of the reference's ``undo-redo`` package (SURVEY.md
§2.4: ``UndoRedoStackManager`` + sequence/map revertibles; upstream paths
UNVERIFIED — empty reference mount).

The manager subscribes to *local* change events on attached DDSes and
pushes a revertible per change (or per ``operation()`` group).  ``undo()``
applies the inverse as a fresh local op — concurrent remote edits merge
against it through the normal op path, exactly like the reference (undo is
"apply the inverse now", not "rewind history").

Supported revertibles:
- SharedMap / SharedCell:  restore the previous value (set/delete).
- SharedCounter:           increment by the negative delta.
- SharedString:            insert ↔ remove (positions re-resolved at the
  revert point via the recorded text — see caveat in ``_StringRevertible``).
- SharedTree:              changeset inversion (``undo_changeset``).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, List, Optional


class _Revertible:
    def __init__(self, fn: Callable[[], None]) -> None:
        self._fn = fn

    def revert(self) -> None:
        self._fn()


class UndoRedoStackManager:
    """Open/closeable operation groups of revertibles with undo/redo."""

    def __init__(self) -> None:
        self._undo: List[List[_Revertible]] = []
        self._redo: List[List[_Revertible]] = []
        self._open: Optional[List[_Revertible]] = None
        self._reverting = False
        self._subscriptions: List[tuple] = []

    # -- attaching DDSes -------------------------------------------------------

    def attach(self, dds) -> None:
        """Track local changes on a DDS (dispatched on its TYPE)."""
        type_name = dds.TYPE
        if type_name in ("map-tpu",):
            fn = dds.events.on("valueChanged",
                               lambda ev, local: self._on_map(dds, ev, local))
        elif type_name == "cell-tpu":
            fn = dds.events.on("valueChanged",
                               lambda ev, local: self._on_cell(dds, ev, local))
        elif type_name == "counter-tpu":
            fn = dds.events.on(
                "incremented",
                lambda ev, local: self._on_counter(dds, ev, local))
        elif type_name == "sequence-tpu":
            fn = dds.events.on(
                "sequenceDelta",
                lambda ev, local: self._on_string(dds, ev, local))
        elif type_name == "tree-tpu":
            fn = dds.events.on("changed",
                               lambda ev, local: self._on_tree(dds, ev, local))
        else:
            raise ValueError(f"no revertible support for {type_name!r}")
        self._subscriptions.append((dds, fn))

    # -- grouping --------------------------------------------------------------

    @contextlib.contextmanager
    def operation(self):
        """Group every tracked change inside into ONE undoable step."""
        self._open = []
        try:
            yield
        finally:
            group, self._open = self._open, None
            if group:
                self._undo.append(group)
                self._redo.clear()

    def _push(self, revertible: _Revertible) -> None:
        if self._reverting:
            return  # reverts are captured by undo()/redo() themselves
        if self._open is not None:
            self._open.append(revertible)
        else:
            self._undo.append([revertible])
            self._redo.clear()

    # -- undo / redo -----------------------------------------------------------

    @property
    def can_undo(self) -> bool:
        return bool(self._undo)

    @property
    def can_redo(self) -> bool:
        return bool(self._redo)

    def undo(self) -> bool:
        return self._revert(self._undo, self._redo)

    def redo(self) -> bool:
        return self._revert(self._redo, self._undo)

    def _revert(self, source: List, sink: List) -> bool:
        if not source:
            return False
        group = source.pop()
        inverse_group: List[_Revertible] = []
        self._reverting = True
        try:
            # Capture each revert's own inverse by re-recording through the
            # same event hooks — but _reverting suppresses _push, so hooks
            # record into inverse_group via _capture instead.
            self._capture_target = inverse_group
            for revertible in reversed(group):
                revertible.revert()
        finally:
            self._reverting = False
            self._capture_target = None
        if inverse_group:
            sink.append(inverse_group)
        return True

    _capture_target: Optional[List[_Revertible]] = None

    def _record(self, revertible: _Revertible) -> None:
        if self._reverting:
            if self._capture_target is not None:
                self._capture_target.append(revertible)
            return
        self._push(revertible)

    # -- per-DDS hooks (local changes only) ------------------------------------

    def _on_map(self, dds, ev: dict, local: bool) -> None:
        if not local:
            return
        key, prev = ev["key"], ev["previousValue"]
        existed = ev.get("previousExisted", prev is not None)

        def revert(key=key, prev=prev, existed=existed):
            if existed:
                dds.set(key, prev)
            elif dds.has(key):
                dds.delete(key)

        self._record(_Revertible(revert))

    def _on_cell(self, dds, ev: dict, local: bool) -> None:
        if not local:
            return
        prev = ev["previousValue"]

        def revert(prev=prev):
            if prev is None:
                dds.delete()
            else:
                dds.set(prev)

        self._record(_Revertible(revert))

    def _on_counter(self, dds, ev: dict, local: bool) -> None:
        if not local:
            return
        delta = ev["incrementAmount"]
        self._record(_Revertible(lambda: dds.increment(-delta)))

    def _on_string(self, dds, ev: dict, local: bool) -> None:
        if not local:
            return
        kind = ev["kind"]
        if kind == "insert":
            pos, text = ev["pos"], ev["text"]

            def revert(pos=pos, text=text):
                # Re-locate the inserted run: concurrent edits may have
                # shifted it.  Search near the original position first.
                current = dds.text
                idx = current.find(text, max(0, pos - 64))
                if idx < 0:
                    idx = current.find(text)
                if idx >= 0:
                    dds.remove_range(idx, idx + len(text))

            self._record(_Revertible(revert))
        elif kind == "remove":
            start, removed = ev["start"], ev["removedText"]
            self._record(_Revertible(
                lambda s=start, t=removed: dds.insert_text(
                    min(s, len(dds.text)), t)
            ))
        elif kind == "annotate":
            pass  # property layering: inverse annotate needs prior props

    def _on_tree(self, dds, ev: dict, local: bool) -> None:
        if not local:
            return
        cs = ev["changeset"]
        self._record(_Revertible(lambda: dds.undo_changeset(cs)))
