"""Presence: ephemeral per-user state over signals (never sequenced).

Capability-equivalent of the reference's ``presence`` package (SURVEY.md
§2.4: workspaces of per-client values rides signals, not ops — nothing
persists, nothing reaches the op log).

Protocol: every local update broadcasts
``{"presence": workspace, "key": ..., "value": ...}``.  A newly attached
presence instance broadcasts a ``presenceRequest``; every peer re-sends
its local values (targeted at the requester), so late joiners see current
presence without any durable state."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..utils.events import EventEmitter


class PresenceWorkspace:
    """One named bag of per-client ephemeral values (e.g. cursors)."""

    def __init__(self, presence: "Presence", name: str) -> None:
        self._presence = presence
        self.name = name
        self.events = EventEmitter()  # "updated" (client_id, key, value)
        self._local: Dict[str, Any] = {}
        self._remote: Dict[str, Dict[str, Any]] = {}  # client -> {key: val}

    # -- local side ------------------------------------------------------------

    def set_local(self, key: str, value: Any) -> None:
        self._local[key] = value
        self._presence._broadcast(self.name, key, value)

    def get_local(self, key: str, default: Any = None) -> Any:
        return self._local.get(key, default)

    # -- remote side -----------------------------------------------------------

    def get(self, client_id: str, key: str, default: Any = None) -> Any:
        return self._remote.get(client_id, {}).get(key, default)

    def clients(self):
        return sorted(self._remote)

    def all(self, key: str) -> Dict[str, Any]:
        return {c: vals[key] for c, vals in sorted(self._remote.items())
                if key in vals}

    # -- wire ------------------------------------------------------------------

    def _apply(self, client_id: str, key: str, value: Any) -> None:
        self._remote.setdefault(client_id, {})[key] = value
        self.events.emit("updated", client_id, key, value)

    def _drop_client(self, client_id: str) -> None:
        if self._remote.pop(client_id, None) is not None:
            self.events.emit("clientLeft", client_id)

    def _resend_local(self, target: Optional[str]) -> None:
        for key, value in self._local.items():
            self._presence._broadcast(self.name, key, value, target=target)


class Presence:
    """Attach to a FluidContainer (or anything with ``submit_signal`` /
    ``on_signal`` / ``client_id``)."""

    def __init__(self, container) -> None:
        self._container = container
        self._workspaces: Dict[str, PresenceWorkspace] = {}
        container.on_signal(self._on_signal)
        # Ask peers for their current state.
        container.submit_signal({"presenceRequest": True})

    def workspace(self, name: str) -> PresenceWorkspace:
        ws = self._workspaces.get(name)
        if ws is None:
            ws = PresenceWorkspace(self, name)
            self._workspaces[name] = ws
        return ws

    # -- wire ------------------------------------------------------------------

    def _broadcast(self, workspace: str, key: str, value: Any,
                   target: Optional[str] = None) -> None:
        self._container.submit_signal(
            {"presence": workspace, "key": key, "value": value},
            target_client_id=target,
        )

    def _on_signal(self, signal: dict) -> None:
        target = signal.get("targetClientId")
        me = self._container.client_id
        if target is not None and target != me:
            return
        sender = signal.get("clientId")
        if sender == me:
            return  # our own broadcast
        content = signal.get("content") or {}
        if content.get("presenceRequest"):
            for ws in self._workspaces.values():
                ws._resend_local(sender)
            return
        name = content.get("presence")
        if name is None:
            return
        self.workspace(name)._apply(sender, content["key"],
                                    content["value"])
