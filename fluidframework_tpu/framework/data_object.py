"""DataObject — the aqueduct capability: a datastore with typed channels.

The reference's ``DataObject``/``DataObjectFactory`` (aqueduct) wrap a
datastore in a class with named DDS members created at initialization and
re-bound at load.  Here a DataObject declares ``CHANNELS`` (name → channel
type string); the factory materializes them on create and binds them as
attributes on load."""

from __future__ import annotations

from typing import Dict, Optional

from ..runtime.container import ContainerRuntime
from ..runtime.datastore import FluidDataStoreRuntime


class DataObject:
    """Subclass with ``CHANNELS = {"text": "sequence-tpu", ...}``; channels
    appear as same-named attributes."""

    CHANNELS: Dict[str, str] = {}

    def __init__(self, datastore: FluidDataStoreRuntime) -> None:
        self.datastore = datastore
        self.id = datastore.id
        for name in type(self).CHANNELS:
            setattr(self, name, datastore.get_channel(name))

    def initialize_first_time(self) -> None:
        """Override: one-time setup when the object is first created
        (before attach) — the reference's initializingFirstTime."""

    def initialize_from_existing(self) -> None:
        """Override: re-initialization when loaded from a summary —
        the reference's initializingFromExisting."""


class DataObjectFactory:
    """Creates/loads a DataObject subclass over a datastore."""

    def __init__(self, cls) -> None:
        self.cls = cls

    def create(self, runtime: ContainerRuntime, datastore_id: str,
               rooted: bool = True) -> DataObject:
        ds = runtime.create_datastore(datastore_id, rooted=rooted)
        for name, type_name in self.cls.CHANNELS.items():
            ds.create_channel(type_name, name)
        obj = self.cls(ds)
        obj.initialize_first_time()
        return obj

    def load(self, runtime: ContainerRuntime,
             datastore_id: str) -> DataObject:
        obj = self.cls(runtime.get_datastore(datastore_id))
        obj.initialize_from_existing()
        return obj
