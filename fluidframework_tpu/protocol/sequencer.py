"""In-process total-order sequencer.

Capability-equivalent of the reference's Deli ``ticket()`` sequencing lambda
wired in-process the way ``memory-orderer``'s ``LocalOrderer`` does
(SURVEY.md §2.3; upstream paths UNVERIFIED — empty reference mount): one class,
no Kafka.  Responsibilities:

- stamp each raw op with a monotonically increasing ``seq``;
- track each connected client's ``ref_seq`` and compute the
  ``minimumSequenceNumber`` (MSN) — min over connected clients' ref_seq;
- dedupe resubmitted ops by (client_id, client_seq);
- broadcast sequenced messages to subscribers in order and append them to the
  durable op log (the scriptorium-equivalent feed that catch-up replay and the
  TPU batch-replay path consume).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional

from .messages import INITIAL_SEQ, MessageType, RawOperation, SequencedMessage


@dataclasses.dataclass
class ClientConnection:
    """Sequencer-side record of a connected client."""

    client_id: str
    ref_seq: int
    last_client_seq: int = 0  # highest client_seq sequenced (dedup floor)


class Sequencer:
    """Single-document total-order sequencer with MSN tracking.

    Deterministic: sequencing depends only on the submission order, so tests
    and the fuzz harness can drive interleavings explicitly.
    """

    def __init__(self, start_seq: int = INITIAL_SEQ) -> None:
        self._seq = start_seq
        self._min_seq = start_seq
        self._clients: Dict[str, ClientConnection] = {}
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        self._log: List[SequencedMessage] = []
        self._clock = itertools.count()

    # -- connection management -------------------------------------------------

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def min_seq(self) -> int:
        return self._min_seq

    @property
    def log(self) -> List[SequencedMessage]:
        """The durable op log (scriptorium feed)."""
        return self._log

    def connect(self, client_id: str) -> ClientConnection:
        """Join a client to the quorum; emits a JOIN message."""
        if client_id in self._clients:
            raise ValueError(f"client {client_id!r} already connected")
        conn = ClientConnection(client_id=client_id, ref_seq=self._seq)
        self._clients[client_id] = conn
        self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=MessageType.JOIN,
            contents={"clientId": client_id},
        )
        return conn

    def disconnect(self, client_id: str) -> None:
        """Remove a client from the quorum; emits LEAVE and recomputes MSN."""
        if client_id not in self._clients:
            return
        del self._clients[client_id]
        self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=MessageType.LEAVE,
            contents={"clientId": client_id},
        )

    # -- sequencing ------------------------------------------------------------

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        """Sequence one raw op (the Deli ``ticket()`` hot loop).

        Returns the sequenced message, or None if the op was a duplicate
        (already-sequenced client_seq, e.g. a redundant resubmit after
        reconnect).
        """
        conn = self._clients.get(op.client_id)
        if conn is None:
            raise ValueError(f"client {op.client_id!r} is not connected")
        if op.client_seq <= conn.last_client_seq:
            return None  # duplicate — dedup by clientSeq
        conn.last_client_seq = op.client_seq
        conn.ref_seq = max(conn.ref_seq, op.ref_seq)
        return self._stamp(
            client_id=op.client_id,
            client_seq=op.client_seq,
            ref_seq=op.ref_seq,
            type_=op.type,
            contents=op.contents,
        )

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        """Heartbeat path: a client reports processed-up-to without an op."""
        conn = self._clients.get(client_id)
        if conn is None:
            return
        conn.ref_seq = max(conn.ref_seq, ref_seq)
        self._recompute_min_seq()

    def tick(self) -> SequencedMessage:
        """Emit a NO_OP heartbeat: advances seq and propagates the current MSN
        to clients without carrying an operation."""
        return self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=MessageType.NO_OP,
            contents=None,
        )

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        """Register a delivery callback; called in total order for every
        sequenced message (the Alfred broadcast capability)."""
        self._subscribers.append(fn)

    # -- internals -------------------------------------------------------------

    def _recompute_min_seq(self) -> None:
        if self._clients:
            msn = min(c.ref_seq for c in self._clients.values())
        else:
            msn = self._seq
        # MSN is monotone.
        self._min_seq = max(self._min_seq, msn)

    def _stamp(
        self,
        client_id: Optional[str],
        client_seq: int,
        ref_seq: int,
        type_: MessageType,
        contents,
    ) -> SequencedMessage:
        self._seq += 1
        self._recompute_min_seq()
        msg = SequencedMessage(
            seq=self._seq,
            client_id=client_id,
            client_seq=client_seq,
            ref_seq=ref_seq,
            min_seq=self._min_seq,
            type=type_,
            contents=contents,
            timestamp=float(next(self._clock)),
        )
        self._log.append(msg)
        for fn in list(self._subscribers):
            fn(msg)
        return msg
