"""In-process total-order sequencer.

Capability-equivalent of the reference's Deli ``ticket()`` sequencing lambda
wired in-process the way ``memory-orderer``'s ``LocalOrderer`` does
(SURVEY.md §2.3; upstream paths UNVERIFIED — empty reference mount): one class,
no Kafka.  Responsibilities:

- stamp each raw op with a monotonically increasing ``seq``;
- track each connected client's ``ref_seq`` and compute the
  ``minimumSequenceNumber`` (MSN) — min over connected clients' ref_seq;
- dedupe resubmitted ops by (client_id, client_seq);
- broadcast sequenced messages to subscribers in order and append them to the
  durable op log (the scriptorium-equivalent feed that catch-up replay and the
  TPU batch-replay path consume).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from .messages import (
    INITIAL_SEQ,
    BatchAbortedError,
    MessageType,
    NackError,
    RawOperation,
    SequencedMessage,
)


@dataclasses.dataclass
class ClientConnection:
    """Sequencer-side record of a connected client."""

    client_id: str
    ref_seq: int
    last_client_seq: int = 0  # highest client_seq sequenced (dedup floor)
    session: Optional[str] = None  # connection epoch (crash-resume identity)


class Sequencer:
    """Single-document total-order sequencer with MSN tracking.

    Deterministic: sequencing depends only on the submission order, so tests
    and the fuzz harness can drive interleavings explicitly.
    """

    def __init__(self, start_seq: int = INITIAL_SEQ,
                 throttle=None) -> None:
        self._seq = start_seq
        self._min_seq = start_seq
        #: optional policy: callable(client_id) -> retry-after seconds when
        #: this submit should be NACKed (throttling), else None.
        self.throttle = throttle
        self.nacks_issued = 0
        self._clients: Dict[str, ClientConnection] = {}
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        self._log: List[SequencedMessage] = []
        self._clock = 0
        # Delivery queue: stamping is allowed *during* a broadcast (e.g. the
        # scribe acks a summary from inside its subscription callback), but
        # delivery must stay in total order — re-entrant stamps are queued
        # and drained by the outermost broadcast.
        self._delivery: List[SequencedMessage] = []
        self._delivering = False
        #: set by _stamp's exception path: True iff the exception unwound
        #: the CALLER's message (it never became durable or visible).
        #: Callers restore their own optimistic state (dedup floor,
        #: quorum membership) ONLY in that case — a failure in a LATER
        #: subscriber leaves the message durably sequenced, and rolling
        #: the floor back then would let a retry double-sequence it.
        self._last_stamp_unwound = False

    # -- connection management -------------------------------------------------

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def min_seq(self) -> int:
        return self._min_seq

    @property
    def log(self) -> List[SequencedMessage]:
        """The durable op log (scriptorium feed)."""
        return self._log

    def connect(self, client_id: str,
                session: Optional[str] = None) -> ClientConnection:
        """Join a client to the quorum; emits a JOIN message.

        ``session`` disambiguates reuse of a client id.  A reconnect that
        presents the *same* session token resumes the existing record —
        no duplicate JOIN, dedup floor preserved (crash-resume of a
        surviving runtime whose client_seq counter continues).  A different
        (or absent) session is a *fresh* runtime whose counter restarts:
        the stale record is dropped (LEAVE+JOIN) so its dedup floor cannot
        silently swallow the new session's ops."""
        existing = self._clients.get(client_id)
        if existing is not None:
            if session is not None and existing.session == session:
                return existing
            self.disconnect(client_id)
        conn = ClientConnection(client_id=client_id, ref_seq=self._seq,
                                session=session)
        self._clients[client_id] = conn
        try:
            self._stamp(
                client_id=None,
                client_seq=-1,
                ref_seq=self._seq,
                type_=MessageType.JOIN,
                contents={"clientId": client_id},
            )
        except BaseException:
            # A JOIN whose durable append failed (unwound) must not
            # leave the client in the quorum: the retry's connect would
            # resume the record and never stamp the JOIN at all.  A JOIN
            # that landed durably (a later subscriber raised) keeps the
            # membership — it matches the log.
            if self._last_stamp_unwound:
                self._clients.pop(client_id, None)
            raise
        return conn

    def connect_many(self, client_ids: List[str],
                     session: Optional[str] = None) -> None:
        """Batch JOIN: admit ``client_ids`` in order with one MSN
        recomputation at the end instead of one per JOIN — connecting N
        clients sequentially is O(N²) in the per-stamp min-scan, which is
        what makes a 10⁵-client ramp phase unaffordable one at a time.
        Each JOIN message carries the batch-start MSN (conservative, same
        argument as :meth:`submit_many`).  Semantics are otherwise
        exactly N :meth:`connect` calls: same-session reconnects resume,
        stale records are dropped via LEAVE+JOIN."""
        try:
            for client_id in client_ids:
                existing = self._clients.get(client_id)
                if existing is not None:
                    if session is not None and existing.session == session:
                        continue
                    self.disconnect(client_id)
                conn = ClientConnection(client_id=client_id,
                                        ref_seq=self._seq, session=session)
                self._clients[client_id] = conn
                try:
                    self._stamp(
                        client_id=None,
                        client_seq=-1,
                        ref_seq=self._seq,
                        type_=MessageType.JOIN,
                        contents={"clientId": client_id},
                        recompute_msn=False,
                    )
                except BaseException:
                    # Same unwind discipline as connect(): an un-stamped
                    # JOIN must not leave the client in the quorum.
                    if self._last_stamp_unwound:
                        self._clients.pop(client_id, None)
                    raise
        finally:
            self._recompute_min_seq()

    def disconnect(self, client_id: str) -> None:
        """Remove a client from the quorum; emits LEAVE and recomputes MSN."""
        if client_id not in self._clients:
            return
        conn = self._clients.pop(client_id)
        try:
            self._stamp(
                client_id=None,
                client_seq=-1,
                ref_seq=self._seq,
                type_=MessageType.LEAVE,
                contents={"clientId": client_id},
            )
        except BaseException:
            # Same unwind discipline as connect: an un-stamped LEAVE must
            # leave the quorum membership (and its MSN contribution)
            # exactly as it was, so the retry re-stamps cleanly; a LEAVE
            # that landed durably keeps the member removed.
            if self._last_stamp_unwound:
                self._clients[client_id] = conn
            raise

    # -- sequencing ------------------------------------------------------------

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        """Sequence one raw op (the Deli ``ticket()`` hot loop).

        Returns the sequenced message, or None if the op was a duplicate
        (already-sequenced client_seq, e.g. a redundant resubmit after
        reconnect).
        """
        return self._submit_one(op, recompute_msn=True)

    def submit_many(self, ops: List[RawOperation]
                    ) -> List[SequencedMessage]:
        """Batch ticket(): sequence ``ops`` in order with ONE MSN
        recomputation for the whole batch instead of one per op — the
        per-op O(connected clients) min-scan is what caps single-op
        ingress at swarm populations.

        Each message is stamped with the MSN as of the batch START (the
        monotone floor is conservative: it may lag by one batch, which
        only delays zamboni collection — it can never exceed a live
        client's view).  Validation (connection, dedup, throttle, stale
        view) is per op and identical to :meth:`submit`; duplicates are
        skipped, not returned.  A failure mid-batch recomputes the MSN
        over what landed and raises :class:`BatchAbortedError` carrying
        the stamped prefix — the caller resubmits the whole batch after
        recovery and dedup absorbs the prefix.
        """
        stamped: List[SequencedMessage] = []
        consumed = 0
        try:
            for op in ops:
                msg = self._submit_one(op, recompute_msn=False)
                if msg is not None:
                    stamped.append(msg)
                consumed += 1
        except BaseException as err:
            self._recompute_min_seq()
            if not isinstance(err, Exception):
                # KeyboardInterrupt/SystemExit must never be converted
                # into a per-document outcome a retry loop would swallow.
                raise
            raise BatchAbortedError(consumed, stamped, err) from err
        self._recompute_min_seq()
        return stamped

    def _submit_one(self, op: RawOperation,
                    recompute_msn: bool) -> Optional[SequencedMessage]:
        conn = self._clients.get(op.client_id)
        if conn is None:
            raise ValueError(f"client {op.client_id!r} is not connected")
        if op.client_seq <= conn.last_client_seq:
            return None  # duplicate — dedup by clientSeq
        if self.throttle is not None:
            retry_after = self.throttle(op.client_id)
            if retry_after is not None:
                self.nacks_issued += 1
                raise NackError("throttled", retry_after=float(retry_after))
        if op.ref_seq < self.min_seq:
            # A view below the collaboration window cannot be resolved
            # (zamboni collected what it referenced): the client must
            # rebase and resubmit against a fresh view (reconnect path).
            self.nacks_issued += 1
            raise NackError(
                f"refSeq {op.ref_seq} below the collaboration window "
                f"(minSeq {self.min_seq})", retry_after=0.0,
                code="staleView",
            )
        prev_client_seq = conn.last_client_seq
        prev_ref_seq = conn.ref_seq
        conn.last_client_seq = op.client_seq
        conn.ref_seq = max(conn.ref_seq, op.ref_seq)
        try:
            return self._stamp(
                client_id=op.client_id,
                client_seq=op.client_seq,
                ref_seq=op.ref_seq,
                type_=op.type,
                contents=op.contents,
                recompute_msn=recompute_msn,
            )
        except BaseException:
            # A failed stamp that UNWOUND (durable append refused the
            # message — see _stamp's rollback) must also restore the
            # dedup floor, or the caller's RETRY of the same client_seq
            # would be treated as a duplicate and silently dropped.  A
            # failure that did NOT unwind (a later subscriber raised
            # after the append landed) keeps the floor: the op is
            # durable, and the resend must dedup, not double-sequence.
            if self._last_stamp_unwound:
                conn.last_client_seq = prev_client_seq
                conn.ref_seq = prev_ref_seq
            raise

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        """Heartbeat path: a client reports processed-up-to without an op."""
        conn = self._clients.get(client_id)
        if conn is None:
            return
        conn.ref_seq = max(conn.ref_seq, ref_seq)
        self._recompute_min_seq()

    def tick(self) -> SequencedMessage:
        """Emit a NO_OP heartbeat: advances seq and propagates the current MSN
        to clients without carrying an operation."""
        return self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=MessageType.NO_OP,
            contents=None,
        )

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        """Register a delivery callback; called in total order for every
        sequenced message (the Alfred broadcast capability)."""
        self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        if fn in self._subscribers:
            self._subscribers.remove(fn)

    def server_message(self, type_: MessageType, contents) -> SequencedMessage:
        """Stamp a server-originated message (scribe summaryAck/Nack — the
        reference's service-generated ops carry clientId null)."""
        return self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=type_,
            contents=contents,
        )

    def replay(self, msg: SequencedMessage) -> None:
        """Advance sequencing state from an already-durable message without
        re-stamping or re-broadcasting — crash-resume when the log is ahead
        of the checkpoint (Deli resuming from its Kafka offset).  The
        message is appended to the in-memory log so late joiners backfill
        the full history."""
        if msg.seq <= self._seq:
            return  # already reflected in the checkpoint
        self._log.append(msg)
        self._seq = msg.seq
        self._min_seq = max(self._min_seq, msg.min_seq)
        self._clock = max(self._clock, int(msg.timestamp) + 1)
        if msg.type is MessageType.JOIN:
            cid = msg.contents["clientId"]
            self._clients.setdefault(
                cid, ClientConnection(client_id=cid, ref_seq=msg.ref_seq)
            )
        elif msg.type is MessageType.LEAVE:
            self._clients.pop(msg.contents["clientId"], None)
        elif msg.client_id is not None:
            conn = self._clients.get(msg.client_id)
            if conn is not None:
                conn.last_client_seq = max(conn.last_client_seq,
                                           msg.client_seq)
                conn.ref_seq = max(conn.ref_seq, msg.ref_seq)

    # -- checkpointing (Deli CheckpointManager capability) ---------------------

    def checkpoint(self) -> dict:
        """Serializable sequencing state: enough to resume stamping
        exactly-once after a crash (the durable log holds the messages;
        this holds the counters and per-client dedup floors)."""
        return {
            "seq": self._seq,
            "minSeq": self._min_seq,
            "clock": self._clock,
            "clients": {
                cid: {"refSeq": c.ref_seq, "lastClientSeq": c.last_client_seq,
                      "session": c.session}
                for cid, c in sorted(self._clients.items())
            },
        }

    @staticmethod
    def restore(
        state: dict, log: Optional[List[SequencedMessage]] = None
    ) -> "Sequencer":
        """Rebuild from a checkpoint; pass the durable messages at or below
        the checkpoint as ``log`` so the in-memory catch-up feed stays
        complete (``replay`` appends everything after it)."""
        seq = Sequencer(start_seq=state["seq"])
        seq._min_seq = state["minSeq"]
        seq._clock = state["clock"]
        seq._log = list(log) if log is not None else []
        for cid, c in state["clients"].items():
            seq._clients[cid] = ClientConnection(
                client_id=cid,
                ref_seq=c["refSeq"],
                last_client_seq=c["lastClientSeq"],
                session=c.get("session"),
            )
        return seq

    # -- internals -------------------------------------------------------------

    def _recompute_min_seq(self) -> None:
        if self._clients:
            msn = min(c.ref_seq for c in self._clients.values())
        else:
            msn = self._seq
        # MSN is monotone.
        self._min_seq = max(self._min_seq, msn)

    def _stamp(
        self,
        client_id: Optional[str],
        client_seq: int,
        ref_seq: int,
        type_: MessageType,
        contents,
        recompute_msn: bool = True,
    ) -> SequencedMessage:
        """``recompute_msn=False`` is the batch path (submit_many /
        connect_many): the message carries the current monotone MSN and
        the caller recomputes once per batch — a conservative floor, not
        a stale one."""
        self._last_stamp_unwound = False
        prev_min_seq = self._min_seq
        self._seq += 1
        if recompute_msn:
            self._recompute_min_seq()
        msg = SequencedMessage(
            seq=self._seq,
            client_id=client_id,
            client_seq=client_seq,
            ref_seq=ref_seq,
            min_seq=self._min_seq,
            type=type_,
            contents=contents,
            timestamp=float(self._clock),
        )
        self._clock += 1
        self._log.append(msg)
        self._delivery.append(msg)
        if not self._delivering:
            self._delivering = True
            try:
                while self._delivery:
                    queued = self._delivery.pop(0)
                    delivered_to = 0
                    try:
                        for fn in list(self._subscribers):
                            fn(queued)
                            delivered_to += 1
                    except BaseException:
                        # The FIRST subscriber is the durability gate
                        # (DocumentOrderer's log append rides there): if
                        # it refused the NEWEST stamp and nobody else saw
                        # the message, un-stamp it completely — seq,
                        # clock, MSN, and the in-memory log roll back so
                        # the caller's retry re-sequences at the SAME
                        # number instead of leaving a durable-log hole
                        # no catch-up could ever repair.  (MSN restore is
                        # only exact for the outermost stamp; a rolled-
                        # back re-entrant stamp keeps the monotone MSN it
                        # observed.)  A failure after any delivery, or of
                        # a message with later stamps behind it, cannot
                        # be unwound and propagates as-is — and then the
                        # caller's message IS durable, so the unwound
                        # flag stays False and the caller must NOT
                        # restore its dedup floor (a restored floor would
                        # re-sequence the retry as a second op).
                        rolled_back = (delivered_to == 0
                                       and not self._delivery
                                       and self._log
                                       and self._log[-1] is queued)
                        if rolled_back:
                            self._log.pop()
                            self._seq -= 1
                            self._clock -= 1
                            if queued is msg:
                                self._min_seq = prev_min_seq
                        self._last_stamp_unwound = (rolled_back
                                                    and queued is msg)
                        raise
            finally:
                self._delivering = False
        return msg
