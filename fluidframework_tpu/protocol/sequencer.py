"""In-process total-order sequencer.

Capability-equivalent of the reference's Deli ``ticket()`` sequencing lambda
wired in-process the way ``memory-orderer``'s ``LocalOrderer`` does
(SURVEY.md §2.3; upstream paths UNVERIFIED — empty reference mount): one class,
no Kafka.  Responsibilities:

- stamp each raw op with a monotonically increasing ``seq``;
- track each connected client's ``ref_seq`` and compute the
  ``minimumSequenceNumber`` (MSN) — min over connected clients' ref_seq;
- dedupe resubmitted ops by (client_id, client_seq);
- broadcast sequenced messages to subscribers in order and append them to the
  durable op log (the scriptorium-equivalent feed that catch-up replay and the
  TPU batch-replay path consume).

Quorum state is COLUMNAR (ISSUE 11): per-client ``ref_seq`` and dedup
floors live in slot-indexed numpy arrays behind a ``client_id → slot``
dict, so the MSN recompute is a vectorized ``min`` over one array
instead of a Python scan of N connection objects — the scan that made
10⁶-client quorums unaffordable — and the batched columnar ingress
(:meth:`Sequencer.submit_columns`) can gather/scatter floors for a whole
batch in a handful of numpy calls.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from .messages import (
    INITIAL_SEQ,
    BatchAbortedError,
    ColumnAppendError,
    MessageType,
    NackError,
    RawOperation,
    SequencedMessage,
)
from .wire import ColumnBatch, JoinColumnSegment, OpColumnSegment

#: ref_seq sentinel for a freed slot: never the min of a live quorum.
_DEAD_REF = np.iinfo(np.int64).max


class ClientConnection:
    """Read view of one connected client's quorum state.

    The authoritative state is the sequencer's columnar arrays; this
    object is the stable façade ``connect()`` hands back (and the shape
    the pre-columnar dataclass exposed): ``client_id``, ``ref_seq``,
    ``last_client_seq`` (dedup floor), ``session``.
    """

    __slots__ = ("_sequencer", "client_id")

    def __init__(self, sequencer: "Sequencer", client_id: str) -> None:
        self._sequencer = sequencer
        self.client_id = client_id

    def _slot(self) -> int:
        slot = self._sequencer._slots.get(self.client_id)
        if slot is None:
            raise KeyError(f"client {self.client_id!r} is not connected")
        return slot

    @property
    def ref_seq(self) -> int:
        return int(self._sequencer._ref[self._slot()])

    @property
    def last_client_seq(self) -> int:
        return int(self._sequencer._floor[self._slot()])

    @property
    def session(self) -> Optional[str]:
        return self._sequencer._session[self._slot()]

    def __repr__(self) -> str:  # debugging aid
        return (f"ClientConnection(client_id={self.client_id!r}, "
                f"ref_seq={self.ref_seq}, "
                f"last_client_seq={self.last_client_seq}, "
                f"session={self.session!r})")


class Sequencer:
    """Single-document total-order sequencer with MSN tracking.

    Deterministic: sequencing depends only on the submission order, so tests
    and the fuzz harness can drive interleavings explicitly.
    """

    def __init__(self, start_seq: int = INITIAL_SEQ,
                 throttle=None) -> None:
        self._seq = start_seq  # durable-shadow: stamp counter
        self._min_seq = start_seq  # durable-shadow: collaboration-window floor
        #: optional policy: callable(client_id) -> retry-after seconds when
        #: this submit should be NACKed (throttling), else None.
        self.throttle = throttle
        self.nacks_issued = 0
        # -- columnar quorum state (client_id -> slot into the arrays) --
        self._slots: Dict[str, int] = {}  # durable-shadow: quorum membership
        self._ref = np.empty(0, dtype=np.int64)  # durable-shadow: ref seqs
        self._floor = np.empty(0, dtype=np.int64)  # durable-shadow: dedup floors
        self._session: List[Optional[str]] = []  # durable-shadow: session tokens
        self._free: List[int] = []
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        #: commit WATCHERS (round 16, the streaming fold's cadence feed):
        #: fired with the new head seq after a stamp (or columnar
        #: segment) has fully committed — durable gate accepted, every
        #: subscriber delivered.  Deliberately NOT subscribers: they
        #: never see messages (nothing to box) and are invisible to
        #: ``has_subscribers_besides``, so watching a document does not
        #: knock its client OP columns off the columnar fast path.
        self._watchers: List[Callable[[int], None]] = []
        self._log: List[SequencedMessage] = []  # durable-shadow: stamped log
        self._clock = 0  # durable-shadow: logical timestamp
        # Delivery queue: stamping is allowed *during* a broadcast (e.g. the
        # scribe acks a summary from inside its subscription callback), but
        # delivery must stay in total order — re-entrant stamps are queued
        # and drained by the outermost broadcast.
        self._delivery: List[SequencedMessage] = []
        self._delivering = False
        #: set by _stamp's exception path: True iff the exception unwound
        #: the CALLER's message (it never became durable or visible).
        #: Callers restore their own optimistic state (dedup floor,
        #: quorum membership) ONLY in that case — a failure in a LATER
        #: subscriber leaves the message durably sequenced, and rolling
        #: the floor back then would let a retry double-sequence it.
        self._last_stamp_unwound = False

    # -- quorum slot management ------------------------------------------------

    def _alloc(self, client_id: str, session: Optional[str],
               ref_seq: int, floor: int = 0) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            slot = len(self._session)
            if slot >= self._ref.shape[0]:
                grow = max(16, self._ref.shape[0])
                self._ref = np.concatenate(
                    [self._ref, np.full(grow, _DEAD_REF, np.int64)])
                self._floor = np.concatenate(
                    [self._floor, np.zeros(grow, np.int64)])
            self._session.append(None)
        self._slots[client_id] = slot
        self._ref[slot] = ref_seq
        self._floor[slot] = floor
        self._session[slot] = session
        return slot

    def _drop(self, client_id: str) -> None:
        slot = self._slots.pop(client_id)
        self._ref[slot] = _DEAD_REF
        self._floor[slot] = 0
        self._session[slot] = None
        self._free.append(slot)

    # -- connection management -------------------------------------------------

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def min_seq(self) -> int:
        return self._min_seq

    @property
    def log(self) -> List[SequencedMessage]:
        """The durable op log (scriptorium feed).

        Columnar stamps (:meth:`submit_columns` / :meth:`connect_columns`)
        do NOT ride this list — their feed is the service-side
        :class:`~fluidframework_tpu.service.oplog.OpLog` the durable gate
        appends to (the in-proc drivers that read this list never drive
        the columnar path)."""
        return self._log

    def connect(self, client_id: str,
                session: Optional[str] = None) -> ClientConnection:
        """Join a client to the quorum; emits a JOIN message.

        ``session`` disambiguates reuse of a client id.  A reconnect that
        presents the *same* session token resumes the existing record —
        no duplicate JOIN, dedup floor preserved (crash-resume of a
        surviving runtime whose client_seq counter continues).  A different
        (or absent) session is a *fresh* runtime whose counter restarts:
        the stale record is dropped (LEAVE+JOIN) so its dedup floor cannot
        silently swallow the new session's ops."""
        slot = self._slots.get(client_id)
        if slot is not None:
            if session is not None and self._session[slot] == session:
                return ClientConnection(self, client_id)
            self.disconnect(client_id)
        self._alloc(client_id, session, self._seq)
        try:
            self._stamp(
                client_id=None,
                client_seq=-1,
                ref_seq=self._seq,
                type_=MessageType.JOIN,
                contents={"clientId": client_id},
            )  # unwinds: _slots
        except BaseException:
            # A JOIN whose durable append failed (unwound) must not
            # leave the client in the quorum: the retry's connect would
            # resume the record and never stamp the JOIN at all.  A JOIN
            # that landed durably (a later subscriber raised) keeps the
            # membership — it matches the log.
            if self._last_stamp_unwound and client_id in self._slots:
                self._drop(client_id)
            raise
        return ClientConnection(self, client_id)

    def connect_many(self, client_ids: List[str],
                     session: Optional[str] = None) -> None:
        """Batch JOIN: admit ``client_ids`` in order with one MSN
        recomputation at the end instead of one per JOIN (the vectorized
        array ``min`` of ``_recompute_min_seq`` — connecting N clients
        one at a time used to be O(N²) in the per-stamp min-scan).  Each
        JOIN message carries the batch-start MSN (conservative, same
        argument as :meth:`submit_many`).  Semantics are otherwise
        exactly N :meth:`connect` calls: same-session reconnects resume,
        stale records are dropped via LEAVE+JOIN.  The fully-columnar
        fresh-cohort form is :meth:`connect_columns`."""
        try:
            for client_id in client_ids:
                slot = self._slots.get(client_id)
                if slot is not None:
                    if session is not None \
                            and self._session[slot] == session:
                        continue
                    self.disconnect(client_id)
                self._alloc(client_id, session, self._seq)
                try:
                    self._stamp(
                        client_id=None,
                        client_seq=-1,
                        ref_seq=self._seq,
                        type_=MessageType.JOIN,
                        contents={"clientId": client_id},
                        recompute_msn=False,
                    )  # unwinds: _slots
                except BaseException:
                    # Same unwind discipline as connect(): an un-stamped
                    # JOIN must not leave the client in the quorum.
                    if self._last_stamp_unwound \
                            and client_id in self._slots:
                        self._drop(client_id)
                    raise
        finally:
            self._recompute_min_seq()

    def connect_columns(self, client_ids: List[str],
                        session: Optional[str],
                        gate: Callable[[JoinColumnSegment], None]) -> bool:
        """Fully-columnar JOIN cohort: admit a FRESH batch of clients with
        one vectorized quorum insert, one lazy
        :class:`JoinColumnSegment` stamp, and one durable-gate call —
        no per-client :class:`SequencedMessage` objects.

        Returns False (taking no action) when any id is already known:
        resume/LEAVE+JOIN semantics stay with the boxed
        :meth:`connect_many`, which the caller then uses.  ``gate`` must
        make the segment durable; a :class:`ColumnAppendError` unwinds
        the un-landed suffix (those clients leave the quorum, the seq
        counter rolls back) and re-raises the underlying cause — the
        exact per-JOIN unwind discipline of the boxed path.
        """
        n = len(client_ids)
        if any(cid in self._slots for cid in client_ids) \
                or len(set(client_ids)) != n:
            # Known ids (resume/LEAVE+JOIN) and duplicate ids within the
            # cohort both need the boxed per-id path — the bulk insert
            # would leak a quorum slot for the shadowed duplicate and
            # that slot's frozen ref would pin the MSN forever.
            return False
        if n == 0:
            return True
        start_ref = self._seq
        if not self._free:
            # Bulk quorum insert: grow the arrays once, vectorize the
            # per-client ref init, and extend the slot map in one update.
            base = len(self._session)
            need = base + n
            if need > self._ref.shape[0]:
                grow = max(need - self._ref.shape[0], self._ref.shape[0],
                           16)
                self._ref = np.concatenate(
                    [self._ref, np.full(grow, _DEAD_REF, np.int64)])
                self._floor = np.concatenate(
                    [self._floor, np.zeros(grow, np.int64)])
            self._ref[base:need] = start_ref + np.arange(n, dtype=np.int64)
            self._floor[base:need] = 0
            self._session.extend([session] * n)
            self._slots.update(zip(client_ids, range(base, need)))
        else:
            for i, cid in enumerate(client_ids):
                self._alloc(cid, session, start_ref + i)
        start = self._seq + 1
        clock0 = self._clock
        self._seq += n
        self._clock += n
        segment = JoinColumnSegment(tuple(client_ids), start,
                                    self._min_seq, clock0)
        try:
            gate(segment)  # commit-point: columnar JOIN cohort; unwinds: _seq, _clock, _ref, _floor, _slots, _session
        except ColumnAppendError as err:
            landed = err.landed
            self._seq = start - 1 + landed
            self._clock = clock0 + landed
            for cid in client_ids[landed:]:
                self._drop(cid)
            self._recompute_min_seq()
            raise err.cause from err
        except BaseException:
            # Gate refused before any row landed (e.g. fenced): unwind
            # the whole cohort.
            self._seq = start - 1
            self._clock = clock0
            for cid in client_ids:
                self._drop(cid)
            self._recompute_min_seq()
            raise
        self._recompute_min_seq()
        if self._watchers:
            self._notify_commit()
        return True

    def disconnect(self, client_id: str) -> None:
        """Remove a client from the quorum; emits LEAVE and recomputes MSN."""
        slot = self._slots.get(client_id)
        if slot is None:
            return
        prev_ref = int(self._ref[slot])
        prev_floor = int(self._floor[slot])
        prev_session = self._session[slot]
        self._drop(client_id)
        try:
            self._stamp(
                client_id=None,
                client_seq=-1,
                ref_seq=self._seq,
                type_=MessageType.LEAVE,
                contents={"clientId": client_id},
            )  # unwinds: _slots
        except BaseException:
            # Same unwind discipline as connect: an un-stamped LEAVE must
            # leave the quorum membership (and its MSN contribution)
            # exactly as it was, so the retry re-stamps cleanly; a LEAVE
            # that landed durably keeps the member removed.
            if self._last_stamp_unwound:
                self._alloc(client_id, prev_session, prev_ref, prev_floor)
            raise

    # -- sequencing ------------------------------------------------------------

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        """Sequence one raw op (the Deli ``ticket()`` hot loop).

        Returns the sequenced message, or None if the op was a duplicate
        (already-sequenced client_seq, e.g. a redundant resubmit after
        reconnect).
        """
        return self._submit_one(op, recompute_msn=True)

    def submit_many(self, ops: List[RawOperation]
                    ) -> List[SequencedMessage]:
        """Batch ticket(): sequence ``ops`` in order with ONE MSN
        recomputation for the whole batch instead of one per op — the
        per-op O(connected clients) min-scan is what caps single-op
        ingress at swarm populations.

        Each message is stamped with the MSN as of the batch START (the
        monotone floor is conservative: it may lag by one batch, which
        only delays zamboni collection — it can never exceed a live
        client's view).  Validation (connection, dedup, throttle, stale
        view) is per op and identical to :meth:`submit`; duplicates are
        skipped, not returned.  A failure mid-batch recomputes the MSN
        over what landed and raises :class:`BatchAbortedError` carrying
        the stamped prefix — the caller resubmits the whole batch after
        recovery and dedup absorbs the prefix.
        """
        stamped: List[SequencedMessage] = []
        consumed = 0
        try:
            for op in ops:
                msg = self._submit_one(op, recompute_msn=False)
                if msg is not None:
                    stamped.append(msg)
                consumed += 1
        except BaseException as err:
            self._recompute_min_seq()
            if not isinstance(err, Exception):
                # KeyboardInterrupt/SystemExit must never be converted
                # into a per-document outcome a retry loop would swallow.
                raise
            raise BatchAbortedError(consumed, stamped, err) from err
        self._recompute_min_seq()
        return stamped

    def submit_columns(self, batch: ColumnBatch, rows: np.ndarray,
                       gate: Callable[[OpColumnSegment], None]
                       ) -> Optional[OpColumnSegment]:
        """Vectorized batch ticket() over a :class:`ColumnBatch` slice —
        :meth:`submit_many`'s contract without per-op Python objects.

        ``rows`` selects this document's batch rows in submission order.
        Stamping is columnar end to end: dedup floors gather/compare/
        scatter through the quorum arrays (numpy compare-and-max), seq
        numbers are an ``arange`` over the kept rows, every message
        carries the batch-start MSN, and the MSN recomputes ONCE at the
        end.  The stamped rows become one lazy
        :class:`OpColumnSegment`; ``gate`` (the durable-append-first
        subscriber's columnar form) must make it durable before this
        method returns — messages are never visible anywhere before the
        gate accepts them.

        Returns None — taking NO action — when the slice needs boxed
        semantics the caller must provide via materialize+
        :meth:`submit_many`: a throttle policy is installed, a client is
        unknown, a client appears twice in the slice, client_seqs are
        not fresh-monotone, or a ref_seq sits below the collaboration
        window (NackError shapes).  A :class:`ColumnAppendError` from
        the gate unwinds the un-landed suffix (seq/clock/floors/
        ref_seqs) and raises :class:`BatchAbortedError` with the landed
        prefix — byte-for-byte the boxed abort-and-resubmit contract.
        """
        n = int(rows.shape[0])
        if n == 0:
            return OpColumnSegment(batch, rows.astype(np.int64),
                                   self._seq + 1, self._min_seq,
                                   self._clock)
        if self.throttle is not None:
            return None
        ids = batch.client_ids
        try:
            # C-level map chain: table index -> client id -> slot; an
            # unknown client raises out to the boxed path (which owes
            # the caller its ValueError shape).
            slot_list = list(map(self._slots.__getitem__,
                                 map(ids.__getitem__,
                                     batch.client_index[rows].tolist())))
        except KeyError:
            return None  # unknown client: boxed path raises its ValueError
        if n > 1 and len(set(slot_list)) != n:
            return None  # same client twice: running-floor dedup is boxed
        slots = np.array(slot_list, np.int64)
        cs = batch.client_seq[rows].astype(np.int64, copy=False)
        rs = batch.ref_seq[rows].astype(np.int64, copy=False)
        # Conservative stale-view probe over ALL rows (a dup row with a
        # stale view forces the boxed path, which silently dedups it —
        # correct either way, never a missed nack).
        if int(rs.min()) < self._min_seq:
            return None  # stale view: boxed path owes a staleView nack
        floors = self._floor[slots]
        keep = cs > floors
        if bool(keep.all()):
            # Steady-state fast path: nothing to dedup — skip the
            # boolean gathers entirely.
            kept_rows = rows.astype(np.int64, copy=False)
            kept_slots = slots
            prev_floors = floors
        else:
            kept_rows = rows[keep].astype(np.int64, copy=False)
            kept_slots = slots[keep]
            prev_floors = floors[keep]
            cs = cs[keep]
            rs = rs[keep]
        m = int(kept_rows.shape[0])
        prev_refs = self._ref[kept_slots].copy()
        self._floor[kept_slots] = cs
        self._ref[kept_slots] = np.maximum(prev_refs, rs)
        start = self._seq + 1
        clock0 = self._clock
        self._seq += m
        self._clock += m
        segment = OpColumnSegment(batch, kept_rows, start,
                                  self._min_seq, clock0)
        try:
            gate(segment)  # commit-point: columnar OP segment; unwinds: _seq, _clock, _floor, _ref
        except ColumnAppendError as err:
            landed = err.landed
            self._seq = start - 1 + landed
            self._clock = clock0 + landed
            self._floor[kept_slots[landed:]] = prev_floors[landed:]
            self._ref[kept_slots[landed:]] = prev_refs[landed:]
            self._recompute_min_seq()
            kept_positions = np.flatnonzero(keep)
            consumed = (int(kept_positions[landed])
                        if landed < kept_positions.shape[0] else n)
            stamped = [segment.materialize(j) for j in range(landed)]
            cause = err.cause
            if not isinstance(cause, Exception):
                raise cause
            raise BatchAbortedError(consumed, stamped, cause) from cause
        except BaseException as err:
            # Gate refused before any row landed (e.g. fenced mid-kill):
            # unwind the whole stamp, report zero consumed.
            self._seq = start - 1
            self._clock = clock0
            self._floor[kept_slots] = prev_floors
            self._ref[kept_slots] = prev_refs
            self._recompute_min_seq()
            if not isinstance(err, Exception):
                raise
            raise BatchAbortedError(0, [], err) from err
        self._recompute_min_seq()
        if self._watchers:
            self._notify_commit()
        return segment

    def _submit_one(self, op: RawOperation,
                    recompute_msn: bool) -> Optional[SequencedMessage]:
        slot = self._slots.get(op.client_id)
        if slot is None:
            raise ValueError(f"client {op.client_id!r} is not connected")
        if op.client_seq <= int(self._floor[slot]):
            return None  # duplicate — dedup by clientSeq
        if self.throttle is not None:
            retry_after = self.throttle(op.client_id)
            if retry_after is not None:
                self.nacks_issued += 1
                raise NackError("throttled", retry_after=float(retry_after))
        if op.ref_seq < self.min_seq:
            # A view below the collaboration window cannot be resolved
            # (zamboni collected what it referenced): the client must
            # rebase and resubmit against a fresh view (reconnect path).
            self.nacks_issued += 1
            raise NackError(
                f"refSeq {op.ref_seq} below the collaboration window "
                f"(minSeq {self.min_seq})", retry_after=0.0,
                code="staleView",
            )
        prev_client_seq = int(self._floor[slot])
        prev_ref_seq = int(self._ref[slot])
        self._floor[slot] = op.client_seq
        self._ref[slot] = max(prev_ref_seq, op.ref_seq)
        try:
            return self._stamp(
                client_id=op.client_id,
                client_seq=op.client_seq,
                ref_seq=op.ref_seq,
                type_=op.type,
                contents=op.contents,
                recompute_msn=recompute_msn,
            )  # unwinds: _floor, _ref
        except BaseException:
            # A failed stamp that UNWOUND (durable append refused the
            # message — see _stamp's rollback) must also restore the
            # dedup floor, or the caller's RETRY of the same client_seq
            # would be treated as a duplicate and silently dropped.  A
            # failure that did NOT unwind (a later subscriber raised
            # after the append landed) keeps the floor: the op is
            # durable, and the resend must dedup, not double-sequence.
            if self._last_stamp_unwound:
                self._floor[slot] = prev_client_seq
                self._ref[slot] = prev_ref_seq
            raise

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        """Heartbeat path: a client reports processed-up-to without an op."""
        slot = self._slots.get(client_id)
        if slot is None:
            return
        self._ref[slot] = max(int(self._ref[slot]), ref_seq)
        self._recompute_min_seq()

    def tick(self) -> SequencedMessage:
        """Emit a NO_OP heartbeat: advances seq and propagates the current MSN
        to clients without carrying an operation."""
        return self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=MessageType.NO_OP,
            contents=None,
        )

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        """Register a delivery callback; called in total order for every
        sequenced message (the Alfred broadcast capability)."""
        self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        if fn in self._subscribers:
            self._subscribers.remove(fn)

    def watch_commits(self, fn: Callable[[int], None]) -> None:
        """Register a commit watcher: ``fn(head_seq)`` after each stamp
        or columnar segment fully commits.  Watchers are not
        subscribers — they see no messages, cannot veto, and do not
        affect :meth:`has_subscribers_besides` (the columnar fast path
        stays on).  A watcher that raises propagates to the submitter
        AFTER the commit (the message is already durable and
        broadcast); keep watchers non-throwing."""
        self._watchers.append(fn)

    def unwatch_commits(self, fn: Callable[[int], None]) -> None:
        if fn in self._watchers:
            self._watchers.remove(fn)

    def _notify_commit(self) -> None:
        for fn in list(self._watchers):
            fn(self._seq)

    def is_connected(self, client_id: str) -> bool:
        """Quorum membership probe (reap/monitoring surfaces)."""
        return client_id in self._slots

    def has_subscribers_besides(self, *known) -> bool:
        """True when anything OTHER than the given callbacks subscribes —
        the columnar fast path's "does this document have live broadcast
        consumers" probe (the durable gate and the scribe are known
        passives for client OP columns)."""
        return any(fn not in known for fn in self._subscribers)

    def server_message(self, type_: MessageType, contents) -> SequencedMessage:
        """Stamp a server-originated message (scribe summaryAck/Nack — the
        reference's service-generated ops carry clientId null)."""
        return self._stamp(
            client_id=None,
            client_seq=-1,
            ref_seq=self._seq,
            type_=type_,
            contents=contents,
        )

    def replay(self, msg: SequencedMessage) -> None:
        """Advance sequencing state from an already-durable message without
        re-stamping or re-broadcasting — crash-resume when the log is ahead
        of the checkpoint (Deli resuming from its Kafka offset).  The
        message is appended to the in-memory log so late joiners backfill
        the full history."""
        if msg.seq <= self._seq:
            return  # already reflected in the checkpoint
        self._log.append(msg)
        self._seq = msg.seq
        self._min_seq = max(self._min_seq, msg.min_seq)
        self._clock = max(self._clock, int(msg.timestamp) + 1)
        if msg.type is MessageType.JOIN:
            cid = msg.contents["clientId"]
            if cid not in self._slots:
                self._alloc(cid, None, msg.ref_seq)
        elif msg.type is MessageType.LEAVE:
            cid = msg.contents["clientId"]
            if cid in self._slots:
                self._drop(cid)
        elif msg.client_id is not None:
            slot = self._slots.get(msg.client_id)
            if slot is not None:
                self._floor[slot] = max(int(self._floor[slot]),
                                        msg.client_seq)
                self._ref[slot] = max(int(self._ref[slot]), msg.ref_seq)

    # -- checkpointing (Deli CheckpointManager capability) ---------------------

    def checkpoint(self) -> dict:
        """Serializable sequencing state: enough to resume stamping
        exactly-once after a crash (the durable log holds the messages;
        this holds the counters and per-client dedup floors)."""
        return {
            "seq": self._seq,
            "minSeq": self._min_seq,
            "clock": self._clock,
            "clients": {
                cid: {"refSeq": int(self._ref[slot]),
                      "lastClientSeq": int(self._floor[slot]),
                      "session": self._session[slot]}
                for cid, slot in sorted(self._slots.items())
            },
        }

    @staticmethod
    def restore(
        state: dict, log: Optional[List[SequencedMessage]] = None
    ) -> "Sequencer":
        """Rebuild from a checkpoint; pass the durable messages at or below
        the checkpoint as ``log`` so the in-memory catch-up feed stays
        complete (``replay`` appends everything after it)."""
        seq = Sequencer(start_seq=state["seq"])
        seq._min_seq = state["minSeq"]
        seq._clock = state["clock"]
        seq._log = list(log) if log is not None else []
        for cid, c in sorted(state["clients"].items()):
            seq._alloc(cid, c.get("session"), c["refSeq"],
                       c["lastClientSeq"])
        return seq

    # -- internals -------------------------------------------------------------

    def _recompute_min_seq(self) -> None:
        if self._slots:
            # Vectorized over the slot arrays; freed slots hold a
            # max-int sentinel so they never win the min.
            msn = int(self._ref[:len(self._session)].min())
        else:
            msn = self._seq
        # MSN is monotone.
        self._min_seq = max(self._min_seq, msn)

    def _stamp(
        self,
        client_id: Optional[str],
        client_seq: int,
        ref_seq: int,
        type_: MessageType,
        contents,
        recompute_msn: bool = True,
    ) -> SequencedMessage:
        """``recompute_msn=False`` is the batch path (submit_many /
        connect_many): the message carries the current monotone MSN and
        the caller recomputes once per batch — a conservative floor, not
        a stale one."""
        self._last_stamp_unwound = False
        prev_min_seq = self._min_seq
        self._seq += 1
        if recompute_msn:
            self._recompute_min_seq()
        msg = SequencedMessage(
            seq=self._seq,
            client_id=client_id,
            client_seq=client_seq,
            ref_seq=ref_seq,
            min_seq=self._min_seq,
            type=type_,
            contents=contents,
            timestamp=float(self._clock),
        )
        self._clock += 1
        self._log.append(msg)
        self._delivery.append(msg)
        if not self._delivering:
            self._delivering = True
            try:
                while self._delivery:
                    queued = self._delivery.pop(0)
                    delivered_to = 0
                    try:
                        for fn in list(self._subscribers):
                            fn(queued)  # commit-point: durable gate rides first; unwinds: _seq, _clock, _log
                            delivered_to += 1
                    except BaseException:
                        # The FIRST subscriber is the durability gate
                        # (DocumentOrderer's log append rides there): if
                        # it refused the NEWEST stamp and nobody else saw
                        # the message, un-stamp it completely — seq,
                        # clock, MSN, and the in-memory log roll back so
                        # the caller's retry re-sequences at the SAME
                        # number instead of leaving a durable-log hole
                        # no catch-up could ever repair.  (MSN restore is
                        # only exact for the outermost stamp; a rolled-
                        # back re-entrant stamp keeps the monotone MSN it
                        # observed.)  A failure after any delivery, or of
                        # a message with later stamps behind it, cannot
                        # be unwound and propagates as-is — and then the
                        # caller's message IS durable, so the unwound
                        # flag stays False and the caller must NOT
                        # restore its dedup floor (a restored floor would
                        # re-sequence the retry as a second op).
                        rolled_back = (delivered_to == 0
                                       and not self._delivery
                                       and self._log
                                       and self._log[-1] is queued)
                        if rolled_back:
                            self._log.pop()
                            self._seq -= 1
                            self._clock -= 1
                            if queued is msg:
                                self._min_seq = prev_min_seq
                        self._last_stamp_unwound = (rolled_back
                                                    and queued is msg)
                        raise
            finally:
                self._delivering = False
            if self._watchers:
                self._notify_commit()
        return msg
