"""Quorum proposals: propose/accept over the sequenced stream.

Capability-equivalent of the reference's protocol-base ``Quorum``
(``IQuorumProposals``; SURVEY.md §1 layer 4, §2.1 protocol-base — upstream
paths UNVERIFIED, empty reference mount), the mechanism behind code-details
agreement: a client proposes ``(key, value)``; the proposal sequences at
seq S and stays *pending* until the minimumSequenceNumber reaches S —
i.e. every connected client has observed it — at which point it commits.

Convergence: acceptance is driven purely by sequenced state (proposal seq
vs stamped MSN), so every replica accepts the same proposals in the same
order at the same fold positions.  Concurrent proposals for one key both
accept in sequence order — the later seq wins the final value, on every
replica alike.

Both the pending set and the accepted values are part of protocol state:
they ride the ``.protocol`` summary blob and survive summarize/reload.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .messages import MessageType, SequencedMessage


class QuorumProposals:
    """Sequenced propose/accept state machine (one per container)."""

    def __init__(self) -> None:
        #: accepted: key -> [accept seq, value]
        self._values: Dict[str, list] = {}
        #: sequenced but unaccepted, ascending seq: [seq, key, value]
        self._pending: List[list] = []

    # -- the sequenced fold ----------------------------------------------------

    def observe(self, msg: SequencedMessage) -> None:
        """Feed every sequenced message: proposals enqueue, and any stamped
        MSN advance commits the pending prefix."""
        if msg.type is MessageType.PROPOSAL:
            self._pending.append(
                [msg.seq, msg.contents["key"], msg.contents["value"]]
            )
        self.advance(msg.min_seq)

    def advance(self, min_seq: int) -> None:
        while self._pending and self._pending[0][0] <= min_seq:
            seq, key, value = self._pending.pop(0)
            self._values[key] = [seq, value]

    # -- reads -----------------------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        entry = self._values.get(key)
        return entry[1] if entry is not None else default

    def accepted(self) -> Dict[str, Any]:
        return {key: entry[1] for key, entry in self._values.items()}

    def pending(self) -> List[dict]:
        return [
            {"seq": seq, "key": key, "value": value}
            for seq, key, value in self._pending
        ]

    def has(self, key: str) -> bool:
        return key in self._values

    # -- summary persistence ---------------------------------------------------

    def serialize(self) -> dict:
        return {
            "values": {k: list(v) for k, v in sorted(self._values.items())},
            "pending": [list(p) for p in self._pending],
        }

    @staticmethod
    def deserialize(obj: Optional[dict]) -> "QuorumProposals":
        """``None`` / missing blob (an N-1 summary written before proposals
        existed) loads as empty state."""
        q = QuorumProposals()
        if obj:
            q._values = {k: list(v) for k, v in obj.get("values", {}).items()}
            q._pending = [list(p) for p in obj.get("pending", [])]
        return q
