"""Canonical wire error-code registry: code <-> exception <-> retryability.

Every error code that crosses a process boundary — frame responses from
``service/server.py`` and ``service/frontdoor.py``, nack bodies, and the
per-doc ``submit_mixed`` outcome channel — is declared here in ONE
top-level dict literal, so the FL-ERR fluidlint family can statically
cross-check both directions: a ``"code"`` literal produced anywhere in the
package must be a registered row, and a registered row must be produced
(and, for the frame channel, handled driver-side) somewhere.  Mirror of
``service/gates.py``: the registry imports nothing from the serving tier,
so it can never participate in an import cycle, and call sites keep their
literals — the AST rules need the strings visible; the registry pins each
one to a declared contract instead of replacing it with a constant.

Retryability classes (the SEMANTICS.md "Error taxonomy & retryability"
contract — what each class promises the host):

``transport``
    The request may never have reached the server.  Resending the SAME
    bytes after backoff is correct; the sequencer's client_seq dedup makes
    it safe even for submits.
``nack-paced``
    Deliberate server pushback.  Wait the server's ``retry_after`` (not
    the client's backoff curve), then resend; ``RetryPolicy`` implements
    the pacing natively.
``reconnect``
    An in-place resend can NEVER succeed: the caller must reconnect,
    re-resolve ownership, or rebase first.  These must ride ``no_retry``
    (or ``on_fence`` for the fence family) at every retry site — blind
    resends burn the budget against a dead contract (the PR 9
    ConnectionLostError bug).
``fatal``
    Deterministic rejection (auth failure, unknown method, a server-side
    exception).  Retrying is never correct.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

TRANSPORT = "transport"
NACK_PACED = "nack-paced"
RECONNECT = "reconnect"
FATAL = "fatal"

RETRY_CLASSES = (TRANSPORT, NACK_PACED, RECONNECT, FATAL)

#: wire channels a code can ride —
#:   ``frame``   — ``{"ok": false, "code": X, ...}`` responses; the
#:                 driver's code-dispatch chain raises the declared
#:                 exception type.
#:   ``nack``    — ``{"ok": false, "nack": {"code": X, ...}}``; decoded
#:                 uniformly into ``NackError`` (the code rides
#:                 ``NackError.code``), so per-code driver branches are
#:                 optional.
#:   ``outcome`` — per-doc ``submit_mixed`` outcome dicts; ``exception``
#:                 names the SERVER-side class the code classifies, and
#:                 drivers decode the whole channel uniformly into
#:                 ``ConnectionError`` text (``procclient._decode_outcome``).
CHANNELS = ("frame", "nack", "outcome")

#: The registry.  Keys are the exact strings that cross the wire; the
#: FL-ERR-CODE rule pins every produced/handled literal in the package to
#: a row here, both directions.
WIRE_ERRORS: Dict[str, Dict[str, str]] = {
    # frame channel ----------------------------------------------------------
    "epochMismatch": {"channel": "frame",
                      "exception": "EpochMismatchError",
                      "retry": "reconnect"},
    "shardFenced": {"channel": "frame",
                    "exception": "ShardFencedError",
                    "retry": "reconnect"},
    "wrongShard": {"channel": "frame",
                   "exception": "DocRelocatedError",
                   "retry": "reconnect"},
    "connectionLost": {"channel": "frame",
                       "exception": "ConnectionLostError",
                       "retry": "reconnect"},
    "internal": {"channel": "frame",
                 "exception": "RpcError",
                 "retry": "fatal"},
    # nack channel -----------------------------------------------------------
    "throttled": {"channel": "nack",
                  "exception": "NackError",
                  "retry": "nack-paced"},
    "staleView": {"channel": "nack",
                  "exception": "NackError",
                  "retry": "reconnect"},
    "overloaded": {"channel": "nack",
                   "exception": "NackError",
                   "retry": "nack-paced"},
    "shuttingDown": {"channel": "nack",
                     "exception": "NackError",
                     "retry": "nack-paced"},
    # outcome channel --------------------------------------------------------
    "fenced": {"channel": "outcome",
               "exception": "ShardFencedError",
               "retry": "reconnect"},
    "unknownDoc": {"channel": "outcome",
                   "exception": "KeyError",
                   "retry": "fatal"},
    "fault": {"channel": "outcome",
              "exception": "Exception",
              "retry": "fatal"},
    "shardDead": {"channel": "outcome",
                  "exception": "ConnectionError",
                  "retry": "reconnect"},
}

#: The typed-exception surface of the protocol/driver tiers and the
#: retryability class each one declares.  ``parent`` is the nearest
#: REGISTERED ancestor (builtin bases like ConnectionError/OSError are
#: deliberately outside the table — ``RetryPolicy`` names them in its
#: default ``retry_on`` and handles Nack/Fence natively).  FL-ERR-RETRY
#: walks these chains: a reconnect- or fatal-class exception whose chain
#: is named in a site's ``retry_on`` must appear in that site's
#: ``no_retry`` (or ride ``on_fence`` for the fence family).
EXCEPTIONS: Dict[str, Dict[str, Optional[str]]] = {
    "RpcError": {"parent": None, "retry": "fatal"},
    "RpcTransportError": {"parent": "RpcError", "retry": "transport"},
    "RpcTimeoutError": {"parent": "RpcError", "retry": "transport"},
    "ConnectionLostError": {"parent": "RpcTransportError",
                            "retry": "reconnect"},
    "EpochMismatchError": {"parent": "RpcError", "retry": "reconnect"},
    "UnknownWireCodeError": {"parent": "RpcError", "retry": "fatal"},
    "NackError": {"parent": None, "retry": "nack-paced"},
    "ShardFencedError": {"parent": None, "retry": "reconnect"},
    "DocRelocatedError": {"parent": "ShardFencedError",
                          "retry": "reconnect"},
    "RetryBudgetExhaustedError": {"parent": None, "retry": "fatal"},
}


def spec(code: str) -> Dict[str, str]:
    """Declared row for a wire code.  KeyError on an unregistered code —
    a producer must register before shipping (FL-ERR-CODE enforces the
    static mirror of this)."""
    return WIRE_ERRORS[code]


def is_registered(code: object) -> bool:
    return isinstance(code, str) and code in WIRE_ERRORS


def codes(channel: Optional[str] = None) -> Tuple[str, ...]:
    """Registered codes, optionally restricted to one wire channel."""
    if channel is None:
        return tuple(WIRE_ERRORS)
    return tuple(c for c, row in WIRE_ERRORS.items()
                 if row["channel"] == channel)


def retry_class(code: str) -> str:
    return WIRE_ERRORS[code]["retry"]


def exception_spec(name: str) -> Dict[str, Optional[str]]:
    """Declared row for a typed exception.  KeyError when unregistered."""
    return EXCEPTIONS[name]


def ancestors(name: str) -> Tuple[str, ...]:
    """Registered ancestor chain of an exception, nearest first."""
    out = []
    cur = EXCEPTIONS[name]["parent"]
    while cur is not None:
        if cur in out:
            raise ValueError(f"parent cycle through {cur!r}")
        out.append(cur)
        cur = EXCEPTIONS[cur]["parent"]
    return tuple(out)


def _validate() -> None:
    for code, row in WIRE_ERRORS.items():
        assert row["channel"] in CHANNELS, (code, row)
        assert row["retry"] in RETRY_CLASSES, (code, row)
        exc = row["exception"]
        # outcome rows classify with whatever the server raised, builtins
        # included; frame/nack rows must name a registered typed exception
        if row["channel"] != "outcome":
            assert exc in EXCEPTIONS, (code, exc)
    for name, row in EXCEPTIONS.items():
        assert row["retry"] in RETRY_CLASSES, (name, row)
        parent = row["parent"]
        assert parent is None or parent in EXCEPTIONS, (name, parent)
        ancestors(name)  # raises on a parent cycle


_validate()
