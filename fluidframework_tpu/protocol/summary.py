"""Canonical summary-tree model and content-addressed storage.

Capability-equivalent of the reference's ``ISummaryTree`` + Historian/gitrest
git-backed summary storage (SURVEY.md §2.1/§2.3; upstream paths UNVERIFIED —
empty reference mount): summaries are trees of named blobs, stored
content-addressed (sha256, git-style), so

- unchanged subtrees can be re-referenced by handle (incremental summaries),
- byte-identity between the CPU-oracle and TPU summary paths is checkable by
  comparing a single root hash.

Canonicalization is the load-bearing property: every serializer in the
framework funnels through :func:`canonical_json` (sorted keys, no whitespace,
explicit utf-8) so that two replicas — or the CPU oracle and the device kernel —
producing the same logical state produce the *same bytes*.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Dict, Union, Optional


def canonical_json(obj) -> bytes:
    """Deterministic JSON bytes: sorted keys, minimal separators, utf-8.

    The single canonical serializer used for summary blobs, op contents
    hashing, and golden-file tests.
    """
    return json.dumps(
        obj, sort_keys=True, separators=(",", ":"), ensure_ascii=False
    ).encode("utf-8")


@dataclass
class SummaryBlob:
    """A leaf: raw bytes (git blob equivalent)."""

    content: bytes

    def digest(self) -> str:
        return hashlib.sha256(b"blob\x00" + self.content).hexdigest()


@dataclass
class SummaryTree:
    """An ordered-by-name map of children (git tree equivalent)."""

    children: Dict[str, Union["SummaryTree", SummaryBlob]] = field(
        default_factory=dict
    )

    def add_blob(self, name: str, content: bytes) -> "SummaryTree":
        self.children[name] = SummaryBlob(content)
        return self

    def add_json_blob(self, name: str, obj) -> "SummaryTree":
        return self.add_blob(name, canonical_json(obj))

    def add_tree(self, name: str) -> "SummaryTree":
        sub = SummaryTree()
        self.children[name] = sub
        return sub

    def digest(self, _memo: Optional[dict] = None) -> str:
        """Merkle digest over sorted child names — the summary handle.
        ``_memo`` (id(node) -> digest) lets bulk walks hash each subtree
        once instead of once per ancestor (incremental upload)."""
        if _memo is not None:
            cached = _memo.get(id(self))
            if cached is not None:
                return cached
        h = hashlib.sha256()
        h.update(b"tree\x00")
        for name in sorted(self.children):
            child = self.children[name]
            h.update(name.encode("utf-8"))
            h.update(b"\x00")
            if isinstance(child, SummaryTree):
                d = child.digest(_memo)
            else:
                d = child.digest()
            h.update(d.encode("ascii"))
            h.update(b"\x00")
        out = h.hexdigest()
        if _memo is not None:
            _memo[id(self)] = out
        return out

    def get(self, path: str) -> Union["SummaryTree", SummaryBlob]:
        """Resolve a '/'-separated path to a node."""
        node: Union[SummaryTree, SummaryBlob] = self
        for part in path.split("/"):
            if not part:
                continue
            if not isinstance(node, SummaryTree):
                raise KeyError(path)
            node = node.children[part]
        return node

    def blob_bytes(self, path: str) -> bytes:
        node = self.get(path)
        if not isinstance(node, SummaryBlob):
            raise KeyError(f"{path} is not a blob")
        return node.content


@dataclass(frozen=True)
class SummaryCommit:
    """A git-style commit object: points at a summary tree, chains to its
    parent commit, and records the sequence number the tree covers — the
    Historian/gitrest capability of per-document commit history (summaries
    upstream are literally git commits over git trees; SURVEY.md §2.3,
    upstream paths UNVERIFIED — empty reference mount)."""

    doc_id: str
    tree: str  # summary-tree digest (the handle clients exchange)
    parent: Optional[str]  # parent commit digest, None for the root commit
    ref_seq: int
    message: str = ""

    def digest(self) -> str:
        # canonical_json delimits fields unambiguously (free-form doc_id /
        # message cannot shift field boundaries) and follows the module's
        # one-serializer convention.
        body = canonical_json({
            "doc": self.doc_id, "tree": self.tree, "parent": self.parent,
            "refSeq": self.ref_seq, "message": self.message,
        })
        return hashlib.sha256(b"commit\x00" + body).hexdigest()


class SummaryStorage:
    """Content-addressed summary store (Historian/gitrest capability).

    Stores summary trees by digest and, per document, a **commit chain**:
    every upload creates a :class:`SummaryCommit` whose parent is the
    document's current head, and advances the ``main`` ref.  Named refs can
    pin any commit (tags / debugging branches); :meth:`history` walks the
    parent chain.  Catch-up = latest summary + op tail from the sequencer
    log.
    """

    DEFAULT_REF = "main"

    def __init__(self) -> None:
        import uuid

        #: storage GENERATION token (odsp EpochTracker capability,
        #: SURVEY §2.4): changes when the store is recreated; clients pin
        #: it so cached snapshots/deltas from a previous generation can
        #: never silently mix with a new one.  File-backed stores persist
        #: it (restart = same epoch; a wiped/recreated dir = new epoch).
        self.epoch: str = uuid.uuid4().hex
        self._objects: Dict[str, Union[SummaryTree, SummaryBlob]] = {}  # guarded-by: _lock
        self._commit_objects: Dict[str, SummaryCommit] = {}  # guarded-by: _lock
        self._refs: Dict[str, Dict[str, str]] = {}  # guarded-by: _lock (doc -> ref -> commit)
        # (doc, tree, ref_seq) -> newest commit digest; O(1) ack stamping.
        self._commit_index: Dict[tuple, str] = {}  # guarded-by: _lock
        # Serializes the head read-modify-write of the commit chain: the
        # server runs bulk catch-up uploads on an executor thread while
        # client uploads ride the event loop — unsynchronized, whichever
        # commit landed second would orphan the other off the chain.
        # Re-entrant so subclass overrides can hold it across their whole
        # persistence step.
        self._lock = threading.RLock()

    def bump_epoch(self, token: str) -> str:
        """Advance the storage generation in place (shard-failover fence):
        every cached snapshot/delta/fold pinned to the old epoch becomes
        unservable, and pinned clients hit the epochMismatch reconnect
        path on their next request.  ``token`` is caller-supplied so the
        fence can be deterministic (replay/test harnesses derive it from
        the old epoch).  File-backed stores persist the bump."""
        self.epoch = token
        return token

    def upload(self, doc_id: str, tree: SummaryTree, ref_seq: int,
               message: str = "") -> str:
        with self._lock:
            handle = self._store(tree)
            commit = SummaryCommit(
                doc_id=doc_id, tree=handle,
                parent=self.head(doc_id), ref_seq=ref_seq, message=message,
            )
            self._record_commit(commit)
            return handle

    # -- commit/ref history chain ----------------------------------------------

    def _record_commit(self, commit: SummaryCommit) -> None:
        # holds-lock: _lock
        digest = commit.digest()
        self._commit_objects[digest] = commit
        self._commit_index[
            (commit.doc_id, commit.tree, commit.ref_seq)
        ] = digest
        self._set_ref(commit.doc_id, self.DEFAULT_REF, digest)

    def _set_ref(self, doc_id: str, name: str, commit_digest: str) -> None:
        # holds-lock: _lock
        self._refs.setdefault(doc_id, {})[name] = commit_digest

    def head(self, doc_id: str, ref: str = DEFAULT_REF) -> Optional[str]:
        """Commit digest the ref points at, or None.  Readers take the
        (re-entrant) lock too: the chain is read from executor threads
        while event-loop uploads advance it (fluidrace FL-RACE-GUARD)."""
        with self._lock:
            return self._refs.get(doc_id, {}).get(ref)

    def read_commit(self, digest: str) -> SummaryCommit:
        with self._lock:
            return self._commit_objects[digest]

    def refs(self, doc_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._refs.get(doc_id, {}))

    def create_ref(self, doc_id: str, name: str, commit_digest: str) -> None:
        """Pin a named ref (tag/branch) at an existing commit.  ``main`` is
        derived from the upload chain and cannot be repointed — that keeps
        the persisted chain the single source of truth for the head."""
        with self._lock:
            if name == self.DEFAULT_REF:
                raise ValueError(f"{name!r} is maintained by upload()")
            if commit_digest not in self._commit_objects:
                raise KeyError(commit_digest)
            if self._commit_objects[commit_digest].doc_id != doc_id:
                raise ValueError(
                    f"commit {commit_digest} belongs to document "
                    f"{self._commit_objects[commit_digest].doc_id!r}, "
                    f"not {doc_id!r}"
                )
            self._set_ref(doc_id, name, commit_digest)

    def _walk(self, digest: Optional[str]):
        """Generator over the parent chain from ``digest``, newest first;
        a missing link is reported as corruption, not a bare KeyError."""
        while digest is not None:
            with self._lock:  # point read per step: a generator must not
                # pin the store lock across its consumer's loop body
                commit = self._commit_objects.get(digest)
            if commit is None:
                raise ValueError(
                    f"corrupt commit chain: commit {digest} is missing "
                    "(truncated or partially-copied store?)"
                )
            yield commit
            digest = commit.parent

    def history(self, doc_id: str, ref: str = DEFAULT_REF,
                limit: Optional[int] = None):
        """Newest-first walk of the commit chain from ``ref``.  With
        ``limit``, the walk stops as soon as it has enough — commits past
        the limit are never resolved (so a truncated tail beyond the
        requested window cannot fail the call)."""
        if limit is not None and limit <= 0:
            return []
        out = []
        for commit in self._walk(self.head(doc_id, ref)):
            out.append(commit)
            if limit is not None and len(out) == limit:
                break
        return out

    def checkout(self, doc_id: str, ref: str = DEFAULT_REF):
        """(tree, ref_seq) at a ref's head, or (None, 0) — the history-aware
        sibling of :meth:`latest`."""
        digest = self.head(doc_id, ref)
        if digest is None:
            return None, 0
        commit = next(self._walk(digest))
        node = self.read(commit.tree)
        assert isinstance(node, SummaryTree)
        return node, commit.ref_seq

    def commit_for(self, doc_id: str, tree_handle: str,
                   ref_seq: int) -> Optional[str]:
        """Digest of the newest commit for (tree, ref_seq) — the pair the
        summarize op carries, so content-identical trees uploaded at
        different sequence points resolve to their own commits (scribe
        stamps this into summary acks)."""
        with self._lock:
            return self._commit_index.get((doc_id, tree_handle, ref_seq))

    def upload_obj(self, doc_id: str, obj: dict, ref_seq: int) -> str:
        """Upload from a (possibly INCREMENTAL) wire object: ``{"h": ...}``
        nodes reference unchanged subtrees of an earlier summary already in
        this store — the reference's handle-reuse upload (incremental
        summaries).  Raises KeyError if a referenced handle is unknown
        (callers fall back to a full upload)."""
        tree = tree_from_obj(obj, resolve=self.read)
        if not isinstance(tree, SummaryTree):
            raise ValueError("summary root must be a tree")
        return self.upload(doc_id, tree, ref_seq)

    def has(self, handle: str) -> bool:
        with self._lock:
            return handle in self._objects

    def _store(self, node: Union[SummaryTree, SummaryBlob]) -> str:
        # holds-lock: _lock
        digest = node.digest()
        self._objects[digest] = node
        if isinstance(node, SummaryTree):
            for child in node.children.values():
                self._store(child)
        return digest

    def latest(self, doc_id: str, at_or_below: int = None):
        """Returns (tree, ref_seq) of the newest summary, or (None, 0).
        With ``at_or_below``, the newest summary whose ref_seq does not
        exceed it (historical reconstruction / replay driver)."""
        tree, ref_seq, _handle = self.latest_with_handle(
            doc_id, at_or_below=at_or_below)
        return tree, ref_seq

    def latest_with_handle(self, doc_id: str, at_or_below: int = None):
        """(tree, ref_seq, tree handle) of the newest summary, or
        (None, 0, None).  The handle comes straight off the commit — the
        digest was computed once at upload time, so callers that key on
        it (the catch-up result cache) never re-hash the whole tree."""
        for commit in self._walk(self.head(doc_id)):
            if at_or_below is None or commit.ref_seq <= at_or_below:
                node = self.read(commit.tree)  # disk-backed stores lazy-load
                assert isinstance(node, SummaryTree)
                return node, commit.ref_seq, commit.tree
        return None, 0, None

    def upload_absent(self, doc_id: str, tree: SummaryTree, ref_seq: int,
                      message: str = "",
                      handle: Optional[str] = None) -> str:
        """Idempotent :meth:`upload`: no-op when a commit for this exact
        (tree, ref_seq) already exists, check-and-upload atomic under the
        store lock — N cache-served catch-up followers publishing the
        same fold chain ONE commit, not N duplicates.  ``handle`` (when
        the caller already knows ``tree.digest()`` — e.g. off a cache
        entry) skips re-hashing; it MUST be the tree's true digest."""
        with self._lock:
            if handle is None:
                handle = tree.digest()
            if self.commit_for(doc_id, handle, ref_seq) is None:
                return self.upload(doc_id, tree, ref_seq, message)
            return handle

    def read(self, handle: str) -> Union[SummaryTree, SummaryBlob]:
        with self._lock:
            return self._objects[handle]


# -- wire codec (versioned) ----------------------------------------------------

#: Summary wire-format version.  Readers accept any version <= this they
#: know how to decode; writers always emit the current version.
SUMMARY_WIRE_VERSION = 1


def _encode_blob(blob: "SummaryBlob") -> dict:
    """ONE wire encoding for blobs (utf-8 text, else base64) — shared by
    the full and incremental encoders so they can never diverge."""
    try:
        return {"b": blob.content.decode("utf-8")}
    except UnicodeDecodeError:
        import base64

        return {"b64": base64.b64encode(blob.content).decode("ascii")}


def tree_to_obj(tree: "SummaryTree") -> dict:
    """SummaryTree -> JSON-safe wire object (version-stamped envelope at the
    root; blobs are utf-8 text when possible, else base64)."""

    def encode(node):
        if isinstance(node, SummaryBlob):
            return _encode_blob(node)
        return {"t": {name: encode(child)
                      for name, child in node.children.items()}}

    return {"v": SUMMARY_WIRE_VERSION, **encode(tree)}


def tree_from_obj(obj: dict, resolve=None) -> "SummaryTree":
    """Inverse of :func:`tree_to_obj`; refuses versions newer than this
    reader understands.  ``resolve(handle)`` materializes ``{"h": ...}``
    nodes (incremental uploads); without it a handle node raises."""
    version = obj.get("v", 1)
    if version > SUMMARY_WIRE_VERSION:
        raise ValueError(
            f"summary wire version {version} is newer than supported "
            f"{SUMMARY_WIRE_VERSION}"
        )

    def decode(node):
        if "h" in node:
            if resolve is None:
                raise ValueError("handle node in a non-incremental context")
            return resolve(node["h"])
        if "b" in node:
            return SummaryBlob(node["b"].encode("utf-8"))
        if "b64" in node:
            import base64

            return SummaryBlob(base64.b64decode(node["b64"]))
        tree = SummaryTree()
        for name, child in node["t"].items():
            tree.children[name] = decode(child)
        return tree

    return decode(obj)


def tree_to_incremental_obj(tree: "SummaryTree",
                            base: Optional["SummaryTree"]) -> dict:
    """Wire object where every subtree/blob unchanged vs ``base`` collapses
    to a ``{"h": digest}`` handle reference (the reference's incremental
    summary upload: unchanged subtrees ride as handles to the previous
    summary).  With ``base=None`` this is :func:`tree_to_obj`."""
    if base is None:
        return tree_to_obj(tree)
    memo: dict = {}

    def digest_of(node):
        return node.digest(memo) if isinstance(node, SummaryTree) \
            else node.digest()

    def encode(node, base_node):
        if base_node is not None and digest_of(node) == digest_of(base_node):
            return {"h": digest_of(node)}
        if isinstance(node, SummaryBlob):
            return _encode_blob(node)
        base_children = base_node.children \
            if isinstance(base_node, SummaryTree) else {}
        return {"t": {
            name: encode(child, base_children.get(name))
            for name, child in node.children.items()
        }}

    return {"v": SUMMARY_WIRE_VERSION, **encode(tree, base)}
