"""Shared wire-protocol constants, framing, and message codecs for the
TCP server/driver.

One definition point so a protocol bump can never ship a client/server
pair that disagree on the version they stamp/accept — or on the field
names a message serializes under.  Every dataclass in
``protocol/messages.py`` has exactly one encode and one decode function
here, registered in ``MESSAGE_CODECS``; drivers, the standalone server,
and the durable op log all dispatch through these instead of calling
``to_dict``/``from_dict`` at scattered call sites (fluidlint's
FL-WIRE-COMPLETE rule pins the registry exhaustive).

Frame layout: [4-byte big-endian length][json bytes].
"""

from __future__ import annotations

import json
import struct

from .messages import RawOperation, SequencedMessage

WIRE_VERSION = 1
LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20


def frame_bytes(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    return LEN.pack(len(payload)) + payload


# -- message codecs -----------------------------------------------------------


def encode_raw_operation(op: RawOperation) -> dict:
    return op.to_dict()


def decode_raw_operation(d: dict) -> RawOperation:
    return RawOperation.from_dict(d)


def encode_sequenced_message(msg: SequencedMessage) -> dict:
    return msg.to_dict()


def decode_sequenced_message(d: dict) -> SequencedMessage:
    return SequencedMessage.from_dict(d)


#: class name -> (encode, decode); the dispatch surface drivers/services
#: use, and the exhaustiveness surface FL-WIRE-COMPLETE checks.
MESSAGE_CODECS = {
    "RawOperation": (encode_raw_operation, decode_raw_operation),
    "SequencedMessage": (encode_sequenced_message, decode_sequenced_message),
}
