"""Shared wire-protocol constants, framing, and message codecs for the
TCP server/driver — plus the columnar batch-ingress wire form (ISSUE 11).

One definition point so a protocol bump can never ship a client/server
pair that disagree on the version they stamp/accept — or on the field
names a message serializes under.  Every dataclass in
``protocol/messages.py`` has exactly one encode and one decode function
here, registered in ``MESSAGE_CODECS``; drivers, the standalone server,
and the durable op log all dispatch through these instead of calling
``to_dict``/``from_dict`` at scattered call sites (fluidlint's
FL-WIRE-COMPLETE rule pins the registry exhaustive — including the wire
dataclasses defined in THIS module).

Frame layout: [4-byte big-endian length][json bytes].

Columnar ingress (SEMANTICS.md "Columnar ingress"): a
:class:`ColumnBatch` carries a whole swarm tick's raw ops as
struct-packed numpy column arrays — no per-op Python objects on the
wire or in the ingress hot path.  The payload vocabulary is CLOSED
(``set``/``increment``/``insert`` over interned key/char tables);
``materialize(i)`` reconstructs the exact boxed ``groupedBatch``
:class:`RawOperation` envelope, which is what makes the boxed path a
byte-identical oracle for the columnar one.  The sequencer's stamped
output rides :class:`OpColumnSegment`/:class:`JoinColumnSegment` — lazy
:class:`SequencedMessage` ranges that materialize per message only when
something actually consumes messages (a broadcast subscriber, a
catch-up read, a failover replay).
"""

from __future__ import annotations

import base64
import dataclasses
import json
import struct
from typing import Sequence, Tuple

import numpy as np

from .messages import MessageType, RawOperation, SequencedMessage

WIRE_VERSION = 1
LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20


def frame_bytes(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    return LEN.pack(len(payload)) + payload


# -- message codecs -----------------------------------------------------------


def encode_raw_operation(op: RawOperation) -> dict:
    return op.to_dict()


def decode_raw_operation(d: dict) -> RawOperation:
    return RawOperation.from_dict(d)


def encode_sequenced_message(msg: SequencedMessage) -> dict:
    return msg.to_dict()


def decode_sequenced_message(d: dict) -> SequencedMessage:
    return SequencedMessage.from_dict(d)


# -- columnar batch ingress ---------------------------------------------------

#: closed op-kind vocabulary of the columnar payload columns
COL_KIND_SET = 0        # kv channel:    {"kind": "set", "key", "value"}
COL_KIND_INCREMENT = 1  # count channel: {"kind": "increment", "delta"}
COL_KIND_INSERT = 2     # text channel:  {"kind": "insert", "pos": 0, "text"}

#: op kind -> channel name (the swarm's three attach channels)
COL_CHANNELS = ("kv", "count", "text")

#: interned payload string tables: the closed vocabulary's key and
#: single-char insert strings are built ONCE here instead of per op
#: (f"k{n}" / chr(97+i) used to be formatted inside the generation loop)
KEY_STRINGS: Tuple[str, ...] = tuple(f"k{n}" for n in range(64))
CHAR_STRINGS: Tuple[str, ...] = tuple(chr(97 + i) for i in range(26))


def key_string(n: int) -> str:
    """Interned ``f"k{n}"`` (table hit for the swarm's 32-key vocabulary)."""
    return KEY_STRINGS[n] if 0 <= n < len(KEY_STRINGS) else f"k{n}"


#: column name -> little-endian dtype, in pack order (the struct layout)
COLUMN_LAYOUT = (
    ("doc_index", "<i4"),
    ("client_index", "<i4"),
    ("client_seq", "<i8"),
    ("ref_seq", "<i8"),
    ("kind", "<i1"),
    ("key_index", "<i2"),
    ("value", "<i8"),
    ("char_index", "<i2"),
)

_COL_MAGIC = b"FCB1"
_COL_HEADER = struct.Struct(">4sII")  # magic, n_rows, tables-json bytes


@dataclasses.dataclass(eq=False)
class ColumnBatch:
    """A batch of raw client ops as parallel numpy columns.

    ``doc_index``/``client_index`` index the ``doc_ids``/``client_ids``
    string tables (shared by reference in-process; compacted to the
    referenced entries when packed to bytes).  ``client_seq``/``ref_seq``
    are the per-op sequencing numbers; ``kind`` selects the payload shape
    from the closed vocabulary above, with ``key_index``/``value``/
    ``char_index`` as its payload columns (``value`` doubles as the
    increment delta).  ``v`` is the groupedBatch envelope version the
    boxed materialization stamps; ``ds`` the target datastore id.
    """

    doc_index: np.ndarray
    client_index: np.ndarray
    client_seq: np.ndarray
    ref_seq: np.ndarray
    kind: np.ndarray
    key_index: np.ndarray
    value: np.ndarray
    char_index: np.ndarray
    doc_ids: Sequence[str]
    client_ids: Sequence[str]
    v: int = 1
    ds: str = "ds"

    def __len__(self) -> int:
        return int(self.doc_index.shape[0])

    def __eq__(self, other) -> bool:
        if not isinstance(other, ColumnBatch):
            return NotImplemented
        return (
            self.v == other.v and self.ds == other.ds
            and tuple(self.doc_ids) == tuple(other.doc_ids)
            and tuple(self.client_ids) == tuple(other.client_ids)
            and all(
                np.array_equal(getattr(self, name), getattr(other, name))
                for name, _dtype in COLUMN_LAYOUT
            )
        )

    # -- boxed equivalence -----------------------------------------------------

    def contents(self, i: int) -> dict:
        """The exact ``groupedBatch`` contents dict the boxed generator
        builds for row ``i`` — the materialization-equivalence surface
        the parity oracle pins byte-for-byte."""
        k = int(self.kind[i])
        if k == COL_KIND_SET:
            inner = {"kind": "set", "key": key_string(int(self.key_index[i])),
                     "value": int(self.value[i])}
        elif k == COL_KIND_INCREMENT:
            inner = {"kind": "increment", "delta": int(self.value[i])}
        elif k == COL_KIND_INSERT:
            inner = {"kind": "insert", "pos": 0,
                     "text": CHAR_STRINGS[int(self.char_index[i])]}
        else:
            raise ValueError(f"unknown column op kind {k}")
        sub = {"clientSeq": int(self.client_seq[i]),
               "refSeq": int(self.ref_seq[i]),
               "ds": self.ds, "channel": COL_CHANNELS[k],
               "contents": inner}
        return {"type": "groupedBatch", "v": self.v, "ops": [sub]}

    def materialize(self, i: int) -> RawOperation:
        """Row ``i`` as the boxed :class:`RawOperation` envelope — the
        per-op fallback (deferred/faulted batches) and the parity oracle."""
        return RawOperation(
            client_id=self.client_ids[int(self.client_index[i])],
            client_seq=int(self.client_seq[i]),
            ref_seq=int(self.ref_seq[i]),
            type=MessageType.OP,
            contents=self.contents(i),
        )

    def client_id(self, i: int) -> str:
        return self.client_ids[int(self.client_index[i])]

    def doc_id(self, i: int) -> str:
        return self.doc_ids[int(self.doc_index[i])]

    def take(self, rows: np.ndarray) -> "ColumnBatch":
        """A new batch holding only ``rows`` (in the given order), sharing
        the string tables by reference — the front door's per-shard split
        of one client batch.  Row order is preserved, so per-document
        stamp order is exactly the original batch's."""
        return ColumnBatch(
            doc_index=self.doc_index[rows],
            client_index=self.client_index[rows],
            client_seq=self.client_seq[rows],
            ref_seq=self.ref_seq[rows],
            kind=self.kind[rows],
            key_index=self.key_index[rows],
            value=self.value[rows],
            char_index=self.char_index[rows],
            doc_ids=self.doc_ids,
            client_ids=self.client_ids,
            v=self.v,
            ds=self.ds,
        )


def column_batch_to_bytes(batch: ColumnBatch) -> bytes:
    """Struct-pack a :class:`ColumnBatch`: fixed-dtype column buffers
    back to back, then a canonical-JSON table blob COMPACTED to the
    referenced ``doc_ids``/``client_ids`` entries (in-process producers
    share full population tables by reference; the wire carries only
    what the batch uses)."""
    n = len(batch)
    doc_u, doc_inv = np.unique(batch.doc_index, return_inverse=True)
    cli_u, cli_inv = np.unique(batch.client_index, return_inverse=True)
    compact = {
        "doc_index": doc_inv, "client_index": cli_inv,
    }
    tables = {
        "v": batch.v,
        "ds": batch.ds,
        "docs": [batch.doc_ids[int(i)] for i in doc_u.tolist()],
        "clients": [batch.client_ids[int(i)] for i in cli_u.tolist()],
    }
    blob = json.dumps(tables, sort_keys=True,
                      separators=(",", ":"), ensure_ascii=False
                      ).encode("utf-8")
    parts = [_COL_HEADER.pack(_COL_MAGIC, n, len(blob))]
    for name, dtype in COLUMN_LAYOUT:
        col = compact.get(name)
        if col is None:
            col = getattr(batch, name)
        parts.append(np.ascontiguousarray(col.astype(dtype, copy=False))
                     .tobytes())
    parts.append(blob)
    return b"".join(parts)


def column_batch_from_bytes(data: bytes) -> ColumnBatch:
    """Inverse of :func:`column_batch_to_bytes`; validates the closed
    vocabulary so a malformed peer fails loudly, not as a KeyError deep
    in materialization."""
    if len(data) < _COL_HEADER.size:
        raise ValueError("column batch frame too short")
    magic, n, blob_len = _COL_HEADER.unpack_from(data, 0)
    if magic != _COL_MAGIC:
        raise ValueError(f"bad column batch magic {magic!r}")
    offset = _COL_HEADER.size
    cols = {}
    for name, dtype in COLUMN_LAYOUT:
        width = np.dtype(dtype).itemsize
        end = offset + n * width
        if end > len(data):
            raise ValueError(f"column batch truncated in column {name!r}")
        # copy so the columns are writable, independent of the frame buffer
        cols[name] = np.frombuffer(data, dtype=dtype, count=n,
                                   offset=offset).copy()
        offset = end
    if offset + blob_len > len(data):
        raise ValueError("column batch truncated in table blob")
    tables = json.loads(data[offset:offset + blob_len].decode("utf-8"))
    batch = ColumnBatch(
        doc_ids=tuple(tables["docs"]),
        client_ids=tuple(tables["clients"]),
        v=int(tables.get("v", 1)),
        ds=str(tables.get("ds", "ds")),
        **cols,
    )
    if n:
        if int(batch.kind.min()) < COL_KIND_SET \
                or int(batch.kind.max()) > COL_KIND_INSERT:
            raise ValueError("column batch op kind outside the vocabulary")
        if int(batch.char_index.min()) < 0 \
                or int(batch.char_index.max()) >= len(CHAR_STRINGS):
            raise ValueError("column batch char index outside the vocabulary")
        if int(batch.key_index.min()) < 0 \
                or int(batch.key_index.max()) >= len(KEY_STRINGS):
            raise ValueError("column batch key index outside the vocabulary")
        if int(batch.doc_index.min()) < 0 \
                or int(batch.doc_index.max()) >= len(batch.doc_ids):
            raise ValueError("column batch doc index outside its table")
        if int(batch.client_index.min()) < 0 \
                or int(batch.client_index.max()) >= len(batch.client_ids):
            raise ValueError("column batch client index outside its table")
    return batch


def encode_column_batch(batch: ColumnBatch) -> dict:
    """Codec-registry form: the struct-packed bytes, base64'd so the
    JSON framing (`frame_bytes`) can carry them unchanged."""
    return {"packed": base64.b64encode(column_batch_to_bytes(batch))
            .decode("ascii")}


def decode_column_batch(d: dict) -> ColumnBatch:
    return column_batch_from_bytes(base64.b64decode(d["packed"]))


# -- lazy sequenced segments --------------------------------------------------


class ColumnSegment:
    """A contiguous run of sequenced messages stored columnar.

    The sequencer's columnar stamp output and the op log's columnar
    storage unit: seq numbers are ``start_seq + j`` by construction, all
    rows share one (conservative, batch-start) ``min_seq``, and
    timestamps are ``clock0 + j`` — so heads, contiguity checks, and
    durable encoding never touch per-message Python objects.
    ``materialize(j)`` rebuilds the exact boxed
    :class:`SequencedMessage`; ``wire_dict(j)`` its codec form.
    """

    __slots__ = ("start_seq", "min_seq", "clock0")

    def __init__(self, start_seq: int, min_seq: int, clock0: int) -> None:
        self.start_seq = start_seq
        self.min_seq = min_seq
        self.clock0 = clock0

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def last_seq(self) -> int:
        return self.start_seq + len(self) - 1

    def materialize(self, j: int) -> SequencedMessage:  # pragma: no cover
        raise NotImplementedError

    def prefix(self, j: int) -> "ColumnSegment":  # pragma: no cover
        raise NotImplementedError

    def messages(self):
        return [self.materialize(j) for j in range(len(self))]

    def wire_dict(self, j: int) -> dict:
        return encode_sequenced_message(self.materialize(j))


class OpColumnSegment(ColumnSegment):
    """The stamped view of a :class:`ColumnBatch` slice: ``rows`` are
    the KEPT (non-duplicate) batch row indexes, in stamp order."""

    __slots__ = ("batch", "rows")

    def __init__(self, batch: ColumnBatch, rows: np.ndarray,
                 start_seq: int, min_seq: int, clock0: int) -> None:
        super().__init__(start_seq, min_seq, clock0)
        self.batch = batch
        self.rows = rows

    def __len__(self) -> int:
        return int(self.rows.shape[0])

    def materialize(self, j: int) -> SequencedMessage:
        i = int(self.rows[j])
        return SequencedMessage(
            seq=self.start_seq + j,
            client_id=self.batch.client_id(i),
            client_seq=int(self.batch.client_seq[i]),
            ref_seq=int(self.batch.ref_seq[i]),
            min_seq=self.min_seq,
            type=MessageType.OP,
            contents=self.batch.contents(i),
            timestamp=float(self.clock0 + j),
        )

    def prefix(self, j: int) -> "OpColumnSegment":
        return OpColumnSegment(self.batch, self.rows[:j],
                               self.start_seq, self.min_seq, self.clock0)


class JoinColumnSegment(ColumnSegment):
    """A bulk-admitted JOIN cohort: one JOIN message per client id, each
    referencing the seq directly before it (the boxed ``connect_many``
    stamping shape)."""

    __slots__ = ("cohort",)

    def __init__(self, cohort: Tuple[str, ...], start_seq: int,
                 min_seq: int, clock0: int) -> None:
        super().__init__(start_seq, min_seq, clock0)
        self.cohort = cohort

    def __len__(self) -> int:
        return len(self.cohort)

    def materialize(self, j: int) -> SequencedMessage:
        return SequencedMessage(
            seq=self.start_seq + j,
            client_id=None,
            client_seq=-1,
            ref_seq=self.start_seq + j - 1,
            min_seq=self.min_seq,
            type=MessageType.JOIN,
            contents={"clientId": self.cohort[j]},
            timestamp=float(self.clock0 + j),
        )

    def prefix(self, j: int) -> "JoinColumnSegment":
        return JoinColumnSegment(self.cohort[:j], self.start_seq,
                                 self.min_seq, self.clock0)


def entry_last_seq(entry) -> int:
    """Highest seq of an op-log entry (message or columnar segment)."""
    return entry.last_seq if isinstance(entry, ColumnSegment) else entry.seq


#: class name -> (encode, decode); the dispatch surface drivers/services
#: use, and the exhaustiveness surface FL-WIRE-COMPLETE checks.
MESSAGE_CODECS = {
    "RawOperation": (encode_raw_operation, decode_raw_operation),
    "SequencedMessage": (encode_sequenced_message, decode_sequenced_message),
    "ColumnBatch": (encode_column_batch, decode_column_batch),
}
