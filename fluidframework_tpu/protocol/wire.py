"""Shared wire-protocol constants and framing for the TCP server/driver.

One definition point so a protocol bump can never ship a client/server
pair that disagree on the version they stamp/accept.

Frame layout: [4-byte big-endian length][json bytes].
"""

from __future__ import annotations

import json
import struct

WIRE_VERSION = 1
LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20


def frame_bytes(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    return LEN.pack(len(payload)) + payload
