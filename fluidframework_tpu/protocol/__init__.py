"""Protocol core: op model, sequencing, summary trees.

Pure Python, zero JAX.  This layer is the capability-equivalent of the
reference's protocol-definitions / protocol-base / memory-orderer packages
(SURVEY.md §1 layers 2–4; upstream paths UNVERIFIED — empty reference mount).
"""

from .messages import (
    UNASSIGNED_SEQ,
    MessageType,
    RawOperation,
    SequencedMessage,
)
from .sequencer import ClientConnection, Sequencer
from .summary import (
    SummaryBlob,
    SummaryCommit,
    SummaryTree,
    SummaryStorage,
    canonical_json,
)

__all__ = [
    "UNASSIGNED_SEQ",
    "MessageType",
    "RawOperation",
    "SequencedMessage",
    "ClientConnection",
    "Sequencer",
    "SummaryBlob",
    "SummaryCommit",
    "SummaryTree",
    "SummaryStorage",
    "canonical_json",
]
