"""The op / sequence-number model.

Capability-equivalent of the reference's ``ISequencedDocumentMessage`` /
``IDocumentMessage`` contracts (SURVEY.md §2.1 driver-definitions; upstream
paths UNVERIFIED — empty reference mount).  The five numbers that drive every
merge decision in the framework:

- ``seq``        — the total-order sequence number stamped by the sequencer.
- ``client_seq`` — per-client monotonically increasing counter, used for
                   resubmit dedup and for matching acks to pending local ops.
- ``ref_seq``    — the latest ``seq`` the submitting client had processed when
                   it created the op.  Defines the *view* the op's positions
                   and conflicts are resolved against.
- ``min_seq``    — the minimum of all connected clients' ``ref_seq`` at stamp
                   time (the collaboration window floor).  State older than
                   ``min_seq`` is visible to every client, so tombstones below
                   it can be compacted (zamboni) and rebase branches below it
                   evicted.
- ``UNASSIGNED_SEQ`` (-1) — marks optimistic local state that has not yet been
  sequenced; it is ordered *after* every assigned seq (it will receive a larger
  seq than anything currently applied).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

# Sentinel sequence number for optimistic local (pending, un-acked) state.
# Ordering rule: UNASSIGNED is *newer* than any assigned seq.
UNASSIGNED_SEQ = -1

# The sequence number that summaries/new documents start from.
INITIAL_SEQ = 0


class MessageType(str, enum.Enum):
    """Wire-level message types (capability parity with the reference's
    protocol MessageType: op/join/leave/propose/summarize/summaryAck...)."""

    OP = "op"                    # a DDS/runtime operation (contents is routed)
    JOIN = "join"                # client joined the quorum
    LEAVE = "leave"              # client left the quorum
    PROPOSAL = "propose"         # quorum proposal (e.g. code details)
    SUMMARIZE = "summarize"      # summarizer announces an uploaded summary
    SUMMARY_ACK = "summaryAck"   # service accepted a summary
    SUMMARY_NACK = "summaryNack"  # service rejected a summary
    NO_OP = "noop"               # heartbeat; advances ref_seq/MSN only
    SIGNAL = "signal"            # unsequenced ephemeral broadcast (presence)


class NackError(ConnectionError):
    """An op the service refused to sequence (throttling, stale ref_seq).

    Subclasses ConnectionError deliberately: the runtime's wire-drain
    already treats ConnectionError as "keep the encoded ops queued and
    retry on a later flush", which is exactly nack semantics — the
    DeltaManager additionally honors ``retry_after`` before re-sending.
    """

    def __init__(self, reason: str, retry_after: float = 0.0,
                 code: str = "throttled", admission=None) -> None:
        super().__init__(reason)
        self.reason = reason
        self.retry_after = retry_after
        #: "throttled" (resend the same bytes later) or "staleView" (the
        #: encoded view is unresolvable: rebase + resubmit via reconnect)
        self.code = code
        #: optional AdmissionController snapshot at shed time (ISSUE 18):
        #: rides the wire so an out-of-proc harness can pin that a
        #: verdict's pacing derived from the shard's REPORTED fold-cost
        #: EMA — replay-identical state — not from wall clock.
        self.admission = admission


class ShardFencedError(ConnectionError):
    """The orderer instance behind this connection was fenced (its shard
    was marked dead and its documents re-owned elsewhere): nothing it
    stamps can reach the durable log anymore, so the submit was refused.

    Subclasses ConnectionError for the same reason NackError does — the
    runtime's wire-drain keeps the encoded ops queued — but recovery is
    NOT "resend later on the same connection": the caller must re-resolve
    the document (the router now hands out the recovered owner's
    endpoint) and reconnect; the DeltaManager raises its
    ``fence_required`` flag so hosts know a plain retry cannot succeed.
    """

    def __init__(self, doc_id: str, reason: str = "") -> None:
        super().__init__(
            reason or f"orderer for {doc_id!r} is fenced (shard died; "
                      f"the document was re-owned — re-resolve and "
                      f"reconnect)"
        )
        self.doc_id = doc_id


class DocRelocatedError(ShardFencedError):
    """The shard that received this request no longer owns the document —
    it migrated to another shard (live rebalance) or the caller's routing
    table is stale after a failover.  The wire form is the ``wrongShard``
    error code (the out-of-process tier's redirect signal).

    Subclasses :class:`ShardFencedError` deliberately: the recovery is
    identical — re-resolve the owner (ask the front door / router) and
    retry there — so every existing fence-handling path (driver no_retry,
    DeltaManager self-heal, front-door re-route) takes it unchanged.
    """

    def __init__(self, doc_id: str, reason: str = "") -> None:
        super().__init__(
            doc_id,
            reason or f"document {doc_id!r} is not served by this shard "
                      f"(migrated or re-owned — re-resolve the owner)",
        )


class BatchAbortedError(ConnectionError):
    """A batched submit (``Sequencer.submit_many``) stopped partway.

    Ops ``[0, consumed)`` of the batch were fully handled — ``stamped``
    holds their sequenced messages (dedup'd duplicates excluded) and they
    are durable; the op at ``consumed`` failed with ``cause`` and every
    later op was left untouched.  The recovery contract is the same as a
    client reconnect: resubmit the WHOLE batch after the failure clears —
    the sequencer's per-client dedup floors absorb the stamped prefix, so
    a blanket resubmit can never double-sequence.

    Subclasses ConnectionError so callers that treat batched ingress like
    any transport (keep the ops queued, retry later) need no special case.
    """

    def __init__(self, consumed: int, stamped: list,
                 cause: BaseException) -> None:
        super().__init__(
            f"batched submit aborted at op {consumed}: {cause!r}"
        )
        self.consumed = consumed
        self.stamped = stamped
        self.cause = cause


class ColumnAppendError(ConnectionError):
    """A bulk columnar durable append (``OpLog.append_columns``) stopped
    partway: rows ``[0, landed)`` of the segment are durable, row
    ``landed`` failed with ``cause``, and no later row was attempted.

    This is the column-path twin of the per-op append failure inside
    :class:`BatchAbortedError`'s contract: the sequencer unwinds the
    un-landed suffix (seq counter, clock, dedup floors, ref_seqs) and
    re-raises the structured batch abort, so callers see exactly the
    whole-batch-resubmit recovery surface they already implement.

    Subclasses ConnectionError for the same queued-ops-survive reason as
    every other ingress failure type in this module.
    """

    def __init__(self, landed: int, cause: BaseException) -> None:
        super().__init__(
            f"columnar append aborted at row {landed}: {cause!r}"
        )
        self.landed = landed
        self.cause = cause


class RetryBudgetExhaustedError(ConnectionError):
    """A bounded retry loop gave up: the policy's attempt count or delay
    budget ran out before the operation succeeded.

    Subclasses ConnectionError so the runtime wire-drain's queued-op
    contract still holds (the encoded ops stay queued; a LATER flush —
    with a fresh budget — may drain them), but the type is distinct so
    hosts and tests can pin "the budget was respected" versus "the op
    happened to fail".  Carries the forensic trail: how many attempts,
    how much injected-clock time was spent sleeping, and the last
    underlying error.
    """

    def __init__(self, operation: str, attempts: int, slept: float,
                 last_error: Optional[BaseException]) -> None:
        super().__init__(
            f"retry budget exhausted for {operation}: {attempts} "
            f"attempt(s), {slept:.3f}s of backoff; last error: "
            f"{last_error!r}"
        )
        self.operation = operation
        self.attempts = attempts
        self.slept = slept
        self.last_error = last_error


@dataclasses.dataclass
class RawOperation:
    """An op as submitted by a client, before sequencing."""

    client_id: str
    client_seq: int
    ref_seq: int
    type: MessageType
    contents: Any = None

    def to_dict(self) -> dict:
        return {
            "clientId": self.client_id,
            "clientSequenceNumber": self.client_seq,
            "referenceSequenceNumber": self.ref_seq,
            "type": self.type.value,
            "contents": self.contents,
        }

    @staticmethod
    def from_dict(d: dict) -> "RawOperation":
        return RawOperation(
            client_id=d["clientId"],
            client_seq=d.get("clientSequenceNumber", -1),
            ref_seq=d.get("referenceSequenceNumber", 0),
            type=MessageType(d["type"]),
            contents=d.get("contents"),
        )


@dataclasses.dataclass
class SequencedMessage:
    """An op after the sequencer stamped it — what every client applies.

    This is the unit of the durable op log, of catch-up replay, and of the
    packed ragged tensors the TPU kernels fold over.
    """

    seq: int
    client_id: Optional[str]     # None for server-generated messages
    client_seq: int
    ref_seq: int
    min_seq: int
    type: MessageType
    contents: Any = None
    timestamp: float = 0.0

    def to_dict(self) -> dict:
        return {
            "sequenceNumber": self.seq,
            "clientId": self.client_id,
            "clientSequenceNumber": self.client_seq,
            "referenceSequenceNumber": self.ref_seq,
            "minimumSequenceNumber": self.min_seq,
            "type": self.type.value,
            "contents": self.contents,
            "timestamp": self.timestamp,
        }

    @staticmethod
    def from_dict(d: dict) -> "SequencedMessage":
        return SequencedMessage(
            seq=d["sequenceNumber"],
            client_id=d.get("clientId"),
            client_seq=d.get("clientSequenceNumber", -1),
            ref_seq=d.get("referenceSequenceNumber", 0),
            min_seq=d.get("minimumSequenceNumber", 0),
            type=MessageType(d["type"]),
            contents=d.get("contents"),
            timestamp=d.get("timestamp", 0.0),
        )
