"""CPU-oracle Distributed Data Structures.

Clean-room Python implementations of the merge engines (SURVEY.md §2.2;
reference capability: packages/dds/* — upstream paths UNVERIFIED, empty
reference mount).  These define the framework's merge semantics (documented in
SEMANTICS.md), serve as correctness oracles for the TPU kernels in
``fluidframework_tpu.ops``, and are the 1× CPU baseline the 50× north star is
measured against.
"""

from .shared_object import SharedObject
from .map import SharedMap, SharedDirectory
from .merge_tree import MergeTreeOracle, Segment
from .sequence import SharedString
from .intervals import Interval, IntervalCollection
from .cell_counter import SharedCell, SharedCounter
from .matrix import SharedMatrix, PermutationVector, SparseArray2D
from .tree import (
    SharedTree,
    SchemaFactory,
    TreeViewConfiguration,
    FieldSchema,
    Forest,
    EditManager,
    compose,
    invert,
)

__all__ = [
    "SharedObject",
    "SharedMap",
    "SharedDirectory",
    "MergeTreeOracle",
    "Segment",
    "SharedString",
    "Interval",
    "IntervalCollection",
    "SharedCell",
    "SharedCounter",
    "SharedMatrix",
    "PermutationVector",
    "SparseArray2D",
    "SharedTree",
    "SchemaFactory",
    "TreeViewConfiguration",
    "FieldSchema",
    "Forest",
    "EditManager",
    "compose",
    "invert",
]
