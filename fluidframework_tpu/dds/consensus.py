"""Consensus-style DDSes: ordered collection, register collection, task
manager.

Capability-equivalent of the reference's ``ordered-collection``
(ConsensusQueue), ``register-collection`` (ConsensusRegisterCollection) and
``task-manager`` packages (SURVEY.md §2.2; upstream paths UNVERIFIED —
empty reference mount).  Unlike the optimistic DDSes, these are
**pessimistic**: a mutation takes effect only when its op is *sequenced* —
nothing is applied optimistically, so every client transitions state at the
same fold position and the sequencer's total order IS the consensus.

These are control-plane structures (work distribution, election, versioned
configuration): their op volume is tiny, so they ride the CPU fold path and
are deliberately not device-kernel targets — the device budget goes to the
content-bearing DDSes (SURVEY.md §7).

Design notes vs the reference:
- ConsensusQueue.acquire(): the reference returns a promise resolved at
  sequencing; here acquire() submits and returns a ticket id — after
  drain(), ``acquired`` holds what this client holds (same protocol, pull
  instead of push).
- Quorum LEAVE handling: items held by (tasks assigned to) a departed
  client re-queue automatically, driven by the sequenced LEAVE — identical
  on every client.  The runtime routes non-OP messages to channels via
  ``observe_protocol`` (see ContainerRuntime.process).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .shared_object import SharedObject


class ConsensusQueue(SharedObject):
    """Ordered work queue with acquire/complete semantics (at-least-once
    hand-off: a held item whose holder leaves returns to the front)."""

    TYPE = "ordered-collection-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._items: List[list] = []       # [id, value] FIFO
        self._held: Dict[str, list] = {}   # item_id -> [value, holder_client]
        self._next_item = 0

    # -- reads -----------------------------------------------------------------

    @property
    def items(self) -> List[Any]:
        return [v for _i, v in self._items]

    @property
    def held_by_me(self) -> Dict[str, Any]:
        return {i: v for i, (v, holder) in self._held.items()
                if holder == self.client_id}

    def holder_of(self, item_id: str) -> Optional[str]:
        entry = self._held.get(item_id)
        return entry[1] if entry else None

    def __len__(self) -> int:
        return len(self._items)

    # -- writes (sequenced-only: no optimistic apply) --------------------------

    def add(self, value: Any) -> None:
        self._submit_local_op({"kind": "add", "value": value})

    def acquire(self) -> None:
        """Ask for the queue head; after the op sequences (drain), the item
        appears in ``held_by_me`` — or nothing does, if the queue was empty
        by then."""
        self._submit_local_op({"kind": "acquire"})

    def complete(self, item_id: str) -> None:
        self._submit_local_op({"kind": "complete", "id": item_id})

    def release(self, item_id: str) -> None:
        self._submit_local_op({"kind": "release", "id": item_id})

    # -- sequenced fold --------------------------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        kind = op["kind"]
        if kind == "add":
            self._items.append([f"item-{self._next_item}", op["value"]])
            self._next_item += 1
        elif kind == "acquire":
            if self._items:
                item_id, value = self._items.pop(0)
                self._held[item_id] = [value, msg.client_id]
        elif kind == "complete":
            self._held.pop(op["id"], None)
        elif kind == "release":
            entry = self._held.pop(op["id"], None)
            if entry is not None:
                self._items.insert(0, [op["id"], entry[0]])
        else:
            raise ValueError(f"unknown queue op {kind!r}")

    def observe_protocol(self, msg: SequencedMessage) -> None:
        """Sequenced LEAVE: everything the departed client held re-queues at
        the front (deterministic: same fold position on every client)."""
        if msg.type is not MessageType.LEAVE:
            return
        gone = msg.contents["clientId"]
        requeue = [(i, v) for i, (v, holder) in self._held.items()
                   if holder == gone]
        for item_id, value in sorted(requeue):
            del self._held[item_id]
            self._items.insert(0, [item_id, value])

    def apply_stashed_op(self, contents) -> None:
        # Pessimistic DDS: nothing was applied locally; re-submit verbatim.
        self._submit_local_op(dict(contents))

    # -- summary ---------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json({
            "items": self._items,
            "held": {k: self._held[k] for k in sorted(self._held)},
            "next": self._next_item,
        }))
        return tree

    def load(self, summary: SummaryTree) -> None:
        obj = json.loads(summary.blob_bytes("header"))
        self._items = [list(x) for x in obj["items"]]
        self._held = {k: list(v) for k, v in obj["held"].items()}
        self._next_item = obj["next"]
        self.discard_pending()


class ConsensusRegisterCollection(SharedObject):
    """Versioned registers: concurrent writes all survive as versions until
    a later write supersedes them (its ref_seq has seen them)."""

    TYPE = "register-collection-tpu"

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        # key -> list of [value, seq] versions, oldest first
        self._registers: Dict[str, List[list]] = {}

    def _resubmit_rebased(self, pending) -> None:
        """Registers carry no positions, but ``ref_seq`` IS semantic here:
        it records which versions the writer had observed (the supersede
        filter in _process_core).  Re-pinning it to the current view would
        silently supersede concurrent versions the author never saw, so a
        stale resubmit keeps the *original* ref_seq — out-of-window is
        harmless because the fold never resolves a view, it only compares
        sequence numbers."""
        for _old_client_seq, contents, metadata, ref_seq in pending:
            self._resubmit_core(contents, metadata, ref_seq)

    # -- reads -----------------------------------------------------------------

    def read(self, key: str, default: Any = None) -> Any:
        """Atomic read: the first (winning) version — first write in total
        order among still-concurrent writes."""
        versions = self._registers.get(key)
        return versions[0][0] if versions else default

    def read_versions(self, key: str) -> List[Any]:
        return [v for v, _seq in self._registers.get(key, [])]

    def keys(self):
        return self._registers.keys()

    # -- writes ----------------------------------------------------------------

    def write(self, key: str, value: Any) -> None:
        self._submit_local_op({"kind": "write", "key": key, "value": value})

    # -- sequenced fold --------------------------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        if op["kind"] != "write":
            raise ValueError(f"unknown register op {op['kind']!r}")
        versions = self._registers.setdefault(op["key"], [])
        # Versions this write has already observed are superseded.
        versions[:] = [v for v in versions if v[1] > msg.ref_seq]
        versions.append([op["value"], msg.seq])

    def apply_stashed_op(self, contents) -> None:
        self._submit_local_op(dict(contents))

    # -- summary ---------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(
            {k: self._registers[k] for k in sorted(self._registers)}
        ))
        return tree

    def load(self, summary: SummaryTree) -> None:
        obj = json.loads(summary.blob_bytes("header"))
        self._registers = {k: [list(v) for v in vs] for k, vs in obj.items()}
        self.discard_pending()


class TaskManager(SharedObject):
    """Exclusive task assignment: clients volunteer for a task id; the
    first in the sequenced volunteer queue holds the task; abandoning or
    leaving passes it down the queue."""

    TYPE = "task-manager-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._queues: Dict[str, List[str]] = {}  # task -> client queue

    # -- reads -----------------------------------------------------------------

    def assigned_to(self, task_id: str) -> Optional[str]:
        queue = self._queues.get(task_id)
        return queue[0] if queue else None

    def assigned_to_me(self, task_id: str) -> bool:
        return (self.client_id is not None
                and self.assigned_to(task_id) == self.client_id)

    def queued(self, task_id: str) -> List[str]:
        return list(self._queues.get(task_id, []))

    # -- writes ----------------------------------------------------------------

    def volunteer(self, task_id: str) -> None:
        self._submit_local_op({"kind": "volunteer", "task": task_id})

    def abandon(self, task_id: str) -> None:
        self._submit_local_op({"kind": "abandon", "task": task_id})

    def complete(self, task_id: str) -> None:
        """The assignee marks the task done: the whole queue clears (the
        reference's task completion semantics)."""
        self._submit_local_op({"kind": "complete", "task": task_id})

    # -- sequenced fold --------------------------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        kind = op["kind"]
        queue = self._queues.setdefault(op["task"], [])
        if kind == "volunteer":
            if msg.client_id not in queue:
                queue.append(msg.client_id)
        elif kind == "abandon":
            if msg.client_id in queue:
                queue.remove(msg.client_id)
        elif kind == "complete":
            if queue and queue[0] == msg.client_id:
                queue.clear()
        else:
            raise ValueError(f"unknown task op {kind!r}")
        if not queue:
            del self._queues[op["task"]]

    def observe_protocol(self, msg: SequencedMessage) -> None:
        if msg.type is not MessageType.LEAVE:
            return
        gone = msg.contents["clientId"]
        for task_id in sorted(self._queues):
            queue = self._queues[task_id]
            if gone in queue:
                queue.remove(gone)
            if not queue:
                del self._queues[task_id]

    def apply_stashed_op(self, contents) -> None:
        self._submit_local_op(dict(contents))

    # -- summary ---------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(
            {k: self._queues[k] for k in sorted(self._queues)}
        ))
        return tree

    def load(self, summary: SummaryTree) -> None:
        self._queues = {
            k: list(v)
            for k, v in json.loads(summary.blob_bytes("header")).items()
        }
        self.discard_pending()
