"""SharedMatrix — 2D collaborative grid over dual permutation merge-trees.

Capability-equivalent of the reference's matrix package (SURVEY.md §2.2:
``SharedMatrix``/``PermutationVector``/``SparseArray2D``; upstream paths
UNVERIFIED — empty reference mount).  North-star config #4.

Design (SEMANTICS.md §matrix):

- **Rows and columns each merge like text.**  A :class:`PermutationVector` is
  a merge-tree (the exact oracle from ``dds/merge_tree.py``) whose segment
  payloads are runs of *handles* — stable replica-local integers — instead of
  characters.  Row/col insert and remove therefore inherit the merge-tree's
  RGA tie-breaks, tombstones, and zamboni unchanged.
- **Cells are keyed by (row_handle, col_handle)**, not positions, so cell
  writes survive any concurrent row/col reordering.  A cell-set op carries
  *positions* resolved against the op's view ``(ref_seq, client)``; every
  replica resolves them through its own permutation vectors to its own local
  handles — handles never go on the wire.
- **Cell conflict policy**: last-writer-wins by default.  ``setPolicy`` ops
  switch the matrix (one-way) to first-writer-wins, where a sequenced set is
  *rejected* iff the cell already holds a sequenced value with
  ``stored_seq > op.ref_seq`` written by a different client — a rule that
  depends only on sequenced state, so every replica decides identically.
- **Summaries are replica-independent**: handles are renumbered canonically
  (document order over the sequenced, non-expired segments) at summary time,
  so converged replicas emit byte-identical blobs despite having allocated
  different local handles.

The device twin (``ops/matrix_kernel.py``) replays both permutation folds
with the merge-tree kernel — handle runs pack into the same ``(tstart,
tlen)`` span arrays as text spans — and reduces cell-sets over the resolved
handles.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..protocol.messages import UNASSIGNED_SEQ, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .merge_tree import MergeTreeOracle, NO_CLIENT, SegmentGroup
from .shared_object import SharedObject

TILE = 16  # SparseArray2D tile edge


class SparseArray2D:
    """Tiled sparse 2D store (reference capability: SparseArray2D): cells
    bucketed into TILE×TILE tiles keyed by handle coordinates.  Handles grow
    without bound; only touched tiles exist."""

    def __init__(self) -> None:
        self._tiles: Dict[Tuple[int, int], Dict[Tuple[int, int], Any]] = {}
        self._count = 0

    def get(self, r: int, c: int, default: Any = None) -> Any:
        tile = self._tiles.get((r // TILE, c // TILE))
        if tile is None:
            return default
        return tile.get((r % TILE, c % TILE), default)

    def set(self, r: int, c: int, value: Any) -> None:
        tile = self._tiles.setdefault((r // TILE, c // TILE), {})
        if (r % TILE, c % TILE) not in tile:
            self._count += 1
        tile[(r % TILE, c % TILE)] = value

    def delete(self, r: int, c: int) -> None:
        key = (r // TILE, c // TILE)
        tile = self._tiles.get(key)
        if tile is not None and tile.pop((r % TILE, c % TILE), None) is not None:
            self._count -= 1
            if not tile:
                del self._tiles[key]

    def __len__(self) -> int:
        return self._count

    def items(self) -> Iterator[Tuple[Tuple[int, int], Any]]:
        for (tr, tc), tile in self._tiles.items():
            for (r, c), value in tile.items():
                yield (tr * TILE + r, tc * TILE + c), value


class PermutationVector:
    """One axis's ordering: a merge-tree whose segments carry handle runs.

    Reference capability: PermutationVector — rows/cols merge like text.
    Handles are allocated sequentially per replica; identity is local, order
    is replicated.
    """

    def __init__(self) -> None:
        self.tree = MergeTreeOracle()
        self._next_handle = 0

    def alloc(self, count: int) -> Tuple[int, ...]:
        handles = tuple(range(self._next_handle, self._next_handle + count))
        self._next_handle += count
        return handles

    def visible_count(self, client: str = NO_CLIENT) -> int:
        return self.tree.length(client=client)

    def handle_at(self, pos: int, ref_seq: int, client: str,
                  up_to_seq: Optional[int] = None) -> Optional[int]:
        """Resolve a visible position in the view to a handle (None if the
        position is beyond the view's length — deterministic no-op)."""
        c = 0
        for seg in self.tree.segments:
            v = self.tree._visible_len(seg, ref_seq, client, up_to_seq)
            if v > 0 and c + v > pos:
                return seg.text[pos - c]
            c += v
        return None

    def live_handles(self) -> set:
        """Handles still physically present (incl. in-window tombstones)."""
        live = set()
        for seg in self.tree.segments:
            live.update(seg.text)
        return live

    # -- canonical summary form ------------------------------------------------

    def canonical_records(self) -> Tuple[List[dict], Dict[int, int]]:
        """(records, handle→canonical map): the merge-tree's normalized
        record list with handle runs replaced by run lengths.  Canonical
        handle = enumeration order over the normalized runs — identical
        across converged replicas.  All clamp/expire/merge rules live in
        MergeTreeOracle.normalized_records (one normalizer, one behavior)."""
        records: List[dict] = []
        handle_map: Dict[int, int] = {}
        for rec in self.tree.normalized_records():
            handles = rec.pop("t")
            for h in handles:
                handle_map[h] = len(handle_map)
            rec["n"] = len(handles)
            records.append(rec)
        return records, handle_map

    def load_records(self, records: List[dict], seq: int, min_seq: int) -> None:
        """Rebuild from canonical records; handles become 0..n-1 in document
        order (i.e. canonical ids)."""
        self._next_handle = 0
        expanded = []
        for rec in records:
            rec = dict(rec)
            rec["t"] = self.alloc(rec.pop("n"))
            expanded.append(rec)
        self.tree.load_records(expanded, seq, min_seq)


class SharedMatrix(SharedObject):
    """2D sparse collaborative matrix (north-star config #4)."""

    TYPE = "matrix-tpu"

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self.rows = PermutationVector()
        self.cols = PermutationVector()
        # Sequenced cell state: (row_handle, col_handle) -> (value, seq, client).
        self._cells = SparseArray2D()
        # Optimistic overlay: (rh, ch) -> list of pending local values (last
        # one is the read view); popped front-first as acks arrive.
        self._overlay: Dict[Tuple[int, int], List[Any]] = {}
        # _policy is SEQUENCED state: it flips only when a setPolicy op is
        # processed in total order, so every replica judges every in-window
        # setCell under the same policy (flipping optimistically diverges —
        # fuzz/review-found).  _policy_local is the optimistic read view.
        self._policy = "lww"
        self._policy_local = "lww"

    # -- reads (local optimistic view) -----------------------------------------

    def _local_client(self) -> str:
        return self.client_id if self.client_id is not None else NO_CLIENT

    @property
    def row_count(self) -> int:
        return self.rows.visible_count(self._local_client())

    @property
    def col_count(self) -> int:
        return self.cols.visible_count(self._local_client())

    @property
    def policy(self) -> str:
        return self._policy_local

    def get_cell(self, row: int, col: int, default: Any = None) -> Any:
        client = self._local_client()
        rh = self.rows.handle_at(row, self.rows.tree.current_seq, client)
        ch = self.cols.handle_at(col, self.cols.tree.current_seq, client)
        if rh is None or ch is None:
            raise IndexError(f"cell ({row}, {col}) out of range")
        pending = self._overlay.get((rh, ch))
        if pending:
            return pending[-1]
        entry = self._cells.get(rh, ch)
        return entry[0] if entry is not None else default

    def to_list(self, default: Any = None) -> List[List[Any]]:
        return [
            [self.get_cell(r, c, default) for c in range(self.col_count)]
            for r in range(self.row_count)
        ]

    # -- local edits (optimistic apply, then submit) ---------------------------

    def insert_rows(self, pos: int, count: int) -> None:
        self._insert_axis(self.rows, "insertRows", pos, count)

    def insert_cols(self, pos: int, count: int) -> None:
        self._insert_axis(self.cols, "insertCols", pos, count)

    def remove_rows(self, start: int, count: int) -> None:
        self._remove_axis(self.rows, "removeRows", start, count)

    def remove_cols(self, start: int, count: int) -> None:
        self._remove_axis(self.cols, "removeCols", start, count)

    def _insert_axis(self, vec: PermutationVector, kind: str,
                     pos: int, count: int) -> None:
        if count <= 0:
            return
        client = self._local_client()
        group = SegmentGroup("insert", client=client)
        vec.tree.apply_insert(
            pos, vec.alloc(count), UNASSIGNED_SEQ, client,
            vec.tree.current_seq, group=group,
        )
        self._submit_local_op(
            {"kind": kind, "pos": pos, "count": count}, ("group", group)
        )
        if not self.is_attached:
            vec.tree.ack_insert(group, 0)

    def _remove_axis(self, vec: PermutationVector, kind: str,
                     start: int, count: int) -> None:
        if count <= 0:
            return
        client = self._local_client()
        group = SegmentGroup("remove", client=client)
        vec.tree.apply_remove(
            start, start + count, UNASSIGNED_SEQ, client,
            vec.tree.current_seq, group=group,
        )
        self._submit_local_op(
            {"kind": kind, "start": start, "end": start + count},
            ("group", group),
        )
        if not self.is_attached:
            vec.tree.ack_remove(group, 0, client)

    def set_cell(self, row: int, col: int, value: Any) -> None:
        client = self._local_client()
        rh = self.rows.handle_at(row, self.rows.tree.current_seq, client)
        ch = self.cols.handle_at(col, self.cols.tree.current_seq, client)
        if rh is None or ch is None:
            raise IndexError(f"cell ({row}, {col}) out of range")
        self._submit_local_op(
            {"kind": "setCell", "row": row, "col": col, "value": value},
            ("cell", rh, ch),
        )
        if self.is_attached:
            self._overlay.setdefault((rh, ch), []).append(value)
        else:
            self._cells.set(rh, ch, (value, 0, None))

    def switch_policy(self, policy: str = "fww") -> None:
        """One-way LWW → FWW switch (reference capability:
        switchSetCellPolicy)."""
        if policy != "fww":
            raise ValueError("only the lww->fww switch is supported")
        self._policy_local = "fww"
        self._submit_local_op({"kind": "setPolicy", "policy": "fww"}, None)
        if not self.is_attached:
            self._policy = "fww"

    def _handle_position(self, vec: PermutationVector, handle: int,
                         allowed: set) -> Optional[int]:
        """Current position of a permutation handle in the rebase view
        (sequenced state + already-regenerated pending groups), or None if
        its slot is gone from that view (sequenced-removed)."""
        c = 0
        for seg in vec.tree.segments:
            if handle in seg.text:
                if vec.tree.rebase_visible_len(seg, allowed) == 0:
                    return None
                return c + seg.text.index(handle)
            c += vec.tree.rebase_visible_len(seg, allowed)
        return None

    def _resubmit_rebased(self, pending) -> None:
        """Regenerate pending ops against the current view (removing the
        former stash-and-rehydrate-only limitation): axis ops re-target
        their permutation segments exactly as SharedString's merge-tree
        regeneration does (segment identity), and setCell regenerates
        row/col from its RESOLVED handles — dropped when either slot was
        sequenced-removed (remote replicas would resolve the same
        nothing)."""
        client = self._local_client()
        allowed_by_vec = {id(self.rows): set(), id(self.cols): set()}
        for _old_client_seq, contents, meta, _ref_seq in pending:
            kind = contents["kind"]
            if kind in ("insertRows", "insertCols",
                        "removeRows", "removeCols"):
                vec = self.rows if kind.endswith("Rows") else self.cols
                allowed = allowed_by_vec[id(vec)]
                _tag, group = meta
                segs = [s for s in vec.tree.segments
                        if group in s.pending_groups]
                for seg in segs:
                    seg.pending_groups.remove(group)
                    if group.kind == "insert":
                        vec.tree.rebase_normalize(seg, allowed)
                        pos = vec.tree.rebase_position(seg, allowed)
                        op = {"kind": kind, "pos": pos,
                              "count": len(seg.text)}
                    else:  # remove
                        if seg.removed_seq is not None \
                                and seg.removed_seq != UNASSIGNED_SEQ:
                            # A remote remove won while we were away.
                            seg.pending_overlap.discard(client)
                            continue
                        start = vec.tree.rebase_position(seg, allowed)
                        op = {"kind": kind, "start": start,
                              "end": start + len(seg.text)}
                    new_group = SegmentGroup(group.kind, client=client)
                    new_group.add(seg)
                    self._submit_local_op(op, ("group", new_group))
                    allowed.add(new_group)
            elif kind == "setCell":
                _tag, rh, ch = meta
                row = self._handle_position(self.rows, rh,
                                            allowed_by_vec[id(self.rows)])
                col = self._handle_position(self.cols, ch,
                                            allowed_by_vec[id(self.cols)])
                if row is None or col is None:
                    # The cell's row/col is gone: drop, and release the
                    # optimistic overlay entry its ack would have popped.
                    entries = self._overlay.get((rh, ch))
                    if entries:
                        entries.pop(0)
                        if not entries:
                            self._overlay.pop((rh, ch), None)
                    continue
                self._submit_local_op(
                    {"kind": "setCell", "row": row, "col": col,
                     "value": contents["value"]},
                    ("cell", rh, ch),
                )
            elif kind == "setPolicy":
                self._submit_local_op(dict(contents), None)
            else:
                raise ValueError(f"unknown pending matrix op {kind!r}")

    def apply_stashed_op(self, contents) -> None:
        kind = contents["kind"]
        if kind in ("insertRows", "insertCols"):
            self._insert_axis(self._axis_for(kind), kind,
                              contents["pos"], contents["count"])
        elif kind in ("removeRows", "removeCols"):
            self._remove_axis(self._axis_for(kind), kind, contents["start"],
                              contents["end"] - contents["start"])
        elif kind == "setCell":
            self.set_cell(contents["row"], contents["col"],
                          contents["value"])
        elif kind == "setPolicy":
            self.switch_policy(contents["policy"])
        else:
            raise ValueError(f"unknown stashed matrix op {kind!r}")

    # -- sequenced path --------------------------------------------------------

    def _axis_for(self, kind: str) -> PermutationVector:
        return self.rows if "Row" in kind else self.cols

    def _process_core(self, msg: SequencedMessage, local: bool, meta) -> None:
        op = msg.contents
        kind = op["kind"]
        client = msg.client_id
        if kind == "setPolicy":
            # One-way; idempotent on ack and remote.  Takes effect exactly at
            # this sequence position on every replica.
            self._policy = "fww"
            self._policy_local = "fww"
        elif kind == "setCell":
            self._process_set_cell(msg, local, meta)
        elif kind in ("insertRows", "insertCols"):
            vec = self._axis_for(kind)
            if local:
                tag, group = meta
                assert tag == "group"
                # The wire client id matters: after a rehydrate adoption
                # the sequenced copy carries the crashed session's id,
                # which every remote recorded as the insert attribution.
                vec.tree.ack_insert(group, msg.seq, msg.client_id)
            else:
                vec.tree.apply_insert(
                    op["pos"], vec.alloc(op["count"]), msg.seq, client,
                    msg.ref_seq,
                )
        elif kind in ("removeRows", "removeCols"):
            vec = self._axis_for(kind)
            if local:
                tag, group = meta
                assert tag == "group"
                vec.tree.ack_remove(group, msg.seq, client)
            else:
                vec.tree.apply_remove(
                    op["start"], op["end"], msg.seq, client, msg.ref_seq
                )
        else:
            raise ValueError(f"unknown matrix op kind {kind!r}")
        self._advance_window(msg.seq, msg.min_seq)

    def _process_set_cell(self, msg: SequencedMessage, local: bool, meta) -> None:
        op = msg.contents
        # Every replica resolves the op's positions in the op's own view,
        # bounded to the fold position (identical to the merge-tree ack-time
        # re-resolution rule) — so all replicas agree on the target handles.
        rh = self.rows.handle_at(op["row"], msg.ref_seq, msg.client_id, msg.seq)
        ch = self.cols.handle_at(op["col"], msg.ref_seq, msg.client_id, msg.seq)
        if local:
            tag, srh, sch = meta
            assert tag == "cell"
            pending = self._overlay.get((srh, sch))
            if pending:
                pending.pop(0)
                if not pending:
                    del self._overlay[(srh, sch)]
        if rh is None or ch is None:
            return  # op targeted beyond the view — deterministic no-op
        if self._policy == "fww":
            entry = self._cells.get(rh, ch)
            if (
                entry is not None
                and entry[1] > msg.ref_seq
                and entry[2] != msg.client_id
            ):
                return  # first sequenced writer wins; this op lost
        self._cells.set(rh, ch, (op["value"], msg.seq, msg.client_id))

    def _advance_window(self, seq: int, min_seq: int) -> None:
        for vec in (self.rows, self.cols):
            vec.tree.current_seq = max(vec.tree.current_seq, seq)
        if min_seq > self.rows.tree.min_seq:
            self.rows.tree.zamboni(min_seq)
            self.cols.tree.zamboni(min_seq)
            self._collect_dead_cells()

    def _collect_dead_cells(self) -> None:
        live_rows = self.rows.live_handles()
        live_cols = self.cols.live_handles()
        dead = [
            (rh, ch)
            for (rh, ch), _ in self._cells.items()
            if rh not in live_rows or ch not in live_cols
        ]
        for rh, ch in dead:
            self._cells.delete(rh, ch)

    def advance(self, seq: int, min_seq: int) -> None:
        self._advance_window(seq, min_seq)

    # -- summary ---------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        row_records, row_map = self.rows.canonical_records()
        col_records, col_map = self.cols.canonical_records()
        msn = self.rows.tree.min_seq
        cells = []
        for (rh, ch), (value, seq, client) in self._cells.items():
            if rh not in row_map or ch not in col_map:
                continue
            if seq <= msn:
                seq, client = 0, None
            cells.append([row_map[rh], col_map[ch], value, seq, client])
        cells.sort(key=lambda e: (e[0], e[1]))
        header = {
            "seq": self.rows.tree.current_seq,
            "minSeq": msn,
            "rows": self.rows.visible_count(),
            "cols": self.cols.visible_count(),
            "policy": self._policy,
        }
        body = {"rows": row_records, "cols": col_records, "cells": cells}
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(header))
        tree.add_blob("body", canonical_json(body))
        return tree

    def load(self, summary: SummaryTree) -> None:
        import json

        header = json.loads(summary.blob_bytes("header"))
        body = json.loads(summary.blob_bytes("body"))
        self.rows.load_records(body["rows"], header["seq"], header["minSeq"])
        self.cols.load_records(body["cols"], header["seq"], header["minSeq"])
        self._cells = SparseArray2D()
        for r, c, value, seq, client in body["cells"]:
            self._cells.set(r, c, (value, seq, client))
        self._overlay.clear()
        self._policy = self._policy_local = header["policy"]
        self.discard_pending()
