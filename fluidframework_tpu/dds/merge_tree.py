"""The merge-tree — core sequence merge engine (CPU oracle).

Capability-equivalent of the reference's merge-tree package (SURVEY.md §2.2:
``MergeTree``/``Client``/``PartialSequenceLengths``/zamboni; upstream paths
UNVERIFIED — empty reference mount).  This oracle defines the framework's
sequence semantics exactly; the TPU kernel in ``ops.mergetree_kernel`` must
reproduce them bit-for-bit (asserted by the fuzz harness and golden-summary
tests).  See SEMANTICS.md §merge-tree for the full rules; in brief:

**State** — an ordered list of segments.  Each segment carries the text run,
``(insert_seq, insert_client)``, optional ``(removed_seq, removed_client)``
plus overlap-removers, and LWW properties.  ``UNASSIGNED_SEQ`` (-1) marks
optimistic local state awaiting ack; it is *newer* than any assigned seq.

**Visibility** — an op resolves positions against its *view*
``(ref_seq, client)``: a segment contributes its length iff its insert is
visible (``insert_seq <= ref_seq`` or same client) and its removal is not
(``removed_seq <= ref_seq`` or removed by this client).

**Insert tie-break (RGA)** — after consuming ``pos`` visible characters, the
walk skips past *pending* segments of another client (this replica's own
un-acked ops, which will sequence later, i.e. newer, and stay left) and stops
before the first *sequenced* segment of any kind — concurrent insert,
tombstone, or visible text.  Since ops apply in total order, the op being
applied is the newest, so same-position concurrent inserts stack newest
first; stopping *before* sequenced tombstones (never sliding past them) keeps
an op that saw a removal order-consistent with a concurrent op that did not
(fuzz-found; see SEMANTICS.md).  These rules make optimistic local placement
agree with every remote replica's placement.

**Remove** — first remove in sequence order wins ``removed_seq``; later
overlapping removers are recorded in ``overlap_removers`` (their views must
still see the segment as removed).  A pending local removal loses its claim to
an earlier-sequenced remote remove.  Concurrent inserts into a concurrently
removed range survive.

**Obliterate** — removes the range *and wins against concurrent inserts*
(the reference's obliterateRange).  Every slot the obliterate covers —
visible segments, existing tombstones, and invisible concurrent inserts
strictly inside the range — accumulates a ``{seq: client}`` STAMP (stamps
are a set: overlapping obliterates all record, monotonically, which makes
every arrival verdict stable once computable).  An insert dies on arrival
iff its tie-break position lands strictly between two slots sharing a
stamp the inserter had not seen (``stamp seq > ref_seq``) from another
client; the EARLIEST such shared stamp becomes its remover.  Endpoint
inserts survive.  Obliterate-killed segments take ``removed_seq <`` their
own insert seq, so no sequenced view ever shows them; tombstone expiry
therefore also waits for ``insert_seq <= min_seq`` and every stamp
``<= min_seq`` — an active obliterate's tombstones must survive
summarize/reload for tail inserts to resolve against (records carry
``ob`` stamp lists while in-window).

**Zamboni** — once the collaboration window floor (``min_seq``) passes a
tombstone's ``removed_seq``, no future op's view can distinguish it, so it is
physically collected.  Summaries are emitted in *normalized* form (seqs at or
below min_seq clamped to the universal epoch, adjacent identical segments
merged) so replicas and device kernels produce byte-identical bytes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from ..protocol.messages import UNASSIGNED_SEQ

# Client id sentinel that matches no real client (used for the "sequenced
# state only" summary view).
NO_CLIENT = "\x00no-client"


class Segment:
    """A run of characters sharing one insert/remove/annotate history."""

    __slots__ = (
        "text",
        "insert_seq",
        "insert_client",
        "removed_seq",
        "removed_client",
        "overlap_removers",
        "pending_overlap",
        "ob_stamps",
        "pending_ref",
        "props",
        "pending_props",
        "pending_groups",
        "refs",
    )

    def __init__(
        self,
        text: str,
        insert_seq: int,
        insert_client: str,
        props: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.text = text
        self.insert_seq = insert_seq
        self.insert_client = insert_client
        # Null prop values mean "delete the key" (see _set_prop); on a fresh
        # segment that is simply absence, so they are dropped here too.
        if props:
            props = {k: v for k, v in props.items() if v is not None}
        self.removed_seq: Optional[int] = None
        self.removed_client: Optional[str] = None
        # Additional removers beyond the winning one.  Sequenced removers are
        # summary-visible ("ro"); a pending local remover demoted by an
        # earlier-sequenced remote remove waits here until its ack.
        self.overlap_removers: Set[str] = set()
        self.pending_overlap: Set[str] = set()
        # SEQUENCED obliterate stamps covering this slot: {seq: client}.
        # Monotone (stamps only accumulate) — the obliterate-on-arrival
        # verdict for concurrent inserts never flips once computable.
        # Pending local obliterates stamp at their ack, not before.
        self.ob_stamps: Dict[int, str] = {}
        # For a pending (UNASSIGNED) insert: the channel seq its author had
        # processed at submit time — the ref_seq its sequenced op will carry
        # (the arrival-verdict prediction compares stamps against it).
        self.pending_ref: int = 0
        self.props: Dict[str, Any] = dict(props) if props else {}
        self.pending_props: Dict[str, int] = {}
        self.pending_groups: List["SegmentGroup"] = []
        self.refs: List["LocalReference"] = []

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        r = f" -({self.removed_seq},{self.removed_client})" if self.removed_seq is not None else ""
        return f"Seg({self.text!r} @{self.insert_seq},{self.insert_client}{r})"


class SegmentGroup:
    """Tracks the segments affected by one pending local op, so the ack can
    assign sequence numbers / release pending holds.  Segment splits add the
    new half to every group the original belonged to (reference capability:
    merge-tree SegmentGroup)."""

    __slots__ = ("kind", "segments", "props", "client", "predicted")

    def __init__(self, kind: str, props: Optional[Dict[str, Any]] = None,
                 client: Optional[str] = None) -> None:
        self.kind = kind
        self.segments: List[Segment] = []
        self.props = props or {}
        #: submitting client (set for pending obliterates — the arrival
        #: prediction marks kills in the obliterator's name)
        self.client = client
        #: segments that joined via the pending-obliterate arrival
        #: prediction (remotes see them as zero-width stamp targets, not
        #: pass-1 coverage — the ack bookkeeping differs)
        self.predicted: set = set()

    def add(self, seg: Segment) -> None:
        self.segments.append(seg)
        seg.pending_groups.append(self)


class LocalReference:
    """A position anchored to (segment, offset) that survives edits and slides
    off removed segments (reference capability: LocalReferencePosition).
    Used by IntervalCollection."""

    __slots__ = ("segment", "offset", "slide")

    def __init__(self, segment: Optional[Segment], offset: int, slide: bool = True):
        self.segment = segment
        self.offset = offset
        self.slide = slide

    def attach(self, segment: Segment, offset: int) -> None:
        if self.segment is not None and self in self.segment.refs:
            self.segment.refs.remove(self)
        self.segment = segment
        self.offset = offset
        segment.refs.append(self)


class MergeTreeOracle:
    """The document state + op application walk.

    Performance note: the oracle stores segments in a flat Python list and
    resolves positions with an O(n) masked walk — the structure the TPU kernel
    mirrors with masked prefix sums over a segment pool.  (The reference's
    B-tree + PartialSequenceLengths achieve O(log n); our device path gets its
    speed from vectorizing the walk instead.)
    """

    def __init__(self) -> None:
        self.segments: List[Segment] = []
        self.current_seq = 0
        self.min_seq = 0
        #: live pending local obliterate groups — the arrival-prediction
        #: fast path: pure sequenced replay (catch-up) never has any, so
        #: apply_insert stays O(1) there instead of scanning the pool
        self.pending_obliterates: set = set()

    # -- visibility ------------------------------------------------------------

    @staticmethod
    def _insert_visible(seg: Segment, ref_seq: int, client: str,
                        up_to_seq: Optional[int] = None) -> bool:
        """Insert visibility in the view (ref_seq, client).

        ``up_to_seq`` bounds the view to the fold position of a *sequenced*
        op being (re-)applied at seq s: the author's own segments count only
        if already sequenced before s.  Without the bound (optimistic local
        apply), all own segments count including pending ones.  The bound is
        what makes an ack-time re-resolution identical to every remote
        replica's resolution (fuzz-found)."""
        if seg.insert_seq != UNASSIGNED_SEQ and seg.insert_seq <= ref_seq:
            return True
        if seg.insert_client != client:
            return False
        if up_to_seq is None:
            return True
        return seg.insert_seq != UNASSIGNED_SEQ and seg.insert_seq < up_to_seq

    @staticmethod
    def _removed_in_view(seg: Segment, ref_seq: int, client: str,
                         up_to_seq: Optional[int] = None) -> bool:
        if seg.removed_seq is None:
            return False
        if seg.removed_seq != UNASSIGNED_SEQ and seg.removed_seq <= ref_seq:
            return True
        involved = (
            client == seg.removed_client or client in seg.overlap_removers
            # An obliterate STAMP makes its author involved too: the
            # author's optimistic view hid every covered slot, so views in
            # the author's name must hide the tombstone even when another
            # client's earlier remove won the removal itself (zero-width
            # stamps carry no remover bookkeeping — the stamp is the only
            # durable record of the author's coverage; fuzz-found).
            or any(cl == client and (up_to_seq is None or s < up_to_seq)
                   for s, cl in seg.ob_stamps.items())
        )
        if up_to_seq is None:
            # Optimistic view: the client's own pending (unsequenced) overlap
            # removal also hides the segment from it.
            involved = involved or client in seg.pending_overlap
        if not involved:
            return False
        if up_to_seq is None:
            return True
        # Bounded fold view: involvement counts only if the removal state is
        # sequenced before the fold position.  (pending_overlap is excluded
        # above — the bound check uses the *winner's* seq, which says nothing
        # about when this client's own overlapping remove sequences.)
        return seg.removed_seq != UNASSIGNED_SEQ and seg.removed_seq < up_to_seq

    def _visible_len(self, seg: Segment, ref_seq: int, client: str,
                     up_to_seq: Optional[int] = None) -> int:
        if not self._insert_visible(seg, ref_seq, client, up_to_seq):
            return 0
        if self._removed_in_view(seg, ref_seq, client, up_to_seq):
            return 0
        return len(seg.text)

    def length(self, ref_seq: Optional[int] = None, client: str = NO_CLIENT) -> int:
        """Visible length in a view — the oracle form of partial lengths."""
        if ref_seq is None:
            ref_seq = self.current_seq
        return sum(self._visible_len(s, ref_seq, client) for s in self.segments)

    def get_text(self, ref_seq: Optional[int] = None, client: str = NO_CLIENT) -> str:
        if ref_seq is None:
            ref_seq = self.current_seq
        return "".join(
            s.text for s in self.segments if self._visible_len(s, ref_seq, client) > 0
        )

    # -- structural helpers ----------------------------------------------------

    def _split(self, idx: int, offset: int) -> None:
        """Split segments[idx] at text offset (0 < offset < len)."""
        seg = self.segments[idx]
        assert 0 < offset < len(seg.text)
        right = Segment(seg.text[offset:], seg.insert_seq, seg.insert_client)
        right.removed_seq = seg.removed_seq
        right.removed_client = seg.removed_client
        right.overlap_removers = set(seg.overlap_removers)
        right.pending_overlap = set(seg.pending_overlap)
        right.ob_stamps = dict(seg.ob_stamps)
        right.pending_ref = seg.pending_ref
        right.props = dict(seg.props)
        right.pending_props = dict(seg.pending_props)
        seg.text = seg.text[:offset]
        # The split halves both belong to any pending op group the original did.
        for group in list(seg.pending_groups):
            group.add(right)
            if seg in group.predicted:
                group.predicted.add(right)
        # Local references at offsets past the split move to the right half.
        keep, move = [], []
        for ref in seg.refs:
            (move if ref.offset >= offset else keep).append(ref)
        seg.refs = keep
        for ref in move:
            ref.segment = right
            ref.offset -= offset
            right.refs.append(ref)
        self.segments.insert(idx + 1, right)

    def _insert_index(self, pos: int, ref_seq: int, client: str) -> int:
        """Resolve an insert position to a list index (splitting if needed).

        Phase 1 consumes ``pos`` visible-in-view characters.  Phase 2 is the
        boundary tie-break: skip past *pending* segments of another client
        (i.e. this replica's own un-acked ops — they will sequence later than
        the op being applied, so newest-first keeps them to the left), then
        stop before the first sequenced segment of any kind.  Stopping before
        sequenced tombstones (not after) is what keeps an op that saw the
        removal order-consistent with a concurrent op that did not — both
        resolve to the same side of the tombstone.
        """
        idx, c = 0, 0
        while idx < len(self.segments) and c < pos:
            seg = self.segments[idx]
            v = self._visible_len(seg, ref_seq, client)
            if c + v > pos:
                self._split(idx, pos - c)
                return idx + 1
            c += v
            idx += 1
        if c < pos:
            raise ValueError(f"insert pos {pos} beyond view length {c}")
        while idx < len(self.segments):
            seg = self.segments[idx]
            if seg.insert_seq == UNASSIGNED_SEQ and seg.insert_client != client:
                idx += 1  # replica's own pending op: sequences later, stays left
                continue
            break
        return idx

    def _walk_range(self, start: int, end: int, ref_seq: int, client: str):
        """Yield the segments exactly covering visible range [start, end) in
        the view, splitting at the boundaries.  Used by remove/annotate."""
        if start >= end:
            return
        idx, c = 0, 0
        while idx < len(self.segments) and c < end:
            seg = self.segments[idx]
            v = self._visible_len(seg, ref_seq, client)
            if v > 0:
                s0, s1 = c, c + v
                lo, hi = max(start, s0), min(end, s1)
                if lo < hi:
                    if lo > s0:
                        self._split(idx, lo - s0)
                        idx += 1
                        seg = self.segments[idx]
                        s0 = lo
                    if hi < s1:
                        self._split(idx, hi - s0)
                        seg = self.segments[idx]
                    yield seg
                c += v
            idx += 1

    # -- op application (sequenced or optimistic-local) ------------------------

    def apply_insert(
        self,
        pos: int,
        text: str,
        seq: int,
        client: str,
        ref_seq: int,
        props: Optional[Dict[str, Any]] = None,
        group: Optional[SegmentGroup] = None,
    ) -> Segment:
        idx = self._insert_index(pos, ref_seq, client)
        seg = Segment(text, seq, client, props)
        if seq == UNASSIGNED_SEQ:
            seg.pending_ref = self.current_seq
        # Obliterate-on-arrival: the insert dies iff it lands strictly
        # between two slots stamped by the SAME obliterate the inserter had
        # not seen (ob_seq > ref_seq).  Endpoint inserts (an unstamped or
        # differently-stamped neighbor on either side) survive.
        if seq != UNASSIGNED_SEQ:
            if not self._arrival_obliterate(seg, idx, idx, ref_seq, client):
                self._pending_obliterate_prediction(seg, idx)
        self.segments.insert(idx, seg)
        if group is not None:
            group.add(seg)
        return seg

    def _arrival_obliterate(self, seg: Segment, left_idx: int,
                            right_idx: int, ref_seq: int,
                            client: str) -> bool:
        """The obliterate-on-arrival neighbor rule for a sequenced insert:
        scan outward to the nearest SEQUENCED slots (replica-local pending
        segments differ across replicas and must not decide a sequenced
        verdict); kill iff the two share a stamp the inserter had not seen
        (``> ref_seq``) from another client — the insert landed strictly
        inside that obliterate's range.  The EARLIEST shared killer stamp
        becomes the remover (deterministic on every replica).  ``left_idx``
        is the exclusive upper bound of the left scan; ``right_idx`` the
        inclusive start of the right scan (pre-insert indices on the apply
        path, post-insert on the ack path)."""
        left = right = None
        for j in range(left_idx - 1, -1, -1):
            if self.segments[j].insert_seq != UNASSIGNED_SEQ:
                left = self.segments[j]
                break
        for j in range(right_idx, len(self.segments)):
            if self.segments[j].insert_seq != UNASSIGNED_SEQ:
                right = self.segments[j]
                break
        if left is None or right is None:
            return False
        killers = [
            s for s, c in left.ob_stamps.items()
            if s > ref_seq and c != client and s in right.ob_stamps
        ]
        if not killers:
            return False
        s = min(killers)
        seg.removed_seq = s
        seg.removed_client = left.ob_stamps[s]
        seg.ob_stamps[s] = left.ob_stamps[s]
        return True

    def _pending_obliterate_prediction(self, seg: Segment, idx: int) -> bool:
        """A replica holding a PENDING local obliterate must give an
        arriving concurrent sequenced insert the verdict every remote
        replica will compute once the obliterate sequences — otherwise the
        obliterator's follow-up ops count text no remote view contains
        (deep-lag divergence, fuzz-found).

        Slot-order test: the insert dies iff it lands STRICTLY between the
        pending group's outermost covered slots.  That is exactly the
        sequenced neighbor rule's eventual verdict: the ack's zero-width
        pass stamps every sequenced slot between covered slots, so at ack
        both arrival neighbors of an interior insert carry the shared
        stamp, while an insert at or beyond a boundary slot keeps an
        unstamped outer neighbor.  The kill is recorded as a pending
        removal in the obliterator's name and the segment joins the group,
        so ``ack_obliterate`` assigns the same final (seq, client) every
        remote computes.  ``idx`` is the pre-insert insertion index."""
        if not self.pending_obliterates:
            return False  # pure sequenced replay: O(1) fast path
        # Bounds come from each pending group's OWN member list over one
        # O(n) identity->index map: O(n + pending-obliterate memberships)
        # per arriving insert, independent of how many other pending
        # groups each segment belongs to (VERDICT r4 weak #6 — previously
        # a nested walk over every segment's full group list).
        index = {id(s): j for j, s in enumerate(self.segments)}
        spans = []
        for g in self.pending_obliterates:
            if g.kind != "obliterate" or g.client is None:
                continue
            members = [index[id(m)] for m in g.segments if id(m) in index]
            if not members:
                continue
            spans.append((min(members), max(members), g))
        killed = False
        # Deterministic order (pending_obliterates is a set); all pending
        # groups carry the LOCAL client so the verdict is order-free, but
        # the walk should not depend on id() hashing regardless.
        for first, last, g in sorted(spans, key=lambda t: (t[0], t[1])):
            if first < idx <= last:
                if not killed:
                    seg.removed_seq = UNASSIGNED_SEQ
                    seg.removed_client = g.client
                    killed = True
                g.add(seg)
                g.predicted.add(seg)
        return killed

    def _mark_removed(self, seg: Segment, seq: int, client: str) -> None:
        """First-wins removal bookkeeping shared by remove and obliterate."""
        if seg.removed_seq is None:
            seg.removed_seq = seq
            seg.removed_client = client
        elif seg.removed_seq == UNASSIGNED_SEQ:
            # A pending local removal loses to this earlier-sequenced
            # remove; demote the pending remover to a *pending* overlap
            # remover (not summary-visible until its own op sequences).
            if seq != UNASSIGNED_SEQ:
                seg.pending_overlap.add(seg.removed_client)
                seg.removed_seq = seq
                seg.removed_client = client
            # (seq == UNASSIGNED here is impossible: a pending-removed
            # segment is invisible to the local view.)
        else:
            # seq is always assigned here: a locally-pending remove can
            # only target view-visible (not-yet-removed) segments.
            seg.overlap_removers.add(client)

    def apply_remove(
        self,
        start: int,
        end: int,
        seq: int,
        client: str,
        ref_seq: int,
        group: Optional[SegmentGroup] = None,
    ) -> None:
        for seg in self._walk_range(start, end, ref_seq, client):
            self._mark_removed(seg, seq, client)
            if seq != UNASSIGNED_SEQ:
                self._slide_refs(seg)
            if group is not None:
                group.add(seg)

    def apply_obliterate(
        self,
        start: int,
        end: int,
        seq: int,
        client: str,
        ref_seq: int,
        group: Optional[SegmentGroup] = None,
    ) -> None:
        """Remove the view range [start, end) AND stamp every covered slot
        so concurrent inserts into the range die on arrival (see the module
        docstring).  The removal bookkeeping is identical to apply_remove;
        the stamp additionally lands on already-tombstoned slots and on
        invisible concurrent inserts strictly inside the range."""
        if start >= end:
            return
        # Collect the visible coverage first (boundary splits happen inside
        # _walk_range), then SNAPSHOT the pristine pass-2 view before any
        # marking: pass 1's removal/overlap bookkeeping on this very op's
        # segments must not collapse the position walk pass 2 resolves the
        # range in (fuzz-found: a covered segment that lost to an earlier
        # remove reads as involved-invisible once pass 1 adds this client
        # to its overlap set, shifting every zero-width slot after it).
        covered = list(self._walk_range(start, end, ref_seq, client))
        pristine = None
        if seq != UNASSIGNED_SEQ:
            pristine = [
                self._visible_len(s, ref_seq, client, up_to_seq=seq)
                for s in self.segments
            ]
        # Pass 1: visible coverage — remove + stamp (the _walk_range split
        # bookkeeping is shared with remove).
        for seg in covered:
            self._mark_removed(seg, seq, client)
            if seq != UNASSIGNED_SEQ:
                seg.ob_stamps[seq] = client
                self._slide_refs(seg)
            if group is not None:
                group.add(seg)
        # Pass 2: zero-width slots strictly inside the range.  A pending
        # local obliterate defers this pass to its ack (the stamp cannot be
        # compared against ref_seqs until it sequences).
        if seq != UNASSIGNED_SEQ:
            self._obliterate_zero_width(start, end, seq, client, ref_seq,
                                        vis=pristine)
            self.current_seq = max(self.current_seq, seq)
        elif group is not None:
            self.pending_obliterates.add(group)

    def _obliterate_zero_width(self, start: int, end: int, seq: int,
                               client: str, ref_seq: int,
                               vis: Optional[List[int]] = None) -> None:
        """Stamp zero-width slots strictly inside the obliterated view
        range: existing tombstones (stamp only) and invisible concurrent
        inserts (remove + stamp).  ``vis`` is the pristine per-segment
        visible-length snapshot taken before this op mutated any state
        (callers pass it whenever earlier passes of the same op marked
        segments; without it the view is computed live)."""
        c = 0
        for i, seg in enumerate(self.segments):
            # Bounded fold view: removals made BY THIS OP (seq == this op,
            # not < it) stay visible, so positions here match the pristine
            # view every remote resolves the range in — the op's own pass-1
            # removals must not collapse the walk (fuzz-found).
            v = vis[i] if vis is not None else \
                self._visible_len(seg, ref_seq, client, up_to_seq=seq)
            if v == 0 and start < c < end \
                    and seg.insert_seq != UNASSIGNED_SEQ:
                # Sequenced zero-width slots strictly inside: existing
                # tombstones (stamp only) and invisible concurrent inserts
                # (remove + stamp).
                if seg.removed_seq is None or \
                        seg.removed_seq == UNASSIGNED_SEQ:
                    self._mark_removed(seg, seq, client)
                    self._slide_refs(seg)
                seg.ob_stamps[seq] = client
            c += v
        # OUR OWN un-acked inserts (only the author's replica holds
        # UNASSIGNED segments) are killed by remote replicas via the
        # ARRIVAL NEIGHBOR RULE when they sequence — predict that verdict
        # now with the same rule, or later local ops would count text no
        # remote view contains.  (Position-in-range is NOT the rule: the
        # fold view can collapse concurrent removals and put a pending
        # segment "inside" a range whose arrival neighbors are unstamped —
        # fuzz-found.)  The verdict is stable from this moment: anything
        # that later lands between a same-stamped pair dies and keeps the
        # pair's stamp, so a pending segment's neighbor verdict never
        # changes before its ack.
        self._predict_pending_kills()

    def _predict_pending_kills(self) -> None:
        """Re-evaluate the arrival verdict for every OWN pending insert."""
        for idx, seg in enumerate(self.segments):
            if seg.insert_seq != UNASSIGNED_SEQ:
                continue
            if seg.removed_seq is not None and \
                    seg.removed_seq != UNASSIGNED_SEQ:
                continue  # already sequenced-dead
            pending_remover = None
            if seg.removed_seq == UNASSIGNED_SEQ:
                pending_remover = seg.removed_client
                seg.removed_seq = None  # let the rule decide cleanly
                seg.removed_client = None
            if self._arrival_obliterate(seg, idx, idx + 1,
                                        seg.pending_ref, seg.insert_client):
                if pending_remover is not None:
                    seg.pending_overlap.add(pending_remover)
                self._slide_refs(seg)
            elif pending_remover is not None:
                seg.removed_seq = UNASSIGNED_SEQ
                seg.removed_client = pending_remover

    def ack_obliterate(self, group: SegmentGroup, seq: int, client: str,
                       start: int, end: int, ref_seq: int) -> None:
        """Own obliterate sequenced: assign the removal seq (ack_remove
        bookkeeping), materialize the stamp, and run the zero-width pass at
        the now-known seq — the author's state converges with every remote
        replica's apply_obliterate."""
        self.pending_obliterates.discard(group)
        mark_id = group.client if group.client is not None else client
        # Pristine pass-2 snapshot BEFORE the group pass promotes demoted
        # removers: promotion makes those segments read involved-invisible
        # and would collapse the zero-width position walk (same hazard the
        # apply path snapshots against).
        pristine = [
            self._visible_len(s, ref_seq, client, up_to_seq=seq)
            for s in self.segments
        ]
        for seg in group.segments:
            if seg.removed_seq == UNASSIGNED_SEQ and \
                    seg.removed_client == mark_id:
                seg.removed_seq = seq
                seg.removed_client = client
            elif mark_id in seg.pending_overlap:
                seg.pending_overlap.discard(mark_id)
                # A segment that joined the group via the arrival
                # prediction and then lost to an earlier-sequenced remove
                # is a ZERO-WIDTH slot to every remote (they stamp it,
                # never record this client as a remover) — promotion to
                # overlap remover would diverge from them.
                if seg not in group.predicted:
                    seg.overlap_removers.add(client)
            seg.ob_stamps[seq] = client
            self._slide_refs(seg)
            seg.pending_groups.remove(group)
        self._obliterate_zero_width(start, end, seq, client, ref_seq,
                                    vis=pristine)

    def apply_annotate(
        self,
        start: int,
        end: int,
        props: Dict[str, Any],
        seq: int,
        client: str,
        ref_seq: int,
        group: Optional[SegmentGroup] = None,
    ) -> None:
        pending = seq == UNASSIGNED_SEQ
        for seg in self._walk_range(start, end, ref_seq, client):
            for key, value in props.items():
                if pending:
                    self._set_prop(seg, key, value)
                    seg.pending_props[key] = seg.pending_props.get(key, 0) + 1
                else:
                    if seg.pending_props.get(key, 0) > 0:
                        continue  # a pending local annotate outranks this op
                    self._set_prop(seg, key, value)
            if group is not None:
                group.add(seg)

    @staticmethod
    def _set_prop(seg: Segment, key: str, value: Any) -> None:
        if value is None:
            seg.props.pop(key, None)  # null deletes the property
        else:
            seg.props[key] = value

    # -- ack (own op sequenced) ------------------------------------------------

    def ack_insert(self, group: SegmentGroup, seq: int,
                   client: str = NO_CLIENT,
                   ref_seq: Optional[int] = None) -> None:
        for seg in group.segments:
            if seg.insert_seq == UNASSIGNED_SEQ:
                seg.insert_seq = seq
                if client is not NO_CLIENT:
                    # Attribution follows the WIRE copy: after a rehydrate
                    # the sequenced copy carries the crashed session's
                    # client id, and every remote recorded that id.
                    seg.insert_client = client
                # Obliterate-on-arrival, author side: remote replicas kill
                # this insert via the neighbor rule the moment it arrives;
                # the author's replica must reach the same verdict at ack.
                if ref_seq is not None and seg.removed_seq is None:
                    try:
                        idx = self.segments.index(seg)
                    except ValueError:
                        idx = -1
                    if idx >= 0:
                        killed = self._arrival_obliterate(
                            seg, idx, idx + 1, ref_seq, client
                        )
                        if killed:
                            self._slide_refs(seg)
            seg.pending_groups.remove(group)

    def ack_remove(self, group: SegmentGroup, seq: int, client: str) -> None:
        # Pending marks carry the SUBMIT-time identity (group.client);
        # the wire ack's client is the attribution every remote recorded —
        # they differ after a rehydrate adoption.
        mark_id = group.client if group.client is not None else client
        for seg in group.segments:
            if seg.removed_seq == UNASSIGNED_SEQ and \
                    seg.removed_client == mark_id:
                seg.removed_seq = seq
                seg.removed_client = client
            elif mark_id in seg.pending_overlap:
                # Our demoted remove is now sequenced: summary-visible.
                seg.pending_overlap.discard(mark_id)
                seg.overlap_removers.add(client)
            self._slide_refs(seg)
            seg.pending_groups.remove(group)

    def ack_annotate(self, group: SegmentGroup, props: Dict[str, Any]) -> None:
        for seg in group.segments:
            for key in props:
                n = seg.pending_props.get(key, 0) - 1
                if n <= 0:
                    seg.pending_props.pop(key, None)
                else:
                    seg.pending_props[key] = n
            seg.pending_groups.remove(group)

    # -- rebase (regenerate pending ops at the current view) -------------------

    def rebase_visible_len(self, seg: Segment, allowed) -> int:
        """Visible length of ``seg`` in the view a *rebased resubmit* op will
        be applied in by remote replicas: the fully-sequenced state plus the
        segments whose pending ops were already regenerated (``allowed`` is
        the set of their SegmentGroups).  Pending ops regenerated later in
        the FIFO are not yet sequenced at that point, so they don't count —
        this is what keeps regenerated positions exact (cf. the reference's
        merge-tree op regeneration on reconnect)."""
        if seg.insert_seq == UNASSIGNED_SEQ and not any(
            g.kind == "insert" and g in allowed for g in seg.pending_groups
        ):
            return 0
        if seg.removed_seq is not None:
            if seg.removed_seq != UNASSIGNED_SEQ:
                return 0
            if any(g.kind in ("remove", "obliterate") and g in allowed
                   for g in seg.pending_groups):
                return 0
        return len(seg.text)

    def rebase_position(self, target: Segment, allowed) -> int:
        """Start position of ``target`` in the rebased-resubmit view."""
        pos = 0
        for seg in self.segments:
            if seg is target:
                return pos
            pos += self.rebase_visible_len(seg, allowed)
        raise ValueError("segment not in tree")

    def rebase_reference_position(self, ref: "LocalReference",
                                  allowed) -> int:
        """Reference position in the rebased-resubmit view (same visibility
        as :meth:`rebase_position`): own pending segments whose ops
        regenerate *later* in the FIFO must not count — their inserts will
        sequence after the op being regenerated."""
        if ref.segment is None:
            return 0
        pos = 0
        for seg in self.segments:
            if seg is ref.segment:
                if self.rebase_visible_len(seg, allowed) > 0:
                    return pos + min(ref.offset, len(seg.text))
                return pos
            pos += self.rebase_visible_len(seg, allowed)
        return pos

    def rebase_length(self, allowed) -> int:
        """Total visible length in the rebased-resubmit view."""
        return sum(self.rebase_visible_len(s, allowed)
                   for s in self.segments)

    def rebase_normalize(self, seg: Segment, allowed) -> None:
        """Physically relocate a pending-insert segment to the index where
        remote replicas will place its regenerated op (the reference's
        segment normalization on reconnect).  ``_insert_index`` stops
        *before* the first sequenced segment at a boundary, so the
        regenerated op lands immediately after the last segment visible in
        the rebase view: cross every invisible neighbor — sequenced
        tombstones AND own un-regenerated pending segments (each of the
        latter is re-placed by its own later regeneration, whose position
        then counts this segment via ``allowed``, keeping author and
        remote orders identical)."""
        i = self.segments.index(seg)
        j = i
        while j > 0 and self.rebase_visible_len(
                self.segments[j - 1], allowed) == 0:
            j -= 1
        if j != i:
            del self.segments[i]
            self.segments.insert(j, seg)

    # -- local references (interval anchors) -----------------------------------

    @staticmethod
    def _sequenced_removed(seg: Segment) -> bool:
        return seg.removed_seq is not None and seg.removed_seq != UNASSIGNED_SEQ

    def _slide_target_ok(self, seg: Segment) -> bool:
        """Valid slide destination: part of the *sequenced* state and not
        sequenced-removed.  Pending local inserts are excluded (other
        replicas don't have them at this sequence point) and pending local
        removals are included (every replica still sees them alive here);
        both directions of skew diverge otherwise (fuzz-found)."""
        return seg.insert_seq != UNASSIGNED_SEQ and not self._sequenced_removed(seg)

    def _slide_refs(self, seg: Segment) -> None:
        """Slide references off a sequenced-removed segment: forward to the
        next valid segment's start, else backward to the previous one's end
        (reference capability: slideOnRemove)."""
        if not seg.refs:
            return
        try:
            idx = self.segments.index(seg)
        except ValueError:
            return
        target, offset = None, 0
        for j in range(idx + 1, len(self.segments)):
            if self._slide_target_ok(self.segments[j]):
                target, offset = self.segments[j], 0
                break
        if target is None:
            for j in range(idx - 1, -1, -1):
                if self._slide_target_ok(self.segments[j]):
                    target, offset = self.segments[j], len(self.segments[j].text)
                    break
        # Non-sliding (stay-on-remove) refs remain attached to the tombstone,
        # which also pins it from zamboni collection.
        for ref in [r for r in seg.refs if r.slide]:
            seg.refs.remove(ref)
            if target is None:
                ref.segment, ref.offset = None, 0
            else:
                ref.attach(target, offset)

    def create_reference(self, pos: int, ref_seq: Optional[int] = None,
                         client: str = NO_CLIENT, slide: bool = True,
                         up_to_seq: Optional[int] = None) -> LocalReference:
        """Anchor a reference at visible position ``pos`` in the view (see
        _insert_visible for the ``up_to_seq`` fold-position bound)."""
        if ref_seq is None:
            ref_seq = self.current_seq
        idx, c = 0, 0
        for seg in self.segments:
            v = self._visible_len(seg, ref_seq, client, up_to_seq)
            if v > 0 and c + v > pos:
                ref = LocalReference(None, 0, slide)
                ref.attach(seg, pos - c)
                return ref
            c += v
        # End of document: anchor to the last visible segment's end.
        ref = LocalReference(None, 0, slide)
        for seg in reversed(self.segments):
            if self._visible_len(seg, ref_seq, client, up_to_seq) > 0:
                ref.attach(seg, len(seg.text))
                return ref
        return ref  # empty document: detached reference at 0

    def reference_position(self, ref: LocalReference, ref_seq: Optional[int] = None,
                           client: str = NO_CLIENT) -> int:
        if ref.segment is None:
            return 0
        if ref_seq is None:
            ref_seq = self.current_seq
        pos = 0
        for seg in self.segments:
            if seg is ref.segment:
                if self._visible_len(seg, ref_seq, client) > 0:
                    return pos + min(ref.offset, len(seg.text))
                return pos
            pos += self._visible_len(seg, ref_seq, client)
        return pos

    # -- zamboni & summaries ---------------------------------------------------

    def zamboni(self, min_seq: Optional[int] = None) -> None:
        """Collect tombstones the collaboration window can no longer see."""
        if min_seq is not None:
            self.min_seq = max(self.min_seq, min_seq)
        msn = self.min_seq
        survivors = []
        for seg in self.segments:
            dead = (
                seg.removed_seq is not None
                and seg.removed_seq != UNASSIGNED_SEQ
                and seg.removed_seq <= msn
                # Obliterate-killed slots have removed_seq < insert_seq and
                # active obliterate stamps must outlive the window: tail
                # inserts resolve their death against these tombstones.
                and seg.insert_seq <= msn
                and all(s <= msn for s in seg.ob_stamps)
                and not seg.pending_groups
                and not seg.refs
            )
            if not dead:
                survivors.append(seg)
        self.segments = survivors

    def normalized_records(self, return_keys: bool = False):
        """Canonical record list for summaries: sequenced state only, seqs at
        or below min_seq clamped to the universal epoch (0 / no client),
        window-expired tombstones dropped, adjacent identical runs merged.
        Both the oracle and the device kernel summary paths emit exactly this,
        which is what makes byte-identity checkable.

        ``return_keys=True`` additionally returns ATTRIBUTION KEYS — for
        each emitted record whose seq got CLAMPED, the pre-clamp insert
        seqs of its merged sub-runs as ``[record_idx, [[chars, seq], ...]]``
        entries (seq 0 = unknown) — without touching the record bytes.
        The clamp deliberately erases seqs from the body; the keys ride a
        separate optional summary blob so attribution survives the window
        (SURVEY §1 layer 8).  Per-sub-run lengths matter: a merged run can
        span DIFFERENT authors, and one key per record would attribute one
        user's text to another after a load (review r4)."""
        msn = self.min_seq
        records: List[dict] = []
        # Per emitted record: [[chars, pre-clamp seq], ...] for clamped
        # records, None for unclamped ones (their seq is in the body).
        run_keys: List[Optional[List[list]]] = []
        for seg in self.segments:
            if seg.insert_seq == UNASSIGNED_SEQ:
                continue  # pending local: not part of the sequenced state
            rs, rc = seg.removed_seq, seg.removed_client
            if rs == UNASSIGNED_SEQ:
                rs, rc = None, None  # pending removal: not sequenced
            # In-window stamps only; expired ones can never decide a
            # future arrival (every later ref >= msn >= stamp).
            stamps = sorted(
                (s, c2) for s, c2 in seg.ob_stamps.items() if s > msn
            )
            if rs is not None and rs <= msn and seg.insert_seq <= msn \
                    and not stamps:
                continue  # expired tombstone (see zamboni for the ob rule)
            s, c = seg.insert_seq, seg.insert_client
            if s <= msn:
                s, c = 0, None
            rec = {"t": seg.text, "s": s, "c": c}
            if rs is not None:
                rec["rs"] = rs
                rec["rc"] = rc
            if stamps:
                rec["ob"] = [[s2, c2] for s2, c2 in stamps]
            if seg.overlap_removers:
                # Sequenced overlap removers are part of the replicated state:
                # their later ops (with old ref_seqs) must still see the
                # segment as removed after a summary load.
                rec["ro"] = sorted(seg.overlap_removers)
            if seg.props:
                rec["p"] = dict(sorted(seg.props.items()))
            if records:
                prev = records[-1]
                if (
                    prev["s"] == rec["s"]
                    and prev["c"] == rec["c"]
                    and prev.get("rs") == rec.get("rs")
                    and prev.get("rc") == rec.get("rc")
                    and prev.get("ob") == rec.get("ob")
                    and prev.get("ro") == rec.get("ro")
                    and prev.get("p") == rec.get("p")
                ):
                    prev["t"] += rec["t"]
                    runs = run_keys[-1]
                    if runs is not None:
                        if runs[-1][1] == seg.insert_seq:
                            runs[-1][0] += len(rec["t"])  # same author run
                        else:
                            runs.append([len(rec["t"]), seg.insert_seq])
                    continue
            records.append(rec)
            run_keys.append(
                [[len(rec["t"]), seg.insert_seq]] if rec["s"] == 0 else None
            )
        if not return_keys:
            return records
        keys = [
            [i, runs] for i, runs in enumerate(run_keys)
            if runs is not None and any(seq for _chars, seq in runs)
        ]
        return records, keys

    @staticmethod
    def split_records_by_attribution_keys(records: List[dict],
                                          keys: List[list]) -> List[dict]:
        """Split merged-run records back per author, restoring pre-clamp
        insert seqs from an "attribution" blob (``[idx, [[chars, seq],
        ...]]`` entries) — IN PLACE, returning ``records``.

        Semantically equivalent to the epoch clamp (a restored seq <= the
        loaded minSeq satisfies every visibility/expiry rule identically),
        and a re-summarize re-merges to identical body bytes.  THE single
        implementation shared by ``SharedString.load`` and the catch-up
        service's warm-base pack — byte parity across the CPU and device
        folds depends on these never diverging (review r5)."""
        for idx, runs in sorted(keys, reverse=True):
            rec = records[idx]
            if rec["s"] != 0:
                continue  # body already carried the seq
            pieces, off = [], 0
            for chars, seq in runs:
                piece = dict(rec)
                piece["t"] = rec["t"][off:off + chars]
                piece["s"] = seq or 0
                pieces.append(piece)
                off += chars
            if off != len(rec["t"]):
                continue  # malformed keys: keep unsplit
            records[idx:idx + 1] = pieces
        return records

    def load_records(self, records: List[dict], seq: int, min_seq: int) -> None:
        self.segments = []
        for rec in records:
            seg = Segment(
                rec["t"],
                rec["s"],
                rec["c"] if rec["c"] is not None else NO_CLIENT,
                rec.get("p"),
            )
            if "rs" in rec:
                seg.removed_seq = rec["rs"]
                seg.removed_client = rec.get("rc")
            if "ob" in rec:
                seg.ob_stamps = {s: c for s, c in rec["ob"]}
            if "ro" in rec:
                seg.overlap_removers = set(rec["ro"])
            self.segments.append(seg)
        self.current_seq = seq
        self.min_seq = min_seq
