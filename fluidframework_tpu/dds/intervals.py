"""IntervalCollection — sliding intervals anchored in a SharedString.

Capability-equivalent of the reference's sequence-package interval collections
(SURVEY.md §2.2: ``IntervalCollection``/``SequenceInterval``, anchored via
local references; upstream paths UNVERIFIED — empty reference mount).

Convergence model (simpler and stronger than per-field pending masking,
which compounds badly across add/delete/change interleavings — fuzz-found):
interval state is a **pure fold of sequenced ops** in total order, with
view-based endpoint resolution.  Every replica applies every remote op when
it arrives and re-applies its *own* op at its ack (idempotent overwrite
semantics), so the sequenced fold is identical everywhere.  The optimistic
local apply at submit time is a provisional overlay for local reads; the ack
re-apply snaps it to the authoritative sequence position.

Rules of the fold:
- ``add``    — replace the interval wholesale (endpoints + exact props).
- ``change`` — update given endpoints; merge props per key (null deletes).
  No-op if the interval was deleted earlier in the order.
- ``delete`` — remove the interval.
- Endpoints carry *positions in the op's view* ``(ref_seq, client)``; each
  replica resolves them at apply time.  The merge-tree keeps tombstones
  inside the collab window, so the view walk reconstructs; endpoints that
  resolve onto a sequenced-removed segment slide immediately (matching the
  author's earlier slide), and slides only ever target sequenced segments
  (see MergeTreeOracle._slide_target_ok).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .merge_tree import LocalReference, MergeTreeOracle, NO_CLIENT


class Interval:
    __slots__ = ("id", "start", "end", "props")

    def __init__(self, interval_id: str, start: LocalReference,
                 end: LocalReference, props: Optional[Dict[str, Any]] = None):
        self.id = interval_id
        self.start = start
        self.end = end
        self.props: Dict[str, Any] = {
            k: v for k, v in (props or {}).items() if v is not None
        }


class IntervalCollection:
    """One named collection of intervals over a SharedString's merge-tree.

    Lifecycle and op routing are owned by the SharedString (ops arrive
    through the sequence channel with kind "intervalAdd"/"intervalChange"/
    "intervalDelete"); this class implements resolution and merge rules.
    """

    def __init__(self, tree: MergeTreeOracle) -> None:
        self._tree = tree
        self.intervals: Dict[str, Interval] = {}
        # Count of in-flight local ops per id: provisional-state marker
        # (summaries exclude such ids; see summary_obj).
        self._pending_ids: Dict[str, int] = {}

    # -- queries ---------------------------------------------------------------

    def get(self, interval_id: str) -> Optional[Interval]:
        return self.intervals.get(interval_id)

    def endpoints(self, interval_id: str, client: str = NO_CLIENT):
        """Current (start, end) positions, or None if the interval no longer
        exists (e.g. a concurrent remote delete) — consistent with get()."""
        iv = self.intervals.get(interval_id)
        if iv is None:
            return None
        return (
            self._tree.reference_position(iv.start, client=client),
            self._tree.reference_position(iv.end, client=client),
        )

    def __len__(self) -> int:
        return len(self.intervals)

    def items(self):
        return self.intervals.items()

    # -- resolution ------------------------------------------------------------

    def _resolve(self, pos: int, ref_seq: int, client: str,
                 up_to_seq=None) -> LocalReference:
        """Anchor a reference at a view position; slide immediately off
        sequenced-removed segments so early (author) and late (remote)
        resolution agree.  ``up_to_seq`` is the fold position for sequenced
        (re-)application — it excludes the author's own still-pending later
        edits from the walk (see MergeTreeOracle._insert_visible)."""
        ref = self._tree.create_reference(
            pos, ref_seq=ref_seq, client=client, up_to_seq=up_to_seq)
        seg = ref.segment
        if seg is not None and self._tree._sequenced_removed(seg):
            self._tree._slide_refs(seg)
        return ref

    def _detach(self, iv: Interval) -> None:
        self._detach_ref(iv, "start")
        self._detach_ref(iv, "end")

    # -- op application (the fold) ---------------------------------------------

    def apply(self, op: dict, ref_seq: int, client: str, local_ack: bool,
              pending: bool, seq=None) -> None:
        """Apply one collection op.

        ``pending``   — optimistic local apply (op not yet sequenced);
        ``local_ack`` — the sequenced echo of our own op (re-applied so the
                        fold is identical on every replica).
        """
        interval_id = op["id"]
        if pending:
            self._pending_ids[interval_id] = (
                self._pending_ids.get(interval_id, 0) + 1
            )
        elif local_ack:
            n = self._pending_ids.get(interval_id, 0) - 1
            if n <= 0:
                self._pending_ids.pop(interval_id, None)
            else:
                self._pending_ids[interval_id] = n

        kind = op["kind"]
        iv = self.intervals.get(interval_id)
        if kind == "intervalAdd":
            if iv is not None:
                self._detach(iv)
            self.intervals[interval_id] = Interval(
                interval_id,
                self._resolve(op["start"], ref_seq, client, seq),
                self._resolve(op["end"], ref_seq, client, seq),
                op.get("props"),
            )
        elif kind == "intervalChange":
            if iv is None:
                return  # deleted earlier in the order: no-op
            if op.get("start") is not None:
                self._detach_ref(iv, "start")
                iv.start = self._resolve(op["start"], ref_seq, client, seq)
            if op.get("end") is not None:
                self._detach_ref(iv, "end")
                iv.end = self._resolve(op["end"], ref_seq, client, seq)
            for key, value in (op.get("props") or {}).items():
                if value is None:
                    iv.props.pop(key, None)
                else:
                    iv.props[key] = value
        elif kind == "intervalDelete":
            if iv is not None:
                self._detach(iv)
                del self.intervals[interval_id]
        else:
            raise ValueError(f"unknown interval op kind {kind!r}")

    def _detach_ref(self, iv: Interval, which: str) -> None:
        ref = getattr(iv, which)
        if ref.segment is not None and ref in ref.segment.refs:
            ref.segment.refs.remove(ref)

    # -- summary ---------------------------------------------------------------

    def summary_obj(self) -> dict:
        """Canonical sequenced-state projection: positions resolved in the
        all-sequenced view, sorted by id.  Ids with in-flight local ops are
        excluded (their fold state is provisional; summarizers run from
        replicas with no pending ops, as in the reference)."""
        out = {}
        for interval_id in sorted(self.intervals):
            if self._pending_ids.get(interval_id, 0) > 0:
                continue
            iv = self.intervals[interval_id]
            rec: Dict[str, Any] = {
                "start": self._tree.reference_position(iv.start),
                "end": self._tree.reference_position(iv.end),
            }
            if iv.props:
                rec["props"] = dict(sorted(iv.props.items()))
            out[interval_id] = rec
        return out

    def load_obj(self, obj: dict) -> None:
        for iv in self.intervals.values():
            self._detach(iv)
        self.intervals = {}
        self._pending_ids = {}
        for interval_id, rec in obj.items():
            start = self._resolve(rec["start"], self._tree.current_seq, NO_CLIENT)
            end = self._resolve(rec["end"], self._tree.current_seq, NO_CLIENT)
            self.intervals[interval_id] = Interval(
                interval_id, start, end, rec.get("props")
            )
