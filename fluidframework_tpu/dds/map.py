"""SharedMap / SharedDirectory — the LWW key-value merge engines.

Capability-equivalent of the reference's map package (SURVEY.md §2.2:
``SharedMap``/``MapKernel``/``SharedDirectory``; upstream paths UNVERIFIED —
empty reference mount).

Merge semantics (documented in SEMANTICS.md §map):

- Sequenced ops apply in total order; set/delete are last-writer-wins because
  later ops simply overwrite.
- Optimistic local reads: a pending local op on a key will be sequenced with a
  *larger* seq than any op arriving before its ack, so it wins — therefore
  remote ops on keys with pending local ops are **not** applied to the local
  view (pending-key tracking, the reference's MapKernel pattern).  The same
  argument applies to a pending ``clear``.
- ``clear`` empties sequenced state; pending local sets survive (they will
  re-populate when sequenced).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..protocol.messages import SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .shared_object import SharedObject


class MapKernel:
    """The LWW kernel shared by SharedMap and each SharedDirectory node.

    This is the logic the ``ops.map_kernel`` TPU path replays in bulk: final
    value per key = the op with the maximum seq for that key, with deletes and
    clears masking earlier sets.
    """

    def __init__(self) -> None:
        self.data: Dict[str, Any] = {}
        self._pending_keys: Dict[str, int] = {}
        self._pending_clears = 0

    # -- local (optimistic) ----------------------------------------------------

    def local_set(self, key: str, value: Any, attached: bool) -> None:
        self.data[key] = value
        if attached:
            self._pending_keys[key] = self._pending_keys.get(key, 0) + 1

    def local_delete(self, key: str, attached: bool) -> bool:
        existed = key in self.data
        self.data.pop(key, None)
        if attached:
            self._pending_keys[key] = self._pending_keys.get(key, 0) + 1
        return existed

    def local_clear(self, attached: bool) -> None:
        self.data.clear()
        if attached:
            self._pending_clears += 1
            self._pending_keys.clear()

    # -- sequenced -------------------------------------------------------------

    def process(self, op: dict, local: bool) -> bool:
        """Apply one sequenced op; returns True when the op changed the
        *visible* state (False for local acks and for remote ops masked by
        pending local ops) — the event-emission signal."""
        kind = op["kind"]
        if kind == "clear":
            if local:
                if self._pending_clears > 0:
                    self._pending_clears -= 1
                    return False  # already applied optimistically
                # Pending hold lost to a kernel reset (subdir delete/recreate
                # sequenced under the in-flight clear): apply like a remote op.
            elif self._pending_clears > 0:
                return False  # our pending clear will win (larger seq)
            # Remote clear: drop sequenced state; keep keys with pending local
            # ops (those will be re-established when our ops sequence).
            survivors = {
                k: v for k, v in self.data.items() if self._pending_keys.get(k, 0) > 0
            }
            self.data = survivors
            return True

        key = op["key"]
        if local:
            n = self._pending_keys.get(key, 0)
            if n > 0:
                # Ack of our own op: value already applied; release the hold.
                if n == 1:
                    self._pending_keys.pop(key, None)
                else:
                    self._pending_keys[key] = n - 1
                return False
            if self._pending_clears > 0:
                return False  # our later clear wiped the hold; it outranks
            # No pending hold: the kernel was reset underneath the in-flight
            # op (e.g. its subdirectory was deleted and recreated).  The op is
            # still the latest writer in sequence order — apply it like a
            # remote op so every replica converges.
        elif self._pending_clears > 0 or self._pending_keys.get(key, 0) > 0:
            return False  # a pending local op outranks this remote op
        if kind == "set":
            self.data[key] = op["value"]
        elif kind == "delete":
            self.data.pop(key, None)
        else:
            raise ValueError(f"unknown map op kind {kind!r}")
        return True

    # -- summary ---------------------------------------------------------------

    def summary_obj(self) -> dict:
        return {"data": self.data}

    def load_obj(self, obj: dict) -> None:
        self.data = dict(obj["data"])
        self._pending_keys.clear()
        self._pending_clears = 0


class SharedMap(SharedObject):
    """Flat LWW key-value DDS."""

    TYPE = "map-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._kernel = MapKernel()

    # -- public API ------------------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        return self._kernel.data.get(key, default)

    def has(self, key: str) -> bool:
        return key in self._kernel.data

    def keys(self):
        return self._kernel.data.keys()

    def __len__(self) -> int:
        return len(self._kernel.data)

    def set(self, key: str, value: Any) -> None:
        existed = key in self._kernel.data
        prev = self._kernel.data.get(key)
        self._kernel.local_set(key, value, self.is_attached)
        self._submit_local_op({"kind": "set", "key": key, "value": value})
        self._emit("valueChanged",
                   {"key": key, "previousValue": prev,
                    "previousExisted": existed}, local=True)

    def delete(self, key: str) -> bool:
        existed = key in self._kernel.data
        prev = self._kernel.data.get(key)
        self._kernel.local_delete(key, self.is_attached)
        self._submit_local_op({"kind": "delete", "key": key})
        if existed:
            self._emit("valueChanged",
                       {"key": key, "previousValue": prev,
                        "previousExisted": True}, local=True)
        return existed

    def clear(self) -> None:
        self._kernel.local_clear(self.is_attached)
        self._submit_local_op({"kind": "clear"})
        self._emit("clear", local=True)

    def apply_stashed_op(self, contents) -> None:
        kind = contents["kind"]
        if kind == "set":
            self.set(contents["key"], contents["value"])
        elif kind == "delete":
            self.delete(contents["key"])
        elif kind == "clear":
            self.clear()
        else:
            raise ValueError(f"unknown stashed map op {kind!r}")

    # -- SharedObject ----------------------------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        key = op.get("key")
        existed = key in self._kernel.data if key is not None else False
        prev = self._kernel.data.get(key) if key is not None else None
        applied = self._kernel.process(op, local)
        if local or not applied:
            return  # optimistic apply already emitted / masked by pending
        if op["kind"] == "clear":
            self._emit("clear", local=False)
        else:
            self._emit("valueChanged",
                       {"key": key, "previousValue": prev,
                        "previousExisted": existed}, local=False)

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(self._kernel.summary_obj()))
        return tree

    def load(self, summary: SummaryTree) -> None:
        import json

        self._kernel.load_obj(json.loads(summary.blob_bytes("header")))
        self.discard_pending()


class SubDirectory:
    """One node of a SharedDirectory: a MapKernel plus named children."""

    def __init__(self) -> None:
        self.kernel = MapKernel()
        self.children: Dict[str, "SubDirectory"] = {}

    def resolve(self, path: str, create: bool = False) -> Optional["SubDirectory"]:
        node = self
        for part in [p for p in path.split("/") if p]:
            child = node.children.get(part)
            if child is None:
                if not create:
                    return None
                child = SubDirectory()
                node.children[part] = child
            node = child
        return node

    def summary_obj(self) -> dict:
        return {
            "data": self.kernel.data,
            "subdirs": {k: v.summary_obj() for k, v in sorted(self.children.items())},
        }

    def load_obj(self, obj: dict) -> None:
        self.kernel.load_obj(obj)
        self.children = {}
        for name, sub in obj.get("subdirs", {}).items():
            child = SubDirectory()
            child.load_obj(sub)
            self.children[name] = child


class SharedDirectory(SharedObject):
    """Hierarchical LWW key-value DDS: a tree of SubDirectories, each with its
    own MapKernel.  Ops carry an absolute path."""

    TYPE = "directory-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._root = SubDirectory()

    # -- public API ------------------------------------------------------------

    @property
    def root(self) -> SubDirectory:
        return self._root

    def get(self, key: str, path: str = "/", default: Any = None) -> Any:
        node = self._root.resolve(path)
        return default if node is None else node.kernel.data.get(key, default)

    def set(self, key: str, value: Any, path: str = "/") -> None:
        node = self._root.resolve(path, create=True)
        node.kernel.local_set(key, value, self.is_attached)
        self._submit_local_op(
            {"kind": "set", "path": path, "key": key, "value": value}
        )

    def delete(self, key: str, path: str = "/") -> None:
        node = self._root.resolve(path, create=True)
        node.kernel.local_delete(key, self.is_attached)
        self._submit_local_op({"kind": "delete", "path": path, "key": key})

    def clear(self, path: str = "/") -> None:
        node = self._root.resolve(path, create=True)
        node.kernel.local_clear(self.is_attached)
        self._submit_local_op({"kind": "clear", "path": path})

    def create_subdirectory(self, path: str) -> None:
        self._root.resolve(path, create=True)
        self._submit_local_op({"kind": "createSubdir", "path": path})

    def delete_subdirectory(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise ValueError("cannot delete root")
        parent = self._root.resolve("/".join(parts[:-1]))
        if parent is not None:
            parent.children.pop(parts[-1], None)
        self._submit_local_op({"kind": "deleteSubdir", "path": path})

    # -- SharedObject ----------------------------------------------------------

    def apply_stashed_op(self, contents) -> None:
        kind = contents["kind"]
        if kind == "set":
            self.set(contents["key"], contents["value"], contents["path"])
        elif kind == "delete":
            self.delete(contents["key"], contents["path"])
        elif kind == "clear":
            self.clear(contents["path"])
        elif kind == "createSubdir":
            self.create_subdirectory(contents["path"])
        elif kind == "deleteSubdir":
            self.delete_subdirectory(contents["path"])
        else:
            raise ValueError(f"unknown stashed directory op {kind!r}")

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        kind = op["kind"]
        if kind == "createSubdir":
            # Idempotent create; both local and remote paths converge.
            self._root.resolve(op["path"], create=True)
            return
        if kind == "deleteSubdir":
            # Applied on both the local ack and the remote path (idempotent):
            # a concurrent createSubdir sequenced before this delete must be
            # deleted again on the deleting replica for convergence.
            parts = [p for p in op["path"].split("/") if p]
            parent = self._root.resolve("/".join(parts[:-1]))
            if parent is not None:
                parent.children.pop(parts[-1], None)
            return
        node = self._root.resolve(op["path"], create=True)
        node.kernel.process(op, local)

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(self._root.summary_obj()))
        return tree

    def load(self, summary: SummaryTree) -> None:
        import json

        self._root = SubDirectory()
        self._root.load_obj(json.loads(summary.blob_bytes("header")))
        self.discard_pending()
