"""SharedString — collaborative text over the merge-tree.

Capability-equivalent of the reference's sequence package (SURVEY.md §2.2:
``SharedString``/``SharedSegmentSequence``; upstream paths UNVERIFIED — empty
reference mount).  Wire format of an op (the unit the sequencer stamps and the
TPU replay path packs into ragged tensors):

    {"kind": "insert",   "pos": int, "text": str, "props": {...}?}
    {"kind": "remove",   "start": int, "end": int}
    {"kind": "annotate", "start": int, "end": int, "props": {...}}

Positions are always relative to the op's view ``(ref_seq, client)``.
"""

from __future__ import annotations

import collections
import json
from typing import Any, Deque, Dict, Optional

from ..protocol.messages import UNASSIGNED_SEQ, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .intervals import IntervalCollection
from .merge_tree import MergeTreeOracle, SegmentGroup, NO_CLIENT
from .shared_object import SharedObject


class SharedString(SharedObject):
    """Collaborative sequence of characters with LWW range annotations."""

    TYPE = "sequence-tpu"

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self.tree = MergeTreeOracle()
        # FIFO of SegmentGroups for pending local ops (acks arrive in order).
        self._pending_groups: Deque[SegmentGroup] = collections.deque()
        self._interval_collections: Dict[str, IntervalCollection] = {}
        self._interval_counter = 0

    # -- reads -----------------------------------------------------------------

    @property
    def text(self) -> str:
        """The local optimistic view (sequenced state + own pending ops)."""
        return self.tree.get_text(client=self._local_client())

    def __len__(self) -> int:
        return self.tree.length(client=self._local_client())

    def _local_client(self) -> str:
        return self.client_id if self.client_id is not None else NO_CLIENT

    # -- local edits (optimistic apply, then submit) ---------------------------

    def insert_text(self, pos: int, text: str,
                    props: Optional[Dict[str, Any]] = None) -> None:
        if not text:
            return
        client = self._local_client()
        group = SegmentGroup("insert", client=client)
        self.tree.apply_insert(
            pos, text, UNASSIGNED_SEQ, client, self.tree.current_seq,
            props=props, group=group,
        )
        self._pending_groups.append(group)
        op = {"kind": "insert", "pos": pos, "text": text}
        if props:
            op["props"] = props
        self._submit_local_op(op)
        if not self.is_attached:
            self._ack_detached(group, op)
        self._emit("sequenceDelta",
                   {"kind": "insert", "pos": pos, "text": text,
                    "props": props}, local=True)

    def remove_range(self, start: int, end: int) -> None:
        if start >= end:
            return
        client = self._local_client()
        removed = self.text[start:end]
        group = SegmentGroup("remove", client=client)
        self.tree.apply_remove(
            start, end, UNASSIGNED_SEQ, client, self.tree.current_seq, group=group
        )
        self._pending_groups.append(group)
        self._submit_local_op({"kind": "remove", "start": start, "end": end})
        if not self.is_attached:
            self._ack_detached(group, {"kind": "remove"})
        self._emit("sequenceDelta",
                   {"kind": "remove", "start": start, "end": end,
                    "removedText": removed}, local=True)

    def obliterate_range(self, start: int, end: int) -> None:
        """Remove [start, end) AND win against concurrent inserts into the
        range (the reference's obliterateRange; see merge_tree docstring for
        the exact arrival rule)."""
        if start >= end:
            return
        client = self._local_client()
        removed = self.text[start:end]
        group = SegmentGroup("obliterate", client=client)
        self.tree.apply_obliterate(
            start, end, UNASSIGNED_SEQ, client, self.tree.current_seq,
            group=group,
        )
        self._pending_groups.append(group)
        self._submit_local_op(
            {"kind": "obliterate", "start": start, "end": end}
        )
        if not self.is_attached:
            self._ack_detached(group, {"kind": "obliterate"})
        self._emit("sequenceDelta",
                   {"kind": "obliterate", "start": start, "end": end,
                    "removedText": removed}, local=True)

    def annotate_range(self, start: int, end: int, props: Dict[str, Any]) -> None:
        if start >= end or not props:
            return
        client = self._local_client()
        group = SegmentGroup("annotate", props=props, client=client)
        self.tree.apply_annotate(
            start, end, props, UNASSIGNED_SEQ, client, self.tree.current_seq,
            group=group,
        )
        self._pending_groups.append(group)
        self._submit_local_op(
            {"kind": "annotate", "start": start, "end": end, "props": props}
        )
        if not self.is_attached:
            self._ack_detached(group, {"kind": "annotate", "props": props})
        self._emit("sequenceDelta",
                   {"kind": "annotate", "start": start, "end": end,
                    "props": props}, local=True)

    # -- attribution (SURVEY §1 layer 8) ---------------------------------------

    def seq_at(self, pos: int) -> Optional[int]:
        """Insert seq of the segment covering visible position ``pos`` in
        the local view (None for out-of-range or a still-pending local
        insert)."""
        client = self._local_client()
        ref_seq = self.tree.current_seq
        c = 0
        for seg in self.tree.segments:
            v = self.tree._visible_len(seg, ref_seq, client)
            if v and pos < c + v:
                return None if seg.insert_seq == UNASSIGNED_SEQ \
                    else seg.insert_seq
            c += v
        return None

    def attribution_at(self, pos: int) -> Optional[dict]:
        """Who inserted the character at ``pos``, and when:
        ``{"user", "timestamp", "seq"}`` via the container attributor
        (None when detached or unattributed)."""
        return self._attribution(self.seq_at(pos))

    # -- interval collections (north-star config #3) ---------------------------

    def get_interval_collection(self, label: str = "default") -> IntervalCollection:
        coll = self._interval_collections.get(label)
        if coll is None:
            coll = IntervalCollection(self.tree)
            self._interval_collections[label] = coll
        return coll

    def _submit_interval_op(self, label: str, op: dict) -> None:
        """Optimistic local apply + submit — shared by all interval ops."""
        self.get_interval_collection(label).apply(
            op, self.tree.current_seq, self._local_client(),
            local_ack=False, pending=self.is_attached,
        )
        self._submit_local_op(op)

    def add_interval(self, start: int, end: int,
                     props: Optional[Dict[str, Any]] = None,
                     label: str = "default",
                     interval_id: Optional[str] = None) -> str:
        if interval_id is None:
            self._interval_counter += 1
            interval_id = f"{self._local_client()}-{self._interval_counter}"
        op = {"kind": "intervalAdd", "label": label, "id": interval_id,
              "start": start, "end": end}
        if props:
            op["props"] = props
        self._submit_interval_op(label, op)
        return interval_id

    def change_interval(self, interval_id: str,
                        start: Optional[int] = None,
                        end: Optional[int] = None,
                        props: Optional[Dict[str, Any]] = None,
                        label: str = "default") -> None:
        op = {"kind": "intervalChange", "label": label, "id": interval_id,
              "start": start, "end": end}
        if props:
            op["props"] = props
        self._submit_interval_op(label, op)

    def delete_interval(self, interval_id: str, label: str = "default") -> None:
        self._submit_interval_op(
            label, {"kind": "intervalDelete", "label": label, "id": interval_id}
        )

    # -- rebase resubmit (view fell below the collaboration window) ------------

    def _resubmit_rebased(self, pending) -> None:
        """Regenerate pending ops against the current view, one op per
        affected segment (the reference's merge-tree op regeneration on
        reconnect).  Exactness comes from segment identity: each pending
        SegmentGroup still holds the very segments the op touched, so the
        rebased op re-targets them at their *current* positions — computed
        in the view remote replicas will apply it in (sequenced state plus
        already-regenerated earlier pending ops; see
        MergeTreeOracle.rebase_visible_len)."""
        groups = list(self._pending_groups)
        self._pending_groups.clear()
        allowed: set = set()
        gi = 0
        for _old_client_seq, contents, _meta, _ref_seq in pending:
            kind = contents["kind"]
            if kind in ("insert", "remove", "annotate", "obliterate"):
                group = groups[gi]
                gi += 1
                self._regen_group(group, contents, allowed)
            elif kind.startswith("interval"):
                self._regen_interval(contents, allowed)
            else:
                raise ValueError(f"unknown pending sequence op {kind!r}")
        assert gi == len(groups), "pending-op / segment-group FIFO skew"

    def _regen_group(self, group: SegmentGroup, contents: dict,
                     allowed: set) -> None:
        segs = [s for s in self.tree.segments if group in s.pending_groups]
        client = self._local_client()
        if group.kind == "obliterate":
            self.tree.pending_obliterates.discard(group)
            # A range obliterate must regenerate as ONE op over its whole
            # span: per-segment ranges would turn interior seams into
            # endpoints (where concurrent inserts survive) and lose the
            # zero-width stamping between covered segments — the feature's
            # defining guarantee (review-found).  Covered segments stay
            # contiguous in the rebase view (interleaved tombstones have
            # zero width there).
            start = end = None
            for seg in segs:
                seg.pending_groups.remove(group)
                if seg.removed_seq is not None \
                        and seg.removed_seq != UNASSIGNED_SEQ:
                    seg.pending_overlap.discard(client)
                    continue
                pos = self.tree.rebase_position(seg, allowed)
                if start is None:
                    start = pos
                end = pos + len(seg.text)
            if start is not None and end > start:
                new_group = SegmentGroup("obliterate", client=client)
                for seg in segs:
                    if seg.removed_seq == UNASSIGNED_SEQ and \
                            seg.removed_client == client:
                        new_group.add(seg)
                self.tree.pending_obliterates.add(new_group)
                self._pending_groups.append(new_group)
                self._submit_local_op(
                    {"kind": "obliterate", "start": start, "end": end}
                )
                allowed.add(new_group)
            return
        for seg in segs:
            seg.pending_groups.remove(group)
            if group.kind == "insert":
                if seg.insert_seq == UNASSIGNED_SEQ and \
                        seg.removed_seq is not None and \
                        seg.removed_seq != UNASSIGNED_SEQ:
                    # A predicted obliterate-kill judged at the OLD
                    # position: the regenerated op goes out at a fresh
                    # in-window ref where every stamp is already seen, so
                    # it cannot be killed on arrival — clear the stale
                    # verdict (and the copied killer stamp) before
                    # re-placing (fuzz-found divergence).
                    seg.ob_stamps.pop(seg.removed_seq, None)
                    seg.removed_seq = None
                    seg.removed_client = None
                    if client in seg.pending_overlap:
                        # The kill demoted our own pending removal of this
                        # very text; restore the pending mark or the
                        # regenerated remove/obliterate would never mark
                        # the segment removed locally (review-found).
                        seg.pending_overlap.discard(client)
                        seg.removed_seq = UNASSIGNED_SEQ
                        seg.removed_client = client
                self.tree.rebase_normalize(seg, allowed)
                pos = self.tree.rebase_position(seg, allowed)
                op = {"kind": "insert", "pos": pos, "text": seg.text}
                if contents.get("props"):
                    op["props"] = contents["props"]
            elif group.kind == "remove":
                if seg.removed_seq is not None \
                        and seg.removed_seq != UNASSIGNED_SEQ:
                    # A remote remove sequenced first while we were away and
                    # ours never reached the log: nothing to resubmit — we
                    # were never a summary-visible overlap remover.
                    seg.pending_overlap.discard(client)
                    continue
                start = self.tree.rebase_position(seg, allowed)
                op = {"kind": "remove", "start": start,
                      "end": start + len(seg.text)}
            else:  # annotate
                if seg.removed_seq is not None \
                        and seg.removed_seq != UNASSIGNED_SEQ:
                    # Sequenced-removed segment: remote replicas would skip
                    # it anyway; release the pending-prop holds.
                    for key in group.props:
                        n = seg.pending_props.get(key, 0) - 1
                        if n <= 0:
                            seg.pending_props.pop(key, None)
                        else:
                            seg.pending_props[key] = n
                    continue
                start = self.tree.rebase_position(seg, allowed)
                op = {"kind": "annotate", "start": start,
                      "end": start + len(seg.text), "props": group.props}
            new_group = SegmentGroup(group.kind, props=group.props or None)
            new_group.add(seg)
            self._pending_groups.append(new_group)
            self._submit_local_op(op)  # fresh ref_seq = the current view
            allowed.add(new_group)

    def _regen_interval(self, contents: dict, allowed: set) -> None:
        """Rebase one pending interval op: endpoints re-read from the
        optimistic overlay's live references (they slid with every edit),
        resolved in the *rebase view* — own pending inserts that regenerate
        later in the FIFO sequence after this op, so counting them would
        shift the anchors right on every replica.  If the interval is gone
        from the overlay, clamp the stale positions into the rebase-view
        length (deterministic for every replica)."""
        label = contents.get("label", "default")
        iv = self.get_interval_collection(label).get(contents["id"])
        op = dict(contents)
        if iv is not None:
            if op.get("start") is not None:
                op["start"] = self.tree.rebase_reference_position(
                    iv.start, allowed)
            if op.get("end") is not None:
                op["end"] = self.tree.rebase_reference_position(
                    iv.end, allowed)
        else:
            n = self.tree.rebase_length(allowed)
            for k in ("start", "end"):
                if op.get(k) is not None:
                    op[k] = min(op[k], n)
        self._submit_local_op(op)

    def apply_stashed_op(self, contents) -> None:
        kind = contents["kind"]
        if kind == "insert":
            self.insert_text(contents["pos"], contents["text"],
                             contents.get("props"))
        elif kind == "remove":
            self.remove_range(contents["start"], contents["end"])
        elif kind == "obliterate":
            self.obliterate_range(contents["start"], contents["end"])
        elif kind == "annotate":
            self.annotate_range(contents["start"], contents["end"],
                                contents["props"])
        elif kind.startswith("interval"):
            self._submit_interval_op(contents["label"], contents)
        else:
            raise ValueError(f"unknown stashed sequence op {kind!r}")

    def _ack_detached(self, group: SegmentGroup, op: dict) -> None:
        """Detached (never-connected) DDS: ops are immediately 'sequenced'
        locally at seq 0 so the state is summary-ready."""
        self._pending_groups.pop()
        if group.kind == "insert":
            self.tree.ack_insert(group, 0)
        elif group.kind == "remove":
            self.tree.ack_remove(group, 0, self._local_client())
        elif group.kind == "obliterate":
            # Detached state has no concurrency: the zero-width pass is
            # vacuous, so ack over an empty range.
            self.tree.ack_obliterate(group, 0, self._local_client(), 0, 0, 0)
        else:
            self.tree.ack_annotate(group, op.get("props", {}))

    # -- sequenced path --------------------------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        op = msg.contents
        kind = op["kind"]
        if kind.startswith("interval"):
            self.get_interval_collection(op.get("label", "default")).apply(
                op, msg.ref_seq, msg.client_id, local_ack=local,
                pending=False, seq=msg.seq,
            )
            self.tree.current_seq = msg.seq
            if msg.min_seq > self.tree.min_seq:
                self.tree.zamboni(msg.min_seq)
            return
        if local:
            group = self._pending_groups.popleft()
            assert group.kind == kind, f"ack mismatch: {group.kind} vs {kind}"
            if kind == "insert":
                self.tree.ack_insert(group, msg.seq, msg.client_id,
                                     msg.ref_seq)
            elif kind == "remove":
                self.tree.ack_remove(group, msg.seq, msg.client_id)
            elif kind == "obliterate":
                self.tree.ack_obliterate(group, msg.seq, msg.client_id,
                                         op["start"], op["end"], msg.ref_seq)
            elif kind == "annotate":
                self.tree.ack_annotate(group, op["props"])
        else:
            if kind == "insert":
                self.tree.apply_insert(
                    op["pos"], op["text"], msg.seq, msg.client_id, msg.ref_seq,
                    props=op.get("props"),
                )
            elif kind == "remove":
                self.tree.apply_remove(
                    op["start"], op["end"], msg.seq, msg.client_id, msg.ref_seq
                )
            elif kind == "obliterate":
                self.tree.apply_obliterate(
                    op["start"], op["end"], msg.seq, msg.client_id, msg.ref_seq
                )
            elif kind == "annotate":
                self.tree.apply_annotate(
                    op["start"], op["end"], op["props"], msg.seq, msg.client_id,
                    msg.ref_seq,
                )
            else:
                raise ValueError(f"unknown sequence op kind {kind!r}")
            # Remote delta event.  Positions are the submitting client's
            # view (op coordinates), mirroring the wire op — a documented
            # deviation from the reference's resolved-range delta events.
            self._emit("sequenceDelta", dict(op), local=False)
        self.tree.current_seq = msg.seq
        if msg.min_seq > self.tree.min_seq:
            self.tree.zamboni(msg.min_seq)

    def advance(self, seq: int, min_seq: int) -> None:
        """Window bookkeeping for messages routed elsewhere (e.g. no-ops)."""
        self.tree.current_seq = max(self.tree.current_seq, seq)
        if min_seq > self.tree.min_seq:
            self.tree.zamboni(min_seq)

    # -- summary ---------------------------------------------------------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        # Interval state is an optimistic fold overlay (see intervals.py):
        # with in-flight local interval ops the overlay is provisional, so a
        # summary taken now would silently drop sequenced interval state.
        # Summarizers must run from pending-free replicas (as the reference's
        # do) — enforce rather than diverge.
        for label, coll in self._interval_collections.items():
            if coll._pending_ids:
                raise RuntimeError(
                    f"{self.id}: cannot summarize with in-flight interval ops "
                    f"on collection {label!r} (ids {sorted(coll._pending_ids)})"
                )
        header = {
            "seq": self.tree.current_seq,
            "minSeq": self.tree.min_seq,
            "length": self.tree.length(),
        }
        tree = SummaryTree()
        tree.add_blob("header", canonical_json(header))
        if self._attributor is not None:
            # Attribution-enabled containers: record the clamped records'
            # pre-clamp insert seqs (per merged sub-run) in a SEPARATE
            # blob (body bytes stay kernel-identical); load() restores the
            # keys so attribution_at survives the window clamp.
            records, keys = self.tree.normalized_records(return_keys=True)
            tree.add_blob("body", canonical_json(records))
            if keys:
                tree.add_blob("attribution", canonical_json(keys))
        else:
            tree.add_blob(
                "body", canonical_json(self.tree.normalized_records())
            )
        intervals = {
            label: coll.summary_obj()
            for label, coll in sorted(self._interval_collections.items())
            if coll.intervals
        }
        if intervals:
            tree.add_blob("intervals", canonical_json(intervals))
        return tree

    def load(self, summary: SummaryTree) -> None:
        header = json.loads(summary.blob_bytes("header"))
        records = json.loads(summary.blob_bytes("body"))
        if "attribution" in summary.children:
            # Restore pre-clamp insert seqs so attribution_at keeps
            # resolving on content below the window — the ONE shared
            # splitter (the catch-up warm-base pack uses it too).
            MergeTreeOracle.split_records_by_attribution_keys(
                records, json.loads(summary.blob_bytes("attribution"))
            )
        self.tree.load_records(records, header["seq"], header["minSeq"])
        self._pending_groups.clear()
        self._interval_collections = {}
        try:
            intervals = json.loads(summary.blob_bytes("intervals"))
        except KeyError:
            intervals = {}
        for label, obj in intervals.items():
            self.get_interval_collection(label).load_obj(obj)
        self.discard_pending()  # in-flight pre-load ops can no longer be acked
