"""SharedTree — the hierarchical-data merge engine.

Capability-equivalent of the reference's new-gen tree DDS (SURVEY.md §2.2:
``SharedTree``/``SharedTreeCore``/``EditManager``/``IForest``/changeset
compose/invert/rebase; upstream paths UNVERIFIED — empty reference mount).

Design (normative rules in SEMANTICS.md §tree).  Two deliberate departures
from the reference's architecture, both TPU-first:

1. **Id-addressed edits.**  Instead of the reference's field-kind changeset
   algebra (index-based OT with per-field mark lists), every node has a
   globally-unique author-assigned id and edits target ids: ``insert`` places
   a content block *after an anchor sibling id* (the author resolves
   index→anchor in their own view), ``remove``/``revive``/``move``/``set``
   name ids directly.  Sequenced application then needs no positional
   transformation at all — ops replay as pure scatters and linked-list
   splices, which is what lets the device kernel fold thousands of documents
   in parallel where index-OT would force a serial position walk per op.

2. **Sequenced forest + predicted view.**  The canonical state is the
   *sequenced forest*: a pure fold of sequenced changesets in total order,
   identical code for remote ops and the client's own acks — convergence is
   determinism of the fold, not delicacy of an overlay.  The user-facing
   optimistic view is a *prediction*: the sequenced forest copied and the
   client's pending changesets replayed on top, rebuilt lazily.  (The
   reference reaches the same split via EditManager trunk + local branch
   rebasing; here the local branch "rebase" is just replaying id-addressed
   edits, which never need rewriting.)

Tombstone discipline matches the merge-tree: removed nodes stay in sibling
lists until ``min_seq`` passes (zamboni), so anchors stay resolvable for
every op still in flight.  Concurrent inserts at one anchor stack
newest-first (the later-sequenced op applies later and lands immediately
after the anchor), the merge-tree rule.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from ..protocol.messages import UNASSIGNED_SEQ, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .shared_object import SharedObject

#: Anchor value meaning "at the start of the field".
FIELD_START = None

#: The hidden root node id; its fields are the document's root fields.
ROOT_ID = ""


def restore_attribution_seqs(keys: Dict[str, list], get_seqs,
                             put_seqs) -> None:
    """Warm-restore of pre-clamp (insert, value) seqs from a summary's
    "attribution" blob: fill ONLY zero seqs (the body already carried
    nonzero ones), skip unknown node ids.  ``get_seqs(nid)`` returns
    ``(ins, val)`` or None; ``put_seqs(nid, ins, val)`` writes back.

    THE single implementation shared by ``SharedTree.load`` and the
    catch-up service's warm-base pack (ops/tree_kernel.py) — byte parity
    across the CPU and device folds depends on these never diverging
    (review r5)."""
    for nid, (ins, val) in keys.items():
        cur = get_seqs(nid)
        if cur is None:
            continue
        cur_ins, cur_val = cur
        put_seqs(
            nid,
            ins if (ins and cur_ins == 0) else cur_ins,
            val if (val and cur_val == 0) else cur_val,
        )


# ---------------------------------------------------------------------------
# Schema (SchemaFactory-lite)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FieldSchema:
    """A field of an object node: ``kind`` is 'value' (node value payload) or
    'sequence' (ordered children restricted to the allowed types)."""

    kind: str                      # "value" | "sequence"
    allowed: Tuple[str, ...] = ()  # allowed child type names (sequence only)


class SchemaFactory:
    """Builds a named-type schema, capability parity with the reference's
    ``SchemaFactory``/``TreeViewConfiguration`` (SURVEY.md §2.2 tree)."""

    def __init__(self, scope: str = "") -> None:
        self.scope = scope
        self.types: Dict[str, Dict[str, FieldSchema]] = {}

    def _qualify(self, name: str) -> str:
        return f"{self.scope}.{name}" if self.scope else name

    def object(self, name: str, fields: Dict[str, FieldSchema]) -> str:
        qname = self._qualify(name)
        self.types[qname] = dict(fields)
        return qname

    def array(self, name: str, allowed: Tuple[str, ...]) -> str:
        return self.object(name, {"": FieldSchema("sequence", tuple(allowed))})

    @staticmethod
    def sequence(*allowed: str) -> FieldSchema:
        return FieldSchema("sequence", tuple(allowed))

    @staticmethod
    def value() -> FieldSchema:
        return FieldSchema("value")


@dataclasses.dataclass
class TreeViewConfiguration:
    """Root configuration: which types the root field admits."""

    schema: SchemaFactory
    root_allowed: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# Forest
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TreeNode:
    """One forest node."""

    id: str
    type: str
    value: Any = None
    value_seq: int = 0                 # seq of the value write (LWW record)
    insert_seq: int = 0                # seq stamped on insert (or last move)
    removed_seq: Optional[int] = None  # tombstone marker
    parent: Optional[Tuple[str, str]] = None  # (parent id, field name)
    fields: Dict[str, List[str]] = dataclasses.field(default_factory=dict)

    def visible(self) -> bool:
        return self.removed_seq is None


class Forest:
    """Id→node store.  Capability-equivalent of the reference's ``IForest``
    (object-forest); the chunked-forest capability (bulk array encoding) is
    what the device kernel's packed representation provides."""

    def __init__(self) -> None:
        self.nodes: Dict[str, TreeNode] = {}
        self.nodes[ROOT_ID] = TreeNode(id=ROOT_ID, type="")

    def copy(self) -> "Forest":
        out = Forest.__new__(Forest)
        out.nodes = {
            nid: dataclasses.replace(
                n, fields={f: list(s) for f, s in n.fields.items()}
            )
            for nid, n in self.nodes.items()
        }
        return out

    # -- queries ---------------------------------------------------------------

    def node(self, node_id: str) -> TreeNode:
        return self.nodes[node_id]

    def contains(self, node_id: str) -> bool:
        return node_id in self.nodes

    def visible_children(self, parent_id: str, field: str) -> List[str]:
        parent = self.nodes[parent_id]
        return [
            cid for cid in parent.fields.get(field, [])
            if self.nodes[cid].visible()
        ]

    def is_visible(self, node_id: str) -> bool:
        """Visible including ancestor removals."""
        if node_id == ROOT_ID:
            return True
        nid: Optional[str] = node_id
        while nid is not None and nid != ROOT_ID:
            n = self.nodes.get(nid)
            if n is None or not n.visible():
                return False
            nid = n.parent[0] if n.parent else None
        return nid == ROOT_ID

    def in_subtree(self, node_id: str, root_id: str) -> bool:
        nid: Optional[str] = node_id
        while nid is not None:
            if nid == root_id:
                return True
            n = self.nodes.get(nid)
            nid = n.parent[0] if n is not None and n.parent else None
        return False

    # -- structure edits -------------------------------------------------------

    def place_block(
        self, parent_id: str, field: str, anchor: Optional[str],
        ids: List[str],
    ) -> None:
        """Splice a block immediately after the anchor (or at field start).
        The op being applied is always the newest state in the fold, so
        same-anchor concurrent inserts stack newest-first automatically."""
        sibs = self.nodes[parent_id].fields.setdefault(field, [])
        if anchor is FIELD_START:
            pos = 0
        else:
            try:
                pos = sibs.index(anchor) + 1
            except ValueError:
                pos = 0  # anchor moved away/purged: deterministic fallback
        sibs[pos:pos] = ids

    def detach(self, node_id: str) -> None:
        n = self.nodes[node_id]
        if n.parent is None:
            return
        parent = self.nodes.get(n.parent[0])
        if parent is None:
            return
        sibs = parent.fields.get(n.parent[1], [])
        try:
            sibs.remove(node_id)
        except ValueError:
            pass

    def purge_expired(self, node_id: str, min_seq: int) -> None:
        """Pop this EXPIRED tombstone; descendants that are alive or still
        in-window become detached LIMBO roots (``parent=None``) — they stay
        addressable by id, because a later sequenced move can rescue a node
        that an earlier op relocated into the tombstone's subtree
        (id-addressed moves make this protocol-reachable; fuzz-found).
        Descendants that are themselves expired pop recursively."""
        n = self.nodes.pop(node_id, None)
        if n is None:
            return
        for _field, kids in list(n.fields.items()):
            for cid in list(kids):
                c = self.nodes.get(cid)
                if c is None:
                    continue
                if c.removed_seq is not None and c.removed_seq <= min_seq:
                    self.purge_expired(cid, min_seq)
                else:
                    c.parent = None  # limbo root

    def limbo_roots(self) -> List[str]:
        """Detached (rescuable) roots, sorted by id — the limbo section of
        summaries."""
        return sorted(
            nid for nid, n in self.nodes.items()
            if nid != ROOT_ID and n.parent is None
        )

    def purge_subtree(self, node_id: str) -> None:
        n = self.nodes.pop(node_id, None)
        if n is None:
            return
        for sibs in n.fields.values():
            for cid in sibs:
                self.purge_subtree(cid)


# ---------------------------------------------------------------------------
# Changeset algebra and the fold
# ---------------------------------------------------------------------------
#
# A changeset is {"edits": [edit, ...]}, edits applied in order.  Edit kinds:
#   insert : parent, field, anchor, content=[NodeSpec]
#   remove : ids=[...]                      (tombstone the nodes)
#   revive : ids, parent, field, anchor, content   (undo of remove)
#   set    : id, value, prev
#   move   : ids, parent, field, anchor, prev=[[id,parent,field,anchor],...]
#
# NodeSpec = {"id", "type", "value", "fields": {f: [NodeSpec]}}
#
# compose = concatenation (transactions squash to one changeset); invert is
# edit-wise reversal (SURVEY.md §3.4 rebaser capability — id-addressing means
# the sequenced-apply path needs no positional rebase, see module docstring).


def compose(changesets: List[dict]) -> dict:
    edits: List[dict] = []
    for cs in changesets:
        edits.extend(cs["edits"])
    return {"edits": edits}


def content_ids(spec: dict) -> List[str]:
    """All node ids in a NodeSpec subtree (pre-order)."""
    out = [spec["id"]]
    for children in spec.get("fields", {}).values():
        for child in children:
            out.extend(content_ids(child))
    return out


def node_spec(forest: Forest, node_id: str) -> dict:
    """Serialize a subtree to a NodeSpec (repair data / wire content).
    Tombstone markers ride along so repair-driven re-materialization does
    not resurrect descendants that were removed by *other* edits (their
    hidden-forever state must survive the purge/revive race)."""
    n = forest.node(node_id)
    spec: Dict[str, Any] = {"id": n.id, "type": n.type}
    if n.value is not None:
        spec["value"] = n.value
    if n.removed_seq is not None:
        spec["removedSeq"] = n.removed_seq
    fields = {
        f: [node_spec(forest, cid) for cid in sibs]
        for f, sibs in sorted(n.fields.items()) if sibs
    }
    if fields:
        spec["fields"] = fields
    return spec


def _location_of(forest: Forest, nid: str) -> Tuple[str, str, Optional[str]]:
    """(parent, field, previous-sibling anchor) of a node, for inverses."""
    n = forest.node(nid)
    pid, field = n.parent if n.parent else (ROOT_ID, "")
    sibs = forest.node(pid).fields.get(field, [])
    idx = sibs.index(nid)
    return pid, field, (sibs[idx - 1] if idx > 0 else FIELD_START)


def invert(changeset: dict, forest: Forest) -> dict:
    """Inverse changeset (for undo), computed against the state in which the
    changeset has applied.  Repair content for removes is captured from the
    forest, so the inverse is self-contained on the wire."""
    out: List[dict] = []
    for edit in reversed(changeset["edits"]):
        kind = edit["kind"]
        if kind == "insert":
            out.append({
                "kind": "remove",
                "ids": [spec["id"] for spec in edit["content"]],
            })
        elif kind == "remove":
            for nid in reversed(edit["ids"]):
                if not forest.contains(nid):
                    continue
                pid, field, anchor = _location_of(forest, nid)
                out.append({
                    "kind": "revive", "ids": [nid], "parent": pid,
                    "field": field, "anchor": anchor,
                    "content": [node_spec(forest, nid)],
                })
        elif kind == "revive":
            out.append({"kind": "remove", "ids": list(edit["ids"])})
        elif kind == "set":
            out.append({
                "kind": "set", "id": edit["id"],
                "value": edit.get("prev"), "prev": edit["value"],
            })
        elif kind == "move":
            for nid, pid, field, anchor in reversed(edit.get("prev", [])):
                if not forest.contains(nid):
                    continue
                back = [[nid, *_location_of(forest, nid)]]
                out.append({
                    "kind": "move", "ids": [nid], "parent": pid,
                    "field": field, "anchor": anchor, "prev": back,
                })
        else:
            raise ValueError(f"unknown edit kind {kind!r}")
    return {"edits": out}


def _materialize(
    forest: Forest, spec: dict, parent_id: str, field: str, seq: int,
) -> bool:
    """Create the spec'd subtree; returns False (creating nothing) when the
    id already exists — a node rescued out of a purged subtree keeps its
    current location ("move wins the location"), so revive repair data must
    not clone it."""
    if forest.contains(spec["id"]):
        return False
    n = TreeNode(
        id=spec["id"], type=spec["type"],
        value=spec.get("value"), value_seq=max(seq, 0),
        insert_seq=seq, removed_seq=spec.get("removedSeq"),
        parent=(parent_id, field),
    )
    forest.nodes[n.id] = n
    for f, children in spec.get("fields", {}).items():
        for child in children:
            if _materialize(forest, child, n.id, f, seq):
                n.fields.setdefault(f, []).append(child["id"])
    return True


def apply_changeset(forest: Forest, cs: dict, seq: int) -> None:
    """THE fold step: apply one changeset at sequence position ``seq``.

    Used identically for remote ops, the client's own acks, catch-up replay,
    and (with ``seq=UNASSIGNED_SEQ``) for predicting pending local ops onto a
    view copy.  Every rule here must be a pure function of (forest, cs, seq)
    — determinism of this function *is* the convergence guarantee, and the
    device kernel (ops.tree_kernel) reproduces it bit-for-bit.
    """
    for edit in cs["edits"]:
        kind = edit["kind"]
        if kind == "insert":
            parent_id = edit["parent"]
            if not forest.contains(parent_id):
                continue  # parent purged with an expired tombstone subtree
            anchor = edit["anchor"]
            prev = anchor if (
                anchor is FIELD_START or forest.contains(anchor)
            ) else FIELD_START
            created = [
                spec["id"] for spec in edit["content"]
                if _materialize(forest, spec, parent_id, edit["field"], seq)
            ]
            forest.place_block(parent_id, edit["field"], prev, created)
        elif kind == "remove":
            for nid in edit["ids"]:
                n = forest.nodes.get(nid)
                if n is not None and n.removed_seq is None:
                    n.removed_seq = seq  # first remover wins the tombstone
        elif kind == "revive":
            for nid in edit["ids"]:
                n = forest.nodes.get(nid)
                if n is not None:
                    n.removed_seq = None
                elif forest.contains(edit["parent"]):
                    # Tombstone already purged: re-insert from repair data.
                    # Descendants keep their own recorded tombstones; only
                    # the revive target itself comes back alive.
                    content = [c for c in edit["content"] if c["id"] == nid]
                    anchor = edit["anchor"]
                    if anchor is not FIELD_START and not forest.contains(
                        anchor
                    ):
                        anchor = FIELD_START
                    created = [
                        spec["id"] for spec in content
                        if _materialize(
                            forest, spec, edit["parent"], edit["field"], seq
                        )
                    ]
                    forest.place_block(
                        edit["parent"], edit["field"], anchor, created
                    )
                    forest.node(nid).removed_seq = None
        elif kind == "set":
            n = forest.nodes.get(edit["id"])
            if n is not None:
                n.value = edit["value"]
                n.value_seq = max(seq, n.value_seq)
        elif kind == "move":
            # Moves relocate alive nodes and tombstones alike ("remove wins
            # the removed state, move wins the location" — remove-by-id is
            # location-independent, so no positional conflict exists).
            ids = [nid for nid in edit["ids"] if forest.contains(nid)]
            if not ids or not forest.contains(edit["parent"]):
                continue
            if any(forest.in_subtree(edit["parent"], nid) for nid in ids):
                continue  # destination inside moved subtree: drop the move
            anchor = edit["anchor"]
            if anchor is not FIELD_START and (
                not forest.contains(anchor) or anchor in ids
            ):
                anchor = FIELD_START
            for nid in ids:
                forest.detach(nid)
            forest.place_block(edit["parent"], edit["field"], anchor, ids)
            for nid in ids:
                n = forest.node(nid)
                n.parent = (edit["parent"], edit["field"])
                n.insert_seq = seq
        else:
            raise ValueError(f"unknown edit kind {kind!r}")


# ---------------------------------------------------------------------------
# EditManager — trunk bookkeeping + collab-window eviction
# ---------------------------------------------------------------------------


class EditManager:
    """Trunk tail above the collaboration window (SURVEY.md §3.4: trunk
    eviction below minimumSequenceNumber).  With id-addressed edits the
    sequenced-apply path needs no trunk replay — the tail serves undo
    windows and introspection; eviction mirrors the collab-window GC."""

    def __init__(self) -> None:
        self.trunk: List[Tuple[int, Optional[str], dict]] = []
        self.evicted_below = 0

    def add_sequenced(self, seq: int, client: Optional[str], cs: dict) -> None:
        self.trunk.append((seq, client, cs))

    def evict(self, min_seq: int) -> None:
        keep = [(s, c, cs) for (s, c, cs) in self.trunk if s > min_seq]
        if len(keep) != len(self.trunk):
            self.trunk = keep
            self.evicted_below = max(self.evicted_below, min_seq)


# ---------------------------------------------------------------------------
# SharedTree
# ---------------------------------------------------------------------------


class SharedTree(SharedObject):
    """The tree DDS.  Public API mirrors the reference's simple-tree surface
    at the capability level: schema'd content, transactions, id-stable
    nodes, structural edits, LWW values, undo via inversion."""

    TYPE = "tree-tpu"
    REBASE_POSITION_FREE = True

    def __init__(
        self, object_id: str,
        config: Optional[TreeViewConfiguration] = None,
    ) -> None:
        super().__init__(object_id)
        self.seq_forest = Forest()
        self.edit_manager = EditManager()
        self.config = config
        self._id_counter = 0
        self._txn_edits: Optional[List[dict]] = None
        self._min_seq = 0
        self._last_seq = 0
        self._view_cache: Optional[Forest] = None

    # -- the predicted view ----------------------------------------------------

    @property
    def view(self) -> Forest:
        """Sequenced forest + pending local changesets replayed on top.
        Detached (never-connected) trees edit the sequenced forest directly
        through the same path: pending is always empty there because
        _submit_local_op drops ops pre-attach, so prediction == state."""
        pending = [entry[1] for entry in self._pending]
        if self._txn_edits:
            pending = pending + [{"edits": self._txn_edits}]
        if not pending:
            return self.seq_forest
        if self._view_cache is None:
            view = self.seq_forest.copy()
            for cs in pending:
                apply_changeset(view, cs, UNASSIGNED_SEQ)
            self._view_cache = view
        return self._view_cache

    def _invalidate(self) -> None:
        self._view_cache = None

    # -- ids -------------------------------------------------------------------

    def _next_id(self) -> str:
        self._id_counter += 1
        prefix = self.client_id if self.client_id else "init"
        return f"{prefix}-{self._id_counter}"

    # -- reads -----------------------------------------------------------------

    def children(self, parent_id: str = ROOT_ID, field: str = "") -> List[str]:
        return self.view.visible_children(parent_id, field)

    def value_of(self, node_id: str) -> Any:
        return self.view.node(node_id).value

    def type_of(self, node_id: str) -> str:
        return self.view.node(node_id).type

    def contains(self, node_id: str) -> bool:
        view = self.view
        return view.contains(node_id) and view.is_visible(node_id)

    def attribution_of(self, node_id: str,
                       kind: str = "insert") -> Optional[dict]:
        """Who created (``kind='insert'``, incl. last move) or last wrote
        the value of (``kind='value'``) a node, resolved through the
        container attributor (SURVEY §1 layer 8); None when detached,
        unattributed, or the stamp is still pending."""
        view = self.view
        if not view.contains(node_id):
            return None  # stale/garbage id or window-dropped subtree
        node = view.node(node_id)
        seq = node.insert_seq if kind == "insert" else node.value_seq
        return self._attribution(seq if seq > 0 else None)

    def to_obj(self, node_id: str = ROOT_ID) -> Any:
        """Nested plain-object view of the visible tree (tests/debugging)."""
        view = self.view
        return self._to_obj(view, node_id)

    def _to_obj(self, view: Forest, node_id: str) -> Any:
        n = view.node(node_id)
        fields = {
            f: [
                self._to_obj(view, cid)
                for cid in view.visible_children(node_id, f)
            ]
            for f in sorted(n.fields)
            if view.visible_children(node_id, f)
        }
        if node_id == ROOT_ID:
            return fields
        out: Dict[str, Any] = {"type": n.type}
        if n.value is not None:
            out["value"] = n.value
        if fields:
            out["fields"] = fields
        return out

    # -- schema ----------------------------------------------------------------

    def _check_schema(self, parent_id: str, field: str, specs: List[dict]):
        if self.config is None:
            return
        if parent_id == ROOT_ID:
            allowed = self.config.root_allowed
        else:
            ptype = self.view.node(parent_id).type
            fs = self._field_schema(ptype, field)
            if fs.kind != "sequence":
                raise ValueError(f"schema: field {field!r} is not a sequence")
            allowed = fs.allowed
        for spec in specs:
            if allowed and spec["type"] not in allowed:
                raise ValueError(
                    f"schema: type {spec['type']!r} not allowed here"
                )
            for f, children in spec.get("fields", {}).items():
                self._check_spec_field(spec["type"], f, children)

    def _field_schema(self, ptype: str, field: str) -> FieldSchema:
        fields = self.config.schema.types.get(ptype)
        if fields is None or field not in fields:
            raise ValueError(f"schema: type {ptype!r} has no field {field!r}")
        return fields[field]

    def _check_spec_field(self, ptype: str, field: str, specs: List[dict]):
        fs = self._field_schema(ptype, field)
        for spec in specs:
            if fs.allowed and spec["type"] not in fs.allowed:
                raise ValueError(
                    f"schema: type {spec['type']!r} not allowed in "
                    f"{ptype}.{field}"
                )
            for f, children in spec.get("fields", {}).items():
                self._check_spec_field(spec["type"], f, children)

    # -- content construction --------------------------------------------------

    def build(self, type_name: str, value: Any = None,
              fields: Optional[Dict[str, List[dict]]] = None) -> dict:
        """Build a NodeSpec with fresh ids (recursively)."""
        spec: Dict[str, Any] = {"id": self._next_id(), "type": type_name}
        if value is not None:
            spec["value"] = value
        if fields:
            spec["fields"] = {
                f: [self._ensure_ids(c) for c in children]
                for f, children in fields.items()
            }
        return spec

    def _ensure_ids(self, spec: dict) -> dict:
        spec = dict(spec)
        if "id" not in spec:
            spec["id"] = self._next_id()
        if spec.get("fields"):
            spec["fields"] = {
                f: [self._ensure_ids(c) for c in children]
                for f, children in spec["fields"].items()
            }
        return spec

    # -- edits (public API) ----------------------------------------------------

    def insert(self, parent_id: str, field: str, index: int,
               content: List[dict]) -> List[str]:
        """Insert NodeSpecs at a visible index; returns the new node ids."""
        if not self.contains(parent_id):
            raise KeyError(f"insert: parent {parent_id!r} not visible")
        content = [self._ensure_ids(c) for c in content]
        self._check_schema(parent_id, field, content)
        anchor = self._anchor_for_index(parent_id, field, index)
        self._do_edit({
            "kind": "insert", "parent": parent_id, "field": field,
            "anchor": anchor, "content": content,
        })
        return [c["id"] for c in content]

    def remove(self, *node_ids: str) -> None:
        for nid in node_ids:
            if not self.contains(nid):
                raise KeyError(f"remove: node {nid!r} not visible")
        self._do_edit({"kind": "remove", "ids": list(node_ids)})

    def remove_range(self, parent_id: str, field: str,
                     start: int, end: int) -> None:
        vis = self.view.visible_children(parent_id, field)
        self.remove(*vis[start:end])

    def set_value(self, node_id: str, value: Any) -> None:
        if not self.contains(node_id):
            raise KeyError(f"set_value: node {node_id!r} not visible")
        prev = self.view.node(node_id).value
        self._do_edit(
            {"kind": "set", "id": node_id, "value": value, "prev": prev}
        )

    def move(self, node_ids: List[str], parent_id: str, field: str,
             index: int) -> None:
        for nid in node_ids:
            if not self.contains(nid):
                raise KeyError(f"move: node {nid!r} not visible")
            if self.view.in_subtree(parent_id, nid):
                raise ValueError("move: destination inside moved subtree")
        anchor = self._anchor_for_index(
            parent_id, field, index, exclude=set(node_ids)
        )
        # Previous locations ride along so any replica can invert the move
        # (undo) without historical state.
        prev = [[nid, *_location_of(self.view, nid)] for nid in node_ids]
        self._do_edit({
            "kind": "move", "ids": list(node_ids), "parent": parent_id,
            "field": field, "anchor": anchor, "prev": prev,
        })

    def undo_changeset(self, cs: dict) -> dict:
        """Invert a changeset against the current sequenced state and submit
        the inverse as a fresh edit (the undo-redo building block)."""
        inverse = invert(cs, self.seq_forest)
        self._submit_changeset(inverse)
        return inverse

    def _anchor_for_index(
        self, parent_id: str, field: str, index: int,
        exclude: Optional[set] = None,
    ) -> Optional[str]:
        vis = self.view.visible_children(parent_id, field)
        if exclude:
            vis = [v for v in vis if v not in exclude]
        if index <= 0 or not vis:
            return FIELD_START
        return vis[min(index, len(vis)) - 1]

    # -- transactions ----------------------------------------------------------

    def transaction(self) -> "_Transaction":
        return _Transaction(self)

    def _do_edit(self, edit: dict) -> None:
        if self._txn_edits is not None:
            self._txn_edits.append(edit)
            self._invalidate()
        else:
            self._submit_changeset({"edits": [edit]})

    def _submit_changeset(self, cs: dict) -> None:
        if self.is_attached:
            self._submit_local_op(cs, local_metadata=cs)
            self._emit("changed", {"changeset": cs}, local=True)
        else:
            # Detached: the edit is immediately "sequenced" locally — the
            # attach summary will carry it (reference: attach serializes
            # initial state).
            apply_changeset(self.seq_forest, cs, seq=0)
        self._invalidate()

    def apply_stashed_op(self, contents) -> None:
        # Changesets are id-addressed: no positional rebase needed — re-apply
        # as a fresh local edit on the rehydrated state.
        self._submit_changeset(contents)

    # -- sequenced apply (SharedObject) ----------------------------------------

    def _process_core(self, msg: SequencedMessage, local: bool, meta) -> None:
        if msg.seq <= self._last_seq:
            return  # tail overlapping the loaded summary: already folded in
        # The recorded sequence point is the last op folded into THIS
        # channel (not container-wide messages), so the summary stays a
        # function of the channel's logical fold position.
        self._last_seq = msg.seq
        cs = msg.contents
        self.edit_manager.add_sequenced(msg.seq, msg.client_id, cs)
        apply_changeset(self.seq_forest, cs, msg.seq)
        self._invalidate()
        self.advance(msg.seq, msg.min_seq)
        if not local:
            self._emit("changed", {"changeset": cs}, local=False)

    # -- window / zamboni ------------------------------------------------------

    def advance(self, seq: int, min_seq: int) -> None:
        if min_seq <= self._min_seq:
            return
        self._min_seq = min_seq
        self.edit_manager.evict(min_seq)
        expired = [
            n.id for n in self.seq_forest.nodes.values()
            if n.removed_seq is not None and n.removed_seq <= min_seq
        ]
        if expired:
            for nid in expired:
                if self.seq_forest.contains(nid):
                    self.seq_forest.detach(nid)
                    self.seq_forest.purge_expired(nid, min_seq)
            self._invalidate()

    # -- summaries (normalized; SEMANTICS.md §canonical-summaries) -------------

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        min_seq = max(min_seq, self._min_seq)
        tree = SummaryTree()
        root_obj = {
            "fields": self._summary_fields(ROOT_ID, min_seq),
            "minSeq": min_seq,
            "seq": self._last_seq,
        }
        # Detached (rescuable) subtrees survive summarize/reload — a later
        # sequenced move can still relocate them by id, so a freshly
        # loaded replica must know them or it would skip the rescue every
        # long-lived replica applies.  Limbo is derived from THIS summary's
        # window, not from past purges: with a caller min_seq beyond the
        # channel's advanced window (the container summarizes with its own
        # MSN), tombstones expire at serialization time and their kept
        # descendants must surface exactly as if the purge had run — the
        # same kept-under-unkept rule the device kernel extraction applies.
        limbo_ids = set(self.seq_forest.limbo_roots())
        for nid, n in self.seq_forest.nodes.items():
            if nid == ROOT_ID or n.parent is None:
                continue
            if not self._summary_keep(nid, min_seq):
                continue
            pid = n.parent[0]
            if pid != ROOT_ID and not self._summary_keep(pid, min_seq):
                limbo_ids.add(nid)
        limbo = [
            self._summary_node(nid, min_seq)
            for nid in sorted(limbo_ids)
            if self._summary_keep(nid, min_seq)
        ]
        if limbo:
            root_obj["limbo"] = limbo
        tree.add_blob("header", canonical_json(root_obj))
        if self._attributor is not None:
            # Attribution-enabled containers: pre-clamp (insert, value)
            # seqs per node in a SEPARATE blob — header bytes stay
            # kernel-identical; load() restores them so attribution_of
            # survives the window clamp (SURVEY §1 layer 8).  Keys cover
            # only nodes the summary actually EMITS (a kept node under an
            # expired-tombstone ancestor is dropped with its subtree and
            # must not leave an orphan key).
            emitted: set = set()

            def collect(node_obj: dict) -> None:
                emitted.add(node_obj["id"])
                for children in node_obj.get("fields", {}).values():
                    for child in children:
                        collect(child)

            for children in root_obj.get("fields", {}).values():
                for child in children:
                    collect(child)
            for spec in root_obj.get("limbo", []):
                collect(spec)
            keys = {
                nid: [n.insert_seq, n.value_seq]
                for nid, n in sorted(self.seq_forest.nodes.items())
                if nid in emitted
                and (0 < n.insert_seq <= min_seq
                     or 0 < n.value_seq <= min_seq)
            }
            if keys:
                tree.add_blob("attribution", canonical_json(keys))
        return tree

    def _summary_fields(self, node_id: str, min_seq: int) -> dict:
        n = self.seq_forest.node(node_id)
        return {
            f: [
                self._summary_node(cid, min_seq)
                for cid in sibs if self._summary_keep(cid, min_seq)
            ]
            for f, sibs in sorted(n.fields.items())
            if any(self._summary_keep(c, min_seq) for c in sibs)
        }

    def _summary_keep(self, node_id: str, min_seq: int) -> bool:
        n = self.seq_forest.nodes.get(node_id)
        if n is None:
            return False
        if n.removed_seq is not None and n.removed_seq <= min_seq:
            return False  # expired tombstone
        return True

    def _summary_node(self, node_id: str, min_seq: int) -> dict:
        n = self.seq_forest.node(node_id)
        obj: Dict[str, Any] = {
            "id": n.id,
            "type": n.type,
            "insertSeq": 0 if n.insert_seq <= min_seq else n.insert_seq,
        }
        if n.value is not None:
            obj["value"] = n.value
            obj["valueSeq"] = 0 if n.value_seq <= min_seq else n.value_seq
        if n.removed_seq is not None:
            obj["removedSeq"] = n.removed_seq
        fields = self._summary_fields(node_id, min_seq)
        if fields:
            obj["fields"] = fields
        return obj

    def load(self, summary: SummaryTree) -> None:
        obj = json.loads(summary.blob_bytes("header"))
        self.seq_forest = Forest()
        self.edit_manager = EditManager()
        self._min_seq = obj.get("minSeq", 0)
        self._last_seq = obj.get("seq", 0)
        root = self.seq_forest.node(ROOT_ID)
        for f, children in obj.get("fields", {}).items():
            for child in children:
                self._load_node(child, ROOT_ID, f)
                root.fields.setdefault(f, []).append(child["id"])
        for spec in obj.get("limbo", []):
            self._load_node(spec, ROOT_ID, "")
            self.seq_forest.node(spec["id"]).parent = None  # detached
        if "attribution" in summary.children:
            # Restore pre-clamp seqs via the ONE shared helper (the
            # catch-up service's warm-base pack uses it too).
            def get_seqs(nid):
                n = self.seq_forest.nodes.get(nid)
                return None if n is None else (n.insert_seq, n.value_seq)

            def put_seqs(nid, ins, val):
                n = self.seq_forest.nodes[nid]
                n.insert_seq, n.value_seq = ins, val

            restore_attribution_seqs(
                json.loads(summary.blob_bytes("attribution")),
                get_seqs, put_seqs,
            )
        self.discard_pending()
        self._invalidate()

    def _load_node(self, obj: dict, parent_id: str, field: str) -> None:
        n = TreeNode(
            id=obj["id"], type=obj["type"],
            value=obj.get("value"), value_seq=obj.get("valueSeq", 0),
            insert_seq=obj["insertSeq"],
            removed_seq=obj.get("removedSeq"),
            parent=(parent_id, field),
        )
        self.seq_forest.nodes[n.id] = n
        for f, children in obj.get("fields", {}).items():
            for child in children:
                self._load_node(child, n.id, f)
                n.fields.setdefault(f, []).append(child["id"])


class _Transaction:
    """Context manager: edits inside are squashed (composed) into a single
    changeset — one op, one ack, atomic for remote replicas.  On exception
    the collected edits are simply dropped (nothing was submitted; the
    predicted view rebuilds without them)."""

    def __init__(self, tree: SharedTree) -> None:
        self.tree = tree

    def __enter__(self) -> "_Transaction":
        if self.tree._txn_edits is not None:
            raise RuntimeError("nested transactions are not supported")
        self.tree._txn_edits = []
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        edits = self.tree._txn_edits
        self.tree._txn_edits = None
        self.tree._invalidate()
        if exc_type is None and edits:
            self.tree._submit_changeset(compose([{"edits": edits}]))
