"""SharedObject — the base class every DDS extends.

Capability-equivalent of the reference's shared-object-base (SURVEY.md §2.1:
``SharedObject``/``SharedObjectCore`` — summary load, op submit/process/
resubmit; upstream paths UNVERIFIED — empty reference mount).

The contract between a DDS and its runtime:

- the DDS applies local mutations optimistically, then calls
  :meth:`_submit_local_op` with the op contents and an opaque *local op
  metadata* record it will need to reconcile the ack;
- the runtime (or mock) later feeds every sequenced message — including the
  client's own — to :meth:`process` in strict total order, with
  ``local=True`` and the matching metadata for the client's own ops;
- on reconnect the runtime asks the DDS to resubmit pending ops
  (:meth:`resubmit_pending`);
- :meth:`summarize` / :meth:`load` round-trip state through the canonical
  summary-tree model.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Optional, Tuple

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree
from ..utils.events import EventEmitter


class StaleOpError(RuntimeError):
    """A pending op's view fell below the collaboration window; the
    container must be stashed and rehydrated (reference: container close
    on too-old pending state)."""


class SharedObject:
    """Base DDS: pending-op bookkeeping + runtime wiring + change events."""

    #: channel type identifier, e.g. "map-tpu"; set by subclasses and used by
    #: the ChannelFactory registry (the plugin boundary).
    TYPE: str = "shared-object"

    #: container-level Attributor (seq -> user/timestamp), wired by the
    #: datastore on attach; None when standalone (mocks, bare DDS tests).
    _attributor = None

    def _attribution(self, seq) -> "Optional[dict]":
        """Resolve a seq stamp to ``{"user", "timestamp", "seq"}`` via the
        container attributor; None when detached from a container or the
        seq predates attribution (SURVEY §1 layer 8)."""
        if self._attributor is None or seq is None:
            return None
        return self._attributor.get(seq)

    def __init__(self, object_id: str) -> None:
        self.id = object_id
        self.client_id: Optional[str] = None
        self._delta_connection = None  # set by connect()
        self._client_seq = 0
        # FIFO of (client_seq, contents, local_metadata, ref_seq) awaiting
        # ack; ref_seq is the view the op was created against (resubmits
        # preserve it so position-carrying contents stay correct).
        self._pending: Deque[Tuple[int, Any, Any, Any]] = collections.deque()
        # Acks at or below this client_seq are silently dropped: they belong
        # to ops submitted before a load() reset the channel's state.
        self._stale_ack_floor = -1
        self._last_submitted_client_seq = -1
        self.events = EventEmitter()
        self._in_event = 0  # op-reentrancy guard depth

    # -- runtime wiring --------------------------------------------------------

    def connect(self, delta_connection, client_id: str) -> None:
        """Attach to a delta connection: an object with
        ``submit(contents) -> client_seq``."""
        self._delta_connection = delta_connection
        self.client_id = client_id

    @property
    def is_attached(self) -> bool:
        return self._delta_connection is not None

    def _emit(self, event: str, *args, **kwargs) -> None:
        """Emit a change event with op-reentrancy detection: mutating a DDS
        from inside its own change event diverges optimistic state across
        clients, so it errors (the reference's op-reentrancy guard —
        SURVEY.md §5 race-detection equivalents)."""
        self._in_event += 1
        try:
            self.events.emit(event, *args, **kwargs)
        finally:
            self._in_event -= 1

    def _submit_local_op(self, contents: Any, local_metadata: Any = None,
                         ref_seq: Any = None) -> None:
        """Send an optimistically-applied local op to the sequencer.
        ``ref_seq`` pins the view the op resolves against (resubmit path);
        None = the current view."""
        if self._in_event:
            raise RuntimeError(
                f"{self.id}: op submitted from inside a change-event "
                f"listener (op re-entrancy is not allowed)"
            )
        if self._delta_connection is None:
            return  # detached: local-only state, nothing to send
        if ref_seq is None:
            ref_seq = getattr(self._delta_connection, "ref_seq", None)
        client_seq = self._delta_connection.submit(contents, ref_seq)
        self._last_submitted_client_seq = client_seq
        self._pending.append((client_seq, contents, local_metadata, ref_seq))

    #: Whether this DDS's op contents are view-independent (no positions
    #: resolved against ``ref_seq``): LWW keys, grow-only counters,
    #: id-addressed tree edits.  Such ops can be rebased to the current view
    #: by simply re-pinning ``ref_seq``; position-carrying DDSes leave this
    #: False and override :meth:`_resubmit_rebased` with real op
    #: regeneration (SharedString) or inherit the StaleOpError.
    REBASE_POSITION_FREE = False

    def resubmit_pending(self, force_rebase: bool = False) -> None:
        """Reconnect path: re-send all unacked ops (same contents, fresh
        client_seqs).  Capability parity with PendingStateManager resubmit.

        If the collaboration window moved past a pending op's view while we
        were away, its original ``ref_seq`` can no longer be sent (remote
        zamboni may have compacted the state that view needs): the whole
        batch is rebased instead — regenerated against the current view
        (the reference's merge-tree op regeneration on reconnect).

        ``force_rebase`` is the REHYDRATE path: the session resubmits under
        a NEW client id, so views pinned to the crashed session's refs are
        id-bound lies (the old id's own sequenced ops would count there,
        the new id's would not — fuzz-found divergence).  Rebasable
        channels regenerate against the current view; others re-pin to the
        current view (their documented reinterpretation semantics)."""
        if self._delta_connection is None:
            return
        pending = list(self._pending)
        self._pending.clear()
        min_seq = getattr(self._delta_connection, "min_seq", None)
        stale = min_seq is not None and any(
            ref_seq is not None and ref_seq < min_seq
            for _cs, _c, _m, ref_seq in pending
        )
        if stale or (force_rebase and self.can_rebase):
            try:
                self._resubmit_rebased(pending)
            except StaleOpError:
                # Restore the snapshot so the documented recovery (stash and
                # rehydrate) can still capture these ops.  Overrides must
                # raise before submitting anything for this to be exact.
                self._pending.extend(pending)
                raise
            return
        for _old_client_seq, contents, metadata, ref_seq in pending:
            self._resubmit_core(
                contents, metadata, None if force_rebase else ref_seq
            )

    @property
    def can_rebase(self) -> bool:
        """Whether stale pending ops can be regenerated against the current
        view: view-independent ops, or a DDS-specific rebase override."""
        return self.REBASE_POSITION_FREE or (
            type(self)._resubmit_rebased is not SharedObject._resubmit_rebased
        )

    def _resubmit_rebased(self, pending) -> None:
        """Re-issue pending ops whose view fell below the collaboration
        window.  Default: view-independent ops are re-pinned to the current
        view (exact); position-carrying DDSes must override with real
        regeneration, else the host must stash and rehydrate."""
        if not self.REBASE_POSITION_FREE:
            raise StaleOpError(
                f"{self.id}: pending op view fell below the collaboration "
                f"window and {type(self).__name__} cannot rebase it; stash "
                f"and rehydrate"
            )
        for _old_client_seq, contents, metadata, _ref_seq in pending:
            self._resubmit_core(contents, metadata, ref_seq=None)

    def _resubmit_core(self, contents: Any, metadata: Any,
                       ref_seq: Any = None) -> None:
        """Default resubmit: send unchanged, pinned to the op's original
        view — position-carrying contents resolve exactly as authored."""
        self._submit_local_op(contents, metadata, ref_seq=ref_seq)

    # -- inbound ---------------------------------------------------------------

    def process(self, msg: SequencedMessage, local: bool) -> None:
        """Apply one sequenced message (strict total order)."""
        if msg.type is not MessageType.OP:
            return
        local_metadata = None
        if local:
            if msg.client_seq <= self._stale_ack_floor:
                return  # ack for an op discarded by a load() reset
            if not self._pending:
                raise AssertionError(
                    f"{self.id}: ack for {msg.client_seq} with no pending ops"
                )
            client_seq, _contents, local_metadata, _ref = \
                self._pending.popleft()
            if client_seq != msg.client_seq:
                raise AssertionError(
                    f"{self.id}: out-of-order ack {msg.client_seq}, "
                    f"expected {client_seq}"
                )
        self._process_core(msg, local, local_metadata)

    # -- subclass surface ------------------------------------------------------

    def apply_stashed_op(self, contents: Any) -> None:
        """Re-apply a stashed (crashed-session pending) op as a fresh local
        mutation: optimistic apply + submit.  Called by the loader's
        rehydrate path with the channel's state positioned exactly where it
        was when the op was created (summary + tail to the stash's ref_seq),
        so position-carrying contents resolve identically.  Capability
        parity with the reference's per-DDS ``applyStashedOp``."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support stashed-op rehydration"
        )

    def discard_pending(self) -> None:
        """Forget in-flight ops (used by load(): state resets make their acks
        meaningless; the floor keeps late acks from tripping the FIFO)."""
        self._pending.clear()
        self._stale_ack_floor = self._last_submitted_client_seq

    def _process_core(
        self, msg: SequencedMessage, local: bool, local_metadata: Any
    ) -> None:
        raise NotImplementedError

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        raise NotImplementedError

    def load(self, summary: SummaryTree) -> None:
        raise NotImplementedError
