"""SharedCell and SharedCounter — the small DDSes.

Capability-equivalent of the reference's cell/counter packages (SURVEY.md
§2.2; upstream paths UNVERIFIED — empty reference mount).  SharedCell is a
single LWW register (pending-local-wins like the map kernel); SharedCounter is
a commutative increment counter (ops always apply — addition commutes, so no
pending masking is needed).
"""

from __future__ import annotations

import json
from typing import Any

from ..protocol.messages import SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from .shared_object import SharedObject


class SharedCell(SharedObject):
    TYPE = "cell-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._value: Any = None
        self._empty = True
        self._pending_writes = 0

    def get(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        return self._empty

    def set(self, value: Any) -> None:
        prev = self._value
        self._value, self._empty = value, False
        if self.is_attached:
            self._pending_writes += 1
        self._submit_local_op({"kind": "set", "value": value})
        self._emit("valueChanged", {"previousValue": prev}, local=True)

    def delete(self) -> None:
        self._value, self._empty = None, True
        if self.is_attached:
            self._pending_writes += 1
        self._submit_local_op({"kind": "delete"})
        self._emit("delete", local=True)

    def apply_stashed_op(self, contents) -> None:
        kind = contents["kind"]
        if kind == "set":
            self.set(contents["value"])
        elif kind == "delete":
            self.delete()
        else:
            raise ValueError(f"unknown stashed cell op {kind!r}")

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        if local:
            self._pending_writes -= 1
            return
        if self._pending_writes > 0:
            return  # pending local write sequences later → wins
        op = msg.contents
        if op["kind"] == "set":
            prev = self._value
            self._value, self._empty = op["value"], False
            if not local:
                self._emit("valueChanged", {"previousValue": prev},
                           local=False)
        else:
            self._value, self._empty = None, True
            if not local:
                self._emit("delete", local=False)

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob(
            "header", canonical_json({"empty": self._empty, "value": self._value})
        )
        return tree

    def load(self, summary: SummaryTree) -> None:
        obj = json.loads(summary.blob_bytes("header"))
        self._empty, self._value = obj["empty"], obj["value"]
        self._pending_writes = 0
        self.discard_pending()


class SharedCounter(SharedObject):
    TYPE = "counter-tpu"
    REBASE_POSITION_FREE = True

    def __init__(self, object_id: str) -> None:
        super().__init__(object_id)
        self._value = 0

    @property
    def value(self) -> int:
        return self._value

    def increment(self, delta: int) -> None:
        if not isinstance(delta, int):
            raise TypeError("counter delta must be an integer")
        self._value += delta  # optimistic; increments commute
        self._submit_local_op({"kind": "increment", "delta": delta})
        self._emit("incremented", {"incrementAmount": delta,
                                   "newValue": self.value}, local=True)

    def apply_stashed_op(self, contents) -> None:
        self.increment(contents["delta"])

    def _process_core(self, msg: SequencedMessage, local: bool, _meta) -> None:
        if local:
            return  # already counted optimistically
        self._value += msg.contents["delta"]
        self._emit("incremented",
                   {"incrementAmount": msg.contents["delta"],
                    "newValue": self._value}, local=False)

    def summarize(self, min_seq: int = 0) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", canonical_json({"value": self._value}))
        return tree

    def load(self, summary: SummaryTree) -> None:
        self._value = json.loads(summary.blob_bytes("header"))["value"]
        self.discard_pending()
